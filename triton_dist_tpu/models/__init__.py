from triton_dist_tpu.models.llama import (  # noqa: F401
    LlamaConfig, init_params, forward, forward_tp_overlap)
from triton_dist_tpu.models.moe import (  # noqa: F401
    MoEConfig, init_moe_params, moe_forward)
