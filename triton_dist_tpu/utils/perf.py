"""Perf measurement + tracing harness.

``perf_func`` mirrors the reference's CUDA-event wall-clock harness
(reference python/triton_dist/utils.py:186-198); on TPU we block on the
output buffers instead of recording events. ``group_profile`` mirrors the
reference's merged chrome-trace context (utils.py:254-501); jax's profiler
already merges multi-host traces, so it is a thin wrapper producing a
Perfetto-loadable trace directory.
"""

from __future__ import annotations

import contextlib
import time

import jax
import numpy as np


def _block(tree):
    """Synchronize on ``tree``'s buffers. ``block_until_ready`` alone is not
    trusted: under remote-execution runtimes (axon tunnel) it can return
    before the device work lands, over-reporting throughput ~100x. A 1-element
    device-to-host pull cannot complete early, so pull one scalar per leaf;
    in-order execution then guarantees everything earlier finished too."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
        if hasattr(leaf, "addressable_shards") and leaf.size:
            np.asarray(leaf.addressable_shards[0].data.ravel()[:1])


def perf_func(func, iters: int = 10, warmup_iters: int = 3, return_result: bool = False):
    """Return (result, avg_ms_per_iter); ``result`` is the last iteration's
    output when ``return_result=True``, else None. ``func`` should return jax
    arrays (they are blocked on for timing)."""
    result = None
    for _ in range(warmup_iters):
        result = func()
    _block(result)
    start = time.perf_counter()
    for _ in range(iters):
        result = func()
    _block(result)
    elapsed_ms = (time.perf_counter() - start) * 1e3 / max(iters, 1)
    if return_result:
        return result, elapsed_ms
    return None, elapsed_ms


@contextlib.contextmanager
def group_profile(name: str = "trace", do_prof: bool = True, out_dir: str = "prof"):
    """Profile the enclosed region into ``{out_dir}/{name}`` (TensorBoard /
    Perfetto format). Multi-host merging is native to jax's profiler."""
    if not do_prof:
        yield
        return
    path = f"{out_dir}/{name}"
    jax.profiler.start_trace(path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
