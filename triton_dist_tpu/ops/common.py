"""Shared kernel utilities (analog of reference
python/triton_dist/kernels/nvidia/common_ops.py).

On GPU the reference needs hand-written device barriers
(common_ops.py:62-159: grid barriers, atomic-CAS intra-node barriers) and
CPU-driven stream signal ops (:178-211). On TPU, barriers are the barrier
semaphore, and there is no separate "stream signal plane" — the DMA
semaphore of each remote copy is the signal.
"""

from __future__ import annotations

import hashlib

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret

# Every kernel family that uses pltpu.get_barrier_semaphore() needs a
# distinct collective id (matching across devices running the same kernel).
# Ids must ONLY be passed for kernels that actually use barrier semaphores —
# compiled TPU rejects them otherwise.
#
# Ids are a STABLE function of the family name, not a first-use counter: in a
# multi-host job, two processes can trace ops in different orders (divergent
# autotuner pruning, conditional model paths), and order-derived ids would
# silently alias different kernel families onto the same barrier across hosts
# (the reference avoids this with fixed per-kernel signal-buffer layouts in
# its ctx dataclasses). Interpret mode narrows ids to int16, so we hash into
# [0, 2**15); a (deterministic, therefore immediately-reproducible) collision
# between two family names raises loudly and can be resolved by pinning.
_COLLECTIVE_ID_PINS: dict[str, int] = {}
_ASSIGNED: dict[int, str] = {}


def collective_id_for(name: str) -> int:
    if name in _COLLECTIVE_ID_PINS:
        cid = _COLLECTIVE_ID_PINS[name]
    else:
        digest = hashlib.blake2b(name.encode(), digest_size=8).digest()
        cid = int.from_bytes(digest, "little") % (1 << 15)
    holder = _ASSIGNED.setdefault(cid, name)
    if holder != name:
        raise ValueError(
            f"collective id collision: {name!r} and {holder!r} both hash to "
            f"{cid}. Pin one explicitly via "
            f"triton_dist_tpu.ops.common._COLLECTIVE_ID_PINS[{name!r}] = <id> "
            f"before first use (any unused id in [0, 32768)).")
    return cid


def norm_axis(ctx: ShmemContext, axis):
    """Normalize an op's ``axis`` argument: None → first mesh axis; a
    1-tuple → its name; a multi-name tuple → tuple (the hierarchical 2-tier
    path, outer/slow tier first)."""
    if axis is None:
        return ctx.axis_names[0]
    if not isinstance(axis, str):
        axis = tuple(axis)
        return axis[0] if len(axis) == 1 else axis
    return axis


def barrier_all_op(ctx: ShmemContext, axis: str | None = None):
    """Host-level device barrier across the mesh — analog of
    ``barrier_all_on_stream`` (reference common_ops.py:162-175). Returns a
    jitted callable performing a full-mesh in-kernel barrier."""
    axes = ctx.axis_names if axis is None else (axis,)

    def kernel(out_ref):
        shd.barrier_all(axes, mesh_axes=ctx.axis_names)
        out_ref[0] = 1

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), "int32"),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("barrier_all")),
            interpret=default_interpret(),
        )()

    return jax.jit(ctx.shard_map(f, in_specs=(), out_specs=P(ctx.axis_names[0])))
