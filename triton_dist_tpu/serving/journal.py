"""Append-only control-plane journal (write-ahead log) for the serving tier.

The serving engines are deterministic in the control plane: greedy argmax
decoding, LIFO page allocation, and strict-FIFO scheduling make every
request's tokens a pure function of ``(params, prompt)``.  That contract
(the same one the producer/consumer signal overlap relies on for bit-exact
results) means crash recovery never has to persist KV bytes — it only has
to remember *which control-plane events happened*.  This module is that
memory: a tiny append-only log of typed events, each stamped with the
engine step index and the FNV-1a control digest of the post-event state.

Event kinds written by the engines:

=================  ============================================================
``submit``         request entered the admission queue (payload: rid, prompt,
                   max_new_tokens) — replayed verbatim on restore
``admit``          request seated in a slot (rid, slot)
``chunk``          prefill chunk advanced (rid, cursor)
``grow``           page-pool growth for a decoding row (rid, pages)
``preempt``        youngest-victim eviction (rid, slot)
``handoff``        disagg: prefill row flipped to MIGRATING (rid)
``migrate``        disagg: migration attempt pushed chunks over the channel
``finish``         request finished (rid, tokens) — the tokens ride in the
                   journal so post-checkpoint finishes survive a crash
``reject``         typed terminal: admission queue at capacity (rid, reason)
``expire``         typed terminal: queued past its TTL deadline (rid, reason)
``fail``           typed terminal: recovery ladder exhausted (rid, kind, reason)
``digest_divergence``  sharded: replicated-decision digest mismatch was
                   quarantined before a restore
``checkpoint``     full engine snapshot (``state`` payload + ``journal_seq``
                   high-water mark); see :mod:`serving.checkpoint`
``restore``        a restore completed (replayed entry count)
``requeue``        elastic drain (ISSUE 18): a queued request left THIS
                   engine for a peer replica (rid) — replay drops it so a
                   post-requeue crash never re-serves a moved request
``scale_up``       controller: a replica was added to the fleet (replica,
                   fleet, attainment)
``drain_begin``    controller: a replica stopped admitting and began its
                   graceful drain (replica, requeued)
``drain_done``     controller: a drain reached quiescence — in-flight work
                   finished or requeued, lend-ahead ran (replica)
``retire``         controller: the drained replica left the fleet (replica)
``spec_rewind``    speculative decoding (ISSUE 20): a verify dispatch
                   rejected a draft suffix and returned its whole pages to
                   the pool (rid, freed, pos) — replay ignores it
=================  ============================================================

Entries are plain JSON-able dicts ``{"seq", "step", "kind", "digest", ...}``
so a journal can be persisted as JSON-lines and reloaded in a fresh process.

Schema versioning (ISSUE 14): persisted journals open with a header line
``{"schema": N}``. v2 stamps ``tenant``/``cls`` on submit/reject/expire
entries; ``load()`` is tolerant — a headerless file is v1 and its entries
replay with the default tenant/class, so pre-ISSUE-14 journals restore
bit-identically under the new code (pinned by a checked-in v1 fixture).
"""

from __future__ import annotations

import json
import os
from typing import Any, Iterable

SCHEMA_VERSION = 2

# Event kinds whose payloads carry the multi-tenant stamps in v2; the
# tolerant loader backfills these defaults on older entries.
_CLASSED_KINDS = ("submit", "reject", "expire")
_CLASS_DEFAULTS = {"tenant": "default", "cls": "default"}

EVENT_KINDS = (
    "submit",
    "admit",
    "chunk",
    "grow",
    "preempt",
    "handoff",
    "migrate",
    "finish",
    "reject",
    "expire",
    "fail",
    "digest_divergence",
    "checkpoint",
    "restore",
    # cluster page lending (ISSUE 17): pages adopted from a peer replica.
    # Observability only — replay ignores it (adopted pages are cache
    # state, and a restored replica re-warms from peers, not from its own
    # pre-crash journal)
    "lend",
    # elastic autoscaling (ISSUE 18). "requeue" lives in the ENGINE
    # journal and is replayed (it cancels an earlier "submit" — the
    # request moved to a peer); the scale kinds live in the CONTROLLER
    # journal and are what an autoscaler restart resumes the fleet from.
    "requeue",
    "scale_up",
    "drain_begin",
    "drain_done",
    "retire",
    # speculative decoding (ISSUE 20): a rejected draft suffix's pages
    # went back to the pool. Observability only — replay ignores it (the
    # token trace is bit-identical spec-on/off, so recovery re-derives
    # page state from the replayed control events exactly as before;
    # folding accept/reject accounting into replay would make recovery
    # depend on a knob that must never change outputs)
    "spec_rewind",
)

# Payload keys elided from one-line renderings (bulky checkpoint state).
_BULKY_KEYS = ("state",)


class ControlJournal:
    """Append-only WAL of control-plane events.

    The journal is the durable artifact of a serving process: a fresh engine
    plus the journal (which embeds periodic checkpoints) reconstructs
    bit-identical serving results.  ``path`` optionally mirrors every entry
    to a JSON-lines file as it is appended.
    """

    def __init__(self, path: str | None = None):
        self._entries: list[dict[str, Any]] = []
        self.path = path
        self.schema = SCHEMA_VERSION
        self._fh = open(path, "a", encoding="utf-8") if path else None
        if self._fh is not None and os.path.getsize(path) == 0:
            # fresh file: lead with the schema header (reopened files
            # already carry theirs — never write a second one)
            self._fh.write(json.dumps({"schema": SCHEMA_VERSION}) + "\n")
            self._fh.flush()

    # ------------------------------------------------------------- append
    def append(self, kind: str, step: int, digest: int, **payload: Any) -> dict[str, Any]:
        assert kind in EVENT_KINDS, f"unknown journal event kind {kind!r}"
        entry = {"seq": len(self._entries), "step": int(step), "kind": kind,
                 "digest": int(digest), **payload}
        self._entries.append(entry)
        if self._fh is not None:
            self._fh.write(json.dumps(entry) + "\n")
            self._fh.flush()
        return entry

    def record_checkpoint(self, step: int, digest: int, state: dict,
                          journal_seq: int) -> dict[str, Any]:
        """Append a checkpoint entry.  ``journal_seq`` is the seq of the last
        entry the snapshot already covers; restore replays only entries with
        ``seq > journal_seq``."""
        return self.append("checkpoint", step, digest, state=state,
                           journal_seq=int(journal_seq))

    # -------------------------------------------------------------- reads
    @property
    def entries(self) -> list[dict[str, Any]]:
        return self._entries

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def last_seq(self) -> int:
        """Seq of the newest entry, or -1 for an empty journal."""
        return self._entries[-1]["seq"] if self._entries else -1

    def suffix(self, after_seq: int) -> Iterable[dict[str, Any]]:
        """Entries with ``seq > after_seq``, oldest first."""
        return [e for e in self._entries if e["seq"] > after_seq]

    def last_checkpoint_entry(self) -> dict[str, Any] | None:
        """Newest ``checkpoint`` entry, or None if never checkpointed."""
        for e in reversed(self._entries):
            if e["kind"] == "checkpoint":
                return e
        return None

    def counts(self) -> dict[str, int]:
        """Event-kind histogram (cheap integrity/debug summary)."""
        out: dict[str, int] = {}
        for e in self._entries:
            out[e["kind"]] = out.get(e["kind"], 0) + 1
        return out

    # -------------------------------------------------------- post-mortem
    def tail(self, n: int = 8) -> list[dict[str, Any]]:
        return self._entries[-n:]

    def format_tail(self, n: int = 8) -> str:
        """Human-readable last-``n`` entries for embedding in error reports,
        bulky payloads elided — a post-mortem never needs a live process."""
        lines = []
        for e in self.tail(n):
            extra = {k: v for k, v in e.items()
                     if k not in ("seq", "step", "kind", "digest") + _BULKY_KEYS}
            if "state" in e:
                extra["state"] = "<elided>"
            lines.append(f"  #{e['seq']} step={e['step']} {e['kind']}"
                         f" digest=0x{e['digest'] & 0xFFFFFFFF:08x}"
                         + (f" {extra}" if extra else ""))
        return "\n".join(lines) if lines else "  <empty journal>"

    # -------------------------------------------------------- persistence
    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"schema": self.schema}) + "\n")
            for e in self._entries:
                fh.write(json.dumps(e) + "\n")

    @classmethod
    def load(cls, path: str) -> "ControlJournal":
        """Tolerant loader: an optional leading ``{"schema": N}`` header
        sets the version (headerless = v1, the pre-ISSUE-14 format);
        v1 submit/reject/expire entries are backfilled with the default
        tenant/class so old journals replay under the v2 engines without
        changing a single control decision."""
        j = cls()
        schema = 1
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                e = json.loads(line)
                if "seq" not in e and "schema" in e:
                    schema = int(e["schema"])
                    continue
                if schema < 2 and e.get("kind") in _CLASSED_KINDS:
                    for k, v in _CLASS_DEFAULTS.items():
                        e.setdefault(k, v)
                j._entries.append(e)
        j.schema = schema
        return j

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
