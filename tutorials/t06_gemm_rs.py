"""Tutorial 06 — overlapping GEMM-ReduceScatter.

Analog of reference tutorials/08 + gemm_reduce_scatter.py. The producer
GEMM walks output segments own-segment-LAST so every remote partial spends
the longest possible time in flight: each remote segment's partial is
computed into a double-buffered stage slot and shipped to its owner as a
non-blocking put, then the n arrived partials reduce on the VPU.

Run:  python -m tutorials.t06_gemm_rs [--sim 4] [--case correctness|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _shapes(ctx, M=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    M = M or 64 * n
    K, N = 128 * n, 128
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    return ctx.shard(a, P(None, "x")), ctx.shard(b, P("x", None))


def _golden(ctx, a, b):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def g(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, "x", scatter_dimension=0,
                                    tiled=True)
    return jax.jit(ctx.shard_map(g, in_specs=(P(None, "x"), P("x", None)),
                                 out_specs=P("x")))(a, b)


@register_case("correctness")
def correctness():
    import jax
    import numpy as np

    from triton_dist_tpu.ops import gemm_rs
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context()
    a, b = _shapes(ctx)
    cfg = GemmConfig(64, 128)
    c = jax.jit(lambda u, v: gemm_rs(ctx, u, v, axis="x", cfg=cfg))(a, b)
    np.testing.assert_allclose(np.asarray(c), np.asarray(_golden(ctx, a, b)),
                               rtol=1e-4, atol=1e-4)
    print(f"overlapped GEMM-RS over {ctx.num_ranks} PEs == dot+psum_scatter")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.ops import gemm_rs
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context()
    n = ctx.num_ranks
    a, b = _shapes(ctx, M=256 * n)
    cfg = GemmConfig(128, 128)
    f = jax.jit(lambda u, v: gemm_rs(ctx, u, v, axis="x", cfg=cfg))
    perf_report("gemm_rs", time_op(lambda: f(a, b)))


if __name__ == "__main__":
    tutorial_main(__doc__)
