"""Sharded serving (ISSUE 8 tentpole): the continuous-batching engine's two
compiled programs — ``prefill_chunk_paged`` and ``decode_multistep_paged`` —
run over a TP/SP/EP mesh, with every sharded layer routed through the
overlap-kernel library via the model hooks:

- **attention (SP)**: the page pool is sharded on its PAGE dim per
  ``page_pool_pspec`` and each layer's KV-write + paged-GQA-attention pair
  runs ``ops.flash_decode.sp_paged_attend_write`` — per-rank masked local
  writes, tiled pool allgather, replicated attention walk.
- **dense projections (TP)**: wq/wk/wv/wo/lm_head run
  ``ops.allgather_gemm.tp_column_linear`` — column-sharded weights,
  last-dim allgather (``tp_impl="ag_gemm"`` swaps in the Pallas
  AllGather-GEMM overlap kernel).
- **MoE FFN (EP)**: ``models.moe.moe_mlp_ep_overlap`` — router →
  low-latency A2A dispatch (fp8 on the wire with ``wire_dtype="auto"``) →
  grouped expert FFN on local experts → A2A combine.

Host control plane stays REPLICATED-DECISION: one ``KVPagePool`` +
``ContinuousBatchingScheduler`` instance makes every allocation/admission/
preemption choice from device-independent inputs (token ids, counters), so
all ranks agree on block tables by construction — and the per-step digest
cross-check (``check_replicated_decisions``) turns "by construction" into a
loud runtime guarantee.

THE numerical contract (tests/test_sharded_serving.py): served tokens are
BITWISE identical across mesh sizes — the n>1 trace replays the n=1 golden
exactly, preemptions and all. This falls out of three exactness facts:

1. column-split matmul + concat allgather == the unsplit matmul (TP);
2. per-row EP dispatch/quant/combine with a fixed k-order fold is
   independent of which rank computed the row (EP, incl. the fp8 wire —
   the n=1 path runs the SAME quantize/dequantize round trip);
3. the SP pool allgather is a pure page-order concatenation (SP).

No cross-rank floating-point REDUCTION exists anywhere in the hot loop —
which is also why ``gemm_rs`` is refused here (docs/serving.md).
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.aot.registry import TunedKey, get_default_registry
from triton_dist_tpu.layers.ep_a2a_layer import EPAll2AllLayer
from triton_dist_tpu.models.moe import MoEConfig, moe_mlp_ep_overlap
from triton_dist_tpu.ops.all_to_all import _DEFAULT_WIRE_FIT, a2a_wire_bytes
from triton_dist_tpu.ops.allgather_gemm import GemmConfig, tp_column_linear
from triton_dist_tpu.ops.flash_decode import (flash_decode_dist,
                                              sp_paged_attend_write)
from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.engine import ServingEngine
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import page_pool_pspec, shard_pool_arrays
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.speculate import resolve_spec_k
from triton_dist_tpu.shmem import faults as faults_mod
from triton_dist_tpu.shmem.context import ShmemContext, initialize_distributed

MESH_AXES = ("tp", "sp", "ep")


class ReplicatedDecisionError(AssertionError):
    """The per-rank control-plane digests diverged: some rank's allocator/
    scheduler made a different decision than rank 0's. Block tables are
    about to disagree across ranks — fail loudly BEFORE a wrong-rank page
    write corrupts live KV, not after."""


def serving_mesh(tp: int = 1, sp: int = 1, ep: int = 1) -> ShmemContext:
    """Build the TP×SP×EP serving mesh (axis names fixed to ``MESH_AXES``
    so the engine, bench rows, and serve_sim all agree on spelling)."""
    return initialize_distributed(axis_names=MESH_AXES,
                                  mesh_shape=(tp, sp, ep))


def fd_attn_split_us(n_sp: int, n_layers: int, slots: int, steps: int,
                     page_kv_bytes: int, slab_row_bytes: int
                     ) -> tuple[float, float]:
    """Modeled per-decode-step attention split for ``flash_decode_dist``
    (ISSUE 19) — the long-context twin of ``_comm_split_us``, priced on
    the SAME PR 8 wire fit (t = t0 + bytes/BW) so serve_sim, bench.py and
    the engine metrics all quote one model:

    - ``attn_local_us``: the per-page partial walk. Each rank streams
      only its own slice of the block-table pages — ``ceil(steps/n_sp)``
      pages per slot per layer at ``page_kv_bytes`` each. This is the
      half that shrinks as the SP mesh grows (∝ kv_len / n).
    - ``attn_fold_wait_us``: the fixed-order fold's wait on remote
      partial slabs — (n−1) slabs of ``slots·steps·slab_row_bytes``
      behind one launch overhead per layer. Grows with n; sublinearity
      of the TOTAL therefore holds exactly when a page's KV bytes
      outweigh its partial-slab row (true for real page sizes — bench.py
      asserts it at {8k, 32k, 64k}-token contexts).

    MODELED, not wall clock: CPU runs serialize ranks and cannot exhibit
    the overlap (docs/serving.md labels every consumer)."""
    fit = _DEFAULT_WIRE_FIT["bf16"]
    bw = fit["gb_per_s"] * 1e3          # bytes per microsecond
    local = n_layers * slots * (-(-steps // n_sp)) * page_kv_bytes / bw
    if n_sp == 1:
        return local, 0.0
    fold = n_layers * (fit["t0_us"]
                       + (n_sp - 1) * slots * steps * slab_row_bytes / bw)
    return local, fold


class ShardedServingEngine(ServingEngine):
    """``ServingEngine`` on a TP/SP/EP mesh serving an MoE model (see the
    module docstring for the layer→kernel map and the bitwise contract).

    ``cfg`` is a ``MoEConfig`` (params from ``init_moe_params``); the
    flagship target is ``MoEConfig.deepseek_infer()``, the reference's
    A2A benchmark shape. ``ctx`` must carry all three ``MESH_AXES``
    (``serving_mesh``); size-1 axes degrade each path to its exact
    single-rank form — the SAME code (hooks set, loops unrolled, fp8 wire
    round-tripped) at every mesh size, which is what makes the n=1 run a
    valid golden for n>1.

    Requirements beyond the base engine:
    - ``prefill_chunk`` is MANDATORY (the bucketed inline prefill has no
      hook plumbing, and the EP FFN is shape-specialized per row count —
      decode serves ``num_slots`` rows, a chunk serves ``prefill_chunk``);
    - ``num_slots % ep == 0`` and ``prefill_chunk % ep == 0`` (the A2A
      context splits token rows evenly over EP ranks);
    - ``d_model % 128 == 0`` (A2A wire lane alignment, asserted there).

    ``wire_dtype="auto"`` picks fp8 for the A2A payload when the platform
    supports it; ``tp_impl="ag_gemm"`` routes the TP projections through
    the Pallas overlap kernel (allclose-only — excluded from the bitwise
    contract; see ``tp_column_linear``). ``digest_every=k`` runs the
    replicated-decision guard every k-th step (0 disables).
    ``long_context=True`` (ISSUE 19) serves 64k–100k-token prompts: the
    SP attention leg becomes ``flash_decode_dist`` over an interleaved
    pool layout (one request's pages round-robined across the SP
    shards), so per-rank attention compute shrinks ∝ 1/|sp| instead of
    replicating — same two compiled programs, same bitwise contract
    (the long-context n=1 run is the golden for every mesh size).

    Disaggregation COMPOSES with this engine (ISSUE 12): the pool carries
    the unified contract — ``sp_ranks``-aware ledger (padding pages are
    allocator-invisible AND ``check_migratable``-refused) over the same
    SP-sharded arrays — so ``DisaggShardedEngine`` (serving/compose.py)
    runs this engine as the decode role of a disaggregated pair, landing
    migrated prefill pages into the sharded pool host-side.
    """

    def __init__(self, params: dict, cfg: MoEConfig, ctx: ShmemContext,
                 num_slots: int = 4, page_size: int = 16,
                 num_pages: int = 64, pages_per_seq: int = 8,
                 max_prefills_per_step: int | None = None,
                 metrics: ServingMetrics | None = None,
                 decode_horizon: int = 1, eos_id: int | None = None,
                 prefill_chunk: int | None = None,
                 stall_deadline_steps: int = 256,
                 wire_dtype: str | None = "auto", tp_impl: str = "xla",
                 tp_cfg: GemmConfig | None = None, moe_block_m: int = 128,
                 overlap: str = "off",
                 overlap_microbatches: int | None = None,
                 digest_every: int = 1,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 fault_plan=None,
                 prefix_cache: bool = False,
                 slo=None,
                 artifact=None, artifact_key: str | None = None,
                 long_context: bool = False,
                 speculate: int | str | None = None,
                 spec_hist: int = 64, spec_bucket: int = 0):
        for ax in MESH_AXES:
            assert ax in ctx.axis_names, (
                f"mesh is missing axis {ax!r} — build it with "
                f"serving_mesh(tp, sp, ep); got {ctx.axis_names}")
        assert prefill_chunk is not None, (
            "sharded serving requires prefill_chunk: the bucketed inline "
            "prefill path has no attn_io/linear/ffn-chunk plumbing")
        self.ctx = ctx
        self.moe_cfg = cfg
        n_tp = ctx.axis_size("tp")
        n_sp = ctx.axis_size("sp")
        n_ep = ctx.axis_size("ep")
        self.mesh_desc = f"{n_tp}x{n_sp}x{n_ep}"
        assert num_slots % n_ep == 0, (
            f"num_slots {num_slots} must split evenly over ep={n_ep}")
        assert prefill_chunk % n_ep == 0, (
            f"prefill_chunk {prefill_chunk} must split evenly over "
            f"ep={n_ep}")

        # speculative decoding (ISSUE 20): resolve the draft length K
        # BEFORE the A2A layers — a verify dispatch runs num_slots * K
        # token rows through the row-count-specialized EP dispatch, so K
        # must be known when the decode layer is sized. Resolution ladder
        # = explicit int → tuned registry (keyed on this mesh + the model
        # dtype + the workload bucket, sigcheck-gated like
        # serving_overlap_mb) → default; the resolved int is handed to
        # the base ctor so it never re-consults the registry.
        self._spec_mesh_shape = (n_tp, n_sp, n_ep)
        spec_k = 0
        if speculate not in (None, 0, "off"):
            spec_k = resolve_spec_k(speculate, self._spec_mesh_shape,
                                    str(jnp.dtype(cfg.base.dtype)),
                                    spec_bucket)
        decode_rows = num_slots * max(1, spec_k)
        assert decode_rows % n_ep == 0

        # TWO A2A layers: the EP dispatch is row-count-specialized, and the
        # engine's two programs run different row counts (decode: the
        # num_slots batch — times K verify rows under speculation; chunk:
        # the prefill_chunk rows)
        mk = lambda rows: EPAll2AllLayer.create(  # noqa: E731
            ctx, max_tokens=rows // n_ep, hidden=cfg.base.d_model,
            topk=cfg.topk, num_experts=cfg.num_experts, axis="ep",
            dtype=cfg.base.dtype, wire_dtype=wire_dtype)
        self.a2a_decode = mk(decode_rows)
        self.a2a_chunk = (self.a2a_decode if prefill_chunk == decode_rows
                          else mk(prefill_chunk))
        self.wire_dtype = str(jnp.dtype(self.a2a_decode.a2a.wire_dtype)) \
            if self.a2a_decode.a2a.wire_dtype is not None else None
        # per-program resolved wire (satellite 6): ``auto`` resolves per
        # dispatch size, so decode and chunk can disagree — serve_sim
        # prints both so "wire=auto" is auditable per mesh (PR 8 caveat).
        self.wire_dtype_chunk = \
            str(jnp.dtype(self.a2a_chunk.a2a.wire_dtype)) \
            if self.a2a_chunk.a2a.wire_dtype is not None else None

        # -- fine-grained compute/comm overlap (ISSUE 16) ------------------
        # ``overlap`` gates the SCHEDULE only, never the math: the EP leg
        # microbatches each dispatch/combine (segmented counted-signal
        # wire, FFN(i) overlapping a2a(i+1)) and the ``ep+sp`` leg starts
        # local attention-pool assembly under the tiled allgather. Every
        # combine stays a concat or fixed-order fold, so the bitwise trace
        # contract above is untouched — asserted by bench.py and
        # tests/test_overlap_serving.py against the overlap=off golden.
        assert overlap in ("off", "ep", "ep+sp"), (
            f"overlap must be 'off', 'ep' or 'ep+sp', got {overlap!r}")
        self.overlap = overlap
        mb = 1
        if overlap != "off":
            mb = overlap_microbatches
            if mb is None:
                # tuned depth: the sigcheck-gated registry key PR 15
                # persists (aot/registry.py GATE_RUNNERS
                # ``serving_overlap_mb``); default 2 = double-buffering
                reg = get_default_registry()
                if reg is not None:
                    mb = reg.get(TunedKey("serving_overlap_mb",
                                          mesh_shape=(n_tp, n_sp, n_ep),
                                          dtype=self.wire_dtype or "none"))
                mb = 2 if mb is None else int(mb)
            mb = int(mb)
            assert mb >= 1, f"overlap_microbatches must be >= 1, got {mb}"
            assert (decode_rows // n_ep) % mb == 0, (
                f"decode rows per rank {decode_rows // n_ep} must split "
                f"evenly into {mb} overlap microbatches")
            assert (prefill_chunk // n_ep) % mb == 0, (
                f"chunk rows per rank {prefill_chunk // n_ep} must split "
                f"evenly into {mb} overlap microbatches")
            if mb > 1:
                # ride the segmented counted-signal wire kernel so each
                # microbatch's put is gated per segment (ops/all_to_all.py
                # ``all_to_all_push_seg``) — same bytes, same slots
                shared = self.a2a_chunk is self.a2a_decode
                seg = lambda l: dataclasses.replace(  # noqa: E731
                    l, a2a=dataclasses.replace(l.a2a, seg_push=2))
                self.a2a_decode = seg(self.a2a_decode)
                self.a2a_chunk = (self.a2a_decode if shared
                                  else seg(self.a2a_chunk))
        self.overlap_microbatches = mb

        def moe_ffn(a2a):
            def ffn(h, p):
                return moe_mlp_ep_overlap(ctx, a2a, h, p["w_router"],
                                          p["we_gate"], p["we_up"],
                                          p["we_down"], block_m=moe_block_m,
                                          microbatches=mb)
            return ffn

        sp_overlap = overlap == "ep+sp"

        # long-context mode (ISSUE 19): swap the SP attention leg from
        # the across-REQUESTS pool-allgather walk (every rank attends
        # over the full pool — per-rank cost ∝ full kv_len) to
        # ``flash_decode_dist`` (each rank walks only its own slice of
        # one request's pages and ships a partial slab — per-rank cost
        # ∝ kv_len/n). The pool layout flips to "interleaved" so one
        # sequence's pages round-robin across the SP shards; the fixed-
        # order page fold makes the attention result placement-
        # invariant, so tokens stay bitwise identical at every mesh size
        # AND across the two layouts' n=1 forms. Same hook surface, same
        # two compiled programs.
        self.long_context = long_context
        if long_context:
            self._pool_layout = "interleaved"

            def attn_io(q, k, v, kp, vp, bt, pos, kv_len, active):
                return flash_decode_dist(ctx, q, k, v, kp, vp, bt, pos,
                                         kv_len, axis="sp", active=active)
        else:
            def attn_io(q, k, v, kp, vp, bt, pos, kv_len, active):
                return sp_paged_attend_write(ctx, q, k, v, kp, vp, bt,
                                             pos, kv_len, axis="sp",
                                             active=active,
                                             overlap=sp_overlap)

        def linear(h, w, name):
            return tp_column_linear(ctx, h, w, axis="tp", impl=tp_impl,
                                    cfg=tp_cfg)

        # modeled per-decode-step wire split (satellite 2): price each EP
        # a2a with the PR 8 wire fit (t = t0 + bytes/BW). With M overlap
        # microbatches the software pipeline hides all but one round per
        # a2a, so exposed = t0 + B/(M*BW) while the total pays the extra
        # (M-1) launch overheads. CPU wall clock serializes ranks and can
        # never show real overlap, so the split is an HONEST MODELED
        # number (docs/serving.md), observed per step into the metrics.
        self._exposed_comm_us, self._overlapped_comm_us = \
            self._comm_split_us(cfg.base.n_layers, mb)
        # modeled long-context attention split (ISSUE 19): zeros unless
        # long_context — the pool-allgather path's wire cost is already
        # priced by the overlap split above
        base = cfg.base
        self._attn_local_us, self._attn_fold_wait_us = (
            fd_attn_split_us(
                n_sp, base.n_layers, num_slots, pages_per_seq,
                2 * base.n_kv_heads * page_size * base.head_dim
                * jnp.dtype(base.dtype).itemsize,
                base.n_heads * (base.head_dim + 128) * 4)
            if long_context else (0.0, 0.0))

        # pool-output sharding pin: must exist BEFORE super().__init__
        # builds the jitted programs (it becomes their out_shardings for
        # the pool pytree — see the comment at the jit construction site
        # in ServingEngine.__init__)
        self._pool_out_sharding = jax.sharding.NamedSharding(
            ctx.mesh, page_pool_pspec("sp"))
        # replicated sharding for the control-plane mirrors (_sync_mirrors
        # commits every upload so pjit's executable cache sees ONE input
        # signature across all dispatches)
        self._rep_sharding = jax.sharding.NamedSharding(ctx.mesh, P())
        # unified pool contract (ISSUE 12): the base engine threads this
        # into KVPagePool(sp_ranks=...) so the ledger knows the device
        # page range (real + SP padding) and refuses padding ids in
        # check_migratable while the allocator never hands them out.
        self._pool_sp_ranks = n_sp

        super().__init__(params, cfg.base, num_slots=num_slots,
                         page_size=page_size, num_pages=num_pages,
                         pages_per_seq=pages_per_seq,
                         ffn=moe_ffn(self.a2a_decode),
                         ffn_chunk=moe_ffn(self.a2a_chunk),
                         attn_io=attn_io, linear=linear,
                         max_prefills_per_step=max_prefills_per_step,
                         metrics=metrics, decode_horizon=decode_horizon,
                         eos_id=eos_id, prefill_chunk=prefill_chunk,
                         stall_deadline_steps=stall_deadline_steps,
                         journal=journal, checkpoint_every=checkpoint_every,
                         queue_cap=queue_cap, ttl_steps=ttl_steps,
                         fault_plan=fault_plan, prefix_cache=prefix_cache,
                         slo=slo, artifact=artifact,
                         artifact_key=artifact_key,
                         speculate=(spec_k or None), spec_hist=spec_hist,
                         spec_bucket=spec_bucket)

        # shard the pool arrays over SP on the page dim, padding the page
        # count up to a multiple of |sp|. The ALLOCATOR never learns about
        # the padding pages — they are never handed out, every block-table
        # fill entry stays the scratch page — so allocation/preemption
        # schedules are identical at every mesh size (part of the bitwise
        # contract). Zero-init padding matches the live pages' init.
        self.pool = shard_pool_arrays(self.pool, n_sp,
                                      self._pool_out_sharding)

        # replicated-decision guard: every rank carries (conceptually) its
        # own copy of the host control plane; the check all-gathers the
        # per-rank digests ON DEVICE (through the same mesh the model
        # runs on) and compares against rank 0. ``_digest_skew`` is the
        # test hook that injects a per-rank divergence to prove the guard
        # trips (there is no organic way to fork a replicated digest in a
        # single-controller process).
        self.digest_every = digest_every
        self.n_ranks = ctx.num_ranks
        self._digest_skew = np.zeros(self.n_ranks, np.uint32)
        # digest-divergence recovery rung (ISSUE 9): per-step count of
        # divergences already recovered (keys FaultPlan.digest_skew's
        # ``attempt`` so a scheduled transient fires exactly once), plus
        # the escalation latch — a second divergence with ZERO clean
        # checks since the last restore means the skew is persistent and
        # the rung must escalate, not loop.
        self._digest_attempts: dict[int, int] = {}
        self._recovered_once = False
        self._checks_since_recovery = 0

        def gather_cmp(v):                       # v [1] int32, my digest
            g = v
            for ax in MESH_AXES:
                g = lax.all_gather(g, ax, axis=0, tiled=True)
            return jnp.any(g != g[0])[None].astype(jnp.int32)

        self._digest_check = jax.jit(ctx.shard_map(
            gather_cmp, in_specs=P(MESH_AXES), out_specs=P(MESH_AXES)))

    def _comm_split_us(self, n_layers: int, mb: int) -> tuple[float, float]:
        """(exposed_us, overlapped_us) per decode step under the wire fit.
        ``mb == 1`` (overlap off) exposes everything; n_ep == 1 has no
        wire at all, so both halves are zero there — which is also why
        overlap can only LOSE at n=1 (it still pays the extra microbatch
        launches while hiding nothing)."""
        a2a = self.a2a_decode.a2a
        if a2a.n_ranks == 1:
            return 0.0, 0.0
        wire = a2a.wire_dtype
        fit = _DEFAULT_WIRE_FIT["fp8" if wire is not None and
                                jnp.dtype(wire).itemsize == 1 else "bf16"]
        bw_us = fit["gb_per_s"] * 1e3          # bytes per microsecond
        b = a2a_wire_bytes(a2a.n_ranks, a2a.max_tokens, a2a.hidden,
                           a2a.topk, wire)
        total = n_layers * (mb * fit["t0_us"] + b / bw_us)
        exposed = n_layers * (fit["t0_us"] + b / (mb * bw_us))
        return exposed, max(0.0, total - exposed)

    def _default_artifact_key(self) -> str:
        return f"sharded:{self.mesh_desc}"

    def _sync_mirrors(self) -> None:
        self._token_dev = jax.device_put(jnp.asarray(self._token),
                                         self._rep_sharding)
        self._pos_dev = jax.device_put(jnp.asarray(self._pos),
                                       self._rep_sharding)
        self._bt_dev = jax.device_put(jnp.asarray(self._bt),
                                      self._rep_sharding)
        if self.spec_k:
            self._hist_dev = jax.device_put(jnp.asarray(self._hist),
                                            self._rep_sharding)
            self._hlen_dev = jax.device_put(jnp.asarray(self._hist_len),
                                            self._rep_sharding)

    # -- replicated-decision guard ----------------------------------------
    # ``control_digest`` lives on the base engine now (ISSUE 9: journal
    # entries on every engine carry it); this class adds the cross-rank
    # comparison and the recovery rung on top.

    def check_replicated_decisions(self) -> None:
        """Cross-rank digest assertion (satellite 1): all-gather each
        rank's control digest over the full mesh and compare to rank 0's.
        Raises ``ReplicatedDecisionError`` on divergence.

        Divergence sources: the ``_digest_skew`` per-rank array (the
        direct test hook) and — ISSUE 9 — an active ``FaultPlan``'s
        ``digest_skew`` schedule, which corrupts one keyed rank's word at
        scheduled/probabilistic steps so seeds can drive the restore rung.
        """
        h = self.control_digest()
        vals = np.full(self.n_ranks, h, np.uint32) + self._digest_skew
        plan = self._fault_plan if self._fault_plan is not None \
            else faults_mod.active_plan()
        if plan is not None and self.n_ranks > 1:
            w = plan.digest_skew(self._steps,
                                 self._digest_attempts.get(self._steps, 0))
            if w:
                vals[plan.skew_rank(self._steps, self.n_ranks)] += \
                    np.uint32(w)
                self.metrics.inc("faults_injected")
        vals = vals.view(np.int32)
        mismatch = np.asarray(self._digest_check(jnp.asarray(vals)))
        self.metrics.inc("digest_checks")
        if mismatch.any():
            bad = np.nonzero(vals != vals[0])[0].tolist()
            raise ReplicatedDecisionError(
                f"control-plane digest diverged across ranks at step "
                f"{self._steps}: ranks {bad or '<device-side only>'} "
                f"disagree with rank 0 (digest 0x{h:08x}, mesh "
                f"{self.mesh_desc}). A replicated-decision input leaked "
                "rank-dependent state — block tables are no longer "
                "trustworthy." + self._postmortem())

    def _post_step(self) -> None:
        """Digest cross-check first (same cadence the pre-ISSUE-9 ``step``
        override ran it on), then the base checkpoint cadence — so a
        checkpoint is only ever captured at a step whose digest all ranks
        just agreed on."""
        self.metrics.observe("exposed_comm_us", self._exposed_comm_us)
        self.metrics.observe("overlapped_comm_us",
                             self._overlapped_comm_us)
        self.metrics.observe("attn_local_us", self._attn_local_us)
        self.metrics.observe("attn_fold_wait_us", self._attn_fold_wait_us)
        if self.digest_every and self._steps % self.digest_every == 0:
            try:
                self.check_replicated_decisions()
            except ReplicatedDecisionError as err:
                self._recover_divergence(err)
                return          # quarantined step: no checkpoint here
            self._checks_since_recovery += 1
        super()._post_step()

    def _recover_divergence(self, err: ReplicatedDecisionError) -> None:
        """The top recovery rung (ISSUE 9 tentpole): quarantine the
        diverged step in the journal, restore every rank's control plane
        from the last agreed checkpoint + journal replay, and keep
        serving. Escalates (re-raises) when there is no journal to
        restore from, or on REPEAT divergence — a second trip with zero
        clean checks since the last restore means the skew is persistent,
        and looping restores would never converge."""
        if self.journal is None:
            raise err
        if self._recovered_once and self._checks_since_recovery == 0:
            raise ReplicatedDecisionError(
                "repeat digest divergence with no agreed step since the "
                "last restore — persistent skew, escalating instead of "
                "looping the restore rung.\nfirst divergence:\n"
                + str(err)) from err
        step = self._steps
        self._digest_attempts[step] = self._digest_attempts.get(step, 0) + 1
        self._jlog("digest_divergence",
                   error=str(err).splitlines()[0])
        self._recovered_once = True
        self._checks_since_recovery = 0
        self.metrics.inc("digest_recoveries")
        t0 = time.perf_counter()
        ckpt_mod.restore(self, ckpt_mod.latest(self.journal), self.journal)
        self.metrics.observe("digest_recovery_s", time.perf_counter() - t0)


__all__ = ["ShardedServingEngine", "ReplicatedDecisionError",
           "serving_mesh", "fd_attn_split_us", "MESH_AXES"]
