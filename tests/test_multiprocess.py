"""Two-process CPU cluster integration test.

Every other test runs the single-process simulator; the reference exercises
its multi-process model in every test via torchrun (SURVEY §4). This spawns
2 coordinator-connected ``jax.distributed`` CPU processes running
tests/mp_worker.py — the only place ``process_count() == 2`` paths execute:
the env-gated bootstrap, a cross-process XLA collective, and the autotuner's
MAX consensus. One variant launches through scripts/launch.sh to cover its
env mapping (generic COORDINATOR_ADDRESS → JAX_COORDINATOR_ADDRESS).
"""

import os
import socket
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "mp_worker.py")
LAUNCH = os.path.join(REPO, "scripts", "launch.sh")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(pid: int, nproc: int, addr: str, generic_env: bool) -> dict:
    env = dict(os.environ)
    # a clean jax env: no axon plugin (a wedged device tunnel must not be
    # able to hang this test), no inherited XLA_FLAGS device-count forcing
    for k in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "JAX_PLATFORMS",
              "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS"):
        env.pop(k, None)
    env["PYTHONPATH"] = REPO
    env["JAX_NUM_PROCESSES"] = str(nproc)
    env["JAX_PROCESS_ID"] = str(pid)
    # the generic spelling exercises launch.sh's mapping
    env["COORDINATOR_ADDRESS" if generic_env
        else "JAX_COORDINATOR_ADDRESS"] = addr
    return env


def _run_cluster(via_launch_sh):
    """Launch the 2-process cluster once; returns (procs, outs) or raises
    TimeoutExpired after killing the children."""
    addr = f"127.0.0.1:{_free_port()}"
    cmd = ([LAUNCH, sys.executable, WORKER] if via_launch_sh
           else [sys.executable, WORKER])
    procs = [
        subprocess.Popen(cmd, env=_worker_env(pid, 2, addr, via_launch_sh),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            # generous: the worker ends with a 45 s overlap-kernel
            # watchdog, and a fully loaded CI box stretches everything
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return procs, outs


@pytest.mark.parametrize("via_launch_sh", [False, True])
def test_two_process_cluster(via_launch_sh):
    try:
        procs, outs = _run_cluster(via_launch_sh)
    except subprocess.TimeoutExpired:
        pytest.fail("multi-process workers timed out")
    if any(p.returncode != 0 for p in procs):
        # one retry with a FRESH port: the free-port probe releases the
        # socket before the children rebind it, and on a busy box another
        # process can grab it in between — a launch race, not a product
        # failure. A second consecutive failure is real and surfaces.
        try:
            procs, outs = _run_cluster(via_launch_sh)
        except subprocess.TimeoutExpired:
            pytest.fail(f"multi-process workers timed out on retry; "
                        f"first attempt: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MP_OK process={pid}/2" in out, out
        # the overlap-kernel attempt (VERDICT r4 #8) must report ONE of
        # its two pinned outcomes — a silent skip is a test bug. Either
        # the interpret-mode Pallas AG composes with the multi-process
        # mesh (MP_AG_OK: output matched the golden) or the runtime
        # rejects it loudly (MP_AG_UNSUPPORTED + the error signature;
        # the in-process interpreter cannot back cross-process
        # DMA/semaphore state — measured outcome: DEADLOCK, caught by
        # the worker's watchdog). MP_AG_WRONG_RESULT (ran, corrupt
        # data) matches neither token and fails here — as it must.
        assert ("MP_AG_OK" in out) or ("MP_AG_UNSUPPORTED" in out), out
    # regex-extract: concurrent C++ (Gloo) log lines can interleave into the
    # same stdout line as the python print
    import re
    picks = {m for out in outs
             for m in re.findall(r"picked=([0-9.]+)", out)}
    assert len(picks) == 1, f"processes picked different configs: {picks}"


def test_two_process_merged_profile(tmp_path):
    """Multi-host ``group_profile``: both processes trace, process 0 merges
    one Perfetto-loadable timeline with per-host tracks (reference
    utils.py:282-501 parity)."""
    import gzip
    import json

    addr = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = _worker_env(pid, 2, addr, generic_env=False)
        env["TDT_PROF_DIR"] = str(tmp_path)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            # generous: the worker ends with a 45 s overlap-kernel
            # watchdog, and a fully loaded CI box stretches everything
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"profiled workers timed out; partial: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
    assert any("MP_PROF_MERGED" in o for o in outs), outs

    merged = tmp_path / "mp" / "merged.trace.json.gz"
    assert merged.exists()
    with gzip.open(merged, "rt") as f:
        data = json.load(f)
    names = {ev["args"]["name"] for ev in data["traceEvents"]
             if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    hosts = {n.split("/")[0] for n in names}
    assert {"host0", "host1"} <= hosts, f"per-host tracks missing: {names}"
    # both processes contributed real events, not just metadata
    pids = {ev.get("pid", 0) for ev in data["traceEvents"]}
    assert any(p >= 200000 for p in pids) and any(
        100000 <= p < 200000 for p in pids), sorted(pids)[:10]
