"""The decode engine: drives ``models.llama.decode_step_paged`` under
``jax.jit`` so the hot loop is ONE compiled step per token regardless of
arrivals, finishes or preemptions.

Shape discipline (the TPU contract):

- the batch is ``num_slots`` fixed rows; a request occupies one slot from
  admission to finish. Inactive rows are parked on the reserved scratch
  page (page 0) with pos 0 — their writes land on scratch, their logits
  are ignored, and the compiled step never sees a shape change.
- the page pool rides the jitted step as a DONATED argument (on backends
  that support donation), so the per-layer scatter of the new (k, v)
  updates pages in place — no pool-sized copy per token.
- prefill runs per request OUTSIDE the batch (shape-keyed by prompt
  length) into a small contiguous cache — the layout the full-sequence
  kernels want — then ``cache_to_pages`` hands the pages to the pool.
  This is the prefill/decode interleave: admissions prefill between
  decode steps, the decode batch itself never stalls on a long prompt.

Determinism: greedy argmax decode + deterministic allocation and policies
mean a request's tokens are a pure function of (params, prompt) — a
preempted-and-restarted request regenerates exactly the tokens it lost,
and a contended run is bit-identical per request to an uncontended one
(tests/test_serving.py asserts both).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from triton_dist_tpu.models.llama import (LlamaConfig, decode_step_paged,
                                          init_kv_cache, init_page_pool,
                                          prefill)
from triton_dist_tpu.serving.kv_pool import KVPagePool, cache_to_pages
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.scheduler import (ContinuousBatchingScheduler,
                                               Request)


class ServingEngine:
    """Continuous-batching serving engine over the paged decode step.

    ``num_pages`` counts usable pages; one extra scratch page (id 0) is
    allocated on top for inactive rows. ``pages_per_seq`` bounds one
    sequence's pages (the block table width — a compiled-shape constant).
    ``ffn(h, p) -> [B, D]`` plugs a custom per-layer FFN into the decode
    step (e.g. ``moe_mlp_ep_overlap`` for the EP-MoE serving path, the
    same hook ``decode_step``/``decode_step_sp`` expose).
    """

    def __init__(self, params: dict, cfg: LlamaConfig, num_slots: int = 4,
                 page_size: int = 16, num_pages: int = 64,
                 pages_per_seq: int = 8, ffn=None,
                 max_prefills_per_step: int | None = None,
                 metrics: ServingMetrics | None = None):
        self.params = params
        self.cfg = cfg
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.max_prefills_per_step = max_prefills_per_step
        self.metrics = metrics or ServingMetrics()

        self.pool = init_page_pool(cfg, num_pages + 1, page_size)
        self.alloc = KVPagePool(num_pages + 1, page_size, reserved=1)
        self.sched = ContinuousBatchingScheduler(num_slots)
        self._next_rid = 0
        self._steps = 0
        self._finished: list[Request] = []

        # host-side mirrors of the per-slot device inputs
        self._token = np.zeros(num_slots, np.int32)
        self._pos = np.zeros(num_slots, np.int32)
        self._bt = np.zeros((num_slots, pages_per_seq), np.int32)

        step = lambda p, t, pos, pages, bt: decode_step_paged(  # noqa: E731
            p, t, pos, cfg, pages, bt, ffn=ffn)
        if jax.default_backend() == "cpu":
            self._step = jax.jit(step)      # CPU: donation unsupported
        else:
            self._step = jax.jit(step, donate_argnums=(3,))
        self._prefill_jit = {}              # keyed by (prompt_len, cache_len)

    # -- request intake ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, rid: int | None = None
               ) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1   # KV the request will hold
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc.num_pages - self.alloc.reserved, (
            f"request needs {need} pages > pool size — it could never run "
            "even alone")
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched.submit(req)
        self.metrics.inc("requests_submitted")
        return rid

    # -- prefill + admission ----------------------------------------------
    def _prefill_fn(self, prompt_len: int, cache_len: int):
        key = (prompt_len, cache_len)
        if key not in self._prefill_jit:
            cfg = self.cfg
            self._prefill_jit[key] = jax.jit(
                lambda p, t, c: prefill(p, t, cfg, c))
        return self._prefill_jit[key]

    def _admit(self, slot: int, req: Request) -> None:
        sp = len(req.prompt)
        n_pages = -(-sp // self.page_size)
        pages = self.alloc.alloc(req.rid, n_pages)
        assert pages is not None, "admissible() guaranteed the pages"
        cache_len = n_pages * self.page_size
        cache = init_kv_cache(self.cfg, 1, cache_len)
        tokens = jnp.asarray(np.asarray(req.prompt, np.int32)[None])
        logits, cache = self._prefill_fn(sp, cache_len)(
            self.params, tokens, cache)
        bt_row = jnp.asarray(np.asarray(pages, np.int32)[None])
        self.pool = {
            "k": cache_to_pages(cache["k"], self.pool["k"], bt_row),
            "v": cache_to_pages(cache["v"], self.pool["v"], bt_row),
        }
        tok0 = int(np.argmax(np.asarray(logits[0])))
        self.sched.activate(slot, req)
        req.generated.append(tok0)
        self.metrics.inc("prefills")
        self.metrics.inc("tokens_generated")
        if req.first_token_time is None:
            req.first_token_step = self._steps
            req.first_token_time = time.perf_counter()
            self.metrics.observe("ttft_s",
                                 req.first_token_time - req.submit_time)
        self._token[slot] = tok0
        self._pos[slot] = sp
        row = self.alloc.block_table_row(req.rid, self.pages_per_seq)
        self._bt[slot] = np.asarray(row, np.int32)
        if req.done:                      # max_new_tokens == 1: no decode
            self._finish(slot)

    # -- slot teardown ----------------------------------------------------
    def _finish(self, slot: int) -> None:
        req = self.sched.finish(slot)
        self.alloc.free_seq(req.rid)
        req.finish_step = self._steps
        self._park(slot)
        self._finished.append(req)
        self.metrics.inc("requests_finished")

    def _preempt(self, slot: int) -> None:
        req = self.sched.slots[slot]
        self.alloc.free_seq(req.rid)
        self.sched.evict(slot)
        self._park(slot)
        self.metrics.inc("preemptions")

    def _park(self, slot: int) -> None:
        """Point an empty slot at the scratch page: its row writes land on
        page 0 (reserved — never a live sequence's), its reads mask out."""
        self._token[slot] = 0
        self._pos[slot] = 0
        self._bt[slot] = 0

    # -- one engine iteration ---------------------------------------------
    def step(self) -> bool:
        """Admissions (prefill) + one batched decode step. Returns False
        when there is nothing to do (engine idle)."""
        if self.sched.idle:
            return False

        def can_hold(req: Request) -> bool:
            return self.alloc.free_pages >= -(-len(req.prompt)
                                              // self.page_size)

        admitted = 0
        while (self.max_prefills_per_step is None
               or admitted < self.max_prefills_per_step):
            adm = self.sched.admissible(can_hold)
            if adm is None:
                break
            self._admit(*adm)
            admitted += 1

        # allocate-on-decode growth, preempting (youngest first) when dry.
        # Slot order is index order — deterministic.
        for slot in range(self.num_slots):
            req = self.sched.slots[slot]
            if req is None:
                continue
            while not self.alloc.ensure(req.rid, int(self._pos[slot]) + 1):
                victim = self.sched.pick_victim(exclude_slot=slot)
                if victim is None:
                    raise RuntimeError(
                        f"KV pool too small: request {req.rid} needs a page "
                        "with no preemptible peer left")
                self._preempt(victim)
            # refresh AFTER growth — the kernel writes this step's (k, v)
            # at bt[slot, pos // page_size], which may be the page ensure()
            # just allocated
            self._bt[slot] = np.asarray(
                self.alloc.block_table_row(req.rid, self.pages_per_seq),
                np.int32)

        active = self.sched.active
        if not active:
            return not self.sched.idle

        t0 = time.perf_counter()
        logits, self.pool = self._step(
            self.params, jnp.asarray(self._token), jnp.asarray(self._pos),
            self.pool, jnp.asarray(self._bt))
        nxt = np.argmax(np.asarray(logits), axis=-1).astype(np.int32)
        dt = time.perf_counter() - t0

        self._steps += 1
        self.metrics.inc("decode_steps")
        self.metrics.observe("queue_depth", self.sched.queue_depth)
        self.metrics.observe("pool_occupancy", self.alloc.occupancy())
        self.metrics.observe("active_slots", len(active))
        for slot, req in active:
            req.generated.append(int(nxt[slot]))
            self._token[slot] = nxt[slot]
            self._pos[slot] += 1
            self.metrics.inc("tokens_generated")
            self.metrics.observe("tok_latency_s", dt)
            if req.done:
                self._finish(slot)
        return True

    def run(self, max_steps: int | None = None,
            arrivals=None) -> dict[int, list[int]]:
        """Drive ``step()`` until idle (or ``max_steps``). ``arrivals`` is
        an optional iterable of (step_index, prompt, max_new_tokens)
        sorted by step — the synthetic-trace replay hook serve_sim uses.
        Returns {rid: generated tokens} for every finished request."""
        pending = list(arrivals or [])
        results: dict[int, list[int]] = {}
        i = 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                _, prompt, mnt = pending.pop(0)
                results_key = self.submit(prompt, mnt)
                results[results_key] = None
            if not self.step() and not pending:
                break
            i += 1
        for req in self._all_requests():
            if req.state.value == "finished":
                results[req.rid] = list(req.generated)
        return results

    def _all_requests(self):
        seen = {}
        for r in (list(self.sched.queue)
                  + [s for s in self.sched.slots if s is not None]
                  + self._finished):
            seen[r.rid] = r
        return seen.values()


__all__ = ["ServingEngine"]
