"""Shared kernel utilities (analog of reference
python/triton_dist/kernels/nvidia/common_ops.py).

On GPU the reference needs hand-written device barriers
(common_ops.py:62-159: grid barriers, atomic-CAS intra-node barriers) and
CPU-driven stream signal ops (:178-211). On TPU, barriers are the barrier
semaphore, and there is no separate "stream signal plane" — the DMA
semaphore of each remote copy is the signal.
"""

from __future__ import annotations

import hashlib

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret

# Every kernel family that uses pltpu.get_barrier_semaphore() needs a
# distinct collective id (matching across devices running the same kernel).
# Ids must ONLY be passed for kernels that actually use barrier semaphores —
# compiled TPU rejects them otherwise.
#
# Ids are a STABLE function of the family name, not a first-use counter: in a
# multi-host job, two processes can trace ops in different orders (divergent
# autotuner pruning, conditional model paths), and order-derived ids would
# silently alias different kernel families onto the same barrier across hosts
# (the reference avoids this with fixed per-kernel signal-buffer layouts in
# its ctx dataclasses). Interpret mode narrows ids to int16, so we hash into
# [0, 2**15); a (deterministic, therefore immediately-reproducible) collision
# between two family names raises loudly and can be resolved by pinning.
_COLLECTIVE_ID_PINS: dict[str, int] = {}
_ASSIGNED: dict[int, str] = {}


def collective_id_for(name: str) -> int:
    if name in _COLLECTIVE_ID_PINS:
        cid = _COLLECTIVE_ID_PINS[name]
    else:
        digest = hashlib.blake2b(name.encode(), digest_size=8).digest()
        cid = int.from_bytes(digest, "little") % (1 << 15)
    holder = _ASSIGNED.setdefault(cid, name)
    if holder != name:
        raise ValueError(
            f"collective id collision: {name!r} and {holder!r} both hash to "
            f"{cid}. Pin one explicitly via "
            f"triton_dist_tpu.ops.common._COLLECTIVE_ID_PINS[{name!r}] = <id> "
            f"before first use (any unused id in [0, 32768)).")
    return cid


# Eager-context step caches (AgGemmContext / GemmRsContext) keep the most
# recent distinct (shape, dtype, cfg) entries; a long-lived serving process
# cycling through more shapes (ragged batches) evicts LRU instead of
# growing without bound (r3 Weak #8).
_CONTEXT_CACHE_SIZE = 32


def require_eager(what: str, alternative: str) -> None:
    """Raise a descriptive error when called under a trace — the eager
    contexts mutate Python state (their workspace handle), which would leak
    as a stale tracer under jit/vmap/scan."""
    from jax._src import core as jcore
    if not jcore.trace_state_clean():
        raise RuntimeError(
            f"{what} is eager-only sugar (its workspace update is Python "
            f"state, which would leak under a trace); inside jit/vmap/scan "
            f"use {alternative} and thread the workspace explicitly")


def lru_step(steps: dict, key, make):
    """Shared LRU policy for the eager contexts' per-shape step caches:
    hit re-inserts as most-recently-used; miss compiles via ``make`` and
    evicts oldest entries down to the bound."""
    step = steps.pop(key, None)
    if step is None:
        step = make()
        while len(steps) >= _CONTEXT_CACHE_SIZE:
            steps.pop(next(iter(steps)))
    steps[key] = step
    return step


def norm_axis(ctx: ShmemContext, axis):
    """Normalize an op's ``axis`` argument: None → first mesh axis; a
    1-tuple → its name; a multi-name tuple → tuple (the hierarchical 2-tier
    path, outer/slow tier first)."""
    if axis is None:
        return ctx.axis_names[0]
    if not isinstance(axis, str):
        axis = tuple(axis)
        return axis[0] if len(axis) == 1 else axis
    return axis


def barrier_all_op(ctx: ShmemContext, axis: str | None = None):
    """Host-level device barrier across the mesh — analog of
    ``barrier_all_on_stream`` (reference common_ops.py:162-175). Returns a
    jitted callable performing a full-mesh in-kernel barrier."""
    axes = ctx.axis_names if axis is None else (axis,)

    def kernel(out_ref):
        shd.barrier_all(axes, mesh_axes=ctx.axis_names)
        out_ref[0] = 1

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1,), "int32"),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("barrier_all")),
            interpret=default_interpret(),
        )()

    return jax.jit(ctx.shard_map(f, in_specs=(), out_specs=P(ctx.axis_names[0])))
