"""Tutorial 12 — end-to-end expert-parallel MoE inference block.

Analog of reference test/nvidia/test_ep_moe_inference.py (the end-to-end
EP block its README showcases): router → low-latency A2A dispatch →
grouped expert FFN on each rank's local experts → A2A combine with top-k
weights — `models.moe.moe_mlp_ep_overlap` over `EPAll2AllLayer`.

Cases: bf16 wire and the fp8 quantized wire with the f32 scale
side-channel (low_latency_all_to_all.py:60-88, README.md:55). The
hierarchical 2-tier dispatch path is exercised at the layer level
(tests/test_layers.py, tests/test_hierarchical.py).

Run:  python -m tutorials.t12_moe_inference [--sim 4]
      [--case correctness|correctness_fp8|decode|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _weights(E, D, F):
    import jax
    import jax.numpy as jnp
    router_w = jax.random.normal(jax.random.key(1), (D, E),
                                 jnp.float32) * 0.3
    mk = lambda k, s: (jax.random.normal(jax.random.key(k), s)
                       * 0.1).astype(jnp.bfloat16)
    return router_w, mk(2, (E, D, F)), mk(3, (E, D, F)), mk(4, (E, F, D))


def _golden(x, router_w, wg, wu, wd, k):
    import jax
    import jax.numpy as jnp
    x32, wg32, wu32, wd32 = (a.astype(jnp.float32) for a in (x, wg, wu, wd))
    logits = x32 @ router_w
    gv, gi = jax.lax.top_k(jax.nn.softmax(logits, -1), k)
    gv = gv / jnp.sum(gv, -1, keepdims=True)
    h = jax.nn.silu(jnp.einsum("td,edf->tef", x32, wg32)) \
        * jnp.einsum("td,edf->tef", x32, wu32)
    ye = jnp.einsum("tef,efd->ted",
                    h.astype(jnp.bfloat16).astype(jnp.float32), wd32)
    sel = jnp.take_along_axis(ye, gi[..., None], axis=1)
    return jnp.sum(sel * gv[..., None], axis=1)


def _run(ctx, axis, wire_dtype=None, T_local=16, D=256, F=256, k=2,
         tol=8e-2):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.layers import EPAll2AllLayer
    from triton_dist_tpu.models.moe import moe_mlp_ep_overlap
    n = ctx.num_ranks
    E = 2 * n
    T = n * T_local
    x = (jax.random.normal(jax.random.key(0), (T, D), jnp.float32)
         * 0.3).astype(jnp.bfloat16)
    router_w, wg, wu, wd = _weights(E, D, F)
    layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D, topk=k,
                                  num_experts=E, axis=axis,
                                  wire_dtype=wire_dtype)
    spec = P(axis) if isinstance(axis, str) or axis is None else P(axis)
    xs = ctx.shard(x, spec)
    got = jax.jit(lambda v: moe_mlp_ep_overlap(
        ctx, layer, v, router_w, wg, wu, wd,
        axis=axis if isinstance(axis, str) else None))(xs)
    gold = _golden(x, router_w, wg, wu, wd, k)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(gold), atol=tol, rtol=tol)
    return layer, xs, (router_w, wg, wu, wd)


@register_case("correctness")
def correctness():
    ctx = world_context()
    _run(ctx, "x")
    print(f"EP MoE block over {ctx.num_ranks} PEs == dense golden")


@register_case("correctness_fp8")
def correctness_fp8():
    import jax.numpy as jnp
    ctx = world_context()
    # fp8 wire: coarser tolerance (the e4m3 payload carries ~2 decimal
    # digits; the f32 per-row scale restores magnitude)
    _run(ctx, "x", wire_dtype=jnp.float8_e4m3fn, tol=2e-1)
    print(f"EP MoE block (fp8 wire + scale channel) over "
          f"{ctx.num_ranks} PEs == dense golden")


@register_case("decode")
def decode():
    """Full serving decode step: SP flash-decode attention over the
    sequence-sharded KV cache + the EP MoE FFN through the A2A — three
    greedy steps with the cache round-tripping
    (``models.moe.moe_decode_step_sp``)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.layers import EPAll2AllLayer
    from triton_dist_tpu.models.llama import LlamaConfig, init_kv_cache
    from triton_dist_tpu.models.moe import (MoEConfig, init_moe_params,
                                            moe_decode_step_sp)
    ctx = world_context()
    n = ctx.num_ranks
    base = LlamaConfig(vocab_size=256, d_model=256, n_layers=2, n_heads=2,
                       n_kv_heads=2, d_ff=256, max_seq_len=n * 32)
    cfg = MoEConfig(base=base, num_experts=2 * n, topk=2, moe_d_ff=128)
    params = init_moe_params(jax.random.key(0), cfg)
    B = n * max(1, 4 // n)   # B = n_ranks * max_tokens at any world size
    layer = EPAll2AllLayer.create(ctx, max_tokens=B // n,
                                  hidden=base.d_model, topk=cfg.topk,
                                  num_experts=cfg.num_experts, axis="x",
                                  dtype=base.dtype)
    cache = init_kv_cache(base, B, base.max_seq_len)
    spec = P(None, None, None, "x", None)
    cache = {k: ctx.shard(v, spec) for k, v in cache.items()}
    step = jax.jit(lambda p, t, pos, c: moe_decode_step_sp(
        ctx, layer, p, t, pos, cfg, c))
    tok = jnp.arange(B, dtype=jnp.int32)
    for pos in range(3):
        logits, cache = step(params, tok, pos, cache)
        assert bool(jnp.all(jnp.isfinite(logits)))
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
    print(f"SP+EP serving decode step over {n} PEs: 3 greedy steps, "
          f"tokens {np.asarray(tok).tolist()}")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.models.moe import moe_mlp_ep_overlap
    ctx = world_context()
    layer, xs, (router_w, wg, wu, wd) = _run(ctx, "x", T_local=64)
    f = jax.jit(lambda v: moe_mlp_ep_overlap(ctx, layer, v, router_w,
                                             wg, wu, wd, axis="x"))
    s = time_op(lambda: f(xs))
    perf_report("moe_ep_block", s, f"({xs.shape[0]} tokens global)")


if __name__ == "__main__":
    tutorial_main(__doc__)
