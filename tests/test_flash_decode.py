"""Flash-decode tests vs dense attention goldens (parity targets: reference
test/nvidia/test_decode_attn.py and test_sp_decode_attn.py — the latter
checks the full SP pipeline against a paged-attention reference)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.flash_decode import (decode_combine,
                                              gqa_decode_partial,
                                              sp_gqa_flash_decode)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def _dense_golden(q, k, v, kv_len):
    """Dense GQA attention golden in numpy."""
    q, k, v = (np.asarray(t, np.float64) for t in (q, k, v))
    B, Hq, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    out = np.zeros((B, Hq, D))
    for b in range(B):
        L = int(kv_len[b])
        for h in range(Hq):
            kh = h // G
            s = (k[b, kh, :L] @ q[b, h]) / math.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ v[b, kh, :L]
    return out


def test_gqa_decode_partial_full_cache():
    B, S, Hq, Hkv, D = 2, 256, 8, 2, 128
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_len = jnp.array([256, 100], jnp.int32)  # one full, one ragged
    out, lse = jax.jit(lambda *a: gqa_decode_partial(*a))(q, k, v, kv_len)
    golden = _dense_golden(q, k, v, np.asarray(kv_len))
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)
    # lse sanity: finite where kv_len > 0, lane-broadcast
    lse = np.asarray(lse)
    assert np.all(lse[..., 0] == lse[..., 1])
    assert np.all(lse[0, :, 0] > -1e29)


def test_decode_combine_matches_monolithic():
    """Splitting a cache into R chunks, decoding each, then combining must
    equal decoding the whole cache."""
    B, S, Hq, Hkv, D, R = 1, 512, 4, 1, 128, 4
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_len = jnp.array([S], jnp.int32)
    chunk = S // R
    outs, lses = [], []
    for r in range(R):
        o, l = jax.jit(lambda *a: gqa_decode_partial(*a))(
            q, k[:, :, r * chunk:(r + 1) * chunk], v[:, :, r * chunk:(r + 1) * chunk],
            jnp.array([chunk], jnp.int32))
        outs.append(o)
        lses.append(l)
    merged = jax.jit(decode_combine)(jnp.stack(outs), jnp.stack(lses))
    golden = _dense_golden(q, k, v, np.asarray(kv_len))
    assert_allclose(np.asarray(merged), golden, atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("ag_method", ["push", "fused"])
def test_sp_flash_decode(ctx, ag_method):
    """Full SP pipeline on the mesh vs dense golden, ragged lengths —
    over the generic push AG and the fused AG+merge latency path."""
    n = ctx.num_ranks
    B, Hq, Hkv, D = 2, 4, 2, 128
    s_local = 128
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_lens = jnp.array([S, S // 2 + 17], jnp.int32)
    ks = ctx.shard(k, P(None, None, "x"))
    vs = ctx.shard(v, P(None, None, "x"))
    f = jax.jit(lambda *a: sp_gqa_flash_decode(ctx, *a, ag_method=ag_method))
    out = f(q, ks, vs, kv_lens)
    golden = _dense_golden(q, k, v, np.asarray(kv_lens))
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)
    # repeated-call safety (ws buffer addresses are reused across calls)
    out2 = f(q, ks, vs, kv_lens)
    assert_allclose(np.asarray(out2), golden, atol=1e-3, rtol=1e-3)
