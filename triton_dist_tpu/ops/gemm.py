"""Tiled MXU matmul building blocks, shared by the overlap ops.

The reference's consumer GEMMs are persistent-TMA Triton kernels
(allgather_gemm.py:131-252, gemm_reduce_scatter.py:104-234). On TPU the
equivalent machinery is ``pltpu.emit_pipeline``: an in-kernel double-buffered
HBM→VMEM pipeline feeding ``jnp.dot`` on the MXU. Keeping it as a helper lets
every overlap kernel (AG-GEMM, GEMM-RS, grouped GEMM) call it per *segment*,
right after that segment's arrival semaphore is waited — the TPU analog of
per-tile ``dl.wait`` + ``tl.dot``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_dist_tpu.utils import default_interpret


@dataclasses.dataclass(frozen=True)
class GemmConfig:
    """Tile config (the analog of the reference's BLOCK_SIZE_M/N/K context
    knobs, e.g. allgather_gemm.py:744-782). ``block_k=None`` keeps K
    un-split — full-K VMEM strips keep the MXU busy without an accumulator
    round-trip; for large-K models (e.g. 405B-class d_model=16k) set
    ``block_k`` so the strips fit the scoped-VMEM budget, at the cost of
    cross-strip accumulation in the output dtype. ``vmem_ok`` guards the
    budget."""

    block_m: int = 128
    block_n: int = 128
    block_k: int | None = None

    def vmem_bytes(self, K: int, bytes_per_el: int) -> int:
        # A strip + B strip + out tile, double-buffered by emit_pipeline
        k = min(self.block_k or K, K)
        return 2 * bytes_per_el * (self.block_m * k + k * self.block_n
                                   + self.block_m * self.block_n)

    # Budget calibrated to Mosaic's 16 MB scoped-VMEM stack limit (not the
    # 128 MB physical VMEM), with headroom for the enclosing kernel's
    # staging buffers. Measured on-chip: tiles above this bound either fail
    # the scoped limit or (with vmem_limit_bytes raised) run SLOWER than
    # (256, 256) at the 4096^3 bench shape — bigger is not better here.
    def vmem_ok(self, K: int, bytes_per_el: int, budget: int = 12 * 2**20) -> bool:
        return self.vmem_bytes(K, bytes_per_el) <= budget


# Measured-best tile table (docs/benchmarks.md sweeps, v5e): tried in
# order, first config whose tiles divide the problem and fit the VMEM
# budget wins. This is the analog of the reference's topology/shape-keyed
# config pick (its GEMM configs are keyed per shape in the perf tests,
# test_ag_gemm_intra_node.py:153-160) — here the key is divisibility, so
# one ordered list covers all six model shapes plus the 4096^3 headline.
_MEASURED_BEST = (
    GemmConfig(512, 512, 2048),   # 179 TFLOP/s @ 4096^3; best square tile
    GemmConfig(512, 256, 2048),   # LLaMA-7B-class N (256-divisible only)
    GemmConfig(1024, 384, 1024),  # Qwen2-72B-class N (384- not 256-div.)
    GemmConfig(1024, 256, 1024),  # tall-M fallback at large N
    GemmConfig(256, 256, 4096),
    GemmConfig(256, 256),
    GemmConfig(256, 128),
    GemmConfig(128, 128),
)


def best_gemm_config(m_rows: int, n_cols: int, K: int, itemsize: int,
                     budget: int = 12 * 2**20) -> GemmConfig:
    """Default tile pick for ``[m_rows, K] @ [K, n_cols]`` inside an overlap
    kernel — ``m_rows``/``n_cols`` are the *per-segment* dims the GEMM
    actually tiles over (local M for AG-GEMM, full N for GEMM-RS). Returns
    the first measured-best config (``_MEASURED_BEST``) that divides the
    shape and fits the scoped-VMEM budget; falls back to the largest
    aligned tile for small/odd shapes so ``cfg=None`` never asserts."""
    for cfg in _MEASURED_BEST:
        if (m_rows % cfg.block_m == 0 and n_cols % cfg.block_n == 0
                and (cfg.block_k is None or K % cfg.block_k == 0)
                and cfg.vmem_ok(K, itemsize, budget)):
            return cfg
    # Odd/tiny shapes (tests, sub-128 toys): largest power-of-two tile that
    # divides each dim, VMEM-guarded by K-splitting if possible.
    def _tile(dim: int, cap: int) -> int:
        t = 1
        while t * 2 <= min(dim, cap) and dim % (t * 2) == 0:
            t *= 2
        return t
    bm, bn = _tile(m_rows, 512), _tile(n_cols, 512)
    while True:
        for bk in (None, 4096, 2048, 1024, 512, 256, 128):
            cfg = GemmConfig(bm, bn, bk)
            if ((bk is None or K % bk == 0)
                    and cfg.vmem_ok(K, itemsize, budget)):
                return cfg
        # No candidate block_k divides K (or fits): shrink the output tile
        # and retry — halving a power-of-two divisor keeps divisibility,
        # and the full-K strip eventually fits the budget.
        if bm >= bn and bm > 1:
            bm //= 2
        elif bn > 1:
            bn //= 2
        else:
            return GemmConfig(1, 1, None)


def emit_gemm(a_ref, b_ref, out_ref, cfg: GemmConfig, out_dtype=None):
    """Run a pipelined GEMM ``out = a @ b`` over HBM refs, inside a kernel.

    a_ref: [M, K], b_ref: [K, N], out_ref: [M, N]. M % block_m == 0,
    N % block_n == 0 (pad upstream — the reference pads M the same way,
    gemm_reduce_scatter.py:482-493).
    """
    M, K = a_ref.shape
    K2, N = b_ref.shape
    assert K == K2, f"inner dims mismatch {K} vs {K2}"
    assert M % cfg.block_m == 0 and N % cfg.block_n == 0, (
        f"gemm shapes [{M},{K}]x[{K},{N}] not divisible by tile "
        f"({cfg.block_m},{cfg.block_n})")
    out_dtype = out_dtype or out_ref.dtype
    bk = min(cfg.block_k or K, K)

    def body(a_blk, b_blk, o_blk):
        o_blk[...] = jnp.dot(a_blk[...], b_blk[...],
                             preferred_element_type=jnp.float32
                             ).astype(out_dtype)

    if bk == K:
        pltpu.emit_pipeline(
            body,
            grid=(M // cfg.block_m, N // cfg.block_n),
            in_specs=[
                pl.BlockSpec((cfg.block_m, K), lambda i, j: (i, 0)),
                pl.BlockSpec((K, cfg.block_n), lambda i, j: (0, j)),
            ],
            out_specs=[pl.BlockSpec((cfg.block_m, cfg.block_n),
                                    lambda i, j: (i, j))],
        )(a_ref, b_ref, out_ref)
        return

    # K-split: k innermost so each output tile stays resident while its
    # K/bk partial products accumulate; the body zero-inits at k == 0 via
    # the pipeline's virtual grid index (cross-strip sums land in
    # ``out_dtype`` — use an f32 out for strict accuracy at large K)
    assert K % bk == 0, f"K={K} not divisible by block_k {bk}"

    def body_acc(a_blk, b_blk, o_blk):
        k = pl.program_id(2)
        part = jnp.dot(a_blk[...], b_blk[...],
                       preferred_element_type=jnp.float32)

        @pl.when(k == 0)
        def _():
            o_blk[...] = part.astype(out_dtype)

        @pl.when(k > 0)
        def _():
            o_blk[...] = (o_blk[...].astype(jnp.float32)
                          + part).astype(out_dtype)

    pltpu.emit_pipeline(
        body_acc,
        grid=(M // cfg.block_m, N // cfg.block_n, K // bk),
        in_specs=[
            pl.BlockSpec((cfg.block_m, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, cfg.block_n), lambda i, j, k: (k, j)),
        ],
        out_specs=[pl.BlockSpec((cfg.block_m, cfg.block_n),
                                lambda i, j, k: (i, j))],
    )(a_ref, b_ref, out_ref)


def matmul(a: jax.Array, b: jax.Array, cfg: GemmConfig | None = None,
           out_dtype=None) -> jax.Array:
    """Standalone single-device Pallas matmul (test/bench baseline)."""
    cfg = cfg or GemmConfig()
    out_dtype = out_dtype or a.dtype
    M, K = a.shape
    _, N = b.shape

    def kernel(a_ref, b_ref, out_ref):
        emit_gemm(a_ref, b_ref, out_ref, cfg, out_dtype)

    flops = 2 * M * N * K
    bytes_accessed = (a.size * a.dtype.itemsize + b.size * b.dtype.itemsize
                      + M * N * jnp.dtype(out_dtype).itemsize)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                  pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        cost_estimate=pl.CostEstimate(flops=flops,
                                      bytes_accessed=bytes_accessed,
                                      transcendentals=0),
        interpret=default_interpret(),
    )(a, b)
