"""The op registry sigcheck iterates: every public name in
``triton_dist_tpu.ops`` is either protocol-checked here (a ``run``
callable that drives the op end to end on a :class:`~.capture.FakeContext`
at tiny, assert-satisfying shapes) or carries a documented skip reason
(pure host math, config dataclasses, eager stateful wrappers whose kernel
path is checked through their functional twin).

tests/test_sigcheck.py asserts this registry and the ``ops`` export
surface stay in lockstep: adding an export without registering it (or
registering a ghost) fails the quick tier.

Shapes follow the ops' own validators: lane-multiple (128) contraction
shards where the compiled path insists (``gemm_rs``, ``moe_reduce_rs``,
``ll_ag_merge``), sublane-multiple page sizes, rank-divisible row counts.
They are chosen per rank count inside ``run`` (the capture instantiates
n ∈ {2, 3, 4}).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .api import DEFAULT_MESHES
from .capture import FakeContext

MESH_2D: Tuple[Dict[str, int], ...] = ({"x": 2, "y": 2},)
MESH_LOCAL: Tuple[Dict[str, int], ...] = ({"x": 1},)
MESH_PAIR: Tuple[Dict[str, int], ...] = ({"role": 2},)
# lend_pages' role-gated protocol must balance at ANY axis size (ranks
# outside the {lender, borrower} pair only hit the entry barrier) — the
# ISSUE 17 satellite pins n ∈ {2, 3, 4}
MESH_LEND: Tuple[Dict[str, int], ...] = ({"role": 2}, {"role": 3},
                                         {"role": 4})
MESH_1D_AND_2D = DEFAULT_MESHES + MESH_2D

f32 = jnp.float32
i32 = jnp.int32


@dataclasses.dataclass
class RegistryEntry:
    name: str
    run: Optional[Callable[[FakeContext], Any]] = None
    meshes: Sequence[Dict[str, int]] = DEFAULT_MESHES
    skip: Optional[str] = None


def _local(fn: Callable[[], Any]) -> Callable[[FakeContext], Any]:
    """Wrap a single-device op (no ctx argument) as a registry run: replay
    it as the body of a 1-rank shard_map so its pallas_calls record."""

    def run(ctx: FakeContext):
        ctx.shard_map(lambda: (fn(), jnp.zeros(()))[1],
                      in_specs=(), out_specs=None)()

    return run


# -- collectives -------------------------------------------------------------

def _run_barrier_all_op(ctx):
    from ..ops import barrier_all_op
    barrier_all_op(ctx)()


def _run_all_gather(ctx):
    from ..ops import all_gather
    n = ctx.num_ranks
    x = jnp.zeros((4 * n, 128), f32)
    if len(ctx.axis_names) > 1:
        for method in ("push_2d", "ring_2d"):
            all_gather(ctx, x, axis=None, method=method)
    else:
        for method in ("push", "ring"):
            all_gather(ctx, x, axis="x", method=method)


def _run_all_gather_ll(ctx):
    from ..ops import all_gather_ll, create_ag_ll_workspace
    n = ctx.num_ranks
    ws = create_ag_ll_workspace(ctx, 4, (128,), f32)
    phase = jnp.zeros((1,), i32)
    all_gather_ll(ctx, jnp.zeros((4 * n, 128), f32), ws, phase)


def _run_broadcast(ctx):
    from ..ops import broadcast
    n = ctx.num_ranks
    broadcast(ctx, jnp.zeros((n, 8, 128), f32), axis="x", root=n - 1)


def _run_reduce_scatter(ctx):
    from ..ops import reduce_scatter
    n = ctx.num_ranks
    x = jnp.zeros((4 * n * n, 128), f32)
    if len(ctx.axis_names) > 1:
        reduce_scatter(ctx, x, axis=None, method="ring_2d")
    else:
        reduce_scatter(ctx, x, axis="x", method="ring")


def _run_all_to_all_push(ctx):
    from ..ops import all_to_all_push
    n = ctx.num_ranks
    all_to_all_push(ctx, jnp.zeros((n * n, 8, 128), f32), axis="x")


def _run_all_to_all_push_seg(ctx):
    from ..ops import all_to_all_push_seg
    n = ctx.num_ranks
    # 16 f32 rows split into two 8-row sublane-aligned segments — a real
    # two-segment counted-signal schedule, not the degenerate "full" path
    all_to_all_push_seg(ctx, jnp.zeros((n * n, 16, 128), f32), axis="x",
                        segments=2)


# -- GEMM overlaps -----------------------------------------------------------

def _gemm_cfg():
    from ..ops.gemm import GemmConfig
    return GemmConfig(block_m=8, block_n=128)


def _run_ag_gemm(ctx):
    from ..ops import ag_gemm
    n = ctx.num_ranks
    a = jnp.zeros((8 * n, 128), f32)
    b = jnp.zeros((128, 128 * n), f32)
    ag_gemm(ctx, a, b, axis="x", cfg=_gemm_cfg())


def _run_ag_gemm_ws(ctx):
    from ..ops import ag_gemm_ws, create_ag_gemm_workspace
    n = ctx.num_ranks
    a = jnp.zeros((8 * n, 128), f32)
    b = jnp.zeros((128, 128 * n), f32)
    ws = create_ag_gemm_workspace(ctx, m_local=8, k=128, dtype=f32)
    ag_gemm_ws(ctx, a, b, ws, axis="x", cfg=_gemm_cfg())


def _run_ag_gemm_diff(ctx):
    from ..ops import ag_gemm_diff
    n = ctx.num_ranks
    ag_gemm_diff(ctx, "x", _gemm_cfg(), jnp.zeros((8 * n, 128), f32),
                 jnp.zeros((128, 128 * n), f32))


def _run_tp_column_linear(ctx):
    from ..ops import tp_column_linear
    n = ctx.num_ranks
    w = jnp.zeros((128, 128 * n), f32)
    tp_column_linear(ctx, jnp.zeros((8, 128), f32), w, axis="x", impl="xla")
    tp_column_linear(ctx, jnp.zeros((8 * n, 128), f32), w, axis="x",
                     impl="ag_gemm", cfg=_gemm_cfg())


def _run_gemm_rs(ctx):
    from ..ops import gemm_rs
    n = ctx.num_ranks
    a = jnp.zeros((4 * n, 128 * n), f32)
    b = jnp.zeros((128 * n, 128), f32)
    gemm_rs(ctx, a, b, axis="x")


def _run_gemm_rs_ws(ctx):
    from ..ops import gemm_rs_ws, create_gemm_rs_workspace
    n = ctx.num_ranks
    a = jnp.zeros((4 * n, 128 * n), f32)
    b = jnp.zeros((128 * n, 128), f32)
    ws, stage = create_gemm_rs_workspace(ctx, m_seg=4, n_cols=128,
                                         out_dtype=f32)
    gemm_rs_ws(ctx, a, b, ws, stage, axis="x")


def _run_gemm_rs_diff(ctx):
    from ..ops import gemm_rs_diff
    n = ctx.num_ranks
    gemm_rs_diff(ctx, "x", None, jnp.zeros((4 * n, 128 * n), f32),
                 jnp.zeros((128 * n, 128), f32))


# -- ring attention ----------------------------------------------------------

def _ra_shapes(n, s_local=128):
    # zigzag layout splits each rank's chunk in half, and the compiled-path
    # validator wants 128-multiple row tiles — so zigzag runs need 256
    B, Hq, Hkv, D = 1, 2, 2, 128
    q = jnp.zeros((B, Hq, n * s_local, D), f32)
    kv = jnp.zeros((B, Hkv, n * s_local, D), f32)
    return q, kv


def _run_ring_attention(ctx):
    from ..ops import ring_attention
    q, kv = _ra_shapes(ctx.num_ranks)
    ring_attention(ctx, q, kv, kv, axis="x", block_q=128, block_k=128)


def _run_ring_attention_fwd(ctx):
    from ..ops import ring_attention_fwd
    for layout, s_local in (("contiguous", 128), ("zigzag", 256)):
        q, kv = _ra_shapes(ctx.num_ranks, s_local)
        ring_attention_fwd(ctx, q, kv, kv, axis="x", block_q=128, block_k=128,
                           layout=layout)


def _run_ring_attention_bwd(ctx):
    from ..ops import ring_attention_bwd, ring_attention_fwd
    q, kv = _ra_shapes(ctx.num_ranks)
    o, lse = ring_attention_fwd(ctx, q, kv, kv, axis="x",
                                block_q=128, block_k=128)
    ring_attention_bwd(ctx, q, kv, kv, o, lse, o, axis="x", causal=True,
                       sm_scale=None, block_q=128, block_k=128)


# -- serving: page migration -------------------------------------------------

def _run_migrate_pages(ctx):
    from ..ops import migrate_pages
    n_roles = ctx.num_ranks
    L, num_pages, Hkv, page_size, D, pmax = 2, 9, 2, 8, 32, 4
    pool = jnp.zeros((n_roles, L, num_pages, Hkv, page_size, D), f32)
    migrate_pages(ctx, pool, pool,
                  jnp.array([1, 2, 0, 0], i32), jnp.array([3, 4, 0, 0], i32),
                  jnp.array([2], i32), axis="role")


def _run_lend_pages(ctx):
    from ..ops import lend_pages
    n_roles = ctx.num_ranks
    L, num_pages, Hkv, page_size, D = 2, 9, 2, 8, 32
    pool = jnp.zeros((n_roles, L, num_pages, Hkv, page_size, D), f32)
    # lender 0 → borrower (last rank): at n > 2 the middle ranks are
    # pure bystanders — the capture proves their signal books still
    # balance (entry barrier only)
    lend_pages(ctx, pool, pool,
               jnp.array([1, 2, 0, 0], i32), jnp.array([3, 4, 0, 0], i32),
               jnp.array([2], i32), axis="role",
               lender=0, borrower=n_roles - 1)


# -- EP all-to-all -----------------------------------------------------------

def _run_ep_dispatch_combine(ctx):
    from ..ops import create_all_to_all_context, dispatch, combine
    n = ctx.num_ranks
    T, H, topk = 4, 128, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T, hidden=H, topk=topk,
                                    num_experts=2 * n, dtype=f32)
    tokens = jnp.zeros((n * T, H), f32)
    topk_ids = jnp.zeros((n * T, topk), i32)
    _, _, layout = dispatch(a2a, tokens, topk_ids)
    processed = jnp.zeros((n * n, a2a.capacity, H), f32)
    combine(a2a, processed, layout, jnp.ones((n * T, topk), f32))


def _run_ep_dispatch_combine_2d(ctx):
    from ..ops import (create_all_to_all_context_2d, dispatch_2d, combine_2d)
    n = ctx.num_ranks
    T, H, topk = 4, 128, 2
    a2a = create_all_to_all_context_2d(ctx, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=n, dtype=f32)
    tokens = jnp.zeros((n * T, H), f32)
    topk_ids = jnp.zeros((n * T, topk), i32)
    recv, _, layouts = dispatch_2d(a2a, tokens, topk_ids)
    combine_2d(a2a, jnp.zeros(recv.shape, f32), layouts,
               jnp.ones((n * T, topk), f32))


# -- flash decode ------------------------------------------------------------

def _fd_gqa_decode_partial():
    from ..ops import gqa_decode_partial
    q = jnp.zeros((1, 4, 128), f32)
    kv = jnp.zeros((1, 2, 128, 128), f32)
    gqa_decode_partial(q, kv, kv, jnp.array([64], i32), block_s=128)


def _fd_gqa_decode_paged():
    from ..ops import gqa_decode_paged
    q = jnp.zeros((1, 4, 128), f32)
    pages = jnp.zeros((8, 2, 8, 128), f32)
    gqa_decode_paged(q, pages, pages, jnp.zeros((1, 4), i32),
                     jnp.array([20], i32))


def _fd_paged_kv_write():
    from ..ops import paged_kv_write
    pages = jnp.zeros((8, 2, 8, 128), f32)
    new = jnp.zeros((1, 2, 128), f32)
    paged_kv_write(pages, pages, new, new, jnp.zeros((1, 4), i32),
                   jnp.array([3], i32))


def _fd_decode_combine():
    from ..ops import decode_combine
    decode_combine(jnp.zeros((2, 1, 4, 128), f32),
                   jnp.zeros((2, 1, 4, 128), f32))


def _run_ll_ag_merge(ctx):
    from ..ops import ll_ag_merge
    n = ctx.num_ranks
    packed = jnp.zeros((n, 1, 4, 128 + 128), f32)
    ll_ag_merge(ctx, packed, 128, f32, "x")


def _run_sp_gqa_flash_decode(ctx):
    from ..ops import sp_gqa_flash_decode
    n = ctx.num_ranks
    q = jnp.zeros((1, 4, 128), f32)
    kv = jnp.zeros((1, 2, n * 128, 128), f32)
    sp_gqa_flash_decode(ctx, q, kv, kv, jnp.array([100], i32), axis="x",
                        block_s=128)


def _run_sp_paged_attend_write(ctx):
    from ..ops import sp_paged_attend_write
    n = ctx.num_ranks
    q = jnp.zeros((1, 4, 128), f32)
    pages = jnp.zeros((4 * n, 2, 8, 128), f32)
    new = jnp.zeros((1, 2, 128), f32)
    sp_paged_attend_write(ctx, q, new, new, pages, pages,
                          jnp.zeros((1, 4), i32), jnp.array([3], i32),
                          jnp.array([4], i32), axis="x")


def _run_pool_ag_start_local(ctx):
    from ..ops import pool_ag_start_local
    n = ctx.num_ranks
    pages = jnp.zeros((4 * n, 2, 8, 128), f32)
    pool_ag_start_local(ctx, pages, pages, axis="x")


def _run_flash_decode_dist(ctx):
    from ..ops import flash_decode_dist
    n = ctx.num_ranks
    q = jnp.zeros((1, 4, 128), f32)
    pages = jnp.zeros((4 * n, 2, 8, 128), f32)
    new = jnp.zeros((1, 2, 128), f32)
    flash_decode_dist(ctx, q, new, new, pages, pages,
                      jnp.zeros((1, 4), i32), jnp.array([3], i32),
                      jnp.array([4], i32), axis="x")


# -- grouped GEMM / MoE ------------------------------------------------------

def _gg_grouped_gemm():
    from ..ops import grouped_gemm
    tokens = jnp.zeros((16, 64), f32)
    w = jnp.zeros((2, 64, 128), f32)
    grouped_gemm(tokens, w, jnp.zeros((2,), i32), block_m=8)


def _gg_grouped_gemm_gated():
    from ..ops import grouped_gemm_gated
    tokens = jnp.zeros((16, 64), f32)
    w = jnp.zeros((2, 64, 128), f32)
    grouped_gemm_gated(tokens, w, w, jnp.zeros((2,), i32), block_m=8)


def _gg_apply_grouped():
    from ..ops import apply_grouped, grouped_gemm
    tokens = jnp.zeros((16, 64), f32)
    w = jnp.zeros((2, 64, 128), f32)
    apply_grouped(tokens, jnp.zeros((16,), i32), 2,
                  lambda x, be, nb: grouped_gemm(x, w, be, block_m=8,
                                                 n_blocks_used=nb),
                  block_m=8)


def _gg_moe_ffn_local():
    from ..ops import moe_ffn_local
    tokens = jnp.zeros((16, 64), f32)
    moe_ffn_local(tokens, jnp.zeros((16,), i32),
                  jnp.zeros((2, 64, 128), f32), jnp.zeros((2, 128, 64), f32),
                  block_m=8)


def _run_ag_moe_group_gemm(ctx):
    from ..ops import ag_moe_group_gemm
    n = ctx.num_ranks
    tokens = jnp.zeros((8 * n, 64), f32)
    ids = jnp.zeros((8 * n,), i32)
    weights = jnp.zeros((2, 64, 16 * n), f32)
    ag_moe_group_gemm(ctx, tokens, ids, weights, axis="x", block_m=8,
                      block_n=16)


def _run_moe_reduce_rs(ctx):
    from ..ops import moe_reduce_rs
    n = ctx.num_ranks
    T, topk = 4 * n, 2
    tokens = jnp.zeros((T * topk, 128 * n), f32)
    ids = jnp.zeros((T * topk,), i32)
    moe_reduce_rs(ctx, tokens, ids, jnp.ones((T, topk), f32),
                  jnp.zeros((2, 128 * n, 16), f32), axis="x", block_m=8)


# -- the registry ------------------------------------------------------------

_SKIP_PURE = "pure host-side math, no DMA/semaphore protocol"
_SKIP_CLASS = "config/context dataclass, not an op"

_ENTRIES = [
    # common
    RegistryEntry("collective_id_for",
                  skip="deterministic name→collective_id hash; " + _SKIP_PURE),
    RegistryEntry("barrier_all_op", _run_barrier_all_op,
                  meshes=MESH_1D_AND_2D),
    # gemm tiling
    RegistryEntry("GemmConfig", skip=_SKIP_CLASS),
    RegistryEntry("best_gemm_config",
                  skip="tile-size heuristic; " + _SKIP_PURE),
    # allgather
    RegistryEntry("all_gather", _run_all_gather, meshes=MESH_1D_AND_2D),
    RegistryEntry("all_gather_ll", _run_all_gather_ll),
    RegistryEntry("create_ag_ll_workspace", _run_all_gather_ll),
    RegistryEntry("AgLLContext",
                  skip="eager stateful wrapper; kernel path checked via "
                       "all_gather_ll"),
    RegistryEntry("broadcast", _run_broadcast),
    # reduce_scatter
    RegistryEntry("reduce_scatter", _run_reduce_scatter,
                  meshes=MESH_1D_AND_2D),
    # AG-GEMM
    RegistryEntry("ag_gemm", _run_ag_gemm),
    RegistryEntry("ag_gemm_ws", _run_ag_gemm_ws),
    RegistryEntry("create_ag_gemm_workspace", _run_ag_gemm_ws),
    RegistryEntry("create_ag_gemm_context",
                  skip="eager stateful wrapper; kernel path checked via "
                       "ag_gemm_ws"),
    RegistryEntry("AgGemmContext",
                  skip="eager stateful wrapper; kernel path checked via "
                       "ag_gemm_ws"),
    RegistryEntry("tp_column_linear", _run_tp_column_linear),
    RegistryEntry("ag_gemm_diff", _run_ag_gemm_diff),
    # GEMM-RS
    RegistryEntry("gemm_rs", _run_gemm_rs),
    RegistryEntry("gemm_rs_ws", _run_gemm_rs_ws),
    RegistryEntry("create_gemm_rs_workspace", _run_gemm_rs_ws),
    RegistryEntry("create_gemm_rs_context",
                  skip="eager stateful wrapper; kernel path checked via "
                       "gemm_rs_ws"),
    RegistryEntry("GemmRsContext",
                  skip="eager stateful wrapper; kernel path checked via "
                       "gemm_rs_ws"),
    RegistryEntry("gemm_rs_diff", _run_gemm_rs_diff),
    # ring attention
    RegistryEntry("ring_attention", _run_ring_attention),
    RegistryEntry("ring_attention_fwd", _run_ring_attention_fwd),
    RegistryEntry("ring_attention_bwd", _run_ring_attention_bwd),
    RegistryEntry("zigzag_indices", skip=_SKIP_PURE),
    # page migration (pairwise producer/consumer role protocol)
    RegistryEntry("migrate_pages", _run_migrate_pages, meshes=MESH_PAIR),
    RegistryEntry("paged_transport",
                  skip="shared transport host wrapper; protocol checked "
                       "via migrate_pages and lend_pages"),
    # cluster page lending (ISSUE 17): same counted-signal protocol,
    # role-gated — must balance with bystander ranks on the axis
    RegistryEntry("lend_pages", _run_lend_pages, meshes=MESH_LEND),
    # EP all-to-all
    RegistryEntry("all_to_all_push", _run_all_to_all_push),
    # segmented counted-signal wire (ISSUE 16 overlap schedule)
    RegistryEntry("all_to_all_push_seg", _run_all_to_all_push_seg),
    RegistryEntry("create_all_to_all_context", _run_ep_dispatch_combine),
    RegistryEntry("dispatch", _run_ep_dispatch_combine),
    RegistryEntry("combine", _run_ep_dispatch_combine),
    RegistryEntry("route_tokens", _run_ep_dispatch_combine),
    RegistryEntry("create_all_to_all_context_2d", _run_ep_dispatch_combine_2d,
                  meshes=MESH_2D),
    RegistryEntry("dispatch_2d", _run_ep_dispatch_combine_2d,
                  meshes=MESH_2D),
    RegistryEntry("combine_2d", _run_ep_dispatch_combine_2d, meshes=MESH_2D),
    RegistryEntry("route_tokens_2d", _run_ep_dispatch_combine_2d,
                  meshes=MESH_2D),
    RegistryEntry("EpAllToAllContext", skip=_SKIP_CLASS),
    RegistryEntry("Ep2dAllToAllContext", skip=_SKIP_CLASS),
    RegistryEntry("a2a_wire_bytes", skip=_SKIP_PURE),
    RegistryEntry("pick_wire_dtype", skip=_SKIP_PURE),
    RegistryEntry("expected_capacity", skip=_SKIP_PURE),
    # flash decode
    RegistryEntry("gqa_decode_partial", _local(_fd_gqa_decode_partial),
                  meshes=MESH_LOCAL),
    RegistryEntry("gqa_decode_paged", _local(_fd_gqa_decode_paged),
                  meshes=MESH_LOCAL),
    RegistryEntry("paged_kv_write", _local(_fd_paged_kv_write),
                  meshes=MESH_LOCAL),
    RegistryEntry("decode_combine", _local(_fd_decode_combine),
                  meshes=MESH_LOCAL),
    RegistryEntry("ll_ag_merge", _run_ll_ag_merge),
    RegistryEntry("sp_gqa_flash_decode", _run_sp_gqa_flash_decode),
    RegistryEntry("sp_paged_attend_write", _run_sp_paged_attend_write),
    # start-local signal-gated pool allgather (ISSUE 16 SP overlap)
    RegistryEntry("pool_ag_start_local", _run_pool_ag_start_local),
    # distributed flash-decode: per-page partial slab exchange + fixed-
    # order page fold (ISSUE 19 long-context serving)
    RegistryEntry("flash_decode_dist", _run_flash_decode_dist),
    # grouped GEMM
    RegistryEntry("grouped_gemm", _local(_gg_grouped_gemm),
                  meshes=MESH_LOCAL),
    RegistryEntry("grouped_gemm_gated", _local(_gg_grouped_gemm_gated),
                  meshes=MESH_LOCAL),
    RegistryEntry("apply_grouped", _local(_gg_apply_grouped),
                  meshes=MESH_LOCAL),
    RegistryEntry("moe_ffn_local", _local(_gg_moe_ffn_local),
                  meshes=MESH_LOCAL),
    RegistryEntry("PackedGatedWeights", skip=_SKIP_CLASS),
    RegistryEntry("pack_gated_weights",
                  skip="pure weight relayout; " + _SKIP_PURE),
    RegistryEntry("align_tokens_by_expert",
                  skip=_SKIP_PURE + "; exercised inside apply_grouped"),
    RegistryEntry("used_block_count",
                  skip=_SKIP_PURE + "; exercised inside apply_grouped"),
    RegistryEntry("emit_grouped_gemm",
                  skip="kernel-body emitter; protocol checked via "
                       "grouped_gemm/grouped_gemm_gated"),
    # MoE overlaps
    RegistryEntry("ag_moe_group_gemm", _run_ag_moe_group_gemm),
    RegistryEntry("moe_reduce_rs", _run_moe_reduce_rs),
    # autotuned wrappers: same kernels behind a config search — the signal
    # protocol is config-independent and checked via the wrapped op
    RegistryEntry("ag_gemm_autotuned",
                  skip="autotune wrapper; protocol checked via ag_gemm"),
    RegistryEntry("gemm_rs_autotuned",
                  skip="autotune wrapper; protocol checked via gemm_rs"),
    RegistryEntry("ag_moe_group_gemm_autotuned",
                  skip="autotune wrapper; protocol checked via "
                       "ag_moe_group_gemm"),
    RegistryEntry("moe_reduce_rs_autotuned",
                  skip="autotune wrapper; protocol checked via moe_reduce_rs"),
    RegistryEntry("grouped_gemm_autotuned",
                  skip="autotune wrapper; protocol checked via grouped_gemm"),
    RegistryEntry("moe_ffn_gated_autotuned",
                  skip="autotune wrapper; protocol checked via "
                       "grouped_gemm_gated"),
    RegistryEntry("ring_attention_autotuned",
                  skip="autotune wrapper; protocol checked via "
                       "ring_attention"),
]

REGISTRY: Dict[str, RegistryEntry] = {e.name: e for e in _ENTRIES}


def surface_names() -> set:
    """Non-module public names exported by ``triton_dist_tpu.ops`` — the set
    the registry must cover exactly."""
    import types
    from .. import ops
    return {name for name in dir(ops)
            if not name.startswith("_")
            and not isinstance(getattr(ops, name), types.ModuleType)}
