"""Tools-layer tests: distributed autotuner, AOT paths, native csrc op
(parity targets: reference python/triton_dist/autotuner.py,
tools/compile_aot.py, csrc/moe_utils.cu)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.tools import (aot_compile, aot_compile_spaces,
                                   contextual_autotune, export_serialized,
                                   load_serialized)


def test_autotuner_picks_and_caches():
    calls = []

    @contextual_autotune(configs=[1, 2, 3], iters=1, warmup=0,
                         prune=lambda c, args, kw: c != 3)
    def op(x, cfg=None):
        calls.append(cfg)
        return x * cfg

    x = jnp.ones((4,))
    y = op(x)
    assert float(y[0]) in (1.0, 2.0)
    assert 3 not in calls          # pruned config never ran
    n_calls = len(calls)
    y2 = op(x)                     # cached: exactly one more call
    assert len(calls) == n_calls + 1
    assert float(y2[0]) == float(y[0])
    # different shape -> re-tune
    op(jnp.ones((8,)))
    assert len(calls) > n_calls + 1


def test_autotuner_explicit_cfg_bypasses():
    @contextual_autotune(configs=[1, 2], iters=1, warmup=0)
    def op(x, cfg=None):
        return x * cfg

    assert float(op(jnp.ones(()), cfg=7)) == 7.0


def test_aot_compile_and_serialize(tmp_path):
    def f(x):
        return jnp.sin(x) * 2

    x = jnp.arange(8, dtype=jnp.float32)
    exe = aot_compile(f, x)
    np.testing.assert_allclose(np.asarray(exe(x)), np.sin(np.arange(8.)) * 2,
                               rtol=1e-6)

    data = export_serialized(f, x)
    assert isinstance(data, bytes) and len(data) > 0
    g = load_serialized(data)
    np.testing.assert_allclose(np.asarray(g(x)), np.asarray(exe(x)),
                               rtol=1e-6)


def test_aot_compile_spaces_dispatch():
    traces = []

    @aot_compile_spaces({
        "small": lambda: (jnp.zeros((4,), jnp.float32),),
        "big": lambda: (jnp.zeros((16,), jnp.float32),),
    })
    def f(x):
        traces.append(x.shape)
        return x + 1

    f.precompile()
    n = len(traces)
    # both declared shapes hit precompiled executables (no new traces)
    f(jnp.ones((4,), jnp.float32))
    f(jnp.ones((16,), jnp.float32))
    assert len(traces) == n
    # undeclared shape falls back to jit
    out = f(jnp.ones((32,), jnp.float32))
    assert out.shape == (32,)


def test_native_moe_align_matches_jnp():
    csrc = pytest.importorskip("triton_dist_tpu.csrc")
    if csrc.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    from triton_dist_tpu.ops.group_gemm import align_tokens_by_expert

    rng = np.random.default_rng(0)
    for T, E, bm in [(64, 4, 16), (100, 7, 32), (5, 3, 8)]:
        ids = rng.integers(-1, E, size=T).astype(np.int32)
        g_n, v_n, b_n = csrc.moe_align_block_size(ids, E, bm)
        g_j, v_j, b_j = jax.jit(
            lambda i: align_tokens_by_expert(i, E, bm))(jnp.asarray(ids))
        np.testing.assert_array_equal(g_n, np.asarray(g_j))
        np.testing.assert_array_equal(v_n, np.asarray(v_j))
        np.testing.assert_array_equal(b_n, np.asarray(b_j))


def test_autotuned_overlap_ops():
    """Autotuned AG-GEMM/GEMM-RS pick a valid tile config and stay correct
    (reference wraps the same thunks, docs/autotuner.md)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.autotuned import (ag_gemm_autotuned,
                                               gemm_rs_autotuned)
    from triton_dist_tpu.shmem.context import initialize_distributed

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))
    n = ctx.num_ranks
    M, K, N = n * 32, 128, n * 64
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    c = ag_gemm_autotuned(ctx, ctx.shard(a, P("x")),
                          ctx.shard(b, P(None, "x")), "x")
    np.testing.assert_allclose(np.asarray(c), np.asarray(a) @ np.asarray(b),
                               atol=1e-3, rtol=1e-3)
    c2 = gemm_rs_autotuned(ctx, ctx.shard(a, P(None, "x")),
                           ctx.shard(b, P("x")), "x")
    ref = np.zeros((M, N), np.float32)
    a_np, b_np = np.asarray(a), np.asarray(b)
    for r in range(n):
        ref += a_np[:, r*(K//n):(r+1)*(K//n)] @ b_np[r*(K//n):(r+1)*(K//n)]
    np.testing.assert_allclose(np.asarray(c2), ref, atol=1e-3, rtol=1e-3)


def test_autotuned_grouped_gemm():
    """The raw grouped-GEMM autotuned entries (VERDICT r4 Missing #5) sweep
    (block_m, block_n) and stay correct, invalid ids included."""
    import jax.numpy as jnp

    from triton_dist_tpu.ops.autotuned import (grouped_gemm_autotuned,
                                               moe_ffn_gated_autotuned)

    E, H, F, T = 4, 128, 128, 96
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), -1, E)
    w = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    out = grouped_gemm_autotuned(tokens, ids, w)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(w)
    gold = np.stack([t[r] @ wn[idn[r]] if idn[r] >= 0 else np.zeros(F)
                     for r in range(T)])
    np.testing.assert_allclose(np.asarray(out), gold, atol=1e-3, rtol=1e-3)

    wg = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    wd = jax.random.normal(jax.random.key(4), (E, F, H), jnp.float32) * 0.1
    out2 = moe_ffn_gated_autotuned(tokens, ids, wg, w, wd)
    gold2 = np.zeros((T, H))
    for r in range(T):
        if idn[r] >= 0:
            g = t[r] @ np.asarray(wg)[idn[r]]
            u = t[r] @ wn[idn[r]]
            h = g / (1 + np.exp(-g)) * u
            gold2[r] = h @ np.asarray(wd)[idn[r]]
    np.testing.assert_allclose(np.asarray(out2), gold2, atol=1e-3, rtol=1e-3)


def test_autotuned_moe_ops():
    """Autotuned fused MoE ops pick a valid block_m and stay correct."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.autotuned import (ag_moe_group_gemm_autotuned,
                                               moe_reduce_rs_autotuned)
    from triton_dist_tpu.shmem.context import initialize_distributed

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))
    n = ctx.num_ranks
    E, H, N, T = 4, 128, n * 128, n * 32
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    w = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32) * 0.1
    out = ag_moe_group_gemm_autotuned(ctx, ctx.shard(tokens, P("x")),
                                      ctx.shard(ids, P("x")),
                                      ctx.shard(w, P(None, None, "x")), "x")
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(w)
    gold = np.stack([t[r] @ wn[idn[r]] for r in range(T)])
    np.testing.assert_allclose(np.asarray(out), gold, atol=1e-3, rtol=1e-3)

    topk = 2
    K2, N2, T2 = n * 32, 64, n * 8
    tok2 = jax.random.normal(jax.random.key(3), (T2 * topk, K2), jnp.float32)
    ids2 = jax.random.randint(jax.random.key(4), (T2 * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(5), (T2, topk)), -1)
    w2 = jax.random.normal(jax.random.key(6), (E, K2, N2), jnp.float32) * 0.1
    out2 = moe_reduce_rs_autotuned(ctx, ctx.shard(tok2, P(None, "x")), ids2,
                                   tw, ctx.shard(w2, P(None, "x", None)), "x")
    t2, id2n, w2n = np.asarray(tok2), np.asarray(ids2), np.asarray(w2)
    rows = np.stack([t2[r] @ w2n[id2n[r]] for r in range(T2 * topk)])
    gold2 = (rows.reshape(T2, topk, N2) * np.asarray(tw)[..., None]).sum(1)
    np.testing.assert_allclose(np.asarray(out2), gold2, atol=1e-3, rtol=1e-3)


def test_native_a2a_route_matches_jnp():
    """C++ slot_assign/bincount vs the jnp one-hot-cumsum device path
    (contract: ops.all_to_all._slot_assign)."""
    import numpy as np

    from triton_dist_tpu import csrc
    from triton_dist_tpu.ops.all_to_all import _slot_assign
    if csrc.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    R, n_dst, cap = 257, 6, 32
    dest = rng.integers(-1, n_dst + 1, size=R).astype(np.int32)
    valid = (rng.random(R) < 0.8).astype(np.uint8)
    for v in (None, valid):
        s_n, ok_n = csrc.a2a_slot_assign(dest, n_dst, cap, v)
        s_j, ok_j = _slot_assign(
            jnp.asarray(dest), n_dst, cap,
            None if v is None else jnp.asarray(v.astype(bool)))
        np.testing.assert_array_equal(s_n, np.asarray(s_j))
        np.testing.assert_array_equal(ok_n, np.asarray(ok_j))
    counts = csrc.a2a_bincount(dest, n_dst)
    ref = np.bincount(dest[(dest >= 0) & (dest < n_dst)], minlength=n_dst)
    np.testing.assert_array_equal(counts, ref)


def test_autotuned_ring_attention():
    import jax
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.autotuned import ring_attention_autotuned
    from triton_dist_tpu.shmem.context import initialize_distributed
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(2,))
    B, Hq, Hkv, D, S = 1, 2, 2, 128, 2 * 128
    qv = jax.random.normal(jax.random.key(0), (B, Hq, S, D), jnp.float32)
    kv = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    vv = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    spec = P(None, None, "x")
    out = ring_attention_autotuned(ctx, ctx.shard(qv, spec),
                                   ctx.shard(kv, spec),
                                   ctx.shard(vv, spec), axis="x")
    assert out.shape == qv.shape


def test_collective_ids_order_independent():
    """Two fresh processes must assign identical collective ids no matter
    what order families are first used in — order-derived ids would alias
    barriers across hosts that trace ops in different orders (reference
    analog: fixed per-kernel signal-buffer layouts in its ctx dataclasses)."""
    import subprocess
    import sys

    names = ["ag_gemm_x", "rs_ring_y", "barrier_all", "all_to_all_tp",
             "ring_attn_sp", "gemm_rs_('x', 'y')", "ll_ag_merge_x"]
    prog = (
        "import sys\n"
        "from triton_dist_tpu.ops.common import collective_id_for\n"
        "names = sys.argv[1:]\n"
        "print({n: collective_id_for(n) for n in names})\n")
    outs = []
    for order in (names, list(reversed(names)), names[3:] + names[:3]):
        r = subprocess.run([sys.executable, "-c", prog, *order],
                           capture_output=True, text=True,
                           env={**__import__('os').environ,
                                "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr
        outs.append(eval(r.stdout.strip()))
    assert outs[0] == outs[1] == outs[2]
    assert len(set(outs[0].values())) == len(names)  # all distinct


def test_host_routing_tables_take_native_path(monkeypatch):
    """Product wiring (VERDICT r3 missing #5): numpy routing tables into
    align_tokens_by_expert / route_tokens dispatch to the C++ host ops, no
    device round-trip; outputs match the jnp twins bit-for-bit."""
    csrc = pytest.importorskip("triton_dist_tpu.csrc")
    if csrc.get_lib() is None:
        pytest.skip("native toolchain unavailable")
    from triton_dist_tpu.ops import all_to_all as a2a_ops
    from triton_dist_tpu.ops.group_gemm import align_tokens_by_expert

    calls = {"align": 0, "slot": 0}
    real_align = csrc.moe_align_block_size
    real_slot = csrc.a2a_slot_assign
    monkeypatch.setattr(csrc, "moe_align_block_size",
                        lambda *a, **k: (calls.__setitem__(
                            "align", calls["align"] + 1), real_align(*a, **k)
                        )[1])
    monkeypatch.setattr(csrc, "a2a_slot_assign",
                        lambda *a, **k: (calls.__setitem__(
                            "slot", calls["slot"] + 1), real_slot(*a, **k)
                        )[1])

    rng = np.random.default_rng(1)
    ids = rng.integers(-1, 6, size=90).astype(np.int32)
    g_n, v_n, b_n, u_n = align_tokens_by_expert(ids, 6, 16,
                                                with_used_count=True)
    assert calls["align"] == 1
    assert isinstance(g_n, np.ndarray) and not isinstance(g_n, jax.Array)
    g_j, v_j, b_j, u_j = jax.jit(
        lambda i: align_tokens_by_expert(i, 6, 16, with_used_count=True))(
        jnp.asarray(ids))
    np.testing.assert_array_equal(g_n, np.asarray(g_j))
    np.testing.assert_array_equal(v_n, np.asarray(v_j))
    np.testing.assert_array_equal(b_n, np.asarray(b_j))
    assert int(u_n) == int(u_j)

    from triton_dist_tpu.shmem.context import initialize_distributed
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(2,))
    a2a = a2a_ops.create_all_to_all_context(ctx, max_tokens=16, hidden=128,
                                            topk=2, num_experts=4, axis="x")
    tk = rng.integers(0, 4, size=(16, 2)).astype(np.int32)
    d_n, s_n, ok_n = a2a_ops.route_tokens(a2a, tk)
    assert calls["slot"] == 1
    d_j, s_j, ok_j = jax.jit(
        lambda i: a2a_ops.route_tokens(a2a, i))(jnp.asarray(tk))
    np.testing.assert_array_equal(d_n, np.asarray(d_j))
    np.testing.assert_array_equal(s_n, np.asarray(s_j))
    np.testing.assert_array_equal(ok_n, np.asarray(ok_j))


def test_a2a_dispatch_wire_model():
    """The DeepEP-comparison wire model (bench.py): explicit, checkable
    terms — measured n=1 kernel + egress bytes over ICI + per-peer hops."""
    import bench   # repo root is on sys.path via conftest

    # n=1: no wire, no hops — the model returns the measurement itself
    assert bench.a2a_dispatch_model_us(65.0, 1) == 65.0
    # DeepSeek-infer shape at 32 ranks, fp8 wire: 128*8*(7168+4) bytes
    # egress * 31/32 over 180e3 B/us + 31 hops + kernel
    m32 = bench.a2a_dispatch_model_us(65.0, 32)
    bytes_out = 128 * 8 * (7168 + 4)
    expect = 65.0 + bytes_out * 31 / 32 / 180e3 + 31.0
    assert abs(m32 - expect) < 1e-6
    # monotone in n: more ranks, more hops (wire term saturates)
    m8 = bench.a2a_dispatch_model_us(65.0, 8)
    assert 65.0 < m8 < m32


def test_a2a_wire_fit_two_segment(monkeypatch):
    """The payload-scaling fit resolves a launch-latency floor meeting a
    bandwidth line (t = max(t_lat, t0 + bytes/BW)) and reports BOTH
    segment residuals — a single affine through floored small points drags
    the slope (the round-5 0.19/0.17 residuals)."""
    import bench

    class _FakeCtx:
        axis_names = ("x",)

        def axis_size(self, axis):
            return 4

    # synthetic truth: 60 µs floor, then 10 µs + bytes / 150 GB/s — at
    # (64 tok, hidden 1024, topk 2) the 1x/2x points sit on the floor and
    # the 4x/8x points on the line (knee at 7.5 MB)
    t_lat, t0, bw = 60e-6, 10e-6, 150e9

    def fake_wire(ctx, tokens, hidden, topk, num_experts, i1, i2,
                  wire_dtype=None, clamp=False):
        b = bench._wire_bytes(4, tokens, hidden, topk, wire_dtype)
        return max(t_lat, t0 + b / bw)

    monkeypatch.setattr(bench, "bench_a2a_wire", fake_wire)
    fit = bench.bench_a2a_wire_fit(_FakeCtx(), tokens_per_rank=64,
                                   hidden=1024, topk=2, num_experts=8,
                                   i1=1, i2=5)
    assert fit["latency_points"] == 2
    assert abs(fit["t_lat_us"] - 60.0) < 0.5
    assert abs(fit["t0_us"] - 10.0) < 0.5
    assert abs(fit["knee_mb"] - 7.5) < 0.1
    assert 145.0 < fit["gb_per_s"] < 155.0
    # both segments resolved well inside the 0.15 gate
    assert fit["fit_residual_small"] <= 0.01
    assert fit["fit_residual_big"] <= 0.01
    # the seed is the model at the 1x payload: on the floor here
    assert abs(fit["wire_us"] - 60.0) < 0.5
    assert fit["t0_pinned_reason"] is None

    # purely linear data (no floor in range): the plain affine wins the
    # split search and the floor terms are absent
    def fake_linear(ctx, tokens, hidden, topk, num_experts, i1, i2,
                    wire_dtype=None, clamp=False):
        return t0 + bench._wire_bytes(4, tokens, hidden, topk,
                                      wire_dtype) / bw

    monkeypatch.setattr(bench, "bench_a2a_wire", fake_linear)
    lin = bench.bench_a2a_wire_fit(_FakeCtx(), tokens_per_rank=64,
                                   hidden=1024, topk=2, num_experts=8,
                                   i1=1, i2=5)
    assert lin["latency_points"] == 0
    assert lin["t_lat_us"] is None and lin["knee_mb"] is None
    assert lin["fit_residual_small"] is None
    assert lin["fit_residual_big"] <= 0.01
    assert abs(lin["t0_us"] - 10.0) < 0.5
