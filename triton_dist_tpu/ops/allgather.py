"""AllGather kernel family (analog of reference
python/triton_dist/kernels/nvidia/allgather.py).

The reference drives AG three ways — copy-engine full-mesh push/pull
(allgather.py:79-135), 1-D ring push (:138-192) and NUMA-aware 2-D rings
(:194-258) — with CPU stream-ordered signal writes as flags. On TPU both
producers become *in-kernel* async remote DMAs whose receive semaphores are
the flags:

- ``push``: every PE puts its shard into each peer's output slot directly —
  one hop, full-mesh traffic; best for small messages / lowest latency.
- ``ring``: each PE forwards the newest segment to its right neighbor —
  n-1 hops but every link carries at most one segment per step; best for
  bandwidth-bound sizes on a 1-D ICI ring.
- ``ring_2d``: hierarchical ring-over-rings for multi-axis meshes
  (ICI torus / multi-slice): ring AG along the minor axis, then ring AG of
  the gathered super-segments along the major axis (analog of the
  reference's NUMA 2-D ring :194-258 / inter-node 2-D :291-375).

TPU grids execute sequentially per core, so per-segment ordering needs no
tile-level spin flags — each segment is waited exactly once.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _ag_push_kernel(axis, mesh_axes, in_ref, out_ref, send_sems, recv_sems):
    """Full-mesh push: put my shard into every peer's slot ``me``."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = in_ref.shape[0]

    # Entry barrier: DMA semaphores are physical registers shared across
    # calls — without this, device A's call-k+1 put could signal device B's
    # recv_sem while B is still draining call k, mis-delivering the arrival
    # (cf. the reference's local_copy_and_barrier_all prologue,
    # allgather_gemm.py:99-116). Devices execute kernels in order, so
    # "everyone entered call k+1" implies "everyone exited call k".
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    # own slot via local DMA
    local = pltpu.make_async_copy(in_ref, out_ref.at[pl.ds(me * m, m)],
                                  recv_sems.at[me])
    local.start()

    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(out_ref.at[pl.ds(me * m, m)], in_ref,
                                    send_sems.at[dst], recv_sems.at[me], pid))

    local.wait()
    for p in range(1, n):
        src = lax.rem(me + p, n)
        shd.wait_recv(out_ref.at[pl.ds(src * m, m)], recv_sems.at[src])
    shd.quiet(*rdmas)


def _ag_ring_kernel(axis, mesh_axes, in_ref, out_ref, send_sem, recv_sems):
    """1-D ring push: forward the newest segment to the right neighbor.
    Segments land directly in their output slots (no relay buffers), so no
    slot-reuse flow control is needed."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = in_ref.shape[0]
    right = shd.pe_at(mesh_axes, axis, lax.rem(me + 1, n))

    # entry barrier: see _ag_push_kernel — protects cross-call semaphore
    # delivery (ring neighbors advance at different speeds)
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    local = pltpu.make_async_copy(in_ref, out_ref.at[pl.ds(me * m, m)],
                                  recv_sems.at[me])
    local.start()
    local.wait()

    for s in range(n - 1):
        seg = lax.rem(me - s + n, n)  # newest segment I hold
        rdma = shd.putmem_nbi(out_ref.at[pl.ds(seg * m, m)],
                              out_ref.at[pl.ds(seg * m, m)],
                              send_sem, recv_sems.at[seg], right)
        prev = lax.rem(me - s - 1 + n, n)
        shd.wait_recv(out_ref.at[pl.ds(prev * m, m)], recv_sems.at[prev])
        rdma.wait_send()


def _ag_ll_kernel(axis, mesh_axes, phase_ref, in_ref, ws_ref, out_ref,
                  ws_out, send_sems, recv_sems):
    """Barrier-free low-latency push AG (the reference's LL flag-parity
    family, low_latency_allgather.py, re-thought for TPU): arrivals land
    in a PERSISTENT double-buffered symmetric workspace ``ws[2, n, m, …]``
    keyed by call parity, delivery is the DMA receive semaphore — no
    entry barrier, no flag words.

    Why parity alone is safe: a peer's call k+1 cannot complete its waits
    without MY call-k+1 put, so no peer is ever more than ONE call ahead.
    While I am in call k the only in-flight signals/writes are calls k
    (phase p) and k+1 (phase 1-p): the phase-keyed semaphore array and
    buffer slot disambiguate both. Call k+2 (phase p again) cannot start
    anywhere before I finish k — my own ws[p] is already drained.
    The write target must be the persistent ws, NOT the per-call output
    (XLA may alias a not-yet-entered call's output buffer to live data —
    an early peer put would corrupt it); the local unpack ws→out is one
    VMEM-speed copy of a latency-sized payload.

    INTERLEAVING HAZARD (why this kernel must not share a program point
    with other collectives): the one-call-ahead argument above bounds
    in-flight traffic *of this kernel* only. Its scratch semaphores are
    per-``pallas_call`` allocations of physical registers, NOT reserved
    across kernels — if another collective runs between a slow peer's
    call k and my call k+1, Mosaic may hand that kernel the same
    registers, and the straggler's put then signals into the bystander's
    wait. Barriered kernels are immune ("everyone entered k+1" implies
    "everyone exited k", so no cross-kernel signal can be outstanding);
    *this* kernel trades exactly that guarantee for latency. Contract:
    back-to-back LL AG calls on one axis may interleave only with each
    other (the phase key disambiguates them) or with collectives that
    open with their own entry barrier — never with another barrier-free
    kernel on an overlapping device group. See docs/primitives.md
    ("Barrier-free kernels")."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m = in_ref.shape[0]
    p = phase_ref[0]

    # own slot goes straight to the output (never through ws)
    local = pltpu.make_async_copy(in_ref, out_ref.at[pl.ds(me * m, m)],
                                  recv_sems.at[p, me])
    local.start()

    rdmas = []
    for k in range(1, n):
        dst = lax.rem(me + k, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(ws_ref.at[p, me], in_ref,
                                    send_sems.at[p, dst],
                                    recv_sems.at[p, me], pid))

    local.wait()
    for k in range(1, n):
        src = lax.rem(me + k, n)
        shd.wait_recv(ws_ref.at[p, src], recv_sems.at[p, src])
        unpack = pltpu.make_async_copy(ws_ref.at[p, src],
                                       out_ref.at[pl.ds(src * m, m)],
                                       recv_sems.at[p, src])
        unpack.start()
        unpack.wait()
    shd.quiet(*rdmas)
    # alias ws through so the caller's buffer stays live & donated
    del ws_out


def all_gather_ll(ctx: ShmemContext, x: jax.Array, ws: jax.Array,
                  phase: jax.Array, axis: str | None = None):
    """Low-latency AG for small (≲64 KB/rank) payloads: one barrier-free
    kernel, phase-keyed double-buffered workspace (see ``_ag_ll_kernel``).

    ``ws``: symmetric [n, 2, n, m, …] from ``create_ag_ll_workspace``,
    aliased in place and returned (thread it like PRNG keys / the AG-GEMM
    workspace). ``phase``: int32 [1], the call count modulo 2 — the caller
    alternates it every call (``AgLLContext`` does the bookkeeping).
    Returns (gathered [n·m, …] replicated, ws)."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names

    def f(phase_l, shard, ws_shard):
        # drop the leading symmetric dim (local size 1): the kernel
        # addresses ws as [2, n, m, …] (cf. ag_gemm_ws's reshape)
        ws_local = ws_shard.reshape(ws_shard.shape[1:])
        out_shape = (jax.ShapeDtypeStruct((n * shard.shape[0],)
                                          + shard.shape[1:], shard.dtype),
                     jax.ShapeDtypeStruct(ws_local.shape, ws_local.dtype))
        kernel = lambda ph, i, w, o, wo, ss, rs: _ag_ll_kernel(
            axis, mesh_axes, ph, i, w, o, wo, ss, rs)
        out, ws_out = pl.pallas_call(
            kernel,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
            input_output_aliases={2: 1},
            scratch_shapes=[pltpu.SemaphoreType.DMA((2, n)),
                            pltpu.SemaphoreType.DMA((2, n))],
            # NO collective_id: the whole point is no barrier — and Mosaic
            # rejects a collective_id on kernels that never call
            # get_barrier_semaphore (real-TPU rule, see the verify skill)
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
            interpret=default_interpret(),
        )(phase_l, shard, ws_local)
        return out, ws_out.reshape(ws_shard.shape)

    sm = ctx.shard_map(
        f, in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(*([None] * x.ndim)), P(axis)))
    return sm(phase, x, ws)


def create_ag_ll_workspace(ctx: ShmemContext, m_local: int, trailing: tuple,
                           dtype, axis: str | None = None) -> jax.Array:
    """Symmetric LL-AG workspace: per-PE [2, n, m_local, *trailing]
    (double-buffered arrival slots), global [n, 2, n, m, …] P(axis)."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    return ctx.create_symm_tensor((2, n, m_local) + tuple(trailing), dtype,
                                  axis=axis)


class AgLLContext:
    """Stateful sugar over ``all_gather_ll``: owns the workspace and the
    call-parity counter (the reference's LL contexts track the same
    call-count parity, low_latency_allgather.py). Eager-mode only — inside
    jit/scan use ``all_gather_ll`` and thread (ws, phase) yourself."""

    def __init__(self, ctx: ShmemContext, m_local: int, trailing: tuple,
                 dtype, axis: str | None = None):
        from triton_dist_tpu.ops.common import require_eager
        self._require_eager = require_eager
        self.ctx = ctx
        self.axis = axis or ctx.axis_names[0]
        self.ws = create_ag_ll_workspace(ctx, m_local, trailing, dtype,
                                         self.axis)
        self.calls = 0
        self._jit = jax.jit(
            lambda ph, x, ws: all_gather_ll(ctx, x, ws, ph, axis=self.axis),
            donate_argnums=(2,))

    def __call__(self, x: jax.Array) -> jax.Array:
        self._require_eager("AgLLContext", "all_gather_ll")
        import jax.numpy as jnp
        phase = jnp.asarray([self.calls % 2], jnp.int32)
        out, self.ws = self._jit(phase, x, self.ws)
        self.calls += 1
        return out


def _ag_call(axis: str, mesh_axes, n: int, method: str, shard):
    """Build + invoke the AG pallas_call on a local shard (inside shard_map)."""
    m = shard.shape[0]
    out_shape = jax.ShapeDtypeStruct((n * m,) + shard.shape[1:], shard.dtype)
    if method == "push":
        kernel = lambda i, o, ss, rs: _ag_push_kernel(axis, mesh_axes, i, o, ss, rs)
        scratch = [pltpu.SemaphoreType.DMA((n,)), pltpu.SemaphoreType.DMA((n,))]
    elif method == "ring":
        kernel = lambda i, o, ss, rs: _ag_ring_kernel(axis, mesh_axes, i, o, ss, rs)
        scratch = [pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA((n,))]
    else:
        raise ValueError(f"unknown allgather method {method!r}")
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True,
            # distinct id per (kernel family, axis): the 2-D hierarchical AG
            # runs two of these back-to-back over different axis subsets, and
            # sharing one physical barrier semaphore would let stage-2
            # signals satisfy a device still waiting in stage 1
            collective_id=collective_id_for(f"ag_{method}_{axis}")),
        interpret=default_interpret(),
    )(shard)


def _ag_1d(ctx: ShmemContext, x: jax.Array, axis: str, method: str):
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    f = lambda shard: _ag_call(axis, mesh_axes, n, method, shard)
    sm = ctx.shard_map(f, in_specs=P(axis), out_specs=P(*([None] * x.ndim)))
    return sm(x)


# Below this per-rank shard size the gather is latency-bound and the
# one-hop full-mesh push wins; above it the per-link-bandwidth-optimal
# ring does. 256 KB ≈ the crossover implied by v5e ICI (~45 GB/s/link,
# ~1 µs hop overhead): push sends (n-1) x bytes over one step, ring
# pipelines n-1 single-segment hops.
_PUSH_BYTES_CEILING = 256 * 1024


def _auto_method(ctx: ShmemContext, x: jax.Array, axis) -> str:
    """Topology/shape-keyed method pick — the analog of the reference's
    NVLink/NUMA-topology dispatch (allgather.py:54-69 backed by
    utils.py:504-607). The TPU topology signal is the mesh itself: how many
    axes the gather spans (one ICI ring vs a torus/multi-slice hierarchy,
    slow tier first by ``initialize_distributed`` convention) and the
    per-rank payload size (latency- vs bandwidth-bound regime)."""
    axis_names = ctx.axis_names
    spans_multi = (axis is None and len(axis_names) > 1) or (
        isinstance(axis, tuple) and len(axis) > 1)
    shard_bytes = (x.size // max(ctx.axis_size(axis), 1)) * x.dtype.itemsize
    if spans_multi:
        # hierarchy: single-kernel relay for small payloads (fewest
        # kernel/barrier rounds), per-axis rings for bandwidth-bound sizes
        return "push_2d" if shard_bytes <= _PUSH_BYTES_CEILING else "ring_2d"
    if ctx.axis_size(axis) <= 4 or shard_bytes <= _PUSH_BYTES_CEILING:
        return "push"
    return "ring"


def all_gather(ctx: ShmemContext, x: jax.Array, axis: str | None = None,
               method: str = "auto"):
    """AllGather ``x`` (sharded on dim 0 along ``axis``) → replicated global
    array. ``method`` ∈ auto|push|ring|ring_2d|push_2d. Analog of the
    reference's ``cp_engine_producer_all_gather_*`` dispatch
    (allgather.py:54-69, which auto-picks by NVLink/NUMA topology; here by
    mesh rank-count/axes). ``ring_2d`` is the bandwidth-oriented multi-axis
    path (per-axis rings), ``push_2d`` the latency-oriented one (single
    kernel, outer relay + inner push)."""
    axis_names = ctx.axis_names
    if axis is None and len(axis_names) == 1:
        axis = axis_names[0]
    involved = (tuple(axis) if isinstance(axis, tuple)
                else axis_names if axis is None else (axis,))
    if method == "xla" or any(ctx.is_dcn_axis(a) for a in involved):
        # DCN tier: remote DMA cannot cross a slice boundary, so a gather
        # group containing a DCN axis runs on XLA collectives end to end
        # (XLA routes intra-slice hops over ICI and inter-slice over DCN
        # itself — the host-driven transport the reference reaches with
        # its inter-node IBRC tier, allgather.py:291-375). ICI-only meshes
        # never take this path unless method="xla" is forced.
        return _ag_xla(ctx, x, involved)
    if method == "auto":
        method = _auto_method(ctx, x, axis)
    if method in ("ring_2d", "push_2d"):
        if len(axis_names) < 2 and not (isinstance(axis, tuple)
                                        and len(axis) > 1):
            raise ValueError(f"{method} allgather needs a >=2-axis mesh; "
                             f"mesh axes are {axis_names}")
        if method == "ring_2d":
            return _ag_ring_2d(ctx, x)
        return _ag_push_2d(ctx, x, axis)
    if axis is None:
        raise ValueError(
            f"all_gather(method={method!r}) on a multi-axis mesh "
            f"{axis_names} requires an explicit axis=")
    return _ag_1d(ctx, x, axis, method)


def _ag_xla(ctx: ShmemContext, x: jax.Array, involved: tuple):
    """XLA-collective all-gather over ``involved`` axes, innermost first so
    the replicated result keeps the P(involved) row order."""
    from jax import lax

    def f(shard):
        y = shard
        for ax in reversed(involved):
            y = lax.all_gather(y, ax, axis=0, tiled=True)
        return y

    sm = ctx.shard_map(f, in_specs=P(involved),
                       out_specs=P(*([None] * x.ndim)))
    return sm(x)


def _ag_push_2d(ctx: ShmemContext, x: jax.Array, axis=None):
    mesh_axes = ctx.axis_names
    axes = tuple(axis) if isinstance(axis, tuple) else tuple(mesh_axes)
    n = ctx.axis_size(axes)

    def f(shard):
        m = shard.shape[0]
        slots = pl.pallas_call(
            lambda i, o, ss, rs: _ag_push_2d_kernel(axes, mesh_axes, i, o,
                                                    ss, rs),
            out_shape=jax.ShapeDtypeStruct((n,) + shard.shape, shard.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n,)),
                            pltpu.SemaphoreType.DMA((n,))],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"ag_push2d_{axes}")),
            interpret=default_interpret(),
        )(shard)
        return slots.reshape((n * m,) + shard.shape[1:])

    sm = ctx.shard_map(f, in_specs=P(axes), out_specs=P(*([None] * x.ndim)))
    return sm(x)


def _ag_push_2d_kernel(axes, mesh_axes, in_ref, slots_ref,
                       send_sems, recv_sems):
    """Single-kernel hierarchical push AG: the 2-tier relay protocol
    (same-inner-index outer ring + inner push, ops.allgather_gemm.
    ag_overlap_protocol_2d) with arrivals landing DIRECTLY in the output's
    [n, m, ...] slots — one kernel, no inter-stage compile boundary, vs
    ``ring_2d``'s two sequential ring kernels. The latency-oriented
    multi-axis path (analog of the reference's hierarchical 2-D/3-D push
    variants, low_latency_allgather.py:345-530)."""
    from triton_dist_tpu.ops.allgather_gemm import ag_overlap_protocol_2d

    state = {"local_emit": True}

    def emit(src_ref, seg):
        # the protocol's first emit call is statically the LOCAL segment
        # (src_ref is in_ref); remote segments already sit in their slots
        if state["local_emit"]:
            state["local_emit"] = False
            pltpu.sync_copy(src_ref, slots_ref.at[seg])

    ag_overlap_protocol_2d(axes, mesh_axes, in_ref, slots_ref,
                           send_sems, recv_sems, emit)


def _bcast_kernel(axis, mesh_axes, root, in_ref, out_ref,
                  send_sems, recv_sem):
    """One-to-all broadcast: the root puts its block into every peer's
    output; peers wait one delivery. Analog of the device-API
    ``broadcast(mem)`` the reference's raw-API tests exercise
    (test_nvshmem_api; libnvshmem_device.py broadcast/fcollect family)."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    # entry barrier: recv_sem is reused across calls (see _ag_push_kernel)
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    @pl.when(me == root)
    def _():
        local = pltpu.make_async_copy(in_ref, out_ref, recv_sem)
        local.start()
        rdmas = []
        for p in range(n):
            if p == 0:
                continue
            dst = lax.rem(root + p, n)
            pid = shd.pe_at(mesh_axes, axis, dst)
            rdmas.append(shd.putmem_nbi(out_ref, in_ref, send_sems.at[dst],
                                        recv_sem, pid))
        local.wait()
        shd.quiet(*rdmas)

    @pl.when(me != root)
    def _():
        shd.wait_recv(out_ref, recv_sem)


def broadcast(ctx: ShmemContext, x: jax.Array, axis: str | None = None,
              root: int = 0) -> jax.Array:
    """Broadcast the ``root`` device's block to all PEs along ``axis``.
    ``x`` is global [n, ...] sharded P(axis) (one candidate block per
    device); returns root's block [...] replicated. Golden: ``x[root]``."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    assert 0 <= root < n, (root, n)
    mesh_axes = ctx.axis_names
    assert x.shape[0] == n, (x.shape, n)

    def f(shard):
        blk = shard.reshape(shard.shape[1:])
        return pl.pallas_call(
            lambda i, o, ss, rs: _bcast_kernel(axis, mesh_axes, root, i, o,
                                               ss, rs),
            out_shape=jax.ShapeDtypeStruct(blk.shape, blk.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((n,)),
                            pltpu.SemaphoreType.DMA(())],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"bcast_{axis}")),
            interpret=default_interpret(),
        )(blk)

    sm = ctx.shard_map(f, in_specs=P(axis),
                       out_specs=P(*([None] * (x.ndim - 1))))
    return sm(x)


def _ag_ring_2d(ctx: ShmemContext, x: jax.Array):
    """Hierarchical AG over a multi-axis mesh, innermost axis first: ring
    along the minor axis (gathering my row's shards into a contiguous
    super-segment), then rings of super-segments along each outer axis in
    turn. Works for any axis count >= 2 — e.g. (slice-major, torus-y,
    torus-x). The innermost axis should be the fastest interconnect tier
    (ICI), the outermost the slowest (DCN/inter-slice), matching the
    reference's NUMA/internode split (allgather.py:194-375) and its 3-D
    hierarchical push (low_latency_allgather.py:345-530). All stages run
    inside one shard_map — intermediates are only partially replicated,
    never mesh-replicated."""
    mesh_axes = ctx.axis_names

    def f(shard):
        out = shard
        for axis in reversed(mesh_axes):
            out = _ag_call(axis, mesh_axes, ctx.axis_size(axis), "ring", out)
        return out

    sm = ctx.shard_map(f, in_specs=P(mesh_axes),
                       out_specs=P(*([None] * x.ndim)))
    return sm(x)


__all__ = ["all_gather", "all_gather_ll", "AgLLContext",
           "create_ag_ll_workspace", "broadcast"]
