"""Model-family tests: dense Llama + MoE — overlap-kernel forward vs the
pure-XLA forward as golden (role analog of the reference's end-to-end MoE
block test, test/nvidia/test_ep_moe_inference.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.layers import EPAll2AllLayer
from triton_dist_tpu.models.llama import (LlamaConfig, forward,
                                          forward_tp_overlap, init_params)
from triton_dist_tpu.models.moe import (MoEConfig, init_moe_params,
                                        moe_forward, moe_mlp_ep_overlap)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


@pytest.fixture(scope="module")
def tiny_cfg():
    return LlamaConfig.tiny(n_layers=2)


def test_dense_forward_shapes(tiny_cfg):
    cfg = tiny_cfg
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_tp_overlap_forward_matches_dense(ctx, tiny_cfg):
    """The Pallas AG-GEMM/GEMM-RS forward must equal the plain XLA forward
    (the reference checks overlap TP against torch matmul the same way,
    test_ag_gemm_intra_node.py:128-148)."""
    cfg = tiny_cfg
    n = ctx.num_ranks
    params = init_params(jax.random.key(0), cfg)
    B, S = 2, n * 32  # T = B*S divisible by n * block tiles
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, cfg.vocab_size)
    golden = jax.jit(lambda p, t: forward(p, t, cfg))(params, tokens)
    got = jax.jit(
        lambda p, t: forward_tp_overlap(ctx, p, t, cfg, axis="x")
    )(params, tokens)
    # bf16 params, f32 logits; overlap path reduces in different order
    assert_allclose(np.asarray(got), np.asarray(golden), atol=5e-2, rtol=5e-2)


def test_moe_forward_shapes():
    cfg = MoEConfig.tiny(n_layers=2, num_experts=4)
    params = init_moe_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                cfg.base.vocab_size)
    logits, aux = jax.jit(lambda p, t: moe_forward(p, t, cfg))(params, tokens)
    assert logits.shape == (2, 16, cfg.base.vocab_size)
    assert bool(jnp.isfinite(aux))
    assert bool(jnp.all(jnp.isfinite(logits)))


@pytest.mark.quick
def test_moe_ep_overlap_matches_dense(ctx):
    """EP dispatch → grouped FFN → combine on the Pallas kernels vs a dense
    per-expert golden (uncapped capacity, so no token drops)."""
    n = ctx.num_ranks
    T_local, D, F, E, k = 16, 128, 128, 2 * n, 2
    T = n * T_local
    key = jax.random.key(0)
    x = (jax.random.normal(key, (T, D), jnp.float32) * 0.3).astype(jnp.bfloat16)
    router_w = jax.random.normal(jax.random.key(1), (D, E), jnp.float32) * 0.3
    wg = (jax.random.normal(jax.random.key(2), (E, D, F)) * 0.1).astype(jnp.bfloat16)
    wu = (jax.random.normal(jax.random.key(3), (E, D, F)) * 0.1).astype(jnp.bfloat16)
    wd = (jax.random.normal(jax.random.key(4), (E, F, D)) * 0.1).astype(jnp.bfloat16)

    layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D, topk=k,
                                  num_experts=E, axis="x")
    xs = ctx.shard(x, P("x"))
    got = jax.jit(lambda x: moe_mlp_ep_overlap(
        ctx, layer, x, router_w, wg, wu, wd, axis="x"))(xs)

    # dense golden: same routing, dense expert FFN, weighted sum (f32 — the
    # CPU backend lacks a bf16 x bf16 dot thunk)
    x32, wg32, wu32, wd32 = (a.astype(jnp.float32) for a in (x, wg, wu, wd))
    logits = x32 @ router_w
    gv, gi = jax.lax.top_k(jax.nn.softmax(logits, -1), k)
    gv = gv / jnp.sum(gv, -1, keepdims=True)
    h = jax.nn.silu(jnp.einsum("td,edf->tef", x32, wg32)) \
        * jnp.einsum("td,edf->tef", x32, wu32)
    ye = jnp.einsum("tef,efd->ted", h.astype(jnp.bfloat16).astype(jnp.float32),
                    wd32)   # [T, E, D]
    sel = jnp.take_along_axis(ye, gi[..., None], axis=1)  # [T, k, D]
    golden = jnp.sum(sel * gv[..., None], axis=1)
    assert_allclose(np.asarray(got, jnp.float32), np.asarray(golden),
                    atol=8e-2, rtol=8e-2)

    # packed serving layout (pack_gated_weights → we_gate_up_packed):
    # bit-identical path semantics, one double-width weight stream
    from triton_dist_tpu.ops.group_gemm import pack_gated_weights
    wgu = pack_gated_weights(wg, wu, block_n=64)
    got_p = jax.jit(lambda x: moe_mlp_ep_overlap(
        ctx, layer, x, router_w, wg, wu, wd, axis="x", block_n=64,
        we_gate_up_packed=wgu))(xs)
    assert_allclose(np.asarray(got_p, jnp.float32),
                    np.asarray(got, jnp.float32), atol=1e-2, rtol=1e-2)


def test_moe_tp_overlap_matches_dense(ctx):
    """TP-MoE block on the FUSED overlap kernels (AG+GroupGEMM up-proj →
    GroupGEMM+topk-reduce+RS down-proj) vs a dense per-expert golden."""
    from triton_dist_tpu.models.moe import moe_mlp_tp_overlap

    n = ctx.num_ranks
    T_local, D, F, E, k = 8, 128, 64 * n, 4, 2
    T = n * T_local
    x = (jax.random.normal(jax.random.key(0), (T, D)) * 0.3).astype(jnp.float32)
    router_w = jax.random.normal(jax.random.key(1), (D, E), jnp.float32) * 0.3
    wu = jax.random.normal(jax.random.key(2), (E, D, F), jnp.float32) * 0.1
    wd = jax.random.normal(jax.random.key(3), (E, F, D), jnp.float32) * 0.1

    got = jax.jit(lambda xx, wuu, wdd: moe_mlp_tp_overlap(
        ctx, xx, router_w, wuu, wdd, topk=k, axis="x", block_m=16))(
        ctx.shard(x, P("x")), ctx.shard(wu, P(None, None, "x")),
        ctx.shard(wd, P(None, "x", None)))

    logits = x @ router_w
    gv, gi = jax.lax.top_k(jax.nn.softmax(logits, -1), k)
    gv = gv / jnp.sum(gv, -1, keepdims=True)
    h = jax.nn.silu(jnp.einsum("td,edf->tef", x, wu))   # [T, E, F]
    ye = jnp.einsum("tef,efd->ted", h, wd)              # [T, E, D]
    sel = jnp.take_along_axis(ye, gi[..., None], axis=1)
    golden = jnp.sum(sel * gv[..., None], axis=1)
    assert_allclose(np.asarray(got, np.float32), np.asarray(golden),
                    atol=5e-2, rtol=5e-2)


def test_moe_ep_overlap_expert_edge_quant(ctx):
    """dequant_edge="expert": dispatch returns QuantTokens (wire-dtype rows
    + scales), the expert grouped GEMMs fold the scale into their f32
    accumulators, and the combine epilogue folds the return-trip scale into
    its gather — no standalone dequant pass anywhere. Must agree with the
    same wire under dequant_edge="post" within fp tolerance (the expert
    edge is MORE precise: fp8→f32 in the MXU accumulator vs an
    intermediate bf16 rounding)."""
    n = ctx.num_ranks
    T_local, D, F, E, k = 16, 128, 128, 2 * n, 2
    T = n * T_local
    x = (jax.random.normal(jax.random.key(7), (T, D), jnp.float32)
         * 0.3).astype(jnp.bfloat16)
    router_w = jax.random.normal(jax.random.key(8), (D, E),
                                 jnp.float32) * 0.3
    wg = (jax.random.normal(jax.random.key(9), (E, D, F)) * 0.1
          ).astype(jnp.bfloat16)
    wu = (jax.random.normal(jax.random.key(10), (E, D, F)) * 0.1
          ).astype(jnp.bfloat16)
    wd = (jax.random.normal(jax.random.key(11), (E, F, D)) * 0.1
          ).astype(jnp.bfloat16)
    xs = ctx.shard(x, P("x"))

    outs = {}
    for de in ("expert", "post"):
        layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D,
                                      topk=k, num_experts=E, axis="x",
                                      wire_dtype=jnp.int8, dequant_edge=de)
        outs[de] = np.asarray(jax.jit(lambda x, l=layer: moe_mlp_ep_overlap(
            ctx, l, x, router_w, wg, wu, wd, axis="x"))(xs), np.float32)
    assert_allclose(outs["expert"], outs["post"], atol=2e-2, rtol=2e-2)
