"""Perf measurement + tracing harness.

``perf_func`` mirrors the reference's CUDA-event wall-clock harness
(reference python/triton_dist/utils.py:186-198); on TPU we block on the
output buffers instead of recording events. ``group_profile`` mirrors the
reference's merged chrome-trace context (utils.py:254-501); jax's profiler
already merges multi-host traces, so it is a thin wrapper producing a
Perfetto-loadable trace directory.
"""

from __future__ import annotations

import contextlib
import time

import jax
import numpy as np


def _block(tree):
    """Synchronize on ``tree``'s buffers. ``block_until_ready`` alone is not
    trusted: under remote-execution runtimes (axon tunnel) it can return
    before the device work lands, over-reporting throughput ~100x. A 1-element
    device-to-host pull cannot complete early, so pull one scalar per leaf;
    in-order execution then guarantees everything earlier finished too."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()
        if hasattr(leaf, "addressable_shards") and leaf.size:
            np.asarray(leaf.addressable_shards[0].data.ravel()[:1])


def perf_func(func, iters: int = 10, warmup_iters: int = 3, return_result: bool = False):
    """Return (result, avg_ms_per_iter); ``result`` is the last iteration's
    output when ``return_result=True``, else None. ``func`` should return jax
    arrays (they are blocked on for timing)."""
    result = None
    for _ in range(warmup_iters):
        result = func()
    _block(result)
    start = time.perf_counter()
    for _ in range(iters):
        result = func()
    _block(result)
    elapsed_ms = (time.perf_counter() - start) * 1e3 / max(iters, 1)
    if return_result:
        return result, elapsed_ms
    return None, elapsed_ms


@contextlib.contextmanager
def group_profile(name: str = "trace", do_prof: bool = True,
                  out_dir: str = "prof", merge: bool = True):
    """Profile the enclosed region into ``{out_dir}/{name}`` (TensorBoard /
    Perfetto format).

    Multi-process jobs (``jax.process_count() > 1`` over a shared
    filesystem): each process traces into ``{path}/proc{i}`` (jax names
    trace files by *hostname*, which collides for same-host processes),
    then process 0 merges every process's chrome trace into ONE
    Perfetto-loadable ``{path}/merged.trace.json.gz`` with per-host track
    names — the analog of the reference's gather-and-merge
    ``group_profile`` (reference python/triton_dist/utils.py:282-501,
    which all-gathers per-rank chrome traces over the process group and
    rewrites pids into per-rank tracks)."""
    if not do_prof:
        yield
        return
    path = f"{out_dir}/{name}"
    multi = jax.process_count() > 1
    local = f"{path}/proc{jax.process_index()}" if multi else path
    jax.profiler.start_trace(local)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        if multi and merge:
            from jax.experimental import multihost_utils
            # every process must have flushed its trace before the merge
            multihost_utils.sync_global_devices(f"group_profile:{name}")
            if jax.process_index() == 0:
                merge_process_traces(path)


def merge_process_traces(path: str) -> str | None:
    """Merge ``{path}/proc*/`` chrome traces into
    ``{path}/merged.trace.json.gz``: one timeline, pids offset per process
    and tracks labeled ``host{i}/...``. Returns the merged file path (None
    when no per-process traces were found). Standalone so offline tooling
    can merge traces gathered from real pod hosts by other means."""
    import glob
    import gzip
    import json
    import os

    events = []
    found = False
    for proc_dir in sorted(glob.glob(f"{path}/proc*")):
        # host index from the directory name, NOT enumeration order —
        # lexicographic glob order misassigns labels at 10+ processes
        # (proc10 sorts before proc2)
        try:
            i = int(os.path.basename(proc_dir)[len("proc"):])
        except ValueError:
            continue
        traces = (glob.glob(f"{proc_dir}/**/*.trace.json.gz",
                            recursive=True)
                  + glob.glob(f"{proc_dir}/**/*.trace.json", recursive=True))
        base = (i + 1) * 100000
        for t in sorted(traces):
            opener = gzip.open if t.endswith(".gz") else open
            with opener(t, "rt") as f:
                data = json.load(f)
            found = True
            for ev in data.get("traceEvents", []):
                if "pid" in ev:
                    ev = dict(ev)
                    ev["pid"] = base + int(ev["pid"])
                    if (ev.get("ph") == "M"
                            and ev.get("name") == "process_name"):
                        args = dict(ev.get("args", {}))
                        args["name"] = f"host{i}/{args.get('name', '')}"
                        ev["args"] = args
                events.append(ev)
    if not found:
        return None
    out = os.path.join(path, "merged.trace.json.gz")
    with gzip.open(out, "wt") as f:
        json.dump({"traceEvents": events,
                   "displayTimeUnit": "ns"}, f)
    return out
