"""Persistent symmetric workspaces for the overlap ops.

Parity target: the reference creates symm workspaces ONCE per context and
reuses them across calls (create_ag_gemm_intra_node_context,
allgather_gemm.py:785-832; create_gemm_rs_context,
gemm_reduce_scatter.py:77-87) instead of allocating per call. Here the
workspace is an explicit aliased operand (functional-state idiom) with
donation, or a stateful *Context object for eager callers.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.allgather_gemm import (ag_gemm_ws,
                                                create_ag_gemm_context,
                                                create_ag_gemm_workspace)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import (create_gemm_rs_context,
                                                     create_gemm_rs_workspace,
                                                     gemm_rs_ws)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_ag_gemm_ws_donated_repeated(ctx):
    n = ctx.num_ranks
    M = K = 16 * n
    N = 128 * n
    cfg = GemmConfig(M // n, 128)
    ws = create_ag_gemm_workspace(ctx, M // n, K, jnp.float32, axis="x")
    f = jax.jit(lambda w, u, v: ag_gemm_ws(ctx, u, v, w, axis="x", cfg=cfg),
                donate_argnums=(0,))
    for it in range(3):
        a = jax.random.normal(jax.random.key(it), (M, K), jnp.float32)
        b = jax.random.normal(jax.random.key(100 + it), (K, N), jnp.float32)
        c, ws = f(ws, ctx.shard(a, P("x")), ctx.shard(b, P(None, "x")))
        assert_allclose(np.asarray(c), np.asarray(a @ b), rtol=1e-4,
                        atol=1e-3)


def test_ag_gemm_ws_in_scan(ctx):
    """The workspace threads through lax.scan as carry — the jit-composable
    form the chain-timing bench uses."""
    n = ctx.num_ranks
    # M == N == K for self-chaining; 128 divides evenly for any TEST_WORLD
    M = K = N = 128
    cfg = GemmConfig(M // n, N // n)
    ws = create_ag_gemm_workspace(ctx, M // n, K, jnp.float32, axis="x")
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jnp.eye(K, N, dtype=jnp.float32) * 0.5
    a_s, b_s = ctx.shard(a, P("x")), ctx.shard(b, P(None, "x"))

    @jax.jit
    def chain(a0, b, ws):
        def body(carry, _):
            x, w = carry
            c, w = ag_gemm_ws(ctx, x, b, w, axis="x", cfg=cfg)
            return (c, w), None
        (c, ws), _ = jax.lax.scan(body, (a0, ws), None, length=3)
        return c, ws

    c, _ = chain(a_s, b_s, ws)
    assert_allclose(np.asarray(c), np.asarray(a) * 0.5 ** 3, rtol=1e-4,
                    atol=1e-4)


def test_ag_gemm_context_stateful(ctx):
    n = ctx.num_ranks
    M = K = 16 * n
    N = 128 * n
    cfg = GemmConfig(M // n, 128)
    agc = create_ag_gemm_context(ctx, M // n, K, jnp.float32, axis="x")
    for it in range(3):
        a = jax.random.normal(jax.random.key(it), (M, K), jnp.float32)
        b = jax.random.normal(jax.random.key(50 + it), (K, N), jnp.float32)
        c = agc(ctx.shard(a, P("x")), ctx.shard(b, P(None, "x")), cfg=cfg)
        assert_allclose(np.asarray(c), np.asarray(a @ b), rtol=1e-4,
                        atol=1e-3)


def test_ag_gemm_context_rejects_outer_jit(ctx):
    n = ctx.num_ranks
    M = K = 16 * n
    agc = create_ag_gemm_context(ctx, M // n, K, jnp.float32, axis="x")
    with pytest.raises(RuntimeError, match="eager-only"):
        jax.jit(lambda a, b: agc(a, b))(
            jnp.zeros((M, K)), jnp.zeros((K, 128 * n)))


def test_gemm_rs_ws_donated_repeated(ctx):
    n = ctx.num_ranks
    M, K, N = n * 32, n * 32, 64
    cfg = GemmConfig(32, 32)
    ws, stage = create_gemm_rs_workspace(ctx, M // n, N, jnp.float32,
                                         axis="x")
    f = jax.jit(lambda w, s, u, v: gemm_rs_ws(ctx, u, v, w, s, axis="x",
                                              cfg=cfg),
                donate_argnums=(0, 1))

    def golden(a, b):
        def g(a_shard, b_shard):
            part = jnp.dot(a_shard, b_shard,
                           preferred_element_type=jnp.float32)
            return jax.lax.psum_scatter(part, "x", scatter_dimension=0,
                                        tiled=True)
        return jax.jit(ctx.shard_map(g, in_specs=(P(None, "x"), P("x", None)),
                                     out_specs=P("x")))(a, b)

    for it in range(3):
        a = ctx.shard(jax.random.normal(jax.random.key(it), (M, K)),
                      P(None, "x"))
        b = ctx.shard(jax.random.normal(jax.random.key(70 + it), (K, N)),
                      P("x", None))
        c, ws, stage = f(ws, stage, a, b)
        assert_allclose(np.asarray(c), np.asarray(golden(a, b)), rtol=1e-4,
                        atol=1e-4)


def test_gemm_rs_context_stateful(ctx):
    n = ctx.num_ranks
    M, K, N = n * 32, n * 32, 64
    cfg = GemmConfig(32, 32)
    rsc = create_gemm_rs_context(ctx, M // n, N, jnp.float32, axis="x")
    for it in range(2):
        a_h = jax.random.normal(jax.random.key(it), (M, K))
        b_h = jax.random.normal(jax.random.key(90 + it), (K, N))
        a = ctx.shard(a_h, P(None, "x"))
        b = ctx.shard(b_h, P("x", None))
        c = rsc(a, b, cfg=cfg)

        def g(a_shard, b_shard):
            part = jnp.dot(a_shard, b_shard,
                           preferred_element_type=jnp.float32)
            return jax.lax.psum_scatter(part, "x", scatter_dimension=0,
                                        tiled=True)
        gold = jax.jit(ctx.shard_map(g, in_specs=(P(None, "x"), P("x", None)),
                                     out_specs=P("x")))(a, b)
        assert_allclose(np.asarray(c), np.asarray(gold), rtol=1e-4,
                        atol=1e-4)


def test_context_cache_lru_and_trace_error(ctx):
    """r3 Weak #8: the eager contexts' per-shape step caches are bounded
    (LRU eviction) and calling them under a trace raises a descriptive
    RuntimeError, not a bare assert."""
    import triton_dist_tpu.ops.common as common

    n = ctx.num_ranks
    M = K = 8 * n
    agc = create_ag_gemm_context(ctx, M // n, K, jnp.float32, axis="x")
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    a_s = ctx.shard(a, P("x"))

    with pytest.raises(RuntimeError, match="eager-only"):
        jax.jit(lambda x: agc(x, x))(a_s)

    old = common._CONTEXT_CACHE_SIZE
    common._CONTEXT_CACHE_SIZE = 2
    try:
        for n_cols in (128, 256, 384, 128):
            b = jax.random.normal(jax.random.key(1), (K, n_cols * n),
                                  jnp.float32)
            c = agc(a_s, ctx.shard(b, P(None, "x")))
            np.testing.assert_allclose(np.asarray(c), np.asarray(a @ b),
                                       rtol=5e-2, atol=5e-1)
            assert len(agc._steps) <= 2
    finally:
        common._CONTEXT_CACHE_SIZE = old
