"""Crash-consistent serving (ISSUE 9): journal/checkpoint/restore held to
the bit-identity contract on all three engines.

The trace-determinism contract (greedy argmax decode + LIFO page
allocation + strict-FIFO scheduling) makes every request's tokens a pure
function of (params, prompt) — so crash recovery never persists KV: a
fresh engine + the journal (which embeds periodic control-plane
checkpoints) replays the WAL suffix, requeues every in-flight request at
cursor 0, and regenerates bit-identical tokens through the
already-compiled programs. The tests pin exactly that:

- **crash sweep**: inject ``InjectedCrash`` at strided steps of the
  50-request forced-preemption trace (every step under ``-m slow``),
  recover into a fresh engine, and assert the union of pre-crash and
  post-recovery finishes is BIT-IDENTICAL to the fault-free golden — on
  colocated, sharded (n ∈ {1, 2, 4}), and disaggregated (including a
  crash with a migration in flight: the restarted decode worker
  re-admits the request through the rebuilt ledger, never fails it).
- **zero new compiles**: restore performs no device dispatches — the jit
  trace-cache sizes are unchanged across ``restore()``, and a recovered
  run still ends at exactly one decode + one chunk program.
- **digest divergence rung**: a seeded transient ``digest_skew`` on the
  sharded mesh is absorbed by quarantine + restore (``digest_recoveries
  == 1``, tokens golden); persistent skew (re-diverging with no agreed
  step in between) escalates instead of looping; no journal = the
  pre-ISSUE-9 hard raise.
- **overload terminals**: a bounded admission queue + TTL shed excess
  load with typed REJECTED terminals while every admitted request still
  finishes bit-identically.

Every test runs under the per-test SIGALRM watchdog (test_chaos.py
pattern)."""

import dataclasses
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import (AdmissionRejected, ControlJournal,
                                     DisaggServingEngine,
                                     ReplicatedDecisionError, ServingEngine,
                                     ShardedServingEngine, TtlExpired,
                                     serving_mesh)
from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.checkpoint import (CheckpointIntegrityError,
                                                rebuild_request,
                                                snapshot_request)
from triton_dist_tpu.serving.kv_pool import KVPagePool
from triton_dist_tpu.serving.scheduler import Request, RequestState
from triton_dist_tpu.shmem import FaultPlan
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.shmem.faults import InjectedCrash

pytestmark = [pytest.mark.recovery, pytest.mark.serving]

WATCHDOG_S = 240          # per-test wall cap — generous, CPU CI is slow
N_REQUESTS = 50
MAX_STEPS = 6000          # far above any legitimate run length
WIRE = jnp.float8_e4m3fn  # pinned wire dtype (test_sharded_serving caveat)


@pytest.fixture(autouse=True)
def recovery_watchdog():
    """Hard per-test wall-clock watchdog: a hang anywhere in the
    crash/recover cycle must kill the test loudly, not stall the suite."""
    def boom(signum, frame):
        raise TimeoutError(
            f"recovery watchdog: test exceeded {WATCHDOG_S}s wall — "
            "the engine (or its recovery harness) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ---------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_model():
    """Chaos-scale 1-layer model — the sweep reruns the trace many times,
    so per-step cost dominates the budget."""
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
                    n_kv_heads=1, d_ff=64, max_seq_len=64),
        dtype=jnp.float32)
    params = init_params(jax.random.key(1), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def moe_model():
    """The micro MoE test_sharded_serving.py uses (d_model=128 is the A2A
    wire-lane floor)."""
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def role_ctx():
    return initialize_distributed(axis_names=("role",), mesh_shape=(2,))


def _trace(n=N_REQUESTS):
    """The 50-request forced-preemption trace (test_chaos idiom):
    staggered arrivals, prompts spanning 1..2 pages, mixed budgets."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        out.append((2 * i, list(rng.randint(1, 128, size=plen)), mnt))
    return out


# ------------------------------------------------------- engine factories
def _colocated(tiny_model, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 12)        # tight: forces preemption
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", None)
    return ServingEngine(params, cfg, **kw)


def _sharded(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)         # tight: forces preemption
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _disagg(tiny_model, ctx, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("num_prefill_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 64)
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("signal_deadline_steps", 3)
    return DisaggServingEngine(params, cfg, ctx=ctx, **kw)


# ----------------------------------------------------- crash/recover harness
def _crash_then_recover(mk_engine, arrivals, crash_step, checkpoint_every=8):
    """The whole crash-consistency cycle at one crash point: journaled run
    crashes at ``crash_step`` (returns None if the trace finished first —
    nothing to recover), then a FRESH engine of the same configuration
    restores from the journal and serves the not-yet-journaled remainder.
    Returns the recovered {rid: tokens} union."""
    journal = ControlJournal()
    eng = mk_engine(journal=journal, checkpoint_every=checkpoint_every,
                    fault_plan=FaultPlan(seed=3, crash_at=(crash_step,)))
    try:
        eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
        return None                      # ran to completion — no crash
    except InjectedCrash:
        pass
    # the journal is the durable artifact; everything else is rebuilt
    done = sum(1 for e in journal.entries if e["kind"] == "submit")
    eng2 = mk_engine(journal=journal, checkpoint_every=checkpoint_every)
    res = eng2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:],
                   recover=True)
    assert eng2.metrics.counters["restores"] == 1
    return res


def _journaled_steps(mk_engine, arrivals):
    """Total step count of the fault-free journaled run (the sweep's
    crash-point domain) plus its result (the golden)."""
    journal = ControlJournal()
    eng = mk_engine(journal=journal, checkpoint_every=8)
    res = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    return eng._steps, res, journal


# ------------------------------------------------------------ journal units
def test_journal_round_trip(tmp_path):
    j = ControlJournal()
    j.append("submit", 0, 0xAB, rid=0, prompt=[1, 2], max_new_tokens=3)
    j.append("admit", 1, 0xCD, rid=0, slot=2)
    j.record_checkpoint(4, 0xEF, {"live": []}, journal_seq=1)
    j.append("finish", 7, 0x11, rid=0, tokens=[5, 6, 7])
    assert len(j) == 4 and j.last_seq == 3
    assert [e["seq"] for e in j.suffix(1)] == [2, 3]
    assert j.last_checkpoint_entry()["journal_seq"] == 1
    assert j.counts() == {"submit": 1, "admit": 1, "checkpoint": 1,
                          "finish": 1}
    # bulky checkpoint state is elided from the post-mortem rendering
    tail = j.format_tail(8)
    assert "<elided>" in tail and "'live'" not in tail
    assert "digest=0x000000ab" in tail
    # jsonl save/load reconstitutes an equivalent journal
    p = tmp_path / "wal.jsonl"
    j.save(str(p))
    j2 = ControlJournal.load(str(p))
    assert j2.entries == j.entries


def test_journal_rejects_unknown_kind():
    with pytest.raises(AssertionError, match="unknown journal event"):
        ControlJournal().append("frobnicate", 0, 0)


def test_journal_path_mirror(tmp_path):
    p = tmp_path / "live.jsonl"
    j = ControlJournal(path=str(p))
    j.append("submit", 0, 1, rid=0, prompt=[1], max_new_tokens=1)
    j.close()
    assert ControlJournal.load(str(p)).entries == j.entries


def test_request_snapshot_round_trip():
    req = Request(rid=7, prompt=(1, 2, 3), max_new_tokens=4, eos_token=9)
    req.generated = [5, 6]
    req.prefill_cursor = 2
    req.preemptions = 1
    req.retries = 2
    back = rebuild_request(snapshot_request(req))
    assert back.rid == 7 and back.prompt == (1, 2, 3)
    assert back.state is RequestState.QUEUED
    assert back.prefill_cursor == 0 and back.generated == []
    assert back.preemptions == 1 and back.retries == 2


def test_pool_snapshot_audit_catches_tamper():
    pool = KVPagePool(8, 4, reserved=1)
    pool.alloc(0, 3)
    snap = pool.snapshot()
    ckpt_mod.audit_pool_snapshot(snap, pool.digest(), 8, 4, 1)  # clean
    snap["free"] = snap["free"][::-1]     # torn snapshot: free-list order
    with pytest.raises(CheckpointIntegrityError, match="torn or tampered"):
        ckpt_mod.audit_pool_snapshot(snap, pool.digest(), 8, 4, 1)


def test_prefix_snapshot_audit_catches_tamper():
    from triton_dist_tpu.serving import PrefixCache

    pool = KVPagePool(8, 4, reserved=1)
    cache = PrefixCache(pool, 4)
    pages = pool.alloc(0, 2)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8], pages)
    snap, dig = cache.snapshot(), cache.digest()
    ckpt_mod.audit_prefix_snapshot(snap, dig)               # clean
    snap[1][1][0] ^= 1                   # tamper one token of one run
    with pytest.raises(CheckpointIntegrityError, match="torn or tampered"):
        ckpt_mod.audit_prefix_snapshot(snap, dig)


def test_fault_plan_engine_tier():
    p = FaultPlan(seed=1, crash_at=(5,), digest_skew_at=(3,))
    assert p.crash(5, incarnation=0) and not p.crash(5, incarnation=1)
    assert not p.crash(4, incarnation=0)
    assert p.digest_skew(3, attempt=0) > 0
    assert p.digest_skew(3, attempt=1) == 0   # transient: attempt 0 only
    assert p.any_engine_faults
    # spec parsing round-trips the engine-tier keys
    q = FaultPlan.from_spec("seed=9,crash_at=4|7,skew=0.5")
    assert q.crash_at == (4, 7) and q.p_digest_skew == 0.5
    # probabilistic draws are seed-deterministic
    assert [q.digest_skew(s) for s in range(6)] == \
        [q.digest_skew(s) for s in range(6)]


# --------------------------------------------------- colocated crash sweep
def test_colocated_crash_sweep_quick(tiny_model):
    """Strided crash points over the full 50-request trace (every step is
    the slow-tier sweep): each crash+recover must reproduce the golden
    bit-for-bit."""
    arrivals = _trace()
    mk = lambda **kw: _colocated(tiny_model, **kw)          # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    assert len(golden) == N_REQUESTS
    stride = max(1, total // 8)
    points = list(range(1, total, stride))
    for s in points:
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None, f"crash at step {s} never fired"
        assert res == golden, f"crash at step {s}: not bit-identical"


def test_colocated_crash_sweep_prefix_cache(tiny_model):
    """Strided crash sweep with the prefix cache ON over a template-
    sharing trace (so adoption/COW state is live at most crash points).
    The restore contract — fresh pool, EMPTY cache, KV re-earned via
    re-prefill — must keep every crash+recover bit-identical to the
    fault-free cache-on golden, which itself must equal the cache-off
    golden (the ISSUE 13 transparency contract composed with ISSUE 9)."""
    rng = np.random.RandomState(13)
    tpls = [rng.randint(1, 128, size=16).tolist() for _ in range(3)]
    arrivals = []
    for i in range(24):
        t = int(rng.randint(0, 3))
        tail = rng.randint(1, 128, size=int(rng.randint(1, 5))).tolist()
        arrivals.append((2 * i, tpls[t] + tail, int(rng.randint(2, 6))))
    mk = lambda **kw: _colocated(tiny_model, prefix_cache=True,  # noqa: E731
                                 **kw)
    total, golden, _ = _journaled_steps(mk, arrivals)
    _, golden_off, _ = _journaled_steps(
        lambda **kw: _colocated(tiny_model, **kw), arrivals)
    assert golden == golden_off, "prefix cache changed tokens"
    stride = max(1, total // 6)
    for s in range(1, total, stride):
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None, f"crash at step {s} never fired"
        assert res == golden, f"crash at step {s}: not bit-identical"


@pytest.mark.slow
def test_colocated_crash_sweep_dense(tiny_model):
    arrivals = _trace()
    mk = lambda **kw: _colocated(tiny_model, **kw)          # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    for s in range(1, total):
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None and res == golden, f"crash at step {s}"


def test_colocated_checkpoint_cadence_sweep(tiny_model):
    """Recovery is cadence-independent: sparse checkpoints only lengthen
    the replay suffix, never change the outcome. cadence=None = no
    checkpoints at all — the whole journal is the suffix."""
    arrivals = _trace(24)
    mk = lambda **kw: _colocated(tiny_model, **kw)          # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    crash = total // 2
    for every in (2, 16, 64, None):
        res = _crash_then_recover(mk, arrivals, crash, checkpoint_every=every)
        assert res == golden, f"checkpoint_every={every}"
    # dense cadence actually produced checkpoints
    j = ControlJournal()
    eng = mk(journal=j, checkpoint_every=2)
    eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    assert eng.metrics.counters["checkpoints"] >= total // 4
    assert j.counts().get("checkpoint", 0) == eng.metrics.counters[
        "checkpoints"]


def test_restore_compiles_nothing(tiny_model):
    """The compile guard (ISSUE 9 acceptance): restore is host-only —
    the jit trace caches are untouched by restore itself, and the whole
    recovered run still ends at exactly one decode + one chunk program."""
    arrivals = _trace(24)
    mk = lambda **kw: _colocated(tiny_model, **kw)          # noqa: E731
    journal = ControlJournal()
    eng = mk(journal=journal, checkpoint_every=8,
             fault_plan=FaultPlan(seed=3, crash_at=(21,)))
    with pytest.raises(InjectedCrash):
        eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    done = sum(1 for e in journal.entries if e["kind"] == "submit")
    eng2 = mk(journal=journal, checkpoint_every=8)
    assert eng2._step._cache_size() == 0
    assert eng2._chunk_step._cache_size() == 0
    info = ckpt_mod.restore(eng2, ckpt_mod.latest(journal), journal)
    # restore dispatched NOTHING: both trace caches still empty
    assert eng2._step._cache_size() == 0
    assert eng2._chunk_step._cache_size() == 0
    assert info["replayed"] > 0
    res = eng2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:])
    golden = _colocated(tiny_model).run(max_steps=MAX_STEPS,
                                        arrivals=arrivals)
    assert res == golden
    stats = eng2.compile_stats
    assert stats["decode_compiles"] == 1
    assert stats["prefill_chunk_compiles"] == 1


def test_recover_without_checkpoint_replays_whole_journal(tiny_model):
    """A crash before the first checkpoint cadence still recovers: the
    journal alone (checkpoint=None path) is a complete WAL."""
    arrivals = _trace(16)
    mk = lambda **kw: _colocated(tiny_model, **kw)          # noqa: E731
    _, golden, _ = _journaled_steps(mk, arrivals)
    journal = ControlJournal()
    eng = mk(journal=journal, checkpoint_every=1000,  # never reached
             fault_plan=FaultPlan(seed=3, crash_at=(7,)))
    with pytest.raises(InjectedCrash):
        eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    assert journal.last_checkpoint_entry() is None
    done = sum(1 for e in journal.entries if e["kind"] == "submit")
    eng2 = mk(journal=journal)
    res = eng2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:],
                   recover=True)
    assert res == golden


# ----------------------------------------------------- sharded crash sweep
@pytest.mark.mesh
@pytest.mark.parametrize("tp,sp,ep,points", [
    (1, 1, 1, 2),
    (1, 2, 1, 2),
    (2, 2, 1, 1),
])
def test_sharded_crash_recovery(moe_model, tp, sp, ep, points):
    """Crash+recover on the mesh (n ∈ {1, 2, 4}): the restored engine
    reproduces the n-rank golden bit-for-bit — recovery composes with the
    cross-mesh bitwise contract instead of breaking it."""
    arrivals = _trace(20)
    mk = lambda **kw: _sharded(moe_model, tp, sp, ep, **kw)  # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    stride = max(1, total // (points + 1))
    for s in range(stride, total, stride)[:points] or [1]:
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None and res == golden, \
            f"mesh {tp}x{sp}x{ep}, crash at step {s}"


@pytest.mark.slow
@pytest.mark.mesh
@pytest.mark.parametrize("tp,sp,ep,stride", [
    (1, 1, 1, 1),
    (1, 2, 1, 3),
    (2, 2, 1, 6),
])
def test_sharded_crash_sweep_dense(moe_model, tp, sp, ep, stride):
    arrivals = _trace()
    mk = lambda **kw: _sharded(moe_model, tp, sp, ep, **kw)  # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    for s in range(1, total, stride):
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None and res == golden, f"crash at step {s}"


# ----------------------------------------------- digest-divergence rung
@pytest.mark.mesh
def test_digest_skew_absorbed_by_restore(moe_model):
    """A transient seeded digest divergence is QUARANTINED and absorbed:
    exactly one digest_recovery, tokens still golden, nothing raised."""
    arrivals = _trace(20)
    golden = _sharded(moe_model, 1, 2, 1).run(max_steps=MAX_STEPS,
                                              arrivals=arrivals)
    journal = ControlJournal()
    eng = _sharded(moe_model, 1, 2, 1, journal=journal, checkpoint_every=4,
                   digest_every=1,
                   fault_plan=FaultPlan(seed=5, digest_skew_at=(9,)))
    res = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    c = eng.metrics.counters
    assert c["digest_recoveries"] == 1
    assert c["restores"] == 1
    assert c["faults_injected"] >= 1
    assert res == golden
    assert journal.counts().get("digest_divergence") == 1
    assert eng.metrics.hist["digest_recovery_s"].count == 1


@pytest.mark.mesh
def test_persistent_digest_skew_escalates(moe_model):
    """Skew that re-diverges with no agreed step since the restore is
    PERSISTENT: the rung escalates (raises) instead of looping, and the
    report embeds the counters + journal tail post-mortem."""
    journal = ControlJournal()
    eng = _sharded(moe_model, 1, 2, 1, journal=journal, checkpoint_every=4,
                   digest_every=1)
    eng._digest_skew[1] = 1               # persistent per-rank corruption
    with pytest.raises(ReplicatedDecisionError, match="persistent skew"):
        eng.run(max_steps=MAX_STEPS, arrivals=_trace(8))
    assert eng.metrics.counters["digest_recoveries"] == 1  # tried once
    try:
        eng2 = _sharded(moe_model, 1, 2, 1, journal=ControlJournal(),
                        checkpoint_every=4, digest_every=1)
        eng2._digest_skew[1] = 1
        eng2.run(max_steps=MAX_STEPS, arrivals=_trace(8))
    except ReplicatedDecisionError as e:
        assert "counters" in str(e) and "journal tail" in str(e)


@pytest.mark.mesh
def test_digest_skew_without_journal_still_raises(moe_model):
    """No journal = no restore rung: the pre-ISSUE-9 hard raise stands
    (fail loud beats silently serving forked block tables)."""
    eng = _sharded(moe_model, 1, 2, 1, digest_every=1)
    eng._digest_skew[1] = 1
    with pytest.raises(ReplicatedDecisionError, match="digest diverged"):
        eng.run(max_steps=MAX_STEPS, arrivals=_trace(8))
    assert eng.metrics.counters["digest_recoveries"] == 0


# ------------------------------------------------------ disagg crash sweep
@pytest.mark.disagg
def test_disagg_crash_recovery(tiny_model, role_ctx):
    """Crash+recover on the disaggregated engine, including a crash with
    a migration IN FLIGHT: the restarted engine re-admits the migrated
    request through the rebuilt ledger (re-prefill + re-migrate), never
    fails it for having been half-handed-off."""
    arrivals = _trace(24)
    mk = lambda **kw: _disagg(tiny_model, role_ctx, **kw)   # noqa: E731
    total, golden, ref = _journaled_steps(mk, arrivals)
    # a crash point with a handoff in flight: a rid went MIGRATING at
    # step s (journal "handoff") and only finished at some step > s + 1
    finish_step = {e["rid"]: e["step"] for e in ref.entries
                   if e["kind"] == "finish"}
    midflight = [e["step"] for e in ref.entries if e["kind"] == "handoff"
                 and finish_step.get(e["rid"], 10**9) > e["step"] + 1]
    points = sorted({max(1, total // 3), midflight[0] if midflight
                     else total // 2, total - 1})
    for s in points:
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None and res == golden, f"crash at step {s}"


@pytest.mark.slow
@pytest.mark.disagg
def test_disagg_crash_sweep_dense(tiny_model, role_ctx):
    arrivals = _trace()
    mk = lambda **kw: _disagg(tiny_model, role_ctx, **kw)   # noqa: E731
    total, golden, _ = _journaled_steps(mk, arrivals)
    for s in range(1, total):
        res = _crash_then_recover(mk, arrivals, s)
        assert res is not None and res == golden, f"crash at step {s}"


@pytest.mark.disagg
def test_disagg_journal_records_migration(tiny_model, role_ctx):
    """The disagg journal carries the migration story: migrate attempts
    (with chunk + page counts), handoffs, and the per-event digest over
    BOTH workers' control planes."""
    journal = ControlJournal()
    eng = _disagg(tiny_model, role_ctx, journal=journal, checkpoint_every=8)
    eng.run(max_steps=MAX_STEPS, arrivals=_trace(8))
    counts = journal.counts()
    assert counts["migrate"] >= counts["handoff"] >= 1
    assert counts["finish"] == 8
    m = next(e for e in journal.entries if e["kind"] == "migrate")
    assert m["pages"] >= 1 and "chunk" in m and "attempt" in m
    # pool audit: nothing leaked through the journaled run
    assert eng.alloc_p.used_pages == 0 and eng.alloc_d.used_pages == 0
    eng.alloc_p.check(eng.channel.ledger)
    eng.alloc_d.check(eng.channel.ledger)


# ------------------------------------------------------- overload terminals
def test_queue_cap_rejects_typed(tiny_model):
    """2x oversubscription against a bounded queue: the excess is shed
    with typed AdmissionRejected terminals, every admitted request
    finishes bit-identical to the uncapped golden, and the engine never
    raises."""
    rng = np.random.RandomState(7)
    arrivals = [(0, list(rng.randint(1, 128, size=int(rng.randint(3, 17)))),
                 int(rng.randint(2, 6))) for _ in range(20)]
    mk = lambda **kw: _colocated(tiny_model, num_slots=2, num_pages=8,
                                 **kw)                       # noqa: E731
    golden = mk().run(max_steps=MAX_STEPS, arrivals=arrivals)
    journal = ControlJournal()
    eng = mk(queue_cap=4, journal=journal)
    res = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    c = eng.metrics.counters
    assert c["rejections"] > 0 and c["rejections"] == len(eng.failed)
    assert c["requests_submitted"] == 20
    for r in eng.failed:
        assert r.state is RequestState.REJECTED
        assert isinstance(r.failure, AdmissionRejected)
        assert not isinstance(r.failure, TtlExpired)
        assert "queue full" in str(r.failure)
    assert len(res) + c["rejections"] == 20
    for rid, toks in res.items():
        assert toks == golden[rid], f"rid {rid} not bit-identical"
    assert journal.counts()["reject"] == c["rejections"]


def test_ttl_expires_typed(tiny_model):
    """A slow-draining queue expires never-admitted requests past their
    TTL with typed TtlExpired terminals; admitted requests are immune
    (preemption requeues never expire) and finish bit-identically."""
    rng = np.random.RandomState(7)
    arrivals = [(0, list(rng.randint(1, 128, size=12)), 5)
                for _ in range(8)]
    mk = lambda **kw: _colocated(tiny_model, num_slots=1, num_pages=8,
                                 **kw)                       # noqa: E731
    golden = mk().run(max_steps=MAX_STEPS, arrivals=arrivals)
    journal = ControlJournal()
    eng = mk(ttl_steps=6, journal=journal)
    res = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    c = eng.metrics.counters
    assert c["expirations"] > 0 and c["rejections"] == 0
    for r in eng.failed:
        assert isinstance(r.failure, TtlExpired)
        assert "TTL" in str(r.failure)
    assert len(res) + c["expirations"] == 8
    for rid, toks in res.items():
        assert toks == golden[rid]
    assert journal.counts()["expire"] == c["expirations"]


def test_overload_survives_crash_recovery(tiny_model):
    """Overload terminals are journaled state: a crash after rejections
    restores them — the recovered engine reports the same terminal set
    and still finishes every admitted request bit-identically."""
    rng = np.random.RandomState(7)
    arrivals = [(0, list(rng.randint(1, 128, size=int(rng.randint(3, 17)))),
                 int(rng.randint(2, 6))) for _ in range(20)]
    mk = lambda **kw: _colocated(tiny_model, num_slots=2, num_pages=8,
                                 queue_cap=4, **kw)          # noqa: E731
    golden_eng = mk()
    golden = golden_eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    golden_failed = sorted(r.rid for r in golden_eng.failed)
    journal = ControlJournal()
    eng = mk(journal=journal, checkpoint_every=4,
             fault_plan=FaultPlan(seed=3, crash_at=(9,)))
    with pytest.raises(InjectedCrash):
        eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
    done = sum(1 for e in journal.entries
               if e["kind"] in ("submit", "reject"))
    eng2 = mk(journal=journal, checkpoint_every=4)
    res = eng2.run(max_steps=MAX_STEPS, arrivals=arrivals[done:],
                   recover=True)
    assert res == golden
    assert sorted(r.rid for r in eng2.failed) == golden_failed
    for r in eng2.failed:
        assert isinstance(r.failure, AdmissionRejected)


# -------------------------------------------------------------- post-mortem
def test_postmortem_embeds_journal_tail(tiny_model):
    """Engine error reports carry the forensic record: non-zero counters
    plus the last journal entries (bulky checkpoint payloads elided)."""
    journal = ControlJournal()
    eng = _colocated(tiny_model, journal=journal, checkpoint_every=4)
    eng.run(max_steps=MAX_STEPS, arrivals=_trace(6))
    pm = eng._postmortem()
    assert "counters" in pm and "journal tail" in pm
    assert "finish" in pm and "tokens_generated" in pm
    assert "<elided>" in pm or "checkpoint" not in journal.counts()
    # without a journal the report says so instead of crashing
    assert "<no journal attached>" in _colocated(tiny_model)._postmortem()
