"""Composable distributed train step: dp / tp / sp / pp / ep over one mesh.

Beyond the reference's scope (its kernels are forward-only; SURVEY.md §2.4:
"DP/PP … NOT present — the TPU build can note jax shard_map/pjit gives
composition for free") — this module is that composition, and what the
driver's multi-chip dryrun compiles:

- **dp**: batch dim sharded over ``plan.dp``.
- **tp**: Megatron param sharding (models.llama.param_specs) over ``plan.tp``;
  XLA inserts/overlaps the TP collectives in the backward too.
- **sp**: Megatron-style sequence parallelism — the residual stream between
  blocks is sequence-sharded over the *tp* axis (norms/elementwise run on
  S/tp rows; cf. SURVEY §5.7's note that the reference's SP story is
  decode-side only).
- **pp**: GPipe microbatch wavefront (parallel.pipeline) over ``plan.pp``.
- **ep**: MoE expert sharding over ``plan.ep`` (models.moe.moe_mlp_gshard's
  dispatch einsums become all-to-alls).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_dist_tpu.models import llama as llama_mod
from triton_dist_tpu.models import moe as moe_mod
from triton_dist_tpu.parallel.pipeline import pipeline_apply


@dataclasses.dataclass(frozen=True)
class ParallelPlan:
    dp: str | None = "dp"
    tp: str | None = "tp"
    pp: str | None = None
    ep: str | None = None
    cp: str | None = None    # context parallelism: ring attention over cp
    sp: bool = True          # sequence-shard the residual over the tp axis
    n_micro: int = 2         # pipeline microbatches (pp only)
    remat: bool = False

    def act_spec(self) -> P:
        if self.cp is not None:
            # the residual's sequence dim belongs to the cp ring; sp's
            # tp-sharding of the same dim would conflict
            return P(self.dp, self.cp, None)
        return P(self.dp, self.tp if self.sp else None, None)


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def _xent(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Mean next-token cross-entropy. logits [B,S,V] f32, tokens [B,S]."""
    lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    tgt = tokens[:, 1:]
    ll = jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_train_step(cfg, mesh, plan: ParallelPlan | None = None,
                    optimizer: optax.GradientTransformation | None = None,
                    ) -> tuple[Callable, Callable]:
    """Returns ``(init_fn, step_fn)``, both jitted over ``mesh``:

    - ``init_fn(key) -> TrainState`` with params laid out per the plan.
    - ``step_fn(state, tokens[B,S]) -> (TrainState, loss)``.

    ``cfg`` is a ``LlamaConfig`` (dense; supports pp) or ``MoEConfig``
    (GShard ep path; no pp).
    """
    plan = plan or ParallelPlan()
    optimizer = optimizer or optax.adamw(3e-4)
    is_moe = isinstance(cfg, moe_mod.MoEConfig)
    if plan.cp is not None:
        assert plan.pp is None and not is_moe, (
            "cp (ring attention) composes with dp/tp only for now")
    if is_moe:
        specs = moe_mod.moe_param_specs(cfg, tp=plan.tp, ep=plan.ep)
        init_raw = lambda key: moe_mod.init_moe_params(key, cfg)
    else:
        specs = llama_mod.param_specs(cfg, tp=plan.tp, pp=plan.pp)
        init_raw = lambda key: llama_mod.init_params(key, cfg)

    def shardings(tree_specs):
        return jax.tree.map(lambda s: NamedSharding(mesh, s), tree_specs,
                            is_leaf=lambda x: isinstance(x, P))

    def constrain(params):
        return jax.tree.map(lax.with_sharding_constraint, params,
                            shardings(specs))

    act_spec = plan.act_spec()

    # ---- forward/loss ----------------------------------------------------
    if is_moe and plan.pp is not None:
        pp, n_micro = plan.pp, plan.n_micro
        n_stages = mesh.shape[pp]
        b = cfg.base
        assert b.n_layers % n_stages == 0

        def stage_fn(blocks, h):
            S = h.shape[1]
            positions = jnp.arange(S)[None, :].repeat(h.shape[0], 0)

            def body(carry, p):
                x, aux = carry
                x, a = moe_mod.moe_block_apply(cfg, x, p, positions,
                                               act_spec)
                return (x, aux + a), None

            if plan.remat:
                body = jax.checkpoint(body)
            (h, aux), _ = lax.scan(body, (h, jnp.float32(0)), blocks)
            return h, aux

        block_pp_specs = jax.tree.map(lambda _: P(pp), specs["blocks"],
                                      is_leaf=lambda x: isinstance(x, P))

        def loss_fn(params, tokens):
            B, S = tokens.shape
            assert B % n_micro == 0, (B, n_micro)
            mb = B // n_micro
            x = params["embed"][tokens].astype(jnp.float32)
            x_micro = x.reshape(n_micro, mb, S, b.d_model)

            pipe = jax.shard_map(
                lambda blocks, xm: (lambda o, a: (o.astype(jnp.float32), a))(
                    *pipeline_apply(stage_fn, blocks,
                                    xm.astype(b.dtype), axis=pp,
                                    with_aux=True)),
                mesh=mesh,
                in_specs=(block_pp_specs, P()),
                out_specs=(P(), P()),
                axis_names={pp},
                check_vma=False,
            )
            outs, aux = pipe(params["blocks"], x_micro)
            x = outs.reshape(B, S, b.d_model)
            x = llama_mod.rmsnorm(x, params["final_norm"], b.norm_eps)
            logits = (x @ params["lm_head"]).astype(jnp.float32)
            return _xent(logits, tokens) + aux
    elif is_moe:
        def loss_fn(params, tokens):
            logits, aux = moe_mod.moe_forward(params, tokens, cfg,
                                              act_spec=act_spec,
                                              remat=plan.remat)
            return _xent(logits, tokens) + aux
    elif plan.pp is None:
        attn_fn = None
        if plan.cp is not None:
            assert not plan.sp, "cp shards the sequence dim; disable sp"
            assert cfg.head_dim % 128 == 0, (
                "ring attention needs a lane-multiple head dim, got "
                f"{cfg.head_dim}")
            from triton_dist_tpu.ops.ring_attention import ring_attention
            from triton_dist_tpu.shmem.context import ShmemContext
            sctx = ShmemContext(mesh=mesh)

            def attn_fn(q, k, v, sm_scale, _ctx=sctx):
                # llama layout [B, S, H, Dh] → ring layout [B, H, S, Dh];
                # heads ride the tp axis, batch the dp axis — each (dp, tp)
                # row is an independent ring over cp
                o = ring_attention(
                    _ctx, q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                    v.transpose(0, 2, 1, 3), axis=plan.cp, causal=True,
                    sm_scale=sm_scale, batch_axis=plan.dp,
                    head_axis=plan.tp)
                return o.transpose(0, 2, 1, 3)

        def loss_fn(params, tokens):
            logits = llama_mod.forward(params, tokens, cfg,
                                       act_spec=act_spec, remat=plan.remat,
                                       attn_fn=attn_fn)
            return _xent(logits, tokens)
    else:
        pp, n_micro = plan.pp, plan.n_micro
        n_stages = mesh.shape[pp]
        assert cfg.n_layers % n_stages == 0

        def stage_fn(blocks, h):
            S = h.shape[1]
            positions = jnp.arange(S)[None, :].repeat(h.shape[0], 0)

            def body(x, p):
                return llama_mod.block_apply(cfg, x, p, positions,
                                             act_spec), None

            if plan.remat:
                body = jax.checkpoint(body)
            h, _ = lax.scan(body, h, blocks)
            return h

        block_pp_specs = jax.tree.map(lambda _: P(pp), specs["blocks"],
                                      is_leaf=lambda x: isinstance(x, P))

        def loss_fn(params, tokens):
            B, S = tokens.shape
            assert B % n_micro == 0, (B, n_micro)
            mb = B // n_micro
            # f32 at the shard_map boundary: the transpose of a replicated
            # (P()) input is a psum over pp, and XLA CPU's AllReducePromotion
            # pass check-fails on the bf16 all-reduce it would produce
            x = params["embed"][tokens].astype(jnp.float32)
            x_micro = x.reshape(n_micro, mb, S, cfg.d_model)

            pipe = jax.shard_map(
                lambda blocks, xm: pipeline_apply(
                    stage_fn, blocks, xm.astype(cfg.dtype),
                    axis=pp).astype(jnp.float32),
                mesh=mesh,
                in_specs=(block_pp_specs, P()),
                out_specs=P(),
                axis_names={pp},
                check_vma=False,
            )
            outs = pipe(params["blocks"], x_micro)
            x = outs.reshape(B, S, cfg.d_model)
            x = llama_mod.rmsnorm(x, params["final_norm"], cfg.norm_eps)
            logits = (x @ params["lm_head"]).astype(jnp.float32)
            return _xent(logits, tokens)

    # ---- init / step -----------------------------------------------------
    @jax.jit
    def init_fn(key) -> TrainState:
        params = constrain(init_raw(key))
        opt_state = optimizer.init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    @jax.jit
    def step_fn(state: TrainState, tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = constrain(optax.apply_updates(state.params, updates))
        return TrainState(params, opt_state, state.step + 1), loss

    return init_fn, step_fn


__all__ = ["ParallelPlan", "TrainState", "make_train_step"]
