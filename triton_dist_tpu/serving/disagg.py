"""Disaggregated prefill/decode serving: KV page migration over the
one-sided shmem layer (ISSUE 6 tentpole; ROADMAP item 2).

The colocated ``ServingEngine`` time-slices ONE worker between chunked
prefill and decode, so a long prompt still steals step time from every
decoding request. This module splits the two roles across a 2-entry mesh
axis (default ``"role"``) and applies the paper's producer/consumer
thesis to the handoff:

- role 0, the **prefill worker**, owns a prompt queue and runs
  ``prefill_chunk_paged`` — at most one chunk per engine step, exactly
  like the colocated engine. As each chunk FINALIZES pages (a page is
  final once the cursor passes its last token, or at the final chunk),
  the migration kernel (``ops.page_migrate``) pushes them with one
  ``putmem_nbi`` per (layer, page) into the decode worker's pool at
  pre-reserved destination ids, then fires ONE counted ``signal_op`` per
  chunk (+n pages). PR 4's chunk cursor and ``KVPagePool.free_tail`` make
  the chunk the natural migration unit: a mid-prefill preemptee keeps its
  filled pages AND its already-migrated pages — nothing is re-sent, the
  resumed prefill migrates only what it newly finalizes.
- role 1, the **decode worker**, never sees a prompt token. Its
  ``KVPagePool`` hands out the destination pages at ADMISSION time
  ("remote reservation" — the prefill worker knows every chunk's
  destination before it runs), its block-table rows expose only the
  landed PREFIX of each request's pages (``KVPagePool.landed_row``), and
  a slot flips to ACTIVE the step the signals covering its prompt pages
  have all fired — signal-gated admission: no barrier, and the wait path
  is the in-kernel ``signal_wait_until``/``wait_recv`` chain, not a host
  round-trip. Only the FIRST TOKEN (one int, argmaxed on the prefill
  device by the final chunk) rides the host control plane.

Metrics isolation is the point: the decode worker's
``step_prefill_tokens`` is identically 0 and its per-step stall no
longer contains prefill work at all — decode ITL is independent of peer
prompt length (pinned by test in token/step space, where CPU-host noise
cannot fake it).

Determinism/bit-identity: migration is an exact page copy, the first
token is computed by the same fused chunk argmax, and decode runs the
same ``decode_multistep_paged`` program over the same page contents — so
per-request outputs are bit-identical to the colocated chunked engine,
including across preemptions on either worker (tests/test_disagg.py).

Topology: one driver process, SPMD over the role axis — every device
program (chunk, decode, migrate) is one ``shard_map`` program both roles
enter; the off-role shard runs the same program on PARKED inputs
(prompt_len 0 / limit 0 rows write only to its own reserved scratch
page). This is the interpret-mesh/TDT_SERIAL form of the two-process
deployment (see docs/serving.md for the launch recipe and the
``MP_BACKEND_NO_MULTIPROC`` caveat).
"""

from __future__ import annotations

import json
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.models.llama import (LlamaConfig,
                                          decode_multistep_paged,
                                          init_page_pool,
                                          prefill_chunk_paged)
from triton_dist_tpu.ops.page_migrate import migrate_pages
from triton_dist_tpu.serving import checkpoint as ckpt_mod
from triton_dist_tpu.serving.deadline import (Backoff, Deadline,
                                              EngineStallError)
from triton_dist_tpu.serving.engine import (class_label, mark_prefill_start,
                                            record_first_token)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.kv_pool import (KVPagePool, PageLedgerError,
                                             _fnv1a)
from triton_dist_tpu.serving.metrics import ServingMetrics
from triton_dist_tpu.serving.prefix_cache import PrefixCache
from triton_dist_tpu.serving.scheduler import (AdmissionRejected,
                                               ContinuousBatchingScheduler,
                                               Request, RequestState,
                                               SLOPolicy, TtlExpired)
from triton_dist_tpu.shmem import faults
from triton_dist_tpu.shmem.context import (ShmemContext,
                                           initialize_distributed)

PREFILL_ROLE = 0
DECODE_ROLE = 1


class MigrationSignalTimeout(RuntimeError):
    """A completed prefill's covering signals never arrived within the
    whole recovery ladder's budget (deadline + every retry rung). Either
    the transport dropped signals/pages repeatedly, the peer is dead, or
    a chunk was never sent — the message names the request, the per-chunk
    expected/landed counts and covered/missing pages (the ledger dump),
    so the operator can tell which. Since ISSUE 7 this is a PER-REQUEST
    failure reason (``Request.failure``), not an engine-wide crash."""


class SignalProtocolError(RuntimeError):
    """Over-signal: a chunk's landed count exceeded the number of pages
    the chunk was ever expected to deliver. A duplicated (or forged)
    signal increment is a protocol violation — before ISSUE 7 it
    silently inflated the count and could expose pages whose delivery
    was never actually confirmed; now it poisons exactly the affected
    request (degrade or fail), never the engine. Carries the ledger
    dump."""


class ChunkSignalLedger:
    """Host mirror of the per-chunk signal protocol.

    The KERNEL is the source of truth — ``landed`` counts come from the
    migration kernel's consumer-side report, which is ordered after every
    ``wait_recv`` of the chunk (ops/page_migrate.py) — the ledger only
    aggregates those reports per (request, chunk) so the scheduler can ask
    "which pages are covered?" without touching the device. Out-of-order
    chunk delivery is tolerated by construction: coverage is the union
    over COMPLETE chunks (landed >= expected), whatever order they
    completed in. Re-``expect``-ing a chunk (preemption restart or a
    deadline-triggered retry re-sends it) resets its count AND bumps its
    generation — the pages must land again before they count, and a
    report stamped with an older generation (a delayed delivery from a
    superseded attempt) is discarded as stale rather than double-counted.
    Over-signal (landed > expected within one generation) raises
    ``SignalProtocolError`` — a duplicate increment must never silently
    widen coverage.
    """

    def __init__(self):
        # rid -> {chunk_idx: [expected dst ids (tuple), landed count,
        #                     src ids (tuple, retry source), generation]}
        self._chunks: dict[int, dict[int, list]] = {}

    def expect(self, rid: int, chunk_idx: int, dst_ids,
               src_ids=(), generation: int = 0) -> None:
        self._chunks.setdefault(rid, {})[chunk_idx] = [
            tuple(int(p) for p in dst_ids), 0,
            tuple(int(p) for p in src_ids), int(generation)]

    def landed(self, rid: int, chunk_idx: int, count: int,
               generation: int = 0) -> bool:
        """Feed one kernel-reported landed count. Returns False (and
        counts nothing) when ``generation`` is stale — the chunk has been
        re-armed by a retry since this report's send was issued. Raises
        ``SignalProtocolError`` on over-signal."""
        ent = self._chunks.get(rid, {}).get(chunk_idx)
        if ent is None:
            raise KeyError(
                f"signal for unknown chunk {chunk_idx} of request {rid}")
        if int(generation) != ent[3]:
            return False
        ent[1] += int(count)
        if ent[1] > len(ent[0]):
            raise SignalProtocolError(
                f"over-signal on chunk {chunk_idx} of request {rid}: "
                f"{ent[1]} landed signals for {len(ent[0])} expected pages "
                f"(generation {ent[3]}) — a signal increment was "
                f"duplicated or forged. Ledger: {self.describe(rid)}")
        return True

    def chunk_complete(self, rid: int, chunk_idx: int) -> bool:
        ent = self._chunks.get(rid, {}).get(chunk_idx)
        return ent is not None and ent[1] >= len(ent[0])

    def covered(self, rid: int) -> set[int]:
        """Page ids whose delivery is fully signalled: the union over
        complete chunks. A chunk at 2/3 signals covers NOTHING — partial
        coverage cannot distinguish which pages landed."""
        out: set[int] = set()
        for ids, got, *_ in self._chunks.get(rid, {}).values():
            if got >= len(ids):
                out.update(ids)
        return out

    def expected(self, rid: int) -> set[int]:
        out: set[int] = set()
        for ids, *_ in self._chunks.get(rid, {}).values():
            out.update(ids)
        return out

    def complete(self, rid: int) -> bool:
        chunks = self._chunks.get(rid, {})
        return all(got >= len(ids) for ids, got, *_ in chunks.values())

    def incomplete_chunks(self, rid: int) -> list[tuple[int, tuple, tuple]]:
        """(chunk_idx, src_ids, dst_ids) of every chunk still short of
        full coverage — the retry work list. Chunks whose send recorded
        no source ids (pre-retention sends) are still listed; the caller
        decides whether their sources survive."""
        return [(ci, ent[2], ent[0])
                for ci, ent in sorted(self._chunks.get(rid, {}).items())
                if ent[1] < len(ent[0])]

    def generation(self, rid: int, chunk_idx: int) -> int | None:
        ent = self._chunks.get(rid, {}).get(chunk_idx)
        return None if ent is None else ent[3]

    def rids(self):
        return list(self._chunks.keys())

    def chunk_items(self, rid: int):
        """(chunk_idx, expected dst ids, landed count) triples — the
        audit interface ``KVPagePool.check(ledger=...)`` consumes."""
        return [(ci, ent[0], ent[1])
                for ci, ent in sorted(self._chunks.get(rid, {}).items())]

    def reset(self, rid: int) -> None:
        self._chunks.pop(rid, None)

    def describe(self, rid: int) -> str:
        """The ledger dump (ISSUE 7 satellite): per-chunk expected vs
        landed counts plus which pages are covered/missing — every typed
        failure reason embeds this, so a field report is actionable
        without a debugger."""
        chunks = self._chunks.get(rid, {})
        if not chunks:
            return "no chunks recorded"
        per_chunk = ", ".join(
            f"chunk {ci}: {got}/{len(ids)} signals gen {gen} "
            f"(pages {list(ids)})"
            for ci, (ids, got, _src, gen) in sorted(chunks.items()))
        covered = self.covered(rid)
        missing = sorted(self.expected(rid) - covered)
        return (f"{per_chunk}; covered pages {sorted(covered)}, "
                f"missing {missing}")


class PageMigrationChannel:
    """The prefill worker's sending half: guards, launches the migration
    kernel for one chunk's finalized pages, and feeds the ledger from the
    kernel's consumer-side landed report.

    Fault injection (ISSUE 7) is consulted HERE, per send event — this is
    the host-tier twin of the trace-time device hooks: on CPU the
    interpret-mode kernel elides the remote ``signal_op`` (delivery rides
    the DMA recv semaphores), so the only place a CPU chaos test can
    observe a lost/duplicated/late *signal* is the report path between
    the kernel and the ledger. A drop loses the landed report (the pages
    may well be there — the protocol must not believe it until a signal
    says so), a dup doubles the counted increment, a delay buffers the
    report for k engine steps (delivered by ``tick``), and a dead peer
    suppresses the launch entirely — nothing lands, nothing reports.
    Every attempt of every chunk gets a monotonically increasing attempt
    number, stamped into the kernel send as its generation tag and
    echoed back in the landed report (ops/page_migrate.py)."""

    def __init__(self, launch, pmax: int, reserved: int,
                 metrics: ServingMetrics, consumer: int = DECODE_ROLE,
                 plan: "faults.FaultPlan | None" = None, clock=None):
        self.ledger = ChunkSignalLedger()
        self._launch = launch          # jitted migrate_pages closure
        self.pmax = pmax
        self.reserved = reserved
        self.metrics = metrics
        self.consumer = consumer
        self.plan = plan
        self._clock = clock or (lambda: 0)   # engine-step supplier
        self._attempt: dict[tuple[int, int], int] = {}
        # delayed landed reports: (deliver_at_step, rid, chunk, count, gen)
        self._delayed: list[tuple[int, int, int, int, int]] = []

    def _active_plan(self):
        return self.plan if self.plan is not None else faults.active_plan()

    def forget(self, rid: int) -> None:
        """Drop attempt counters for a request leaving the system
        (finished/failed). Its ledger entries are reset separately; any
        still-buffered delayed report for it is delivered to a missing
        entry and discarded as stale."""
        for key in [k for k in self._attempt if k[0] == rid]:
            del self._attempt[key]

    def send_chunk(self, rid: int, chunk_idx: int, src_ids, dst_ids,
                   pool_k, pool_v):
        """Push one chunk's pages; returns the threaded pools. The id
        arrays are padded to the compiled ``pmax`` width (one program for
        every chunk size); padding is never dereferenced by the kernel.
        Re-sending the same chunk (preemption restart or deadline retry)
        bumps its attempt number/generation."""
        n = len(src_ids)
        assert n == len(dst_ids), (src_ids, dst_ids)
        assert 0 < n <= self.pmax, (n, self.pmax)
        for p in (*src_ids, *dst_ids):
            if p < self.reserved:
                raise PageLedgerError(
                    f"refusing to migrate reserved scratch page {p} "
                    f"(request {rid}) — scratch is engine-local parking")
        attempt = self._attempt.get((rid, chunk_idx), -1) + 1
        self._attempt[(rid, chunk_idx)] = attempt
        self.ledger.expect(rid, chunk_idx, dst_ids, src_ids=src_ids,
                           generation=attempt)
        plan = self._active_plan()
        now = self._clock()
        if plan is not None and plan.peer_dead(now):
            # dead link: the launch never happens — no pages move, no
            # report arrives, and the ledger stays at 0/n until the
            # consumer-side deadline walks the recovery ladder
            self.metrics.inc("faults_injected")
            return pool_k, pool_v
        src = np.zeros(self.pmax, np.int32)
        dst = np.zeros(self.pmax, np.int32)
        src[:n] = src_ids
        dst[:n] = dst_ids
        t0 = time.perf_counter()
        pool_k, pool_v, landed = self._launch(
            jnp.asarray(src), jnp.asarray(dst),
            jnp.asarray([n], np.int32), jnp.asarray([attempt], np.int32),
            pool_k, pool_v)
        row = np.asarray(landed)[self.consumer]
        got, echoed = int(row[0]), int(row[1])
        assert echoed == attempt, (
            f"migrate kernel echoed tag {echoed} for send attempt "
            f"{attempt} (rid {rid} chunk {chunk_idx})")
        dt = time.perf_counter() - t0
        self.metrics.inc("migrate_chunks")
        self.metrics.observe("migrate_s", dt)
        action, k = (("ok", 0) if plan is None
                     else plan.signal_action(rid, chunk_idx, attempt))
        if action == "drop":
            # the signal never arrives: pages moved, the protocol must
            # not (and does not) believe it
            self.metrics.inc("faults_injected")
            return pool_k, pool_v
        if action == "delay":
            self.metrics.inc("faults_injected")
            self._delayed.append((now + k, rid, chunk_idx, got, attempt))
            return pool_k, pool_v
        if action == "dup":
            self.metrics.inc("faults_injected")
            got *= 2                   # duplicated increment — over-signal
        if self.ledger.landed(rid, chunk_idx, got, generation=attempt):
            self.metrics.inc("pages_migrated", min(got, n))
            self.metrics.observe("migrate_pages_per_chunk", min(got, n))
        return pool_k, pool_v

    def tick(self, now: int) -> list[tuple[int, Exception]]:
        """Deliver delayed landed reports whose time has come. Returns
        the (rid, error) pairs of any report that tripped a protocol
        error on delivery — the engine routes those into the affected
        request's failure domain. Reports for unknown chunks (the
        request finished/failed/was re-armed meanwhile) and stale
        generations are discarded and counted as ``stale_signals``."""
        if not self._delayed:
            return []
        due = [d for d in self._delayed if d[0] <= now]
        self._delayed = [d for d in self._delayed if d[0] > now]
        poisoned: list[tuple[int, Exception]] = []
        for _, rid, chunk_idx, got, gen in due:
            try:
                fresh = self.ledger.landed(rid, chunk_idx, got,
                                           generation=gen)
            except KeyError:
                fresh = False
            except SignalProtocolError as e:
                poisoned.append((rid, e))
                continue
            if fresh:
                self.metrics.inc("pages_migrated", got)
                self.metrics.observe("migrate_pages_per_chunk", got)
            else:
                self.metrics.inc("stale_signals")
        return poisoned


class DisaggServingEngine:
    """Continuous-batching serving with prefill and decode on separate
    workers, KV handed off by page migration (module docstring).

    ``num_pages``/``page_size`` size EACH role's pool (plus one scratch
    page per role). ``num_slots`` is the decode batch width;
    ``num_prefill_slots`` bounds concurrent chunked prefills.
    ``prefill_chunk`` is mandatory here — chunks ARE the migration unit.

    Recovery ladder (ISSUE 7): a MIGRATING request's wait for covering
    signals runs against a ``Deadline`` of ``signal_deadline_steps``
    decode-worker steps. On expiry the engine RETRIES — re-issues the
    ``migrate_pages`` send for every incomplete chunk (the prefill worker
    RETAINS its source pages through MIGRATING precisely so the bytes
    still exist to re-send) — with exponential backoff over at most
    ``max_retries`` rungs. When the rungs run dry (or the sources are
    gone, or a chunk was never sent, or the ledger detected over-signal)
    the request DEGRADES: the decode worker re-prefills the prompt
    locally into its own reserved pages using the same compiled chunk
    program (real inputs in the DECODE_ROLE row — the PR-6 preemption
    fallback run in place, without bouncing through the possibly-dead
    peer), up to ``max_degradations`` times. Only with
    ``allow_degradation=False`` (a decode worker genuinely unable to
    prefill) or the degradation budget spent does the request become
    ``FAILED`` — with a typed reason carrying the ledger dump — while
    the engine and every other request keep running. ``engine.run`` adds
    a global progress watchdog (``stall_deadline_steps``, auto-sized
    above the whole ladder budget) raising ``EngineStallError`` so no
    residual bug can ever present as a hang. ``fault_plan`` injects a
    seeded :class:`~triton_dist_tpu.shmem.faults.FaultPlan` into the
    migration channel (tests/test_chaos.py drives this).

    Request lifecycle: QUEUED (prefill queue) → PREFILLING (prefill slot;
    decode-side pages reserved; chunks run and migrate) → MIGRATING
    (prefill done, first token in hand, prefill-side pages RETAINED as
    the retry source; waiting for a decode slot + covering signals) →
    ACTIVE (decoding; prefill-side pages released on the flip) →
    FINISHED, with the FAILED terminal only at the bottom of the ladder.
    A decode-side victim loses its pages AND its migrated KV: it requeues
    at the FRONT of the prefill queue and re-prefills from scratch —
    greedy determinism regenerates identical tokens. A prefill-side
    victim (``force_preempt_prefill``) keeps its filled + migrated pages
    and resumes at its chunk cursor.
    """

    def __init__(self, params: dict, cfg: LlamaConfig,
                 ctx: ShmemContext | None = None, axis: str = "role",
                 num_slots: int = 4, num_prefill_slots: int = 2,
                 page_size: int = 16, num_pages: int = 64,
                 pages_per_seq: int = 8, prefill_chunk: int = 16,
                 decode_horizon: int = 1, eos_id: int | None = None,
                 ffn=None, signal_deadline_steps: int = 16,
                 max_retries: int = 3, allow_degradation: bool = True,
                 max_degradations: int = 1,
                 stall_deadline_steps: int | None = None,
                 wall_deadline_s: float | None = None,
                 fault_plan: "faults.FaultPlan | None" = None,
                 metrics: ServingMetrics | None = None,
                 metrics_decode: ServingMetrics | None = None,
                 journal: ControlJournal | None = None,
                 checkpoint_every: int | None = None,
                 queue_cap: int | None = None,
                 ttl_steps: int | None = None,
                 prefix_cache: bool = False,
                 slo: SLOPolicy | None = None,
                 artifact=None, artifact_key: str | None = None):
        assert prefill_chunk >= 1 and decode_horizon >= 1
        assert signal_deadline_steps >= 1 and max_retries >= 0
        assert checkpoint_every is None or checkpoint_every >= 1
        assert queue_cap is None or queue_cap >= 1
        assert ttl_steps is None or ttl_steps >= 1
        assert checkpoint_every is None or journal is not None, (
            "checkpoint_every needs a journal to record into")
        if ctx is None:
            ctx = initialize_distributed(axis_names=(axis,), mesh_shape=(2,))
        assert ctx.axis_size(axis) == 2, (
            f"disaggregation needs exactly 2 ranks on axis {axis!r}")
        self.ctx = ctx
        self.axis = axis
        self.params = params
        self.cfg = cfg
        self.page_size = page_size
        self.pages_per_seq = pages_per_seq
        self.num_slots = num_slots
        self.prefill_chunk = prefill_chunk
        self.decode_horizon = decode_horizon
        self.eos_id = eos_id
        self.signal_deadline_steps = signal_deadline_steps
        self.max_retries = max_retries
        self.allow_degradation = allow_degradation
        self.max_degradations = max_degradations
        self.wall_deadline_s = wall_deadline_s
        # the whole ladder's worst-case wait for ONE request: the initial
        # deadline plus every backoff rung — the stall watchdog must sit
        # safely above it, or legitimate ladder waits would trip it
        ladder = signal_deadline_steps * (2 ** (max_retries + 1) - 1)
        self._stall_steps = (stall_deadline_steps if stall_deadline_steps
                             is not None else max(256, 4 * ladder))
        # TTFT lives on the prefill worker's panel, ITL on the decode
        # worker's — the isolation the disaggregation exists to provide
        self.metrics = metrics or ServingMetrics()
        self.metrics_decode = metrics_decode or ServingMetrics()

        # ONE symmetric pool pair: each role owns an identical local
        # [L, P+1, Hkv, ps, D] shard (id 0 reserved as that role's scratch
        # page); the migration kernel's remote refs resolve into the peer
        # shard by construction.
        ref = init_page_pool(cfg, 1, page_size)      # shape/dtype template
        local = (cfg.n_layers, num_pages + 1) + ref["k"].shape[2:]
        self.pool_k = ctx.create_symm_tensor(local, ref["k"].dtype, axis=axis)
        self.pool_v = ctx.create_symm_tensor(local, ref["v"].dtype, axis=axis)
        self.alloc_p = KVPagePool(num_pages + 1, page_size, reserved=1)
        self.alloc_d = KVPagePool(num_pages + 1, page_size, reserved=1)
        # prefix cache (ISSUE 13) lives on the PREFILL pool only: hits
        # skip the chunk compute but every page still migrates, so the
        # decode worker never needs to know a prefix was cached. Adopted
        # pages must be solely owned (check_migratable's refcount clause),
        # so adoption stops at the first matched page another live
        # request still references.
        self.prefix_cache = (PrefixCache(self.alloc_p, page_size)
                             if prefix_cache else None)
        # the bounded admission queue (ISSUE 9) guards the PREFILL worker's
        # intake — that is where fresh arrivals wait; preemption requeues
        # (front=True) are exempt by scheduler construction
        # SLO policy (ISSUE 14) attaches to the PREFILL scheduler — that is
        # the only admission point; the decode scheduler stays policy-free
        # and its class-aware victim ordering reads the shed_level stamp
        # each request carries
        self.slo = slo
        self.sched_p = ContinuousBatchingScheduler(num_prefill_slots,
                                                   queue_cap=queue_cap,
                                                   policy=slo)
        self.sched_d = ContinuousBatchingScheduler(num_slots)
        # crash consistency (ISSUE 9): journal + checkpoint cadence + the
        # overload knobs, mirroring ServingEngine's control surface
        self.journal = journal
        self.checkpoint_every = checkpoint_every
        self.ttl_steps = ttl_steps
        self._fault_plan = fault_plan
        self._journal_muted = False
        self._replaying = False
        self._incarnation = 0
        self._last_ckpt_step = -1
        self._rejected: list[Request] = []
        self._handoff: deque[Request] = deque()   # MIGRATING, no slot yet
        self._dslot: dict[int, int] = {}          # rid -> decode slot
        self._wait_steps: dict[int, int] = {}     # rid -> signal-wait steps
        # recovery ladder state (ISSUE 7): per-MIGRATING-request deadline
        # + backoff; requests whose ledger tripped a protocol error
        # (poisoned coverage — degrade/fail on sight, never retry); rids
        # currently re-prefilling LOCALLY on the decode worker
        self._recovery: dict[int, tuple[Deadline, Backoff]] = {}
        self._poisoned: dict[int, Exception] = {}
        self._local_prefill: set[int] = set()
        self._finished: list[Request] = []
        self._failed: list[Request] = []
        self._next_rid = 0
        self._steps = 0

        # decode-worker slot mirrors (control plane); the [2, B] stacked
        # device arrays are authoritative between dispatches — row
        # PREFILL_ROLE is permanently parked (zeros → scratch page)
        B = num_slots
        self._token = np.zeros(B, np.int32)
        self._pos = np.zeros(B, np.int32)
        self._bt = np.zeros((B, pages_per_seq), np.int32)
        self._z_row = np.zeros(B, np.int32)
        self._z_bt = np.zeros((B, pages_per_seq), np.int32)
        # uploads are placed with the stacked-role sharding up front so the
        # decode program sees ONE argument signature from the very first
        # dispatch (host-upload steps and steady-state feedback steps would
        # otherwise compile two variants — the compile guard pins this)
        self._up = lambda a: ctx.shard(jnp.asarray(a), P(axis))
        self._token_dev = self._up(np.stack([self._z_row, self._token]))
        self._pos_dev = self._up(np.stack([self._z_row, self._pos]))
        self._bt_dev = self._up(np.stack([self._z_bt, self._bt]))
        self._dirty = False

        # -- the three device programs (each ONE compiled SPMD program
        # both roles enter; the off-role shard runs on parked inputs) ----
        pspec = P(axis)

        def chunk_f(p, toks, start, plen, kp, vp, bt):
            pages = {"k": kp[0], "v": vp[0]}
            tok, pages = prefill_chunk_paged(
                p, toks[0], start[0], plen[0], cfg, pages, bt[0], ffn=ffn)
            return tok[None], pages["k"][None], pages["v"][None]

        chunk_sm = ctx.shard_map(
            chunk_f, in_specs=(P(),) + (pspec,) * 6,
            out_specs=(pspec,) * 3)

        K = decode_horizon

        def dec_f(p, tok, pos, kp, vp, bt, lim):
            pages = {"k": kp[0], "v": vp[0]}
            toks, tok2, pos2, pages = decode_multistep_paged(
                p, tok[0], pos[0], cfg, pages, bt[0], lim[0],
                horizon=K, eos_id=eos_id, ffn=ffn)
            return (toks[None], tok2[None], pos2[None],
                    pages["k"][None], pages["v"][None])

        dec_sm = ctx.shard_map(
            dec_f, in_specs=(P(),) + (pspec,) * 6,
            out_specs=(pspec,) * 5)

        def mig_f(src, dst, n, tag, kp, vp):
            return migrate_pages(ctx, kp, vp, src, dst, n, axis=axis,
                                 producer=PREFILL_ROLE,
                                 consumer=DECODE_ROLE, tag=tag)

        if jax.default_backend() == "cpu":   # CPU: donation unsupported
            self._chunk_step = jax.jit(chunk_sm)
            self._dec_step = jax.jit(dec_sm)
            self._migrate = jax.jit(mig_f)
        else:
            self._chunk_step = jax.jit(chunk_sm, donate_argnums=(4, 5))
            self._dec_step = jax.jit(dec_sm, donate_argnums=(3, 4))
            self._migrate = jax.jit(mig_f, donate_argnums=(4, 5))

        # AOT artifact seeding (ISSUE 15): replace all three SPMD programs
        # with the artifact's deserialized executables BEFORE the channel
        # captures the migrate launch — zero fresh traces from cold start
        # to first token (compile_stats reports aot_programs)
        self._aot_artifact = artifact
        if artifact is not None:
            self._aot_key = artifact_key or "disagg"
            self._chunk_step = artifact.program(self._aot_key, "chunk")
            self._dec_step = artifact.program(self._aot_key, "decode")
            self._migrate = artifact.program(self._aot_key, "migrate")

        # widest possible per-chunk migration: a C-token chunk can
        # finalize at most C//ps whole pages plus the straddle page it
        # completes plus the final chunk's partial last page — and a
        # RETRY may need to re-send a whole prompt's pages in one call
        pmax = max(prefill_chunk // page_size + 2, pages_per_seq)

        # TDT_SIGCHECK=1: build-time determinism lint of the three role-
        # stacked SPMD programs (sigcheck rung 0 — docs/debugging.md);
        # trace-only, abstract args, raises before any request is admitted
        if os.environ.get("TDT_SIGCHECK") == "1":
            from triton_dist_tpu.analysis.lint import lint_engine_programs
            abstract = lambda tree: jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)
            i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
            kv = (abstract(self.pool_k), abstract(self.pool_v))
            lint_engine_programs({
                "prefill_chunk_paged": (chunk_sm, (
                    abstract(self.params), i32(2, prefill_chunk), i32(2),
                    i32(2), *kv, i32(2, pages_per_seq))),
                "decode_multistep_paged": (dec_sm, (
                    abstract(self.params), i32(2, B), i32(2, B), *kv,
                    i32(2, B, pages_per_seq), i32(2, B))),
                "migrate_pages": (mig_f, (
                    i32(pmax), i32(pmax), i32(1), i32(), *kv)),
            }, type(self).__name__)

        self.channel = PageMigrationChannel(
            self._migrate, pmax, reserved=1, metrics=self.metrics,
            consumer=DECODE_ROLE, plan=fault_plan,
            clock=lambda: self._steps)

    # -- request intake (prefill worker) ----------------------------------
    def _ttl_for(self, req: Request) -> int | None:
        """Class TTL override (ISSUE 14) beats the engine-wide knob."""
        spec = self.sched_p.class_spec(req)
        if spec is not None and spec.ttl_steps is not None:
            return spec.ttl_steps
        return self.ttl_steps

    def submit(self, prompt, max_new_tokens: int, rid: int | None = None,
               tenant: str | None = None, cls: str | None = None) -> int:
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        assert prompt and max_new_tokens >= 1
        total = len(prompt) + max_new_tokens - 1
        need = -(-total // self.page_size)
        assert need <= self.pages_per_seq, (
            f"request needs {need} pages > pages_per_seq "
            f"{self.pages_per_seq}")
        assert need <= self.alloc_d.num_pages - self.alloc_d.reserved, (
            f"request needs {need} pages > decode pool size")
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid + 1)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      eos_token=self.eos_id, submit_step=self._steps,
                      submit_time=time.perf_counter())
        self.sched_p.stamp(req, tenant=tenant, cls=cls)
        self.metrics.inc("requests_submitted")
        self.metrics.inc_class("requests_submitted", class_label(req))
        # bounded admission (ISSUE 9): shed fresh arrivals at capacity —
        # journal replay bypasses the cap (the WAL holds the authoritative
        # accept/reject decisions). Per-class caps (ISSUE 14) shed batch
        # while chat still admits.
        if self.sched_p.at_capacity_for(req.cls) and not self._replaying:
            spec = self.sched_p.class_spec(req)
            cap = (spec.queue_cap if spec is not None
                   and spec.queue_cap is not None
                   and not self.sched_p.at_capacity
                   else self.sched_p.queue_cap)
            req.state = RequestState.REJECTED
            req.failure = AdmissionRejected(
                f"admission queue full for class {req.cls!r} (cap {cap}) — "
                f"request {rid} rejected")
            self._rejected.append(req)
            self.metrics.inc("rejections")
            self.metrics.inc_class("rejections", class_label(req))
            self._jlog("reject", rid=rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)
            return rid
        ttl = self._ttl_for(req)
        if ttl is not None:
            req.deadline = Deadline(ttl, req.submit_step)
        self.sched_p.submit(req)
        self._jlog("submit", rid=rid, prompt=list(prompt),
                   max_new_tokens=max_new_tokens,
                   tenant=req.tenant, cls=req.cls)
        return rid

    # -- prefill worker ----------------------------------------------------
    def _can_hold(self, req: Request) -> bool:
        """Admission needs BOTH sides: prefill pages to compute into (a
        mid-prefill preemptee kept its filled ones) and the decode-side
        reservation (kept across prefill preemptions)."""
        need = -(-len(req.prompt) // self.page_size)
        need_p = need - len(self.alloc_p.pages_of(req.rid))
        need_d = need - len(self.alloc_d.pages_of(req.rid))
        # refcount-0 cached pages are reclaimable capacity on the prefill
        # side (no hit discount: adoption trades evictable for owed 1:1,
        # so the bound stays valid whether or not the prompt hits)
        avail_p = self.alloc_p.free_pages + (
            self.prefix_cache.evictable if self.prefix_cache else 0)
        return (avail_p >= max(need_p, 0)
                and self.alloc_d.free_pages >= max(need_d, 0))

    def _cache_adopt(self, req: Request) -> None:
        """Match the prompt against the prefix index and adopt the
        longest SOLELY-ADOPTABLE prefix of the hit: every adopted page
        must be refcount-0 (on the cached LRU list) so that after
        ``acquire`` it is solely owned and ``check_migratable`` accepts
        it. A matched page another live request still references
        truncates the adoption there — correctness never depends on the
        truncation, the chunks just recompute."""
        cache = self.prefix_cache
        if (cache is None or req.prefill_cursor > 0
                or self.alloc_p.holds(req.rid)):
            return
        hit = cache.match(req.prompt)
        solo = []
        for p in hit:
            if self.alloc_p.refcount(p) != 0:
                break
            solo.append(p)
        if not solo:
            self.metrics.inc("prefix_misses")
            return
        self.alloc_p.acquire(req.rid, solo)
        req.cache_hit_tokens = len(solo) * self.page_size
        self.metrics.inc("prefix_hits")
        self.metrics.inc("prefix_hit_tokens", req.cache_hit_tokens)

    def _admit_prefill(self, slot: int, req: Request) -> None:
        self._cache_adopt(req)
        sp = len(req.prompt)
        need = -(-sp // self.page_size)
        have_p = len(self.alloc_p.pages_of(req.rid))
        if need > have_p:
            short = (need - have_p) - self.alloc_p.free_pages
            if short > 0 and self.prefix_cache is not None:
                self.metrics.inc("prefix_evictions",
                                 self.prefix_cache.evict(short))
            got = self.alloc_p.alloc(req.rid, need - have_p)
            assert got is not None, "admissible() guaranteed the pages"
        # remote reservation: the decode worker's pages for this prompt
        # are fixed NOW, so every later chunk knows its destination ids
        # without a round-trip — and landed KV survives prefill-side
        # preemption because the reservation does
        have_d = len(self.alloc_d.pages_of(req.rid))
        if need > have_d:
            got = self.alloc_d.alloc(req.rid, need - have_d)
            assert got is not None, "admissible() guaranteed the pages"
        self.sched_p.activate(slot, req)
        self._jlog("admit", rid=req.rid, slot=slot)
        req.state = RequestState.PREFILLING
        mark_prefill_start(req, self.metrics, self._steps)
        self.metrics.inc("prefills")

    def _migrate_finalized(self, req: Request, start: int,
                           cursor_new: int) -> None:
        """Send exactly the pages this chunk FINALIZED: whole pages whose
        last token the cursor just passed, plus (on the final chunk) the
        partial last page. Derived from the cursor, so each page is sent
        exactly once per prefill attempt and a cursor-resumed preemptee
        never re-sends what it migrated before the eviction."""
        ps = self.page_size
        sp = len(req.prompt)
        done_before = start // ps
        done_after = (-(-sp // ps) if cursor_new >= sp
                      else cursor_new // ps)
        if done_after <= done_before:
            return
        src = self.alloc_p.pages_of(req.rid)[done_before:done_after]
        dst = self.alloc_d.pages_of(req.rid)[done_before:done_after]
        self.alloc_p.check_migratable(req.rid, src)
        self.alloc_d.check_migratable(req.rid, dst)
        chunk_idx = start // self.prefill_chunk
        self.pool_k, self.pool_v = self.channel.send_chunk(
            req.rid, chunk_idx, src, dst, self.pool_k, self.pool_v)
        # the migration attempt rides the journal (ISSUE 9): a restarted
        # decode worker re-admits migrated requests through the rebuilt
        # ledger instead of failing them — the journal records that the
        # attempt happened, the ledger decides whether it still counts
        self._jlog("migrate", rid=req.rid, chunk=chunk_idx,
                   pages=len(src), attempt=self.channel._attempt.get(
                       (req.rid, chunk_idx), 0))

    def _oldest_local_prefill(self) -> tuple[int, Request] | None:
        """Oldest (by admission ticket) degraded request re-prefilling
        locally on the decode worker — the DECODE_ROLE row's candidate
        for this step's chunk dispatch."""
        best = None
        for rid in self._local_prefill:
            slot = self._dslot[rid]
            r = self.sched_d.slots[slot]
            if r is None:
                continue
            if best is None or r.admitted_seq < best[1].admitted_seq:
                best = (slot, r)
        return best

    def _dispatch_chunks(self) -> int:
        """At most ONE chunk per WORKER per step (Sarathi co-scheduling,
        same policy as the colocated engine), in a single dispatch of the
        role-symmetric chunk program: the PREFILL_ROLE row advances the
        oldest PREFILLING prefill slot; the DECODE_ROLE row — parked in
        healthy operation — carries a DEGRADED request's local re-prefill
        chunk (ISSUE 7): same compiled program, real tokens/block-table
        in the decode row, writing straight into the decode worker's own
        reserved pages. That is what makes degradation free of new
        compiles AND free of the possibly-dead peer.

        The prefill row's final chunk hands the request off as MIGRATING
        with its device-argmaxed first token on the host control plane;
        its prefill-side pages are RETAINED (the retry source) until the
        decode side confirms coverage. The decode row's final chunk flips
        its request straight to ACTIVE — the KV and first token were
        recomputed locally, no signals to wait for. Returns PREFILL-row
        prompt tokens processed (the decode row's tokens are accounted
        separately as degraded_prefill_tokens — the decode worker's
        step_prefill_tokens isolation invariant only covers healthy
        operation)."""
        slot_p, req_p = None, None
        for i, r in enumerate(self.sched_p.slots):
            if (r is not None and r.state is RequestState.PREFILLING
                    and (req_p is None
                         or r.admitted_seq < req_p.admitted_seq)):
                slot_p, req_p = i, r
        local = self._oldest_local_prefill()
        if slot_p is None and local is None:
            return 0
        C = self.prefill_chunk
        # cache-hit fast path (ISSUE 13): a chunk fully inside the
        # adopted prefix skips the device compute — its pages already
        # hold that KV — but still advances the cursor and still
        # migrates, so the decode worker stays cache-oblivious. A chunk
        # that straddles the hit boundary recomputes in full (a
        # bit-identical rewrite into solely-owned pages, by greedy
        # determinism), and the FINAL chunk always computes: its fused
        # argmax produces the first token.
        skip_p = (req_p is not None
                  and req_p.prefill_cursor + C <= req_p.cache_hit_tokens
                  and req_p.prefill_cursor + C < len(req_p.prompt))
        tok_np = None
        dt = 0.0
        if not (skip_p and local is None):
            toks = np.zeros((2, C), np.int32)
            starts = np.zeros(2, np.int32)
            plens = np.zeros(2, np.int32)
            bt = np.zeros((2, self.pages_per_seq), np.int32)
            if req_p is not None and not skip_p:
                part = req_p.prompt[req_p.prefill_cursor:
                                    req_p.prefill_cursor + C]
                toks[PREFILL_ROLE, :len(part)] = part
                starts[PREFILL_ROLE] = req_p.prefill_cursor
                plens[PREFILL_ROLE] = len(req_p.prompt)
                bt[PREFILL_ROLE] = np.asarray(self.alloc_p.block_table_row(
                    req_p.rid, self.pages_per_seq), np.int32)
            if local is not None:
                slot_d, req_d = local
                part_d = req_d.prompt[req_d.prefill_cursor:
                                      req_d.prefill_cursor + C]
                toks[DECODE_ROLE, :len(part_d)] = part_d
                starts[DECODE_ROLE] = req_d.prefill_cursor
                plens[DECODE_ROLE] = len(req_d.prompt)
                bt[DECODE_ROLE] = np.asarray(self.alloc_d.block_table_row(
                    req_d.rid, self.pages_per_seq), np.int32)
            t0 = time.perf_counter()
            tok_dev, self.pool_k, self.pool_v = self._chunk_step(
                self.params, jnp.asarray(toks), jnp.asarray(starts),
                jnp.asarray(plens), self.pool_k, self.pool_v,
                jnp.asarray(bt))
            tok_np = np.asarray(tok_dev)                # fence + maybe toks
            dt = time.perf_counter() - t0

        ptoks = 0
        if req_p is not None:
            sp = len(req_p.prompt)
            start = req_p.prefill_cursor
            ptoks = min(C, sp - start)
            cursor_new = min(start + C, sp)
            req_p.prefill_cursor = cursor_new
            if skip_p:
                self.metrics.inc("prefix_skipped_chunks")
            else:
                self.metrics.inc("prefill_chunks")
                self.metrics.observe("prefill_stall_s", dt)
            self._jlog("chunk", rid=req_p.rid, cursor=cursor_new)
            try:
                self._migrate_finalized(req_p, start, cursor_new)
            except SignalProtocolError as e:
                self._poison(slot_p, req_p, e)
            if req_p.state is RequestState.PREFILLING and cursor_new >= sp:
                # prefill complete: the request leaves this worker's
                # SCHEDULER, but its pages stay owned — they are the
                # retry source until the decode side confirms coverage
                # (released on the ACTIVE flip / degradation / failure).
                # skip_p can't be set here (final chunks always compute),
                # so tok_np is real.
                if self.prefix_cache is not None:
                    self.prefix_cache.insert(
                        req_p.prompt,
                        self.alloc_p.pages_of(req_p.rid)[
                            :sp // self.page_size])
                    if req_p.first_token_time is None:
                        self.metrics.observe(
                            "ttft_cached_s" if req_p.cache_hit_tokens
                            else "ttft_cold_s",
                            time.perf_counter() - req_p.submit_time)
                req_p.first_token = int(tok_np[PREFILL_ROLE])
                record_first_token(req_p, self.metrics, self._steps)
                self.metrics.inc("tokens_generated")
                self.metrics.inc("handoffs")
                self.sched_p.remove(slot_p)
                req_p.state = RequestState.MIGRATING
                self._jlog("handoff", rid=req_p.rid)
                if req_p.rid not in self._dslot:
                    self._handoff.append(req_p)

        if local is not None:
            sp_d = len(req_d.prompt)
            start_d = req_d.prefill_cursor
            req_d.prefill_cursor = min(start_d + C, sp_d)
            self.metrics_decode.observe("degraded_prefill_tokens",
                                        min(C, sp_d - start_d))
            if req_d.prefill_cursor >= sp_d:
                self._complete_local_prefill(slot_d, req_d,
                                             int(tok_np[DECODE_ROLE]))
        return ptoks

    def _complete_local_prefill(self, slot: int, req: Request,
                                tok0: int) -> None:
        """A degraded request's local re-prefill finished: flip straight
        to ACTIVE. The first token was recomputed by the same fused chunk
        argmax (bit-identical to the remote one by greedy determinism);
        no handoff is counted — this request never completed one."""
        rid = req.rid
        self._local_prefill.discard(rid)
        self.metrics_decode.observe(
            "degraded_ttft_s", time.perf_counter() - req.submit_time)
        req.state = RequestState.ACTIVE
        req.generated.append(tok0)
        self.metrics_decode.inc("tokens_generated")
        self._token[slot] = tok0
        self._pos[slot] = len(req.prompt)
        self._bt[slot] = np.asarray(self.alloc_d.block_table_row(
            rid, self.pages_per_seq), np.int32)
        self._dirty = True
        if req.done:
            self._finish_decode(slot)

    def force_preempt_prefill(self) -> int | None:
        """Forced mid-prefill preemption on the PREFILL worker (test/ops
        hook): evict the youngest PREFILLING slot. Filled prefill pages
        survive via ``free_tail`` (cursor resume), and the decode-side
        reservation plus already-MIGRATED pages are untouched — the
        resumed prefill migrates only what it newly finalizes. Returns
        the evicted slot, or None when nothing is prefilling."""
        victim = self.sched_p.pick_victim()
        if victim is None:
            return None
        self._preempt_prefill(victim)
        return victim

    def _preempt_prefill(self, slot: int) -> None:
        req = self.sched_p.slots[slot]
        if req.prefill_cursor > 0:
            filled = -(-req.prefill_cursor // self.page_size)
            if filled < len(self.alloc_p.pages_of(req.rid)):
                self.alloc_p.free_tail(req.rid, keep=filled)
                # adopted pages past the kept prefix were just released
                # (back to the cached list — still indexed): the resumed
                # prefill re-allocs FRESH pages there, so the skip window
                # must shrink to what the kept pages actually cover, or
                # empty pages would migrate as if they held the prefix
                req.cache_hit_tokens = min(req.cache_hit_tokens,
                                           filled * self.page_size)
            else:
                # no unfilled tail to reclaim: full restart. The decode
                # reservation keeps its ids, so the restarted prefill
                # re-migrates to the SAME destinations (idempotent —
                # identical recomputed contents, re-counted signals).
                self.alloc_p.free_seq(req.rid)
                req.prefill_cursor = 0
                req.cache_hit_tokens = 0
        else:
            self.alloc_p.free_seq(req.rid)
            req.prefill_cursor = 0
            req.cache_hit_tokens = 0
        self.sched_p.evict(slot)
        self.metrics.inc("preemptions")
        self._jlog("preempt", rid=req.rid, slot=slot, worker="prefill")

    # -- decode worker -----------------------------------------------------
    def _seat_decode_slots(self) -> None:
        while self._handoff:
            slot = self.sched_d.free_slot()
            if slot is None:
                return
            req = self._handoff.popleft()
            self.sched_d.place(slot, req)
            self._dslot[req.rid] = slot

    def _check_signal_gate(self, slot: int, covered: set[int]) -> None:
        """The landmine invariant (ISSUE 6 acceptance): a MIGRATING slot's
        block-table row may expose ONLY pages whose delivery signal has
        fired. ``landed_row`` guarantees this by construction; this check
        makes any future regression loud instead of a silent garbage
        read."""
        for p in self._bt[slot]:
            p = int(p)
            if p >= self.alloc_d.reserved and p not in covered:
                raise RuntimeError(
                    f"signal-gate violation: decode block table exposes "
                    f"page {p} before its delivery signal fired")

    def _patch_and_admit(self) -> None:
        """Block-table patching + signal-gated admission, in slot order
        (deterministic). A MIGRATING slot's row tracks the landed prefix
        each step; the slot flips to ACTIVE the step its prompt pages are
        fully covered — the admission gate is the LEDGER (fed only by the
        kernel's post-wait landed reports), never a host-side clock.

        The wait is DEADLINED (ISSUE 7): expiry walks the recovery
        ladder — re-send the incomplete chunks with exponential backoff,
        then degrade to decode-local re-prefill, then (and only then)
        fail THIS request with a typed reason. The engine never raises
        out of here for a transport fault."""
        for slot in range(self.num_slots):
            req = self.sched_d.slots[slot]
            if req is None or req.state is not RequestState.MIGRATING:
                continue
            rid = req.rid
            if rid in self._poisoned:
                # coverage was voided by a protocol error: nothing the
                # ledger says about this request can be trusted, so the
                # retry rungs are skipped entirely
                self._degrade_or_fail(slot, req, self._poisoned.pop(rid))
                continue
            covered = self.channel.ledger.covered(rid)
            row = np.asarray(self.alloc_d.landed_row(
                rid, covered, self.pages_per_seq), np.int32)
            if not np.array_equal(row, self._bt[slot]):
                self._bt[slot] = row
                self._dirty = True
            self._check_signal_gate(slot, covered)
            sp = len(req.prompt)
            need = set(self.alloc_d.pages_of(rid)[:-(-sp // self.page_size)])
            if req.first_token is not None and need <= covered:
                self.metrics_decode.observe(
                    "migrate_wait_steps", self._wait_steps.pop(rid, 0))
                if req.retries:
                    # the ladder's retry rung earned this handoff
                    self.metrics_decode.observe(
                        "recovered_ttft_s",
                        time.perf_counter() - req.submit_time)
                self._recovery.pop(rid, None)
                if self.alloc_p.holds(rid):
                    # coverage confirmed: the retry source has served its
                    # purpose — release the prefill-side copies
                    self.alloc_p.free_seq(rid)
                req.state = RequestState.ACTIVE
                req.generated.append(req.first_token)
                self.metrics_decode.inc("handoffs")
                self._token[slot] = req.first_token
                self._pos[slot] = sp
                self._bt[slot] = np.asarray(self.alloc_d.block_table_row(
                    rid, self.pages_per_seq), np.int32)
                self._dirty = True
                if req.done:      # max_new_tokens == 1 or tok0 == eos_id
                    self._finish_decode(slot)
                continue
            self._wait_steps[rid] = self._wait_steps.get(rid, 0) + 1
            rec = self._recovery.get(rid)
            if rec is None:
                rec = (Deadline(self.signal_deadline_steps, self._steps,
                                wall_s=self.wall_deadline_s),
                       Backoff(self.signal_deadline_steps,
                               max_retries=self.max_retries))
                self._recovery[rid] = rec
            deadline, backoff = rec
            if not deadline.expired(self._steps):
                continue
            budget = backoff.next_budget()
            retried = False
            if budget is not None:
                try:
                    retried = self._retry_migration(req)
                except SignalProtocolError as e:
                    self._degrade_or_fail(slot, req, e)
                    continue
            if retried:
                deadline.rearm(budget, self._steps)
                continue
            missing = sorted(need - covered)
            self._degrade_or_fail(slot, req, MigrationSignalTimeout(
                f"request {rid} waited {self._wait_steps.get(rid, 0)} "
                f"decode steps (deadline {self.signal_deadline_steps}, "
                f"{backoff.attempt} retry rung(s) spent) for migration "
                f"signals covering pages {missing}; ledger: "
                f"{self.channel.ledger.describe(rid)}. A signal or page "
                "delivery was lost (or a chunk was never sent)."))

    # -- recovery ladder (ISSUE 7) ----------------------------------------
    def _retry_migration(self, req: Request) -> bool:
        """Rung 1: re-issue the ``migrate_pages`` send for every chunk
        still short of coverage. Possible only while the prefill-side
        source pages survive (they are retained through MIGRATING for
        exactly this) and every missing page belongs to a chunk that WAS
        sent — an unsent chunk or freed sources cannot be retried, the
        caller moves straight down the ladder. Returns True when a
        re-send was actually issued."""
        rid = req.rid
        if not self.alloc_p.holds(rid):
            return False
        incomplete = self.channel.ledger.incomplete_chunks(rid)
        if not incomplete:
            # complete per-chunk coverage yet an uncovered needed page:
            # some chunk was never sent at all — re-sending fixes nothing
            return False
        src_owned = set(self.alloc_p.pages_of(rid))
        for _, src_ids, _ in incomplete:
            if not src_ids or not set(src_ids) <= src_owned:
                return False
        for ci, src_ids, dst_ids in incomplete:
            self.pool_k, self.pool_v = self.channel.send_chunk(
                rid, ci, list(src_ids), list(dst_ids),
                self.pool_k, self.pool_v)
            self._jlog("migrate", rid=rid, chunk=ci, pages=len(src_ids),
                       attempt=self.channel._attempt.get((rid, ci), 0),
                       retry=True)
        req.retries += 1
        self.metrics_decode.inc("retries")
        return True

    def _degrade_or_fail(self, slot: int, req: Request,
                         exc: Exception) -> None:
        """Rung 2 vs the terminal: local re-prefill while the degradation
        budget and capability allow, typed per-request failure after."""
        if (self.allow_degradation
                and req.degradations < self.max_degradations):
            self._degrade(slot, req)
        else:
            self._fail_decode(slot, req, exc)

    def _degrade(self, slot: int, req: Request) -> None:
        """Rung 2: decode-local re-prefill (the PR-6 preemption fallback
        run IN PLACE). The request keeps its decode slot and its decode-
        side page reservation; the prompt KV is recomputed by the same
        compiled chunk program with real inputs in the DECODE_ROLE row
        (``_dispatch_chunks``), so the possibly-dead peer is out of the
        loop entirely. All migrated coverage is voided — the locally
        computed pages are the only ones trusted from here on."""
        rid = req.rid
        req.degradations += 1
        self.metrics_decode.inc("degradations")
        self.channel.ledger.reset(rid)
        self._recovery.pop(rid, None)
        self._wait_steps.pop(rid, None)
        self._poisoned.pop(rid, None)
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)   # source copies are useless now
        req.state = RequestState.PREFILLING
        req.prefill_cursor = 0
        self._local_prefill.add(rid)
        self._park(slot)

    def _fail_decode(self, slot: int, req: Request, exc: Exception) -> None:
        """The ladder's terminal: THIS request fails, typed, with the
        ledger dump riding on ``exc`` — the engine and every other
        request keep running (per-request failure domain)."""
        rid = req.rid
        self.sched_d.remove(slot)
        req.state = RequestState.FAILED
        req.failure = exc
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        self.alloc_d.free_seq(rid)
        self.channel.ledger.reset(rid)
        self.channel.forget(rid)
        self._recovery.pop(rid, None)
        self._wait_steps.pop(rid, None)
        self._poisoned.pop(rid, None)
        self._local_prefill.discard(rid)
        del self._dslot[rid]
        self._park(slot)
        self._failed.append(req)
        self.metrics_decode.inc("failed_requests")
        self._jlog("fail", rid=rid, error_type=type(exc).__name__,
                   reason=str(exc).splitlines()[0])

    def _poison(self, slot: int, req: Request, exc: Exception) -> None:
        """A protocol error surfaced while the request still sits on the
        PREFILL worker: void all coverage now; the ladder's degrade/fail
        decision runs when (if) the request reaches a decode slot —
        unless degradation is impossible, in which case it fails right
        here rather than limping through a doomed migration."""
        rid = req.rid
        self.channel.ledger.reset(rid)
        if (self.allow_degradation
                and req.degradations < self.max_degradations):
            self._poisoned[rid] = exc
            return
        self.sched_p.remove(slot)
        req.state = RequestState.FAILED
        req.failure = exc
        if self.alloc_p.holds(rid):
            self.alloc_p.free_seq(rid)
        if self.alloc_d.holds(rid):
            self.alloc_d.free_seq(rid)
        self.channel.forget(rid)
        self._failed.append(req)
        self.metrics_decode.inc("failed_requests")
        self._jlog("fail", rid=rid, error_type=type(exc).__name__,
                   reason=str(exc).splitlines()[0])

    def _finish_decode(self, slot: int) -> None:
        req = self.sched_d.finish(slot)
        self.alloc_d.free_seq(req.rid)
        if self.alloc_p.holds(req.rid):
            self.alloc_p.free_seq(req.rid)
        self.channel.ledger.reset(req.rid)
        self.channel.forget(req.rid)
        self._recovery.pop(req.rid, None)
        self._wait_steps.pop(req.rid, None)
        self._poisoned.pop(req.rid, None)
        self._local_prefill.discard(req.rid)
        del self._dslot[req.rid]
        req.finish_step = self._steps
        self._park(slot)
        self._finished.append(req)
        self.metrics_decode.inc("requests_finished")
        self.metrics_decode.inc_class("requests_finished", class_label(req))
        # finished tokens ride the journal so post-checkpoint finishes
        # survive a crash without re-running the request; the terminal
        # metadata rides along so the restored record stays faithful
        self._jlog("finish", rid=req.rid, tokens=list(req.generated),
                   submit_step=req.submit_step,
                   first_token_step=req.first_token_step,
                   preemptions=req.preemptions)

    def _preempt_decode(self, slot: int) -> None:
        """Decode-side eviction loses the migrated KV with the pages: the
        victim restarts as a fresh prefill (FRONT of the prefill queue) —
        determinism regenerates identical tokens. ``remove`` (not
        ``evict``): the requeue target is the PEER scheduler. A MIGRATING
        victim also drops its retained prefill-side retry source and any
        in-flight recovery state; a locally-re-prefilling victim rejoins
        the normal remote pipeline."""
        req = self.sched_d.remove(slot)
        req.state = RequestState.QUEUED
        req.preemptions += 1
        req.generated.clear()
        req.prefill_cursor = 0
        req.first_token = None
        req.cache_hit_tokens = 0
        self.alloc_d.free_seq(req.rid)
        if self.alloc_p.holds(req.rid):
            self.alloc_p.free_seq(req.rid)
        self.channel.ledger.reset(req.rid)
        self._recovery.pop(req.rid, None)
        self._wait_steps.pop(req.rid, None)
        self._poisoned.pop(req.rid, None)
        self._local_prefill.discard(req.rid)
        del self._dslot[req.rid]
        self.sched_p.submit(req, front=True)
        self._park(slot)
        self.metrics_decode.inc("preemptions")
        self._jlog("preempt", rid=req.rid, slot=slot, worker="decode")

    def _park(self, slot: int) -> None:
        self._token[slot] = 0
        self._pos[slot] = 0
        self._bt[slot] = 0
        self._dirty = True

    # -- one driver iteration ---------------------------------------------
    @property
    def idle(self) -> bool:
        return (self.sched_p.idle and not self._handoff
                and all(s is None for s in self.sched_d.slots))

    def step(self) -> bool:
        """One step of BOTH workers. Thin wrapper (ISSUE 9): TTL expiry
        sweep before the iteration, checkpoint cadence after a productive
        one — mirroring ``ServingEngine.step``."""
        self.sched_p.tick(self._steps)
        self._expire_queued()
        progressed = self._step_impl()
        self.metrics.counters["quota_throttled"] = \
            self.sched_p.quota_throttled
        if progressed:
            self._maybe_checkpoint()
        return progressed

    def _expire_queued(self) -> None:
        for req in self.sched_p.expire(self._steps):
            ttl = self._ttl_for(req)
            req.failure = TtlExpired(
                f"request {req.rid} (class {req.cls!r}) queued past its "
                f"TTL ({ttl} steps from step {req.submit_step}) "
                "without admission")
            self._rejected.append(req)
            self.metrics.inc("expirations")
            self.metrics.inc_class("expirations", class_label(req))
            self._jlog("expire", rid=req.rid, reason=str(req.failure),
                       tenant=req.tenant, cls=req.cls)

    def _step_impl(self) -> bool:
        """One step of BOTH workers (single-driver SPMD: each device
        program below is entered by both roles). Returns False when fully
        idle."""
        if self.idle:
            return False

        # ---- prefill worker: admissions + ≤1 chunk + migration ----------
        while True:
            adm = self.sched_p.admissible(self._can_hold)
            if adm is None:
                break
            self._admit_prefill(*adm)
        ptoks = self._dispatch_chunks()
        self.metrics.observe("step_prefill_tokens", ptoks)

        # ---- decode worker: seating, patching, gated admission ----------
        t_d = time.perf_counter()
        # deliver any fault-delayed landed reports BEFORE gating, so a
        # late signal can still admit this step; a report that arrives
        # poisoned (over-signal) voids its request's coverage instead of
        # crashing the engine — the ladder decides its fate at seat time
        for rid, exc in self.channel.tick(self._steps):
            self._poisoned.setdefault(rid, exc)
        self._seat_decode_slots()
        self._patch_and_admit()

        limits = np.zeros(self.num_slots, np.int32)
        for slot in range(self.num_slots):
            req = self.sched_d.slots[slot]
            if req is None or req.state is not RequestState.ACTIVE:
                continue
            pos = int(self._pos[slot])
            while not self.alloc_d.ensure(req.rid, pos + 1):
                victim = self.sched_d.pick_victim(exclude_slot=slot)
                if victim is None:
                    raise RuntimeError(
                        f"decode KV pool too small: request {req.rid} "
                        "needs a page with no preemptible peer left")
                self._preempt_decode(victim)
            want = min(self.decode_horizon, req.remaining)
            lim = 1
            while lim < want and self.alloc_d.ensure(req.rid, pos + lim + 1):
                lim += 1
            limits[slot] = lim
            row = np.asarray(self.alloc_d.block_table_row(
                req.rid, self.pages_per_seq), np.int32)
            if not np.array_equal(row, self._bt[slot]):
                self._bt[slot] = row
                self._dirty = True
        for slot in range(self.num_slots):
            r = self.sched_d.slots[slot]
            if r is None or r.state is not RequestState.ACTIVE:
                limits[slot] = 0
        # the decode worker NEVER runs prefill: its per-step stall is pure
        # control-plane work, independent of any peer prompt length — and
        # its step_prefill_tokens is identically 0 (both test-pinned)
        self.metrics_decode.observe("decode_stall_s",
                                    time.perf_counter() - t_d)
        self.metrics_decode.observe("step_prefill_tokens", 0)

        active = [(s, r) for s, r in self.sched_d.active
                  if r.state is RequestState.ACTIVE]
        if not active:
            # prefill chunks / inflight migrations still progressed
            self._steps += 1
            return True

        if self._dirty:
            self._token_dev = self._up(np.stack([self._z_row, self._token]))
            self._pos_dev = self._up(np.stack([self._z_row, self._pos]))
            self._bt_dev = self._up(np.stack([self._z_bt, self._bt]))
            self._dirty = False
            self.metrics_decode.inc("host_syncs")

        lim2 = np.zeros((2, self.num_slots), np.int32)
        lim2[DECODE_ROLE] = limits
        t_disp = time.perf_counter()
        (toks, self._token_dev, self._pos_dev,
         self.pool_k, self.pool_v) = self._dec_step(
            self.params, self._token_dev, self._pos_dev,
            self.pool_k, self.pool_v, self._bt_dev, jnp.asarray(lim2))
        slab = np.asarray(toks)[DECODE_ROLE]           # [K, B]
        t_done = time.perf_counter()

        self._steps += 1
        self.metrics_decode.inc("dispatches")
        self.metrics_decode.inc("decode_steps", int(limits.max()))
        self.metrics_decode.observe("queue_depth", len(self._handoff))
        self.metrics_decode.observe("pool_occupancy",
                                    self.alloc_d.occupancy())
        self.metrics_decode.observe("active_slots", len(active))

        n_tokens = 0
        emitted_by_slot: dict[int, int] = {}
        for slot, req in active:
            emitted = 0
            for i in range(int(limits[slot])):
                req.generated.append(int(slab[i, slot]))
                emitted += 1
                self.metrics_decode.inc("tokens_generated")
                if req.done:
                    break
            self._token[slot] = slab[emitted - 1, slot]
            self._pos[slot] += emitted
            n_tokens += emitted
            emitted_by_slot[slot] = emitted
            if req.done:
                self._finish_decode(slot)

        dev_dt = t_done - t_disp
        host_dt = (t_disp - t_d) + (time.perf_counter() - t_done)
        self.metrics_decode.observe("step_device_s", dev_dt)
        self.metrics_decode.observe("step_host_s", host_dt)
        per_tok = (dev_dt + host_dt) / max(n_tokens, 1)
        for _ in range(n_tokens):
            self.metrics_decode.observe("tok_latency_s", per_tok)
        for slot, req in active:
            label = class_label(req)
            if label is not None:
                for _ in range(emitted_by_slot.get(slot, 0)):
                    self.metrics_decode.observe_class("itl_s", label, per_tok)
        return True

    def run(self, max_steps: int | None = None,
            arrivals=None, recover=None) -> dict[int, list[int]]:
        """Drive ``step()`` until idle (or ``max_steps``); same contract
        as ``ServingEngine.run`` — returns {rid: tokens} for FINISHED
        requests only (``failed`` exposes the casualties).

        ``recover`` (ISSUE 9): truthy = restore from the journal's last
        checkpoint + suffix replay before stepping. A decode-worker
        restart re-admits every in-flight (including mid-migration)
        request through the rebuilt ledger: the request re-prefills and
        re-migrates deterministically, nothing is failed for having been
        half-migrated at the crash.

        A global progress WATCHDOG (ISSUE 7) backstops the per-request
        ladder: if no externally visible progress marker moves for
        ``_stall_steps`` consecutive non-idle steps — longer than any
        legitimate full-ladder wait — the engine raises
        ``EngineStallError`` with a state dump. Chaos runs assert this
        never fires: every fault path must END somewhere (handoff,
        degradation, or typed failure), not spin."""
        if recover:
            assert self.journal is not None, "recover= needs a journal"
            ck = recover if isinstance(recover, ckpt_mod.Checkpoint) \
                else ckpt_mod.latest(self.journal)
            ckpt_mod.restore(self, ck, self.journal)
        pending = deque(arrivals or [])
        i = 0
        marker, since = self._progress_marker(), 0
        while max_steps is None or i < max_steps:
            while pending and pending[0][0] <= i:
                item = pending.popleft()
                self.submit(item[1], item[2],
                            tenant=item[3] if len(item) > 3 else None,
                            cls=item[4] if len(item) > 4 else None)
            if not self.step() and not pending:
                break
            i += 1
            plan = self._fault_plan if self._fault_plan is not None \
                else faults.active_plan()
            if plan is not None and plan.crash(self._steps,
                                               self._incarnation):
                self.metrics.inc("faults_injected")
                raise faults.InjectedCrash(
                    f"injected crash at step {self._steps} "
                    f"(incarnation {self._incarnation})")
            m = self._progress_marker()
            if m != marker:
                marker, since = m, 0
            else:
                since += 1
                if since >= self._stall_steps and not self.idle:
                    raise EngineStallError(self._stall_report(since)
                                           + self._postmortem())
        return {req.rid: list(req.generated) for req in self._finished}

    def _progress_marker(self) -> tuple:
        """Anything that moves when the engine is making real progress:
        tokens, chunks, migrations, and every rung of the ladder
        (retries/degradations/failures count as progress — they bound a
        wait, they don't extend it)."""
        c, d = self.metrics.counters, self.metrics_decode.counters
        return (c["prefill_chunks"], c["pages_migrated"], c["migrate_chunks"],
                c["restores"], c["expirations"],
                d["tokens_generated"], d["handoffs"], d["retries"],
                d["degradations"], d["failed_requests"], d["preemptions"],
                len(self._finished), len(self._failed),
                self.metrics_decode.hist["degraded_prefill_tokens"].count)

    def _stall_report(self, since: int) -> str:
        rows = []
        for name, sched in (("prefill", self.sched_p),
                            ("decode", self.sched_d)):
            for slot, req in sched.active:
                rows.append(
                    f"{name}[{slot}]: rid={req.rid} {req.state.value} "
                    f"cursor={req.prefill_cursor} retries={req.retries} "
                    f"degradations={req.degradations}")
        return (f"engine made no progress for {since} steps "
                f"(stall deadline {self._stall_steps}, step {self._steps}); "
                f"queues: prefill={self.sched_p.queue_depth} "
                f"handoff={len(self._handoff)} "
                f"local_prefill={sorted(self._local_prefill)} "
                f"recovering={sorted(self._recovery)} "
                f"poisoned={sorted(self._poisoned)}; slots: "
                + ("; ".join(rows) if rows else "<none>"))

    # -- crash consistency (ISSUE 9) --------------------------------------
    def control_digest(self) -> int:
        """FNV-1a digest over BOTH workers' control planes (each role's
        allocator + scheduler) — the per-event stamp journal entries
        carry."""
        return _fnv1a(0x811C9DC5, self.alloc_p.digest(),
                      self.sched_p.digest(), self.alloc_d.digest(),
                      self.sched_d.digest())

    def _jlog(self, kind: str, **payload) -> None:
        if self.journal is None or self._journal_muted:
            return
        self.journal.append(kind, self._steps, self.control_digest(),
                            **payload)

    def _maybe_checkpoint(self) -> None:
        if (self.journal is None or not self.checkpoint_every
                or self._steps == 0
                or self._steps % self.checkpoint_every
                or self._steps == self._last_ckpt_step):
            return
        self.checkpoint()

    def checkpoint(self) -> "ckpt_mod.Checkpoint":
        """Capture a control-plane snapshot of both workers into the
        journal. Host-only — no device work, no KV bytes, no migration
        state beyond the ledger audit artifact."""
        assert self.journal is not None, "checkpoint() needs a journal"
        t0 = time.perf_counter()
        ck = ckpt_mod.capture(self)
        self.journal.record_checkpoint(ck.step, ck.digest, ck.state,
                                       ck.journal_seq)
        self._last_ckpt_step = self._steps
        self.metrics.inc("checkpoints")
        self.metrics.observe("checkpoint_s", time.perf_counter() - t0)
        return ck

    def _capture_state(self) -> dict:
        """JSON-able snapshot of BOTH workers' control planes. Live
        requests are recorded in deterministic order — decode seats by
        admission ticket, the handoff queue, prefill seats by ticket,
        then the prefill queue — and every one of them restores as a
        fresh QUEUED prefill: restart-from-prompt re-earns pages AND
        re-migrates, so no migration state needs to survive."""
        live: list[Request] = []
        seen: set[int] = set()

        def add(r: Request | None) -> None:
            if r is not None and r.rid not in seen:
                seen.add(r.rid)
                live.append(r)

        for _, r in sorted(((r.admitted_seq, r)
                            for _, r in self.sched_d.active),
                           key=lambda t: t[0]):
            add(r)
        for r in self._handoff:
            add(r)
        for _, r in sorted(((r.admitted_seq, r)
                            for _, r in self.sched_p.active),
                           key=lambda t: t[0]):
            add(r)
        for r in self.sched_p.queue:
            add(r)
        return {
            "engine": "disagg",
            "step": self._steps,
            "next_rid": self._next_rid,
            "admit_ticket_p": self.sched_p._admit_ticket,
            "admit_ticket_d": self.sched_d._admit_ticket,
            "pool_p": self.alloc_p.snapshot(),
            "pool_p_digest": self.alloc_p.digest(),
            "pool_d": self.alloc_d.snapshot(),
            "pool_d_digest": self.alloc_d.digest(),
            "prefix_index": (None if self.prefix_cache is None
                             else self.prefix_cache.snapshot()),
            "prefix_digest": (None if self.prefix_cache is None
                              else self.prefix_cache.digest()),
            "live": [ckpt_mod.snapshot_request(r) for r in live],
            "finished": [ckpt_mod.snapshot_finished(r)
                         for r in self._finished],
            "failed": [{"rid": r.rid,
                        "error_type": type(r.failure).__name__,
                        "reason": str(r.failure).splitlines()[0]}
                       for r in self._failed],
            "rejected": [{"rid": r.rid, "kind": "expire"
                          if isinstance(r.failure, TtlExpired) else "reject",
                          "reason": str(r.failure), "tenant": r.tenant,
                          "cls": r.cls} for r in self._rejected],
            "policy": self.sched_p.policy_state(),
            "counters": dict(self.metrics.counters),
            "counters_decode": dict(self.metrics_decode.counters),
        }

    def _restore_state(self, state: dict | None) -> None:
        """Rebuild both workers' host control state (None = from nothing).
        The symmetric device pools are left untouched: every live request
        re-prefills and RE-MIGRATES from scratch, rewriting its pages'
        bytes before any decode read, so stale device KV is unreachable.
        The signal ledger and the channel's attempt/delay state are
        cleared — coverage must be re-earned by fresh signals, never
        trusted across a restart."""
        self.alloc_p = KVPagePool(self.alloc_p.num_pages, self.page_size,
                                  reserved=1)
        self.alloc_d = KVPagePool(self.alloc_d.num_pages, self.page_size,
                                  reserved=1)
        if self.prefix_cache is not None:
            # the cache restarts EMPTY on the fresh ledger: cached KV is
            # device state, and restore's contract is that every page's
            # bytes are re-earned by re-prefill before any read
            self.prefix_cache = PrefixCache(self.alloc_p, self.page_size)
        self.sched_p = ContinuousBatchingScheduler(
            self.sched_p.num_slots, queue_cap=self.sched_p.queue_cap,
            policy=self.sched_p.policy)
        self.sched_d = ContinuousBatchingScheduler(self.num_slots)
        self._handoff.clear()
        self._dslot.clear()
        self._wait_steps.clear()
        self._recovery.clear()
        self._poisoned.clear()
        self._local_prefill.clear()
        self._finished = []
        self._failed = []
        self._rejected = []
        self.channel.ledger = ChunkSignalLedger()
        self.channel._attempt.clear()
        self.channel._delayed.clear()
        for slot in range(self.num_slots):
            self._park(slot)
        self._token_dev = self._up(np.stack([self._z_row, self._token]))
        self._pos_dev = self._up(np.stack([self._z_row, self._pos]))
        self._bt_dev = self._up(np.stack([self._z_bt, self._bt]))
        self._dirty = False
        if state is None:
            return
        ckpt_mod.audit_pool_snapshot(
            state["pool_p"], state["pool_p_digest"],
            self.alloc_p.num_pages, self.page_size, 1)
        ckpt_mod.audit_pool_snapshot(
            state["pool_d"], state["pool_d_digest"],
            self.alloc_d.num_pages, self.page_size, 1)
        if state.get("prefix_index") is not None:
            ckpt_mod.audit_prefix_snapshot(state["prefix_index"],
                                           state["prefix_digest"])
        self._steps = state["step"]
        self._next_rid = state["next_rid"]
        self.sched_p._admit_ticket = state["admit_ticket_p"]
        self.sched_d._admit_ticket = state["admit_ticket_d"]
        for snap in state["live"]:
            req = ckpt_mod.rebuild_request(snap)
            req.submit_time = time.perf_counter()
            ttl = self._ttl_for(req)
            if ttl is not None:
                req.deadline = Deadline(ttl, req.submit_step)
            self.sched_p.submit(req)
        # WFQ/bucket books restore AFTER the requeues: submit()'s idle-
        # class vfloor snap ran against zeroed counters above, and the
        # checkpoint values now overwrite them (order-dependent)
        self.sched_p.restore_policy_state(state.get("policy"))
        for f in state["finished"]:
            self._restore_finished(f["rid"], f["tokens"], meta=f)
        for f in state["failed"]:
            self._restore_terminal(f["rid"], "fail", f["reason"],
                                   f.get("error_type"))
        for f in state["rejected"]:
            self._restore_terminal(f["rid"], f["kind"], f["reason"])

    _ERROR_TYPES = {
        "MigrationSignalTimeout": MigrationSignalTimeout,
        "SignalProtocolError": SignalProtocolError,
        "AdmissionRejected": AdmissionRejected,
        "TtlExpired": TtlExpired,
    }

    def _restore_finished(self, rid: int, tokens: list[int],
                          meta: dict | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            prompt = tuple((meta or {}).get("prompt", (0,)))
            req = Request(rid=rid, prompt=prompt,
                          max_new_tokens=len(tokens), eos_token=self.eos_id)
        req.state = RequestState.FINISHED
        req.generated = list(tokens)
        for k in ("submit_step", "first_token_step", "preemptions"):
            if meta is not None and k in meta:
                setattr(req, k, meta[k])
        self._finished.append(req)

    def _restore_terminal(self, rid: int, kind: str, reason: str,
                          error_type: str | None = None) -> None:
        req = self._pop_queued(rid)
        if req is None:
            req = Request(rid=rid, prompt=(0,), max_new_tokens=1,
                          eos_token=self.eos_id)
        if kind == "fail":
            req.state = RequestState.FAILED
            cls = self._ERROR_TYPES.get(error_type or "", RuntimeError)
            req.failure = cls(reason)
            self._failed.append(req)
        else:
            req.state = RequestState.REJECTED
            req.failure = (TtlExpired(reason) if kind == "expire"
                           else AdmissionRejected(reason))
            self._rejected.append(req)

    def _pop_queued(self, rid: int) -> Request | None:
        for r in self.sched_p.queue:
            if r.rid == rid:
                self.sched_p.queue.remove(r)
                return r
        return None

    def _postmortem(self) -> str:
        counters = {k: v for k, v in self.metrics.counters.items() if v}
        counters_d = {k: v for k, v in self.metrics_decode.counters.items()
                      if v}
        tail = (self.journal.format_tail(8) if self.journal is not None
                else "  <no journal attached>")
        return ("\ncounters: " + json.dumps(counters)
                + "\ncounters_decode: " + json.dumps(counters_d)
                + "\njournal tail:\n" + tail)

    @property
    def failed(self) -> list[Request]:
        """Requests the recovery ladder could not save plus overload
        terminals (REJECTED), in failure order; each carries its typed
        reason in ``req.failure``."""
        return list(self._failed) + list(self._rejected)

    # -- introspection ----------------------------------------------------
    @property
    def compile_stats(self) -> dict:
        """Each role compiles a BOUNDED program set: one chunk program
        (prefill worker, every prompt length), one decode program, one
        migration program (every chunk size ≤ pmax) — no per-prompt-length
        recompiles anywhere (test-pinned)."""
        def n(fn, fallback):
            try:
                return int(fn._cache_size())
            except Exception:
                return fallback

        stats = {
            "prefill_chunk_compiles": n(
                self._chunk_step,
                1 if self.metrics.counters["prefill_chunks"] else 0),
            "decode_compiles": n(self._dec_step, 1 if self._steps else 0),
            "migrate_compiles": n(
                self._migrate,
                1 if self.metrics.counters["migrate_chunks"] else 0),
        }
        if self._aot_artifact is not None:
            from triton_dist_tpu.aot.artifact import LoadedProgram
            stats["aot_programs"] = sum(
                isinstance(f, LoadedProgram)
                for f in (self._chunk_step, self._dec_step, self._migrate))
        return stats


__all__ = ["DisaggServingEngine", "PageMigrationChannel",
           "ChunkSignalLedger", "MigrationSignalTimeout",
           "SignalProtocolError", "PREFILL_ROLE", "DECODE_ROLE"]
