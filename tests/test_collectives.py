"""Collectives vs jax.lax goldens.

Parity targets: reference test/nvidia/test_all_gather.py,
test_fast_allgather.py, test_reduce_scatter.py (golden-check pattern of
test_ag_gemm_intra_node.py:128-148: run distributed op, compare against the
framework collective)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import all_gather, reduce_scatter, barrier_all_op
from conftest import TEST_WORLD
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


@pytest.fixture(scope="module")
def ctx2d():
    return initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))


def test_all_gather_ll_repeated(ctx):
    """The barrier-free LL AG (phase-keyed double-buffered workspace,
    reference low_latency_allgather.py parity): five consecutive calls
    through ONE context with fresh data each call — the parity scheme's
    cross-call reuse is exactly what this exercises."""
    from triton_dist_tpu.ops import AgLLContext

    n = ctx.num_ranks
    m = 16
    ag = AgLLContext(ctx, m_local=m, trailing=(128,), dtype=jnp.float32)
    for it in range(5):
        x = jax.random.normal(jax.random.key(it), (n * m, 128), jnp.float32)
        y = ag(ctx.shard(x, P("x")))
        assert_allclose(np.asarray(y), np.asarray(x))


@pytest.mark.quick
def test_all_gather_ll_functional(ctx):
    """Functional ws-threading form under jit (donate-style usage)."""
    from triton_dist_tpu.ops import all_gather_ll, create_ag_ll_workspace

    n = ctx.num_ranks
    m = 8
    ws = create_ag_ll_workspace(ctx, m, (128,), jnp.float32)
    f = jax.jit(lambda ph, v, w: all_gather_ll(ctx, v, w, ph, axis="x"))
    for it in range(3):
        x = jax.random.normal(jax.random.key(10 + it), (n * m, 128))
        phase = jnp.asarray([it % 2], jnp.int32)
        y, ws = f(phase, ctx.shard(x, P("x")), ws)
        assert_allclose(np.asarray(y), np.asarray(x))


@pytest.mark.quick
@pytest.mark.parametrize("method", ["push", "ring"])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_all_gather_1d(ctx, method, dtype):
    n = ctx.num_ranks
    key = jax.random.key(0)
    x = jax.random.normal(key, (n * 16, 128), dtype=jnp.float32).astype(dtype)
    x = ctx.shard(x, P("x"))
    y = jax.jit(lambda v: all_gather(ctx, v, axis="x", method=method))(x)
    assert_allclose(np.asarray(y, dtype=np.float32),
                    np.asarray(x, dtype=np.float32))


def test_all_gather_2d(ctx2d):
    # asymmetric (2,3) mesh: a major/minor axis swap would change results
    x = jnp.arange(6 * 8 * 128, dtype=jnp.float32).reshape(6 * 8, 128)
    x = ctx2d.shard(x, P(("a", "b")))
    y = jax.jit(lambda v: all_gather(ctx2d, v, method="ring_2d"))(x)
    assert_allclose(np.asarray(y), np.asarray(x))


@pytest.mark.quick
def test_reduce_scatter_ring(ctx):
    n = ctx.num_ranks
    M = 32  # per-device contribution rows
    # integer-valued data → exact sums in f32
    x = jnp.round(jax.random.normal(jax.random.key(1), (n * M, 128)) * 4)
    x = ctx.shard(x.astype(jnp.float32), P("x"))
    y = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))(x)

    # golden: psum_scatter of each device's local block
    def g(shard):
        return jax.lax.psum_scatter(shard, "x", scatter_dimension=0, tiled=True)
    golden = jax.jit(ctx.shard_map(g, in_specs=P("x"), out_specs=P("x")))(x)
    assert_allclose(np.asarray(y), np.asarray(golden))


def test_barrier_all_op(ctx):
    f = barrier_all_op(ctx)
    out = f()
    assert np.all(np.asarray(out) == 1)


@pytest.mark.quick
@pytest.mark.parametrize("root", [0, 2])
def test_broadcast(ctx, root):
    """One-to-all broadcast (device-API parity: the reference's raw
    broadcast, exercised by test_nvshmem_api)."""
    from triton_dist_tpu.ops import broadcast
    n = ctx.num_ranks
    x = jnp.stack([jnp.full((16, 128), float(i)) for i in range(n)])
    xs = ctx.shard(x, P("x"))
    f = jax.jit(lambda v: broadcast(ctx, v, axis="x", root=root))
    for _ in range(2):  # repeated calls: entry barrier protects sem reuse
        y = f(xs)
        assert_allclose(np.asarray(y), np.asarray(x[root]))


def test_all_gather_push_2d(ctx2d):
    """Single-kernel hierarchical push AG (outer relay + inner push) on the
    asymmetric (2,3) mesh, repeated calls."""
    from triton_dist_tpu.ops import all_gather
    x = jnp.arange(6 * 12 * 128, dtype=jnp.float32).reshape(6 * 12, 128)
    xs = ctx2d.shard(x, P(("a", "b")))
    f = jax.jit(lambda v: all_gather(ctx2d, v, method="push_2d"))
    for _ in range(2):
        assert_allclose(np.asarray(f(xs)), np.asarray(x))


def test_all_gather_push_2d_3axis():
    from triton_dist_tpu.ops import all_gather
    ctx3 = initialize_distributed(axis_names=("a", "b", "c"),
                                  mesh_shape=(2, 2, 2))
    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(8 * 8, 128)
    xs = ctx3.shard(x, P(("a", "b", "c")))
    y = jax.jit(lambda v: all_gather(ctx3, v, method="push_2d"))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))
