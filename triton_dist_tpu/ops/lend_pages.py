"""KV page lending: the cluster prefix-sharing kernel (ISSUE 17).

Same wire protocol as ``migrate_pages`` — per-(layer, page) one-sided
``putmem_nbi`` puts plus a counted ``signal_op`` announcement, consumer
waits on exactly the signals covering what it will read — applied to a
different serving relationship: the **lender** pushes refcount-0 *cached*
prefix pages (pages the prefix index retains after their last reference
dropped — see ``KVPagePool.check_lendable``) into a **borrower**
replica's reserved destination pages, so a prompt routed away from its
prefix's home replica still adopts the KV instead of re-prefilling it.

Role semantics vs migration:

- migration moves pages a sequence OWNS (sole ownership via
  ``check_migratable``) and the source side forgets them — a handoff.
- lending copies pages nobody references (refcount 0, cached) and the
  lender KEEPS them — a replication. Greedy-decode determinism makes the
  bytes identical to what the borrower would have re-prefilled, which is
  what preserves the bit-identical trace contract (the same argument
  that makes local prefix-cache adoption safe, stretched across
  replicas).

The sole-ownership/COW contract is untouched: a lent page is refcount-0
on the lender (no writer exists there) and lands in a freshly allocated
page on the borrower (no reader exists yet); both sides' ledgers audit
clean (``KVPagePool.check``). The host tier (serving/lending.py) wraps
this call in the PR 7 ``Deadline``/``Backoff``/degrade ladder — a dead
or slow lender degrades to local re-prefill, never a stall.

Every rank on the role axis enters the SPMD call (one program, like all
collectives in ops/); ranks outside the ``{lender, borrower}`` pair
participate only in the entry barrier, which is what keeps the kernel
sigcheck-clean at any axis size (registered at n ∈ {2, 3, 4})."""

from __future__ import annotations

import jax

from triton_dist_tpu.ops.page_migrate import paged_transport
from triton_dist_tpu.shmem.context import ShmemContext


def lend_pages(ctx: ShmemContext, pool_k: jax.Array, pool_v: jax.Array,
               src_ids: jax.Array, dst_ids: jax.Array, n_pages: jax.Array,
               axis: str | None = None, lender: int = 0, borrower: int = 1,
               tag: jax.Array | int = 0):
    """Lend ``n_pages`` cached prefix pages from ``lender`` to
    ``borrower`` over ``axis``. Argument and return contracts are
    :func:`~triton_dist_tpu.ops.page_migrate.paged_transport`'s:
    ``src_ids`` are lender-local cached page ids (host-checked via
    ``KVPagePool.check_lendable`` — refcount-0, index-retained),
    ``dst_ids`` the borrower's freshly allocated destination ids, and
    ``landed[borrower] == (count, tag)`` is the delivery ground truth the
    lending tier gates its prefix-cache insert on."""
    return paged_transport(ctx, pool_k, pool_v, src_ids, dst_ids, n_pages,
                           axis=axis, producer=lender, consumer=borrower,
                           tag=tag, name="lend_pages")


__all__ = ["lend_pages"]
