"""Serving metrics: counters + histograms, emitted as JSON lines.

One ``ServingMetrics`` per engine. Everything is host-side and O(1) per
event; histograms keep (count, sum, min, max) plus a bounded reservoir so
percentiles stay cheap and memory stays flat over million-request runs.
``json_line()`` is the wire format — one self-contained JSON object per
call, the shape ``scripts/serve_sim.py`` prints and ``bench.py`` folds
into its extras.
"""

from __future__ import annotations

import json
import time
from collections import deque


class Histogram:
    """Streaming histogram: exact count/sum/min/max + a bounded sample
    reservoir (deterministic stride thinning, no RNG — replays emit
    identical metrics) for approximate percentiles."""

    def __init__(self, max_samples: int = 512):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._samples: list[float] = []
        self._max_samples = max_samples
        self._stride = 1

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if (self.count - 1) % self._stride == 0:
            self._samples.append(value)
            if len(self._samples) >= self._max_samples:
                # thin deterministically: keep every other sample, double
                # the stride — the reservoir stays size-bounded and replay-
                # stable (random eviction would jitter the percentiles)
                self._samples = self._samples[::2]
                self._stride *= 2

    def percentile(self, q: float) -> float | None:
        if not self._samples:
            return None
        s = sorted(self._samples)
        idx = min(int(q / 100.0 * len(s)), len(s) - 1)
        return s[idx]

    @property
    def mean(self) -> float | None:
        return self.total / self.count if self.count else None

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class AttainmentWindow:
    """Windowed SLO attainment over keyed latency series (ISSUE 18).

    The autoscaler's sensor: per key (e.g. ``("ttft", "chat")``) a
    bounded FIFO window of the most recent observations; ``attainment``
    is the fraction of the window at or under a budget. Deterministic by
    construction — observations arrive in engine-step order and the
    window is a plain deque, so the same trace always yields the same
    scale decisions (no wall clock, no decay constants to drift)."""

    def __init__(self, window: int = 128):
        assert window >= 1
        self.window = window
        self._series: dict = {}

    def observe(self, key, value: float) -> None:
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = deque(maxlen=self.window)
        s.append(float(value))

    def count(self, key) -> int:
        s = self._series.get(key)
        return len(s) if s is not None else 0

    def attainment(self, key, budget: float) -> float | None:
        """Fraction of the window ≤ ``budget``; None while empty."""
        s = self._series.get(key)
        if not s:
            return None
        return sum(1 for v in s if v <= budget) / len(s)

    def snapshot(self) -> dict:
        return {str(k): {"count": len(s),
                         "newest": s[-1] if s else None}
                for k, s in sorted(self._series.items(), key=lambda t:
                                   str(t[0]))}


class ServingMetrics:
    """The engine's instrument panel (ISSUE 2 tentpole part 4):

    counters — tokens generated, requests submitted/finished, prefills,
    preemptions, decode steps (inner device steps: += horizon per
    dispatch), dispatches (host→device decode launches — at horizon K one
    dispatch covers up to K steps, so dispatches ≲ decode_steps / K),
    host_syncs (dispatches that had to re-upload host slot state after a
    control-plane change — admission, finish, preemption, growth; a quiet
    dispatch reuses the device-resident carry and uploads nothing);
    histograms — TTFT (s), per-token latency (s), queue depth (sampled
    per step), pool occupancy (fraction, sampled per step), batch
    occupancy (active slots per step), per-dispatch device time and host
    overhead (s) — the device/host split bench.py reports.
    """

    def __init__(self):
        self.counters = {
            "requests_submitted": 0,
            "requests_finished": 0,
            "prefills": 0,
            "preemptions": 0,
            "decode_steps": 0,
            "dispatches": 0,
            "host_syncs": 0,
            "tokens_generated": 0,
            # chunked-prefill dispatches (ISSUE 5): with chunking on,
            # EVERY prompt token enters pages through a chunk program —
            # the contiguous-cache converters and the host argmax never
            # run (tests assert this via prefill_chunks > 0)
            "prefill_chunks": 0,
            # sharded serving (ISSUE 8): replicated-decision digest
            # cross-checks run (each one all-gathered the control-plane
            # digest over the mesh and compared every rank to rank 0)
            "digest_checks": 0,
            # disaggregated serving (ISSUE 6): pages pushed over the
            # one-sided shmem layer, migration kernel launches (one per
            # finished chunk with at least one finalized page), and
            # completed prefill→decode handoffs
            "pages_migrated": 0,
            "migrate_chunks": 0,
            "handoffs": 0,
            # robustness ladder (ISSUE 7): signal-deadline expiries that
            # re-issued a chunk's migrate send, requests rescued by
            # decode-local re-prefill after retries ran out, requests
            # that exhausted the whole ladder and were failed (typed,
            # per-request — the engine keeps running), landed reports
            # discarded because their generation tag was stale (they
            # arrived after a retry re-armed the chunk), and host-tier
            # fault-plan injections actually applied to this engine
            "retries": 0,
            "degradations": 0,
            "failed_requests": 0,
            "stale_signals": 0,
            "faults_injected": 0,
            # crash consistency (ISSUE 9): control-plane checkpoints
            # captured into the journal, restores completed (from a
            # checkpoint + journal-suffix replay), digest divergences
            # absorbed by the sharded restore rung instead of raised,
            # and the overload terminals — submits rejected at a full
            # bounded queue, queued requests expired past their TTL
            "checkpoints": 0,
            "restores": 0,
            "digest_recoveries": 0,
            "rejections": 0,
            "expirations": 0,
            # prefix caching (ISSUE 13): admissions that adopted at least
            # one cached page vs admissions that matched nothing, total
            # prompt tokens served straight from adopted pages (never
            # recomputed), copy-on-write page copies (a writer diverged
            # from a shared page), and cached pages reclaimed by LRU
            # eviction to refill the free list
            "prefix_hits": 0,
            "prefix_misses": 0,
            "prefix_hit_tokens": 0,
            "cow_copies": 0,
            "prefix_evictions": 0,
            "prefix_skipped_chunks": 0,
            "router_radix_hits": 0,
            "router_radix_misses": 0,
            # multi-tenant SLO policy (ISSUE 14): admission-scan skips of
            # a class head whose tenant token bucket was dry (mirrored
            # from the scheduler's cumulative count each step), and
            # prefill chunks shrunk below prefill_chunk because a
            # stall-budgeted class was decoding (deadline-aware sizing)
            "quota_throttled": 0,
            "chunk_shrinks": 0,
            # cluster prefix lending (ISSUE 17): completed lends (one per
            # borrowed prefix), pages and prompt tokens delivered through
            # them, lend attempts that degraded to local re-prefill (dead
            # or slow lender — the request proceeds cold, never stalls),
            # and prefixes a restored replica re-warmed from peers
            # instead of cold re-prefilling
            "lends": 0,
            "lent_pages": 0,
            "lend_tokens": 0,
            "lend_degradations": 0,
            "rewarmed_prefixes": 0,
            # elastic autoscaling (ISSUE 18): fleet membership changes
            # (replicas added / drains begun / drains reaching quiescence
            # / replicas retired), queued requests a draining replica
            # handed back through its journal cursor for re-placement on
            # a peer, total replica-steps actually run (the counterfactual
            # bench row divides this by static-peak provisioning), drain-
            # time lend-ahead pushes (one per prefix landed on its
            # rendezvous successor, plus the pages they carried), and
            # lend-ahead attempts that degraded to a typed no-op because
            # an engine lacked the lend surface (mixed fleets)
            "scale_ups": 0,
            "drains_begun": 0,
            "drains_done": 0,
            "retires": 0,
            "requeues": 0,
            "replica_steps": 0,
            "lend_aheads": 0,
            "lend_ahead_pages": 0,
            "lend_ahead_noops": 0,
            # speculative decoding (ISSUE 20): verify dispatches run with
            # speculation on, draft positions those dispatches scored
            # (position 0 consumes the authentic last token, so a
            # K-horizon dispatch drafts K-1), drafts that committed
            # (draft == verified argmax — ``draft_hit_rate`` in
            # ``snapshot()`` is accepted/drafted), and dispatches that
            # rejected a suffix and rewound its KV past the accepted
            # cursor
            "spec_dispatches": 0,
            "draft_tokens": 0,
            "draft_accepted": 0,
            "spec_rewinds": 0,
        }
        self.hist = {
            "ttft_s": Histogram(),
            # TTFT split: queue wait (submit → first admission) vs
            # prefill latency (first admission → first token) — the two
            # levers chunked prefill trades between
            "ttft_queue_s": Histogram(),
            "ttft_prefill_s": Histogram(),
            "tok_latency_s": Histogram(),
            "queue_depth": Histogram(),
            "pool_occupancy": Histogram(),
            "active_slots": Histogram(),
            "step_device_s": Histogram(),
            "step_host_s": Histogram(),
            # per-chunk dispatch latency (one prefill chunk per step max)
            "prefill_stall_s": Histogram(),
            # per-step decode stall: time the step spent on admission +
            # prefill work before the decode dispatch could launch —
            # bounded by one chunk when chunking is on, by the whole
            # prompt (inline prefill) when it is off
            "decode_stall_s": Histogram(),
            # prompt tokens prefilled in the step (the token-space stall
            # bound the simulator regression test asserts: max ≤ chunk)
            "step_prefill_tokens": Histogram(),
            # disaggregated serving (ISSUE 6): per-chunk migration launch
            # latency (s), pages per migrated chunk, and how many decode-
            # worker steps a completed prefill waited for its covering
            # signals (0 = admitted the very step the last chunk landed)
            "migrate_s": Histogram(),
            "migrate_pages_per_chunk": Histogram(),
            "migrate_wait_steps": Histogram(),
            # robustness ladder (ISSUE 7): TTFT of requests that needed
            # at least one retry but still handed off (recovered), TTFT
            # of requests rescued by decode-local re-prefill (degraded;
            # measured at local prefill completion), and prompt tokens
            # re-prefilled locally per degraded chunk — kept OUT of
            # step_prefill_tokens so the decode-cadence isolation
            # invariant (max == 0 on the decode panel in fault-free
            # runs) stays pinned
            "recovered_ttft_s": Histogram(),
            "degraded_ttft_s": Histogram(),
            "degraded_prefill_tokens": Histogram(),
            # crash consistency (ISSUE 9): wall time per checkpoint
            # capture, per restore (snapshot rebuild + journal-suffix
            # replay — host-only, zero dispatches), and per absorbed
            # digest divergence (the sharded restore rung end-to-end)
            "checkpoint_s": Histogram(),
            "restore_s": Histogram(),
            "digest_recovery_s": Histogram(),
            # prefix caching (ISSUE 13): the TTFT split the cache exists
            # to move — first-token latency of admissions that adopted
            # cached pages vs ones that prefilled from scratch
            "ttft_cached_s": Histogram(),
            "ttft_cold_s": Histogram(),
            # overlapped serving (ISSUE 16): per-decode-step EP wire time
            # split by the wire-fit model — comm the schedule still
            # exposes on the critical path vs comm hidden behind expert
            # FFN compute by the microbatch pipeline. MODELED (t = t0 +
            # bytes/BW per a2a round), not wall clock: CPU test runs
            # serialize ranks and can never exhibit real overlap, so the
            # honest number is the model, labeled as such (docs/
            # serving.md). overlap=off exposes everything; n_ep=1 has no
            # wire and observes zeros.
            "exposed_comm_us": Histogram(),
            "overlapped_comm_us": Histogram(),
            # long-context serving (ISSUE 19): per-decode-step attention
            # split under ``flash_decode_dist`` — the local per-page
            # partial walk (∝ this rank's OWN slice of the block-table
            # pages: the half that shrinks as the SP mesh grows) vs the
            # fixed-order fold's wait on the remote partial slabs.
            # MODELED on the same wire fit as exposed/overlapped_comm_us
            # (CPU runs serialize ranks and cannot exhibit the real
            # overlap), labeled as such in docs/serving.md; zeros outside
            # long_context mode.
            "attn_local_us": Histogram(),
            "attn_fold_wait_us": Histogram(),
            # cluster prefix lending (ISSUE 17): the kill/restore TTFT
            # split — cold (no cached pages), cached (locally cached
            # pages adopted), re-warmed (adopted pages arrived via the
            # lending tier: a peer's lend or a post-restore re-warm).
            # The ``_steps`` trio is the deterministic engine-step-space
            # twin the SimEngine/cluster_sim panels report (wall TTFT is
            # meaningless for a host-only engine); ``ttft_rewarmed_s``
            # extends the ISSUE 13 wall-clock pair for device engines.
            "ttft_rewarmed_s": Histogram(),
            "ttft_cold_steps": Histogram(),
            "ttft_cached_steps": Histogram(),
            "ttft_rewarmed_steps": Histogram(),
            # lend wall time per page (µs) — the bench row
            "lend_us_per_page": Histogram(),
            # elastic autoscaling (ISSUE 18): deterministic step-space
            # TTFT/ITL (the series the per-class SLO attainment windows
            # sample — wall clock would make scale decisions replay-
            # unstable), fleet size sampled once per cluster step, and
            # the wall seconds each scale-up spent building its engine
            # (artifact load dominates when an AOT artifact is threaded —
            # the scale-up-to-first-token split cluster_sim reports)
            "ttft_steps": Histogram(),
            "itl_steps": Histogram(),
            "fleet_size": Histogram(),
            "scale_up_build_s": Histogram(),
            # speculative decoding (ISSUE 20): tokens COMMITTED per slot
            # per verify dispatch (1 = speculation earned nothing over
            # greedy that dispatch; mean > 1 is the whole win — the bench
            # gate asserts it on the repetitive workload)
            "accepted_per_dispatch": Histogram(),
        }
        self._t0 = time.perf_counter()

    def inc(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def observe(self, name: str, value: float) -> None:
        self.hist[name].observe(value)

    # -- per-class labels (ISSUE 14) --------------------------------------
    # Labeled series live in the SAME flat dicts under Prometheus-style
    # keys (``ttft_s{class=chat}``), created lazily on first touch so an
    # unpoliced engine emits exactly the pre-ISSUE-14 panel. ``itl_s`` is
    # the per-class twin of ``tok_latency_s`` (inter-token latency).
    @staticmethod
    def class_key(name: str, cls: str) -> str:
        return f"{name}{{class={cls}}}"

    def inc_class(self, name: str, cls: str | None, by: int = 1) -> None:
        if cls is None:
            return
        key = self.class_key(name, cls)
        self.counters[key] = self.counters.get(key, 0) + by

    def observe_class(self, name: str, cls: str | None,
                      value: float) -> None:
        if cls is None:
            return
        key = self.class_key(name, cls)
        if key not in self.hist:
            self.hist[key] = Histogram()
        self.hist[key].observe(value)

    def classes(self) -> list[str]:
        """Class labels seen so far (sorted — deterministic panels)."""
        out = set()
        for d in (self.counters, self.hist):
            for k in d:
                if "{class=" in k:
                    out.add(k.split("{class=", 1)[1].rstrip("}"))
        return sorted(out)

    def per_class(self) -> dict:
        """The two-panel serve_sim summary's per-class block: TTFT/ITL
        p50/p99 plus the shed/throttle counts, one entry per class."""
        out = {}
        for cls in self.classes():
            ttft = self.hist.get(self.class_key("ttft_s", cls))
            itl = self.hist.get(self.class_key("itl_s", cls))
            out[cls] = {
                "ttft_p50_s": ttft.percentile(50) if ttft else None,
                "ttft_p99_s": ttft.percentile(99) if ttft else None,
                "itl_p50_s": itl.percentile(50) if itl else None,
                "itl_p99_s": itl.percentile(99) if itl else None,
                "finished": self.counters.get(
                    self.class_key("requests_finished", cls), 0),
                "rejections": self.counters.get(
                    self.class_key("rejections", cls), 0),
                "expirations": self.counters.get(
                    self.class_key("expirations", cls), 0),
            }
        return out

    def snapshot(self) -> dict:
        wall = time.perf_counter() - self._t0
        toks = self.counters["tokens_generated"]
        drafted = self.counters["draft_tokens"]
        return {
            "wall_s": round(wall, 4),
            "tok_per_s": round(toks / wall, 2) if wall > 0 else None,
            # derived: fraction of draft positions whose token committed
            # (ISSUE 20); None when speculation never drafted
            "draft_hit_rate": round(
                self.counters["draft_accepted"] / drafted, 4)
                if drafted else None,
            **self.counters,
            **{k: v.summary() for k, v in self.hist.items()},
        }

    def json_line(self) -> str:
        return json.dumps(self.snapshot())

    def emit(self, file=None) -> None:
        """Print one JSON line (the serve_sim / log-scraper format)."""
        print(self.json_line(), file=file)


__all__ = ["AttainmentWindow", "Histogram", "ServingMetrics"]
