"""Distributed train-step composition tests: dp/tp/sp GSPMD sharding, GPipe
pipeline parallelism, and GShard MoE expert parallelism — one jitted step
each on the virtual CPU mesh (this is what the driver's multi-chip dryrun
compiles; beyond the reference's kernel-library scope, SURVEY.md §2.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401  (conftest sets up the mesh)
from triton_dist_tpu.models.llama import LlamaConfig
from triton_dist_tpu.models.moe import MoEConfig
from triton_dist_tpu.parallel import (ParallelPlan, factorize_devices,
                                      make_mesh, make_train_step)


def _tokens(cfg, B=4, S=16):
    vocab = cfg.base.vocab_size if isinstance(cfg, MoEConfig) else cfg.vocab_size
    return jax.random.randint(jax.random.key(7), (B, S), 0, vocab)


def test_factorize_devices():
    assert factorize_devices(8) == {"dp": 2, "pp": 2, "tp": 2}
    assert factorize_devices(4) == {"dp": 1, "pp": 2, "tp": 2}
    assert factorize_devices(2) == {"dp": 1, "pp": 1, "tp": 2}
    assert factorize_devices(1) == {"dp": 1, "pp": 1, "tp": 1}


def test_dense_dp_tp_sp_step():
    cfg = LlamaConfig.tiny(n_layers=2)
    mesh = make_mesh({"dp": 2, "tp": 2})
    plan = ParallelPlan(dp="dp", tp="tp", sp=True)
    init_fn, step_fn = make_train_step(cfg, mesh, plan)
    with jax.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        tokens = _tokens(cfg)
        losses = []
        for _ in range(3):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    # same batch re-fed: the optimizer must reduce the loss
    assert losses[-1] < losses[0], losses


def test_dense_pp_matches_no_pp():
    """GPipe pipeline forward/backward must be numerically equivalent to the
    sequential layer scan."""
    cfg = LlamaConfig.tiny(n_layers=2)
    tokens = _tokens(cfg)

    mesh1 = make_mesh({"dp": 1, "tp": 2})
    init1, step1 = make_train_step(cfg, mesh1, ParallelPlan(dp="dp", tp="tp"))
    mesh2 = make_mesh({"pp": 2, "tp": 2})
    init2, step2 = make_train_step(
        cfg, mesh2, ParallelPlan(dp=None, tp="tp", pp="pp", n_micro=2))

    with jax.set_mesh(mesh1):
        s1 = init1(jax.random.key(0))
        _, loss1 = step1(s1, tokens)
    with jax.set_mesh(mesh2):
        s2 = init2(jax.random.key(0))
        _, loss2 = step2(s2, tokens)
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-2)


def test_moe_ep_step():
    cfg = MoEConfig.tiny(n_layers=2, num_experts=4)
    mesh = make_mesh({"dp": 2, "ep": 2})
    plan = ParallelPlan(dp="dp", tp=None, ep="ep", sp=False)
    init_fn, step_fn = make_train_step(cfg, mesh, plan)
    with jax.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        state, loss = step_fn(state, _tokens(cfg))
    assert np.isfinite(float(loss))


def _cp_cfg():
    # ring attention needs lane-multiple head_dim: 512 / 4 = 128
    return LlamaConfig(vocab_size=512, d_model=512, n_layers=2, n_heads=4,
                       n_kv_heads=2, d_ff=256, max_seq_len=64)


def test_dense_dp_cp_step():
    """Context-parallel training: ring attention over the cp axis, batch
    over dp (sequence dim sharded end-to-end; the long-context training
    composition the reference lacks, SURVEY §5.7)."""
    cfg = _cp_cfg()
    mesh = make_mesh({"dp": 2, "cp": 2})
    plan = ParallelPlan(dp="dp", tp=None, cp="cp", sp=False)
    init_fn, step_fn = make_train_step(cfg, mesh, plan)
    with jax.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        tokens = _tokens(cfg, B=4, S=32)
        losses = []
        for _ in range(3):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_dense_tp_cp_step():
    """cp composes with tp: heads ride the tp axis (independent rings per
    tp row), params Megatron-sharded."""
    cfg = _cp_cfg()
    mesh = make_mesh({"tp": 2, "cp": 2})
    plan = ParallelPlan(dp=None, tp="tp", cp="cp", sp=False)
    init_fn, step_fn = make_train_step(cfg, mesh, plan)
    with jax.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        tokens = _tokens(cfg, B=2, S=32)
        state, loss = step_fn(state, tokens)
        state, loss2 = step_fn(state, tokens)
    assert np.isfinite(float(loss)) and float(loss2) < float(loss)


def test_cp_matches_dense_forward():
    """The cp loss equals the no-cp loss on identical params/tokens."""
    cfg = _cp_cfg()
    mesh = make_mesh({"cp": 2})
    plan_cp = ParallelPlan(dp=None, tp=None, cp="cp", sp=False)
    plan_ref = ParallelPlan(dp=None, tp=None, sp=False)
    init_cp, step_cp = make_train_step(cfg, mesh, plan_cp)
    init_ref, step_ref = make_train_step(cfg, mesh, plan_ref)
    with jax.set_mesh(mesh):
        tokens = _tokens(cfg, B=2, S=32)
        s_cp = init_cp(jax.random.key(0))
        s_ref = init_ref(jax.random.key(0))
        _, l_cp = step_cp(s_cp, tokens)
        _, l_ref = step_ref(s_ref, tokens)
    np.testing.assert_allclose(float(l_cp), float(l_ref), rtol=2e-3)


def test_moe_pp_step():
    """PP+MoE composition: GPipe wavefront with per-stage MoE blocks and
    bubble-masked aux-loss accumulation."""
    cfg = MoEConfig.tiny(n_layers=2, num_experts=4)
    mesh = make_mesh({"pp": 2})
    plan = ParallelPlan(dp=None, tp=None, pp="pp", ep=None, sp=False,
                        n_micro=2)
    init_fn, step_fn = make_train_step(cfg, mesh, plan)
    with jax.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        tokens = _tokens(cfg.base, B=4, S=16)
        losses = []
        for _ in range(3):
            state, loss = step_fn(state, tokens)
            losses.append(float(loss))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_moe_pp_matches_no_pp():
    """PP+MoE loss ~= the no-pp MoE loss on identical params/tokens (the
    balance aux is microbatch-averaged under pp — tolerance covers it)."""
    cfg = MoEConfig.tiny(n_layers=2, num_experts=4)
    mesh = make_mesh({"pp": 2})
    plan_pp = ParallelPlan(dp=None, tp=None, pp="pp", ep=None, sp=False,
                          n_micro=2)
    plan_ref = ParallelPlan(dp=None, tp=None, ep=None, sp=False)
    init_pp, step_pp = make_train_step(cfg, mesh, plan_pp)
    init_ref, step_ref = make_train_step(cfg, mesh, plan_ref)
    with jax.set_mesh(mesh):
        tokens = _tokens(cfg.base, B=4, S=16)
        s_pp = init_pp(jax.random.key(0))
        s_ref = init_ref(jax.random.key(0))
        _, l_pp = step_pp(s_pp, tokens)
        _, l_ref = step_ref(s_ref, tokens)
    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=5e-2)
