"""``dl`` — the device-language surface of triton_dist_tpu.

Mirrors the reference's ``triton_dist.language`` builtins
(python/triton_dist/language.py:57-112: ``wait``, ``consume_token``,
``rank``, ``num_ranks``, ``symm_at``, ``notify``) so kernels here read like
the reference's kernels, while lowering to TPU-native constructs:

- the reference's *token* discipline (``wait`` returns a token,
  ``consume_token`` creates an artificial data dependency so loads are
  ordered after the spin-wait — DistributedOps.td:79-109) is unnecessary on
  TPU: Pallas semaphore waits are program-ordered with subsequent DMA/compute
  already. ``wait``/``consume_token`` are kept for API parity and readability.
- ``notify``'s SET mode (DistributedAttrDefs.td:36-40) has no TPU analog —
  TPU semaphores count; all protocols in ``ops/`` use arrival counting.

Usage inside a Pallas kernel::

    import triton_dist_tpu.language as dl
    me = dl.rank("x")
    dl.notify(peer_sem, dl.symm_at(("x",), "x", peer), inc=1)
    token = dl.wait(recv_sem, 1)
    data = dl.consume_token(buf_ref, token)
"""

from __future__ import annotations

from typing import Sequence

from triton_dist_tpu.shmem import device as _shd

rank = _shd.my_pe
num_ranks = _shd.n_pes


def wait(sem_ref, count):
    """Wait until ``sem_ref`` has accumulated ``count`` arrivals (consuming
    them), and return a token ordering subsequent accesses. Analog of
    ``dl.wait(barrier_ptrs, N, scope, semantic)`` (language.py:57-71); scope
    and memory semantics are implicit in TPU semaphore hardware."""
    _shd.signal_wait_until(sem_ref, count)
    return ()


def wait_recv(dst_ref, recv_sem):
    """Wait for delivery of a one-sided put into ``dst_ref`` (DMA-semaphore
    flavor of ``wait``)."""
    _shd.wait_recv(dst_ref, recv_sem)
    return ()


def consume_token(ref, token):
    """API-parity no-op (language.py:74-81): on TPU the wait above already
    orders the accesses below it."""
    del token
    return ref


def notify(sem_ref, pe=None, inc=1):
    """Signal a (possibly remote) semaphore — analog of ``dl.notify``
    (language.py:103-112) with ADD semantics."""
    _shd.signal_op(sem_ref, inc, pe)


def symm_at(axis_names: Sequence[str], axis: str, index):
    """Flat logical device id of the peer at ``index`` along ``axis`` —
    the addressing analog of ``dl.symm_at(ptr, rank)`` (language.py:96-100):
    no pointer translation, remote refs are (buffer, device_id) pairs."""
    return _shd.pe_at(axis_names, axis, index)
