"""GEMM-ReduceScatter overlap (analog of reference
python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py).

The reference runs a producer GEMM that writes tiles into a symmetric buffer
and sets per-tile scatter signals, with a reduce-scatter consumer draining
them on a second stream under an SM budget (gemm_reduce_scatter.py:77-87,
:104-234, :482-521). TPU-native single-kernel design:

1. Walk output segments in swizzled order ``me+1, me+2, …, me`` (own segment
   LAST — its result never travels, so remote partials spend the longest
   possible time in flight behind compute).
2. For each remote segment: pipelined MXU GEMM of that segment's rows into a
   double-buffered staging slot, then a non-blocking put of the partial into
   the owner's symmetric slot ``me``. Stage slots are reused every 2 steps,
   guarded by the send semaphore of the put issued 2 steps earlier.
3. Own segment: GEMM straight into our symmetric slot ``me`` (no copy).
4. Reduce phase: wait each peer's arrival once, then a pipelined VPU
   reduction over the ``n`` partial slots → output shard.

Row-parallel TP semantics: A is [M, K] K-sharded, B is [K, N] K-sharded
(row-parallel weight); each rank's partial is A_local @ B_local and ranks
receive the M/n rows they own, summed over all ranks.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.ops.gemm import GemmConfig, emit_gemm
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _gemm_rs_kernel(axis, mesh_axes, cfg, acc_dtype,
                    a_ref, b_ref, out_ref, ws_ref, stage_ref,
                    send_sems, recv_sems):
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    m_seg = out_ref.shape[0]

    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    rdmas = [None] * max(n - 1, 0)
    for s in range(n - 1):
        seg = lax.rem(me + 1 + s, n)
        slot = s % 2
        if s >= 2:
            rdmas[s - 2].wait_send()  # stage slot free again
        emit_gemm(a_ref.at[pl.ds(seg * m_seg, m_seg)], b_ref,
                  stage_ref.at[slot], cfg, acc_dtype)
        pid = shd.pe_at(mesh_axes, axis, seg)
        rdmas[s] = shd.putmem_nbi(ws_ref.at[me], stage_ref.at[slot],
                                  send_sems.at[slot], recv_sems.at[me], pid)

    # own segment straight into our own slot
    emit_gemm(a_ref.at[pl.ds(me * m_seg, m_seg)], b_ref,
              ws_ref.at[me], cfg, acc_dtype)

    for s in range(max(n - 3, 0), n - 1):
        rdmas[s].wait_send()
    for p in range(1, n):
        src = lax.rem(me + p, n)
        shd.wait_recv(ws_ref.at[src], recv_sems.at[src])

    # reduction over the n partial slots (VPU), pipelined over output tiles
    bm = min(cfg.block_m, m_seg)
    N = out_ref.shape[1]
    bn = min(cfg.block_n, N)

    def body(ws_blk, o_blk):
        o_blk[...] = jnp.sum(
            ws_blk[...].astype(jnp.float32), axis=0
        ).astype(out_ref.dtype)

    pltpu.emit_pipeline(
        body,
        grid=(m_seg // bm, N // bn),
        in_specs=[pl.BlockSpec((n, bm, bn), lambda i, j: (0, i, j))],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )(ws_ref, out_ref)


def gemm_rs(ctx: ShmemContext, a: jax.Array, b: jax.Array,
            axis: str | None = None, cfg: GemmConfig | None = None,
            out_dtype=None) -> jax.Array:
    """Row-parallel GEMM + ReduceScatter: ``a`` [M, K] sharded P(None, axis),
    ``b`` [K, N] sharded P(axis, None). Returns sum_r(a_r @ b_r) scattered
    over M — global [M, N] sharded P(axis). Entry analog: ``gemm_rs``
    (gemm_reduce_scatter.py:524-538); golden: dot + psum_scatter."""
    axis = axis or ctx.axis_names[0]
    cfg = cfg or GemmConfig()
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    M, K = a.shape
    Kb, N = b.shape
    assert K == Kb, f"A/B inner dims {K} vs {Kb}"
    assert M % n == 0, f"M={M} not divisible by ranks {n}"
    m_seg = M // n
    out_dtype = out_dtype or a.dtype
    acc_dtype = jnp.float32 if out_dtype == jnp.bfloat16 else out_dtype
    # clamp tiles to the segment, then require exact divisibility
    cfg = GemmConfig(block_m=min(cfg.block_m, m_seg),
                     block_n=min(cfg.block_n, N))
    assert m_seg % cfg.block_m == 0, (
        f"segment rows {m_seg} not divisible by block_m {cfg.block_m}")
    assert N % cfg.block_n == 0, (
        f"N={N} not divisible by block_n {cfg.block_n}")
    k_local_g = K // n
    assert cfg.vmem_ok(k_local_g, jnp.dtype(a.dtype).itemsize), (
        f"tile config exceeds VMEM budget for K_local={k_local_g}")

    def f(a_shard, b_shard):
        kernel = lambda *refs: _gemm_rs_kernel(axis, mesh_axes, cfg,
                                               acc_dtype, *refs)
        k_local = a_shard.shape[1]
        out, _ws, _stage = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((m_seg, N), out_dtype),
                jax.ShapeDtypeStruct((n, m_seg, N), acc_dtype),   # symm slots
                jax.ShapeDtypeStruct((2, m_seg, N), acc_dtype),   # send stage
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((n,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for("gemm_rs")),
            cost_estimate=pl.CostEstimate(
                flops=2 * M * N * k_local,
                bytes_accessed=(a_shard.size + b_shard.size + m_seg * N)
                * jnp.dtype(a_shard.dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )(a_shard, b_shard)
        return out

    sm = ctx.shard_map(f, in_specs=(P(None, axis), P(axis, None)),
                       out_specs=P(axis))
    return sm(a, b)


__all__ = ["gemm_rs"]
