"""Tutorial 03 — ReduceScatter: 1-D ring with ack-credit flow control, and
the 2-D hierarchical multi-tier form.

Analog of reference tutorials/05 + kernels/nvidia/reduce_scatter.py. Each
segment travels the ring once, accumulating every PE's contribution on the
VPU; relay slots are reused under receiver ack credits. The ring_2d form
reduces along the fast (minor) axis first so each row crosses the slow
tier exactly once, already reduced.

Run:  python -m tutorials.t03_reduce_scatter [--sim 6] [--case correctness]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops import reduce_scatter
    ctx = world_context()
    n = ctx.num_ranks
    x = jnp.round(jax.random.normal(jax.random.key(0), (n * 32, 128)) * 4)
    xs = ctx.shard(x.astype(jnp.float32), P("x"))
    got = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))(xs)
    gold = jax.jit(ctx.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))(xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold))
    print(f"ring reduce_scatter over {n} PEs == psum_scatter golden")


@register_case("correctness_2d")
def correctness_2d():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tutorials.common import world_size
    from triton_dist_tpu.ops import reduce_scatter
    n_dev = world_size()
    if n_dev < 4 or n_dev % 2:
        raise SystemExit(f"need an even device count >= 4, have {n_dev} "
                         "(try --sim 6)")
    ctx = world_context(axis_names=("a", "b"), mesh_shape=(2, n_dev // 2))
    x = jnp.round(jax.random.normal(jax.random.key(1),
                                    (n_dev * n_dev * 4, 128)) * 4)
    xs = ctx.shard(x.astype(jnp.float32), P(("a", "b")))
    got = jax.jit(lambda v: reduce_scatter(ctx, v))(xs)
    gold = jax.jit(ctx.shard_map(
        lambda s: jax.lax.psum_scatter(s, ("a", "b"), scatter_dimension=0,
                                       tiled=True),
        in_specs=P(("a", "b")), out_specs=P(("a", "b"))))(xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(gold))
    print(f"hierarchical ring_2d RS over a (2, {n_dev // 2}) mesh == golden")


@register_case("perf")
def perf():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops import reduce_scatter
    ctx = world_context()
    n = ctx.num_ranks
    x = jax.random.normal(jax.random.key(0), (n * 256, 256), jnp.float32)
    xs = ctx.shard(x, P("x"))
    f = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))
    perf_report("reduce_scatter[ring]", time_op(lambda: f(xs)),
                f"({xs.nbytes / 1e6:.1f} MB global)")


if __name__ == "__main__":
    tutorial_main(__doc__)
