"""Tutorial 04 — low-latency EP All-to-All dispatch/combine (+ fp8 wire).

Analog of reference tutorials/04 + low_latency_all_to_all.py (the README
showcase kernel: 137 µs vs DeepEP's 182 µs on 32 GPUs, fp8 + scale
side-channel). Routing is a static-shape VPU cumsum (no atomic slot
counters); the wire is one put per (peer, payload); fp8 mode quantizes
tokens per-row with an f32 scale payload.

Run:  python -m tutorials.t04_all_to_all [--sim 4]
      python -m tutorials.t04_all_to_all --case correctness_fp8
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _roundtrip(ctx, wire_dtype=None):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.all_to_all import (combine,
                                                create_all_to_all_context,
                                                dispatch)
    n = ctx.num_ranks
    T, H, topk = n * 16, 256, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x",
                                    wire_dtype=wire_dtype)
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(1), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def run(t, i, ww):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, ww)   # identity expert

    out = jax.jit(run)(ctx.shard(tokens, P("x")), ctx.shard(ids, P("x")),
                       ctx.shard(w, P("x")))
    tol = 0.15 if wire_dtype is not None else 0.03
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(tokens, np.float32),
                               rtol=tol, atol=tol)
    return a2a


@register_case("correctness")
def correctness():
    ctx = world_context()
    a2a = _roundtrip(ctx)
    print(f"dispatch→combine roundtrip over {a2a.n_ranks} PEs "
          f"(cap={a2a.capacity}/pair) == identity")


@register_case("correctness_fp8")
def correctness_fp8():
    import jax.numpy as jnp
    ctx = world_context()
    a2a = _roundtrip(ctx, wire_dtype=jnp.float8_e4m3fn)
    print(f"fp8-wire roundtrip over {a2a.n_ranks} PEs within quantization "
          "tolerance")


@register_case("perf")
def perf():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.all_to_all import (create_all_to_all_context,
                                                dispatch)
    ctx = world_context()
    n = ctx.num_ranks
    # the DeepSeek-infer BASELINE shape (128 tok/rank, topk=8, h=7168)
    T, H, topk, E = n * 128, 7168, 8, max(64, n)
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=E - E % n or n,
                                    axis="x")
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32
                               ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(1), (T, topk), 0,
                             a2a.num_experts)
    ts = ctx.shard(tokens, P("x"))
    ids_s = ctx.shard(ids, P("x"))
    f = jax.jit(lambda t, i: dispatch(a2a, t, i)[0])
    s = time_op(lambda: f(ts, ids_s), iters=20)
    perf_report("a2a dispatch (deepseek-infer shape)", s)


if __name__ == "__main__":
    tutorial_main(__doc__)
