"""MoE tensor-parallel overlap ops (analog of reference
python/triton_dist/kernels/nvidia/allgather_group_gemm.py and
moe_reduce_rs.py).

Both ops are single arrival-driven Pallas kernels — the collective and the
grouped expert GEMM genuinely overlap, matching the reference's defining
capability:

- ``ag_moe_group_gemm`` reuses the AG-GEMM skeleton (allgather_gemm.py
  here): non-blocking puts of the local token block to every peer, then a
  swizzled start-local segment walk where each remote segment is waited
  once and immediately fed to the in-kernel grouped GEMM
  (``emit_grouped_gemm``). Reference:
  kernel_consumer_m_parallel_scatter_group_gemm
  (allgather_group_gemm.py:229-316) waits per token-block; TPU grids are
  sequential per core, so the per-*segment* wait is the same granularity
  the hardware can exploit.
- ``moe_reduce_rs`` reuses the GEMM-RS skeleton (gemm_reduce_scatter.py):
  own-segment-last swizzle, per-segment grouped GEMM into a
  double-buffered send stage, non-blocking put of each partial to its
  owner, then a pipelined reduction over the n arrived partials.
  Reference: producer grouped-GEMM scatter kernel + topk-reduce-RS
  consumer (moe_reduce_rs.py:365-548).

TPU-native routing design — *sender-side alignment*: each segment's tokens
are sorted by expert and block-padded BEFORE they ride the wire, so every
wire block is expert-pure and the consumer needs only a scalar-prefetch
``block_expert`` table (no receiver-side row gather, which TPU DMA does
poorly). Routing ids are allgathered first as a small lane-aligned int32
wire (the reference distributes topk ids ahead of the fused kernel the same
way, allgather_group_gemm.py:317-440); all alignment metadata is then
recomputed identically on every rank from the gathered ids. For
``moe_reduce_rs``, the topk fold commutes with the cross-rank sum, so the
ring reduces *aligned* rows and the fold + unscramble run once at the end.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.allgather_gemm import (ag_overlap_protocol,
                                                ag_overlap_protocol_2d)
from triton_dist_tpu.ops.common import collective_id_for, norm_axis
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.ops.gemm_reduce_scatter import (emit_slot_reduction,
                                                     rs_overlap_protocol)
from triton_dist_tpu.ops.group_gemm import (align_tokens_by_expert,
                                            emit_grouped_gemm)
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _default_bn(k_contract: int, n_avail: int, dtype) -> int:
    """Weight-tile width for the fused grouped GEMMs: sub-256 KB tiles are
    DMA-overhead-bound (the down grouped GEMM measured 433→200 µs going
    128→512, docs/benchmarks.md tile sweep), but the (k_contract, bn) tile
    must stay inside a ~4 MB double-buffered budget at large contraction
    dims. One definition — both fused MoE ops share it."""
    itemsize = jnp.dtype(dtype).itemsize
    cap = max(128, (4 * 2**20) // (2 * k_contract * itemsize) // 128 * 128)
    return min(512, n_avail, cap)


def _gather_ids(ctx: ShmemContext, ids: jax.Array, axis, t_local: int):
    """AllGather routing ids as a lane-aligned int32 wire block; returns the
    [n, t_local] gathered id matrix (replicated). ``axis`` may be a tuple
    (hierarchical push)."""
    n = ctx.axis_size(axis)
    pad = _round_up(t_local, 128) - t_local

    def pack(ids_shard):
        w = jnp.pad(ids_shard, (0, pad), constant_values=-1)
        return w.reshape(-1, 128)

    ids_wire = ctx.shard_map(pack, in_specs=P(axis), out_specs=P(axis))(ids)
    if isinstance(axis, tuple):
        g = all_gather(ctx, ids_wire, axis=axis, method="push_2d")
    else:
        g = all_gather(ctx, ids_wire, axis=axis, method="push")
    return g.reshape(n, -1)[:, :t_local]


def _segment_alignment(gids: jax.Array, num_experts: int, block_m: int):
    """Per-segment sender-side alignment metadata from the gathered ids
    [n, t_seg_rows] — identical on every rank by construction. Returns
    (gather_idx, row_valid, block_expert, n_blocks_used[n]) — the last is
    the per-segment runtime block bound the fused kernels use to skip
    padding blocks (reference ``num_tokens_post_padded`` parity)."""
    return jax.vmap(
        lambda i: align_tokens_by_expert(i, num_experts, block_m,
                                         with_used_count=True))(gids)


# ---------------------------------------------------------------------------
# AG + GroupGEMM (fused)
# ---------------------------------------------------------------------------

def _ag_moe_xla(ctx: ShmemContext, tokens, ids, weights, axis):
    """XLA-collective AG-MoE for a token axis that crosses slice boundaries
    (``is_dcn_axis``): remote DMA cannot cross DCN, so the token + routing-id
    gather runs as plain ``lax.all_gather`` over every tier and the grouped
    GEMM as a masked dense per-expert matmul — the op's golden, computed
    directly (the MoE twin of gemm_reduce_scatter's ``_gemm_rs_xla``).
    Output layout matches the fused path: [T, N] sharded P(None, axis)."""
    axes_t = axis if isinstance(axis, tuple) else (axis,)
    E = weights.shape[0]
    out_dtype = tokens.dtype

    def f(tok_shard, ids_shard, w_shard):
        tok, gids = tok_shard, ids_shard
        for ax in reversed(axes_t):     # P(axes) flattening order
            tok = lax.all_gather(tok, ax, axis=0, tiled=True)
            gids = lax.all_gather(gids, ax, axis=0, tiled=True)
        out = jnp.zeros((tok.shape[0], w_shard.shape[-1]), jnp.float32)
        for e in range(E):              # -1 pad rows match no expert
            ye = jnp.dot(tok, w_shard[e],
                         preferred_element_type=jnp.float32)
            out = out + jnp.where((gids == e)[:, None], ye, 0.0)
        return out.astype(out_dtype)

    sm = ctx.shard_map(f, in_specs=(P(axis), P(axis), P(None, None, axis)),
                       out_specs=P(None, axis))
    return sm(tokens, ids, weights)


def _ag_moe_kernel(axis, mesh_axes, bm, bn, out_dtype, n_blocks,
                   x_ref, w_ref, be_ref, nb_ref, out_ref, ws_ref,
                   send_sems, recv_sems):
    P_s = x_ref.shape[0]

    def emit(src_ref, seg):
        emit_grouped_gemm(src_ref, w_ref, out_ref.at[pl.ds(seg * P_s, P_s)],
                          be_ref, seg * n_blocks, bm, bn, out_dtype,
                          n_blocks_used=nb_ref[seg])

    if isinstance(axis, tuple) and len(axis) > 1:
        ag_overlap_protocol_2d(axis, mesh_axes, x_ref, ws_ref,
                               send_sems, recv_sems, emit)
    else:
        ag_overlap_protocol(axis, mesh_axes, x_ref, ws_ref,
                            send_sems, recv_sems, emit)


def ag_moe_group_gemm(ctx: ShmemContext, tokens: jax.Array, ids: jax.Array,
                      weights: jax.Array, axis: str | None = None,
                      block_m: int = 128,
                      block_n: int | None = None) -> jax.Array:
    """tokens [T, H] sharded P(axis); ids [T] int32 expert per row (-1 pad);
    weights [E, H, N] sharded P(None, None, axis) (N column-parallel).
    Returns all ranks' tokens processed by their experts against the local
    weight shard: [T, N_local] per device → global [T, N] sharded
    P(None, axis). Golden: all_gather + dense per-expert matmul.
    Entry analog: ag_group_gemm_intra_node
    (allgather_group_gemm.py:317-770). ``axis`` may be an (outer, inner…)
    tuple — the hierarchical 2-tier AG feeds the grouped GEMM (inter-node
    analog, allgather_group_gemm.py:171-228). A DCN (slice-crossing) axis
    routes to the XLA-collective fallback — remote DMA cannot cross DCN —
    and must sit at the FRONT of a hierarchical tuple (slow tier
    outermost), same rules as ``gemm_rs``/``ag_gemm``."""
    axis = norm_axis(ctx, axis)
    if isinstance(axis, tuple):
        dcn = tuple(ax for ax in axis if ctx.is_dcn_axis(ax))
        if dcn and dcn != axis[:len(dcn)]:
            raise ValueError(
                f"DCN (slice-crossing) axes {dcn} must come first in the "
                f"hierarchical axis tuple {axis} — put the slow tier "
                "outermost (the fast-tier gather is remote DMA, which "
                "cannot cross DCN; cf. ag_moe_group_gemm docstring)")
        if dcn:
            # DCN-prefix group: the whole gather goes over XLA transport
            # (a mixed DCN-outer/Pallas-inner tier swap would need the
            # grouped-GEMM alignment recomputed per tier — correctness
            # first, the fused fast tier stays ICI-only)
            return _ag_moe_xla(ctx, tokens, ids, weights, axis)
    elif ctx.is_dcn_axis(axis):
        return _ag_moe_xla(ctx, tokens, ids, weights, axis)
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    T, H = tokens.shape
    E = weights.shape[0]
    assert T % n == 0
    t_local = T // n
    bm = block_m
    P_s = _round_up(t_local, bm) + E * bm
    n_blocks = P_s // bm
    out_dtype = tokens.dtype

    gids = _gather_ids(ctx, ids, axis, t_local)               # [n, t_local]
    gi, rv, be, nb = _segment_alignment(gids, E, bm)          # [n, P_s] ×2, [n, n_blocks], [n]
    be_flat = be.reshape(-1)

    def f(tok_shard, gi_full, rv_full, be_full, nb_full, w_shard):
        me = shd.my_pe(axis)
        # sender-side alignment of MY segment's tokens
        gi_me = lax.dynamic_index_in_dim(gi_full, me, keepdims=False)
        rv_me = lax.dynamic_index_in_dim(rv_full, me, keepdims=False)
        x = tok_shard[gi_me] * rv_me[:, None].astype(tok_shard.dtype)

        n_local = w_shard.shape[-1]
        # emit_grouped_gemm gcd-clamps when n_local is narrower
        bn = block_n or _default_bn(H, n_local, w_shard.dtype)
        kernel = lambda *refs: _ag_moe_kernel(axis, mesh_axes, bm,
                                              bn, out_dtype,
                                              n_blocks, *refs)
        y, _ws = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((n * P_s, n_local), out_dtype),
                jax.ShapeDtypeStruct((n, P_s, H), tok_shard.dtype),  # symm ws
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((n,)),
                pltpu.SemaphoreType.DMA((n,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"ag_moe_{axis}")),
            cost_estimate=pl.CostEstimate(
                flops=2 * n * P_s * H * n_local,
                bytes_accessed=(n * P_s * (H + n_local) + E * H * n_local)
                * jnp.dtype(tok_shard.dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )(x, w_shard, be_full, nb_full)

        # unscramble: aligned rows → original token order (invalid → drop;
        # this also drops the garbage rows past each segment's block bound)
        dest = jnp.arange(n, dtype=jnp.int32)[:, None] * t_local + gi_full
        dest = jnp.where(rv_full, dest, T).reshape(-1)
        valid = rv_full.reshape(-1)[:, None].astype(y.dtype)
        return jnp.zeros((T, n_local), y.dtype).at[dest].add(
            y * valid, mode="drop")

    sm = ctx.shard_map(
        f, in_specs=(P(axis), P(None, None), P(None, None), P(None), P(None),
                     P(None, None, axis)),
        out_specs=P(None, axis))
    return sm(tokens, gi, rv, be_flat, nb, weights)


# ---------------------------------------------------------------------------
# GroupGEMM + topk-reduce + RS (fused)
# ---------------------------------------------------------------------------

def _moe_rs_kernel(axis, mesh_axes, bm, bn, n_blocks,
                   x_ref, w_ref, be_ref, nb_ref, out_ref, ws_ref, stage_ref,
                   send_sems, recv_sems):
    P_seg = out_ref.shape[0]

    def emit(seg, dst_ref):
        emit_grouped_gemm(x_ref.at[pl.ds(seg * P_seg, P_seg)], w_ref,
                          dst_ref, be_ref, seg * n_blocks, bm, bn,
                          n_blocks_used=nb_ref[seg])

    rs_overlap_protocol(axis, mesh_axes, ws_ref, stage_ref,
                        send_sems, recv_sems, emit)
    emit_slot_reduction(ws_ref, out_ref, bm, bn)


def _moe_rs_2d_kernel(axes, mesh_axes, bm, bn, n_blocks, P_seg,
                      x_ref, w_ref, be_ref, nb_ref, red_ref, ws_ref,
                      stage_ref, send_sems, recv_sems):
    """Fast-tier stage of the hierarchical GroupGEMM-RS: the inner-group RS
    segments are the *strided* aligned chunks {(r, j) : r < no} in
    outer-major block order (same layout trick as _gemm_rs_2d_stage_kernel),
    ready for the outer ring without re-permute."""
    outer, inner = axes[0], tuple(axes[1:])
    no = shd.n_pes(outer)
    ni = shd.n_pes(inner)

    def emit(j, dst_ref):
        for r in range(no):
            seg = r * ni + j
            emit_grouped_gemm(x_ref.at[pl.ds(seg * P_seg, P_seg)], w_ref,
                              dst_ref.at[pl.ds(r * P_seg, P_seg)],
                              be_ref, seg * n_blocks, bm, bn,
                              n_blocks_used=nb_ref[seg])

    rs_overlap_protocol(inner, mesh_axes, ws_ref, stage_ref,
                        send_sems, recv_sems, emit)
    emit_slot_reduction(ws_ref, red_ref, bm, bn)


def _moe_rs_xla(ctx: ShmemContext, tokens, ids, topk_weights, weights, axis):
    """XLA-collective GroupGEMM-RS for a scatter axis that crosses slice
    boundaries (``is_dcn_axis``): the grouped down-GEMM partial runs as a
    masked dense per-expert matmul on the local K-shard, the topk fold
    commutes with the cross-rank sum, and ``psum_scatter`` routes the
    reduction over the right transport — the op's golden (dense +
    psum_scatter), computed directly. Output matches the fused path:
    [T, N] sharded P(axis)."""
    T, topk = topk_weights.shape
    E, _, N = weights.shape
    out_dtype = tokens.dtype

    def f(tok_shard, ids_full, tw_full, w_shard):
        part = jnp.zeros((tok_shard.shape[0], N), jnp.float32)
        for e in range(E):              # -1 pad rows match no expert
            ye = jnp.dot(tok_shard, w_shard[e],
                         preferred_element_type=jnp.float32)
            part = part + jnp.where((ids_full == e)[:, None], ye, 0.0)
        folded = jnp.sum(part.reshape(T, topk, N)
                         * tw_full[..., None].astype(jnp.float32), axis=1)
        out = lax.psum_scatter(folded, axis, scatter_dimension=0, tiled=True)
        return out.astype(out_dtype)

    sm = ctx.shard_map(f, in_specs=(P(None, axis), P(None), P(None, None),
                                    P(None, axis, None)),
                       out_specs=P(axis))
    return sm(tokens, ids, topk_weights, weights)


def moe_reduce_rs(ctx: ShmemContext, tokens: jax.Array, ids: jax.Array,
                  topk_weights: jax.Array, weights: jax.Array,
                  axis: str | None = None, block_m: int = 128) -> jax.Array:
    """Second MoE-TP stage: ``tokens`` [T*topk, K] sharded P(None, axis) on K
    (the up-projection's activations, one row per (token, k) pair);
    ``ids`` [T*topk] global expert of each row (replicated);
    ``topk_weights`` [T, topk]; ``weights`` [E, K, N] sharded
    P(None, axis, None). Computes the grouped down-GEMM partial per output
    segment, ring-scatters partials to their owners overlapped with compute,
    reduces, then folds topk rows into per-token rows → [T, N] sharded
    P(axis). Golden: dense compute + psum_scatter
    (cf. moe_reduce_rs.py:889-1027). ``axis`` may be an (outer, inner…)
    tuple — fused GroupGEMM + fast-tier RS, then a slow-tier ring (the
    inter-node analog, moe_reduce_rs.py:590-670). A DCN (slice-crossing)
    scatter axis routes to the XLA-collective fallback; in a hierarchical
    tuple DCN may only be the OUTER tier (slow tier outermost, same rule
    as ``gemm_rs``) — the outer ring then becomes an XLA ``psum_scatter``
    while the fused fast tier stays Pallas."""
    axis = norm_axis(ctx, axis)
    dcn_outer = False
    if isinstance(axis, tuple):
        inner_dcn = tuple(ax for ax in axis[1:] if ctx.is_dcn_axis(ax))
        if inner_dcn:
            raise ValueError(
                f"DCN (slice-crossing) axes {inner_dcn} must come first in "
                f"the hierarchical axis tuple {axis} — put the slow tier "
                "outermost (the fast-tier stage is remote DMA, which "
                "cannot cross DCN; cf. moe_reduce_rs docstring)")
        dcn_outer = ctx.is_dcn_axis(axis[0])
    elif ctx.is_dcn_axis(axis):
        return _moe_rs_xla(ctx, tokens, ids, topk_weights, weights, axis)
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names
    Tk, K = tokens.shape
    if not default_interpret() and (K // n) % 128:
        raise ValueError(
            f"moe_reduce_rs on compiled TPU needs a lane-multiple K shard: "
            f"K={K} over {n} ranks gives K_local={K // n} (Mosaic tiles "
            "lanes by 128; the interpret-mode simulator does not enforce "
            "this)")
    T, topk = topk_weights.shape
    assert Tk == T * topk
    assert T % n == 0, f"T={T} not divisible by ranks {n}"
    t_seg = T // n
    seg_rows = t_seg * topk
    E, _, N = weights.shape
    bm = min(block_m, _round_up(seg_rows, 8))
    P_seg = _round_up(seg_rows, bm) + E * bm
    n_blocks = P_seg // bm

    # ids are replicated → every rank computes identical per-segment
    # alignment; the ring reduces ALIGNED rows (topk fold commutes with the
    # cross-rank sum and runs once at the end)
    gi, rv, be, nb = _segment_alignment(ids.reshape(n, seg_rows), E, bm)
    be_flat = be.reshape(-1)

    def f(tok_shard, gi_full, rv_full, be_full, nb_full, tw_full, w_shard):
        me = shd.my_pe(axis)
        # aligned rows for every segment, from my K-shard of the tokens
        base = (jnp.arange(n, dtype=jnp.int32) * seg_rows)[:, None]
        rows = jnp.clip(base + gi_full, 0, Tk - 1).reshape(-1)
        x = (tok_shard[rows]
             * rv_full.reshape(-1)[:, None].astype(tok_shard.dtype))

        bn = _default_bn(tok_shard.shape[-1], N, w_shard.dtype)
        hier = isinstance(axis, tuple)
        if hier:
            ni = ctx.axis_size(tuple(axis[1:]))
            no = ctx.axis_size(axis[0])
            chunk = no * P_seg
            kernel = lambda *refs: _moe_rs_2d_kernel(axis, mesh_axes, bm, bn,
                                                     n_blocks, P_seg, *refs)
        else:
            ni, no, chunk = n, 1, P_seg
            kernel = lambda *refs: _moe_rs_kernel(axis, mesh_axes, bm, bn,
                                                  n_blocks, *refs)
        y, _ws, _stage = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((chunk, N), jnp.float32),
                jax.ShapeDtypeStruct((ni, chunk, N), jnp.float32),  # symm
                jax.ShapeDtypeStruct((2, chunk, N), jnp.float32),   # stage
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pl.ANY),
                      pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.SMEM)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 3,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((ni,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"moe_rs_{axis}")),
            cost_estimate=pl.CostEstimate(
                flops=2 * n * P_seg * tok_shard.shape[1] * N,
                bytes_accessed=(n * P_seg * (tok_shard.shape[1] + N))
                * jnp.dtype(tok_shard.dtype).itemsize,
                transcendentals=0),
            interpret=default_interpret(),
        )(x, w_shard, be_full, nb_full)
        if hier:
            if dcn_outer:
                # slow tier over XLA: same surviving-chunk layout, same
                # segment order — only the transport changes (gemm_rs's
                # dcn_outer pattern)
                y = lax.psum_scatter(y, axis[0], scatter_dimension=0,
                                     tiled=True)       # [P_seg, N] f32
            else:
                from triton_dist_tpu.ops.reduce_scatter import _rs_call
                y = _rs_call(axis[0], mesh_axes, no, y)   # [P_seg, N] f32

        # my segment's metadata: unscramble aligned rows → (token, k) rows
        gi_me = lax.dynamic_index_in_dim(gi_full, me, keepdims=False)
        rv_me = lax.dynamic_index_in_dim(rv_full, me, keepdims=False)
        dest = jnp.where(rv_me, gi_me, seg_rows)
        rows_out = jnp.zeros((seg_rows, N), jnp.float32).at[dest].add(
            y * rv_me[:, None].astype(y.dtype), mode="drop")
        # topk fold with my segment's weights
        tw_me = lax.dynamic_slice_in_dim(tw_full, me * t_seg, t_seg)
        folded = jnp.sum(rows_out.reshape(t_seg, topk, N)
                         * tw_me[..., None].astype(jnp.float32), axis=1)
        return folded.astype(tokens.dtype)

    sm = ctx.shard_map(
        f, in_specs=(P(None, axis), P(None, None), P(None, None), P(None),
                     P(None), P(None, None), P(None, axis, None)),
        out_specs=P(axis))
    return sm(tokens, gi, rv, be_flat, nb, topk_weights, weights)


__all__ = ["ag_moe_group_gemm", "moe_reduce_rs"]
