"""Kernel library — overlapping distributed ops (the analog of reference
python/triton_dist/kernels/nvidia/*, re-exported the same way its
kernels/nvidia/__init__.py:25-89 does)."""

from triton_dist_tpu.ops.common import collective_id_for, barrier_all_op  # noqa: F401
from triton_dist_tpu.ops.allgather import (all_gather, all_gather_ll,  # noqa: F401
                                           AgLLContext,
                                           create_ag_ll_workspace, broadcast)
from triton_dist_tpu.ops.reduce_scatter import reduce_scatter  # noqa: F401
from triton_dist_tpu.ops.allgather_gemm import (  # noqa: F401
    ag_gemm, ag_gemm_ws, create_ag_gemm_context, create_ag_gemm_workspace)
from triton_dist_tpu.ops.gemm_reduce_scatter import (  # noqa: F401
    gemm_rs, gemm_rs_ws, create_gemm_rs_context, create_gemm_rs_workspace)
from triton_dist_tpu.ops.autodiff import ag_gemm_diff, gemm_rs_diff  # noqa: F401
from triton_dist_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention, ring_attention_fwd)
from triton_dist_tpu.ops.page_migrate import migrate_pages  # noqa: F401
