"""Differentiable overlap ops — custom VJPs for the TP linears.

The reference is an inference kernel library (torch, no autograd through
its Triton kernels). On TPU the functional-transform story makes training
composition natural: AG-GEMM and GEMM-RS are *each other's adjoints*, so
the backward of each overlap op is the other overlap op:

    y = AG(a) @ b            (column-parallel forward, ag_gemm)
    da = RS(dy @ bᵀ)         → gemm_rs(dy, bᵀ)
    db = AG(a)ᵀ @ dy         → local GEMM on a re-gathered a

    y = RS(x @ w)            (row-parallel forward, gemm_rs)
    dx = AG(dy) @ wᵀ         → ag_gemm(dy, wᵀ)
    dw = xᵀ @ AG(dy)         → local GEMM on a re-gathered dy

Every term keeps its operand's sharding (the dualities above are exact at
the PartitionSpec level), so these drop into jax.grad/optax training loops
with the hand-overlapped kernels on both passes. Activations are
re-gathered in backward instead of saved gathered (rematerialization: an
AG is cheap next to the saved-[M, K]-replicated memory).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.allgather_gemm import (ag_gemm, ag_gemm_ws,
                                                create_ag_gemm_workspace)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
from triton_dist_tpu.shmem.context import ShmemContext


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def ag_gemm_diff(ctx: ShmemContext, axis: str | None,
                 cfg: GemmConfig | None, a: jax.Array,
                 b: jax.Array) -> jax.Array:
    """Differentiable column-parallel linear: C = all_gather(a) @ b.
    a [M, K] P(axis); b [K, N] P(None, axis); C [M, N] P(None, axis)."""
    return ag_gemm(ctx, a, b, axis=axis, cfg=cfg)


def _ag_gemm_fwd(ctx, axis, cfg, a, b):
    return ag_gemm(ctx, a, b, axis=axis, cfg=cfg), (a, b)


def _bwd_cfg(cfg, rows_local: int, cols: int) -> GemmConfig:
    """Tile config for a backward op whose output dims are the forward's
    swapped — gcd-clamp so divisibility holds for any shape."""
    base = cfg or GemmConfig()
    return GemmConfig(math.gcd(base.block_m, rows_local),
                      math.gcd(base.block_n, cols), base.block_k)


def _ag_gemm_bwd(ctx, axis, cfg, res, dc):
    a, b = res
    n = ctx.axis_size(axis or ctx.axis_names[0])
    # da = reduce_scatter(dc @ bᵀ): dc [M, N] P(None, axis) is exactly
    # gemm_rs's K-sharded lhs; bᵀ [N, K] P(axis, None) its row-sharded rhs;
    # result [M, K] P(axis) matches a.
    da = gemm_rs(ctx, dc, jnp.swapaxes(b, 0, 1), axis=axis,
                 cfg=_bwd_cfg(cfg, dc.shape[0] // n, b.shape[0]),
                 out_dtype=a.dtype)
    # db = AG(a)ᵀ @ dc: re-gather a (rematerialized), then a local GEMM —
    # dc's N-sharding propagates to db [K, N] P(None, axis) with no comms.
    a_g = all_gather(ctx, a, axis=axis)
    db = jnp.dot(jnp.swapaxes(a_g, 0, 1), dc,
                 preferred_element_type=jnp.float32).astype(b.dtype)
    return da, db


ag_gemm_diff.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def gemm_rs_diff(ctx: ShmemContext, axis: str | None,
                 cfg: GemmConfig | None, x: jax.Array,
                 w: jax.Array) -> jax.Array:
    """Differentiable row-parallel linear: y = reduce_scatter(x @ w).
    x [M, K] P(None, axis); w [K, N] P(axis, None); y [M, N] P(axis)."""
    return gemm_rs(ctx, x, w, axis=axis, cfg=cfg)


def _gemm_rs_fwd(ctx, axis, cfg, x, w):
    return gemm_rs(ctx, x, w, axis=axis, cfg=cfg), (x, w)


def _gemm_rs_bwd(ctx, axis, cfg, res, dy):
    x, w = res
    ax = axis or ctx.axis_names[0]
    n = ctx.axis_size(ax)
    M, N = dy.shape
    m_local = M // n
    # dx = all_gather(dy) @ wᵀ: dy [M, N] P(axis) is exactly ag_gemm's
    # M-sharded lhs; wᵀ [N, K] P(None, axis) its column-sharded rhs;
    # result [M, K] P(None, axis) matches x. The workspace-threading form
    # lets dw below reuse the gathered dy segments instead of a second
    # all-gather of the same tensor (half the backward ICI traffic).
    ws = create_ag_gemm_workspace(ctx, m_local, N, dy.dtype, axis=ax)
    dx, ws = ag_gemm_ws(ctx, dy, jnp.swapaxes(w, 0, 1), ws, axis=ax,
                        cfg=_bwd_cfg(cfg, m_local, w.shape[0] // n),
                        out_dtype=x.dtype)

    # reconstruct AG(dy) from the workspace: slot s holds rank s's segment
    # for every s except our own (the local segment reads the input
    # directly by design), which we fill from our dy shard
    def rebuild(ws_local, dy_shard):
        me = jax.lax.axis_index(ax)
        g = ws_local.reshape(n, m_local, N).astype(dy_shard.dtype)
        g = jax.lax.dynamic_update_index_in_dim(g, dy_shard, me, axis=0)
        return g.reshape(M, N)

    dy_g = ctx.shard_map(rebuild, in_specs=(P(ax), P(ax)),
                         out_specs=P(None))(ws, dy)
    # dw = xᵀ @ AG(dy): local GEMM; x's K-sharding propagates to
    # dw [K, N] P(axis, None) with no comms.
    dw = jnp.dot(jnp.swapaxes(x, 0, 1), dy_g,
                 preferred_element_type=jnp.float32).astype(w.dtype)
    return dx, dw


gemm_rs_diff.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


__all__ = ["ag_gemm_diff", "gemm_rs_diff"]
