"""Three-tier hierarchy + odd-shape coverage.

The reference ships 3-D hierarchical AG variants (low_latency_allgather.py
:345-530 push_3d family) and deliberately tests odd shapes (M = 999 ×
num_ranks, test_ag_gemm_intra_node.py:78). Here the N-axis design covers
both for free — these tests pin that so a refactor can't silently narrow
the support back to 2 tiers / aligned shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops import all_gather, reduce_scatter
from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose

AXES3 = ("a", "b", "c")


@pytest.fixture(scope="module")
def ctx3():
    """(2,2,2) = 8 participants over 12 virtual devices: full-device
    participation deadlocks the interpreter's device threads
    intermittently (conftest note), so 3-tier tests keep 4 spares."""
    return initialize_distributed(axis_names=AXES3, mesh_shape=(2, 2, 2))


@pytest.mark.parametrize("method", ["ring_2d", "push_2d"])
def test_all_gather_three_tier(ctx3, method):
    n = 8
    x = jax.random.normal(jax.random.key(0), (n * 8, 128), jnp.float32)
    xs = ctx3.shard(x, P(AXES3))
    y = jax.jit(lambda v: all_gather(ctx3, v, method=method))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))


def test_reduce_scatter_three_tier(ctx3):
    n = 8
    x = jnp.round(jax.random.normal(jax.random.key(1), (n * n * 2, 128)) * 4)
    xs = ctx3.shard(x.astype(jnp.float32), P(AXES3))
    got = jax.jit(lambda v: reduce_scatter(ctx3, v, axis=AXES3))(xs)
    gold = jax.jit(ctx3.shard_map(
        lambda s: jax.lax.psum_scatter(s, AXES3, scatter_dimension=0,
                                       tiled=True),
        in_specs=P(AXES3), out_specs=P(AXES3)))(xs)
    assert_allclose(np.asarray(got), np.asarray(gold))


def test_ag_gemm_three_tier(ctx3):
    n = 8
    M, K, N = n * 2, 128, n * 16
    a = jax.random.normal(jax.random.key(2), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(3), (K, N), jnp.float32)
    out = jax.jit(lambda u, v: ag_gemm(ctx3, u, v, axis=AXES3,
                                       cfg=GemmConfig(2, 16)))(
        ctx3.shard(a, P(AXES3)), ctx3.shard(b, P(None, AXES3)))
    assert_allclose(np.asarray(out, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)


def test_ag_gemm_odd_shapes():
    """M = 33 per shard (odd, not a tile multiple) — reference parity for
    its deliberate M = 999 × num_ranks case."""
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))
    n = ctx.num_ranks
    M, K, N = 33 * n, 64, n * 32
    a = jax.random.normal(jax.random.key(4), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(5), (K, N), jnp.float32)
    out = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis="x",
                                       cfg=GemmConfig(33, 32)))(
        ctx.shard(a, P("x")), ctx.shard(b, P(None, "x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)
