"""Test bootstrap: force an 8-device virtual CPU mesh.

The distributed kernels run in Pallas TPU interpret mode on CPU devices —
this is the single-process cluster simulator the reference lacks (its tests
need real GPUs + torchrun; see SURVEY.md §4).

The container's axon sitecustomize eagerly initializes the single-chip TPU
backend at interpreter start, so setting JAX_PLATFORMS=cpu in the
environment is not enough — we re-point jax at CPU and drop the cached
backend before any test imports run.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(8, skip_if_satisfied=False)

assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)

# NOTE: kernel tests build meshes over a 4-device *subset* of the 8 virtual
# devices. On a single-core host, the Pallas TPU interpreter's device threads
# can deadlock nondeterministically when >=7 of them block in semaphore
# waits/barriers concurrently (threads pile up in the interpreter's internal
# _barrier/_allocate_buffer); <=6 participating devices is reliable. The
# kernels themselves are rank-count-generic.
TEST_WORLD = 4
