"""Distributed thunk-level autotuner (analog of reference
python/triton_dist/autotuner.py ``contextual_autotune``).

The reference cannot use Triton's per-kernel autotuner for overlap ops — a
config change alters *multi-kernel pipelines with side effects* (symmetric
buffers, signals), and each rank must pick the SAME config or the job
deadlocks. So it tunes whole thunks by re-running full calls per config and
reaches cross-rank consensus by all-reducing MAX of the timings
(autotuner.py:225-256).

Same shape here, simpler by construction:
- a "thunk" is a pure jitted function → re-running per config is safe by
  default (no serial-mode bisection needed);
- consensus: jax is single-controller per process, but multi-host jobs still
  time differently per host — we allgather per-host timings and take the
  elementwise MAX (a config is as slow as its slowest host), exactly the
  reference's consensus rule;
- results are cached per (function, static key, arg shapes) and logged to
  ``.autotune_logs/process-N.log`` (cf. autotuner.py:57-67; the directory
  moves with ``TDT_AUTOTUNE_LOG_DIR`` or the ``log_dir=`` kwarg);
- winners can OUTLIVE the process (ISSUE 15): pass ``registry=`` (or
  install one with ``aot.registry.set_default_registry``) and the wrapper
  consults the persisted ``(op, mesh_shape, dtype, shape_bucket)`` key
  before timing anything — an exact hit skips the sweep entirely (the
  ``registry_hit`` log marker), a same-(op, dtype) near-hit is promoted to
  the front of the candidate list, and a fresh winner is written back
  through the registry's sigcheck admission gate.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

from triton_dist_tpu.utils.perf import perf_func

_CACHE: dict = {}


def _consensus_times(times: np.ndarray) -> np.ndarray:
    """Elementwise MAX of per-host timings across processes (reference
    all_reduce(MAX) consensus, autotuner.py:225-238)."""
    if jax.process_count() == 1:
        return times
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(times)  # [P, n_cfg]
    return np.max(np.asarray(gathered), axis=0)


def _log(msg: str, log_dir: str | None = None) -> None:
    d = (log_dir or os.environ.get("TDT_AUTOTUNE_LOG_DIR")
         or ".autotune_logs")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"process-{jax.process_index()}.log")
    with open(path, "a") as f:
        f.write(f"[{time.strftime('%H:%M:%S')}] {msg}\n")


def _tuned_key(op: str, bound_args: dict):
    """Registry key for this call: mesh shape from the first context-like
    argument, dtype + pow2 shape bucket from the array operands."""
    from triton_dist_tpu.aot.registry import TunedKey, shape_bucket_of
    mesh_shape: tuple = ()
    dtype = "float32"
    shapes = []
    for v in bound_args.values():
        mesh = getattr(v, "mesh", None)
        if not mesh_shape and mesh is not None and hasattr(mesh, "devices"):
            mesh_shape = tuple(int(d) for d in np.shape(mesh.devices))
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            if not shapes:
                dtype = str(v.dtype)
            shapes.append(tuple(v.shape))
    return TunedKey(op=op, mesh_shape=mesh_shape, dtype=dtype,
                    shape_bucket=shape_bucket_of(*shapes))


def contextual_autotune(configs: Sequence[Any], iters: int = 5,
                        warmup: int = 2,
                        prune: Callable[[Any, tuple, dict], bool] | None = None,
                        op: str | None = None,
                        registry=None,
                        log_dir: str | None = None):
    """Decorator: ``fn(*args, cfg=<config>, **kw)`` gets its ``cfg`` picked
    by timing every candidate on the first call per arg-shape signature.

    ``prune(config, args, kw)`` may return False to skip invalid candidates
    (e.g. tile sizes that don't divide the shapes — the analog of Triton's
    early-config-prune).

    ``op`` names the kernel in the persisted registry (defaults to the
    function's qualname); ``registry`` pins a
    :class:`~triton_dist_tpu.aot.registry.TunedConfigRegistry` for this
    wrapper (default: whatever ``set_default_registry`` installed, if
    anything — no registry means the winner dies with the process, the
    pre-ISSUE-15 behavior).
    """
    configs = list(configs)

    def _sig(a):
        return ((tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else a)

    def deco(fn):
        import inspect
        fn_sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            if kw.get("cfg") is not None:
                return fn(*args, **kw)
            # kwargs like axis/out_dtype select different code paths, so they
            # are part of the tuning signature (cfg itself is excluded).
            # Bind to the canonical parameter form so positional vs keyword
            # spelling of the same argument shares one cache entry.
            bound = fn_sig.bind(*args, **kw)
            bound.apply_defaults()
            key = (fn.__qualname__,
                   tuple((k, _sig(v)) for k, v in bound.arguments.items()
                         if k != "cfg"))
            if key not in _CACHE:
                cands = [c for c in configs
                         if prune is None or prune(c, args, kw)]
                assert cands, f"all autotune configs pruned for {key}"
                # persisted-registry consult (ISSUE 15): exact key hit
                # skips the sweep; a same-(op, dtype) near-hit only jumps
                # the queue (still timed against the rest)
                from triton_dist_tpu.aot.registry import \
                    get_default_registry
                reg = registry if registry is not None \
                    else get_default_registry()
                op_name = op or fn.__qualname__
                tkey = None
                if reg is not None:
                    tkey = _tuned_key(op_name, bound.arguments)
                    winner = reg.get(tkey)
                    if winner is not None and (
                            prune is None or prune(winner, args, kw)):
                        _CACHE[key] = winner
                        _log(f"{op_name} {tkey}: registry_hit "
                             f"{winner} (no sweep)", log_dir)
                        return fn(*args, **dict(kw, cfg=winner))
                    near = reg.get_similar(op_name, tkey.dtype)
                    if near is not None and near in cands:
                        cands.remove(near)
                        cands.insert(0, near)
                times = np.full((len(cands),), np.inf)
                for i, c in enumerate(cands):
                    try:
                        kw2 = dict(kw, cfg=c)
                        _, ms = perf_func(lambda: fn(*args, **kw2),
                                          iters=iters, warmup_iters=warmup)
                        times[i] = ms
                    except Exception as e:  # config failed to compile/run
                        _log(f"{fn.__qualname__} cfg {c}: FAILED {e!r}",
                             log_dir)
                times = _consensus_times(times)
                best = int(np.argmin(times))
                assert np.isfinite(times[best]), (
                    f"every autotune config failed for {key}")
                _CACHE[key] = cands[best]
                _log(f"{fn.__qualname__} {key[1]}: picked {cands[best]} "
                     f"({times[best]:.3f} ms; "
                     f"{np.sum(np.isfinite(times))}/{len(cands)} ok)",
                     log_dir)
                if reg is not None:
                    from triton_dist_tpu.aot.registry import \
                        RegistryAdmissionError
                    try:
                        reg.put(tkey, cands[best])
                        _log(f"{op_name} {tkey}: recorded winner "
                             f"{cands[best]}", log_dir)
                    except (RegistryAdmissionError, TypeError) as e:
                        # the in-process pick stands; it just never
                        # becomes a persisted default
                        _log(f"{op_name} {tkey}: registry REFUSED "
                             f"winner {cands[best]}: {e}", log_dir)
            return fn(*args, **dict(kw, cfg=_CACHE[key]))

        def _registry_handle():
            """The registry this wrapper reads/writes right now (the
            explicit ``registry=`` pin, else the process default)."""
            if registry is not None:
                return registry
            from triton_dist_tpu.aot.registry import get_default_registry
            return get_default_registry()

        wrapper._autotune_cache = _CACHE
        wrapper._autotune_op = op or fn.__qualname__
        wrapper._autotune_registry = _registry_handle
        return wrapper

    return deco


__all__ = ["contextual_autotune"]
