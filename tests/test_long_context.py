"""Long-context serving (ISSUE 19): distributed flash-decode over the SP
mesh, held to the same bitwise cross-mesh contract as the base sharded
engine.

THE contract: ``long_context=True`` flips the SP attention leg from the
pool-allgather walk to ``flash_decode_dist`` — one request's KV pages
round-robined across the SP shards (``KVPagePool(layout="interleaved")``),
per-rank attention compute ∝ kv_len/n — and a 50-request forced-preemption
trace served on an n>1 interpret mesh is still BIT-IDENTICAL per request
to the n=1 golden. Two goldens, in fact:

- the long-context engine at mesh 1x1x1 (same code path, n=1 fold), and
- the PLAIN (``long_context=False``) engine at 1x1x1 — layout and op
  choice are balance knobs, never allowed to move a token.

Also covered here: the op-level ``flash_decode_dist`` bit-identity (with
and without ``active`` parking), the ledger-id → device-row bijection,
the ``long``/``lplen`` workload population and its RNG-stream-preserving
``long=0`` form, ``parse_slo``'s 3-class long tier, the modeled
``fd_attn_split_us`` sublinearity, and the per-class ``chunk_budget``
drip (runtime scalar — one compiled chunk program).

Every test runs under the per-test SIGALRM watchdog (test_chaos.py
pattern): a mesh-collective hang must kill the test loudly, not stall
the suite.
"""

import dataclasses
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.ops import flash_decode_dist
from triton_dist_tpu.serving import (ServingEngine, ShardedServingEngine,
                                     serving_mesh)
from triton_dist_tpu.serving.kv_pool import KVPagePool, PageLedgerError
from triton_dist_tpu.serving.scheduler import ClassSpec, SLOPolicy
from triton_dist_tpu.serving.sharded import fd_attn_split_us
from triton_dist_tpu.serving.workload import (WorkloadSpec, generate_arrivals,
                                              parse_slo, parse_workload)

pytestmark = [pytest.mark.longctx, pytest.mark.serving]

WATCHDOG_S = 240          # per-test wall cap — generous, CPU CI is slow
N_REQUESTS = 50
MAX_STEPS = 100_000       # engine's own stall watchdog trips far earlier
WIRE = jnp.float8_e4m3fn  # pinned (NOT "auto") — see test_sharded_serving


@pytest.fixture(autouse=True)
def longctx_watchdog():
    """Hard per-test wall-clock watchdog (test_chaos.py pattern): SIGALRM,
    not a thread, so even a wedged collective inside jax is interrupted."""
    def boom(signum, frame):
        raise TimeoutError(
            f"longctx watchdog: test exceeded {WATCHDOG_S}s wall — "
            "a mesh collective (or the engine) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# --------------------------------------------------------- engine fixtures
@pytest.fixture(scope="module")
def moe_model():
    """Micro MoE (test_sharded_serving.py shape): the smallest config that
    exercises every sharded path."""
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def tiny_model():
    cfg = dataclasses.replace(
        LlamaConfig(vocab_size=128, d_model=32, n_layers=1, n_heads=2,
                    n_kv_heads=1, d_ff=64, max_seq_len=64),
        dtype=jnp.float32)
    params = init_params(jax.random.key(1), cfg)
    return cfg, params


def _trace():
    """50 requests, bursty arrivals (two per step) against a 9-page pool —
    growth-driven preemption is forced, not incidental. Deterministic,
    and deliberately the SAME trace test_sharded_serving.py replays: the
    long-context engine must serve the ordinary workload too."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(N_REQUESTS):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        prompt = rng.randint(1, 128, size=plen).tolist()
        out.append((i // 2, prompt, mnt))
    return out


def _engine(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preemption
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    kw.setdefault("long_context", True)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _serve(moe_model, tp, sp, ep, **kw):
    eng = _engine(moe_model, tp, sp, ep, **kw)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    m = eng.metrics
    return {"tokens": tokens, "compiles": eng.compile_stats,
            "counters": dict(m.counters),
            "layout": eng.alloc.layout,
            "attn_count": m.hist["attn_local_us"].count,
            "attn_local_mean": m.hist["attn_local_us"].mean,
            "attn_fold_mean": m.hist["attn_fold_wait_us"].mean}


@pytest.fixture(scope="module")
def golden(moe_model):
    """The n=1 golden: the SAME long-context engine at mesh 1x1x1."""
    return _serve(moe_model, 1, 1, 1)


@pytest.fixture(scope="module")
def n2_run(moe_model):
    return _serve(moe_model, 1, 2, 1)


@pytest.fixture(scope="module")
def n4_run(moe_model):
    """sp=4 with the OTHER decode horizon: K=4 multi-token dispatches —
    the trace must still replay the K=1 n=1 golden exactly."""
    return _serve(moe_model, 1, 4, 1, decode_horizon=4)


# --------------------------------------------- engine cross-mesh bitwise
def test_longctx_n2_bitwise(golden, n2_run):
    assert n2_run["tokens"] == golden["tokens"]


def test_longctx_n4_bitwise(golden, n4_run):
    assert n4_run["tokens"] == golden["tokens"]


def test_longctx_n1_equals_replicated(moe_model, golden):
    """Layout + op choice are balance knobs: the long-context n=1 run
    must match the plain replicated engine token-for-token."""
    plain = _serve(moe_model, 1, 1, 1, long_context=False)
    assert plain["tokens"] == golden["tokens"]
    assert plain["layout"] == "blocked"


def test_longctx_trace_forces_preemption(golden):
    """The contract is vacuous unless preemption actually fires — and
    every request must still finish."""
    assert golden["counters"]["preemptions"] >= 1
    assert len(golden["tokens"]) == N_REQUESTS


def test_longctx_one_program_per_path(n4_run):
    """ONE decode program, ONE chunk program at n>1 — the interleaved
    layout and the fold are runtime data, never a shape."""
    assert n4_run["compiles"]["decode_compiles"] == 1
    assert n4_run["compiles"]["prefill_chunk_compiles"] == 1


def test_longctx_layout_and_attn_metrics(golden, n4_run):
    """long_context flips the pool to interleaved, and the modeled
    attention split lands in the histograms: the fold-wait half is zero
    at n=1 (nothing to fold) and strictly positive at n=4."""
    assert golden["layout"] == "interleaved"
    assert n4_run["layout"] == "interleaved"
    assert n4_run["attn_count"] > 0
    assert (n4_run["attn_local_mean"] or 0.0) > 0.0
    assert (n4_run["attn_fold_mean"] or 0.0) > 0.0
    assert (golden["attn_fold_mean"] or 0.0) == 0.0


# ------------------------------------------------- op-level bit-identity
def _op_inputs(seed=3, B=2, Hq=4, Hkv=2, ps=8, D=128, pages=8, S=4):
    """A mixed-ownership shape: each row's block table touches every
    rank's slice at n=4 (pages 8 / 4 ranks = 2 per rank)."""
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, Hq, D), jnp.float32)
    kn = jnp.asarray(rng.randn(B, Hkv, D), jnp.float32)
    vn = jnp.asarray(rng.randn(B, Hkv, D), jnp.float32)
    kp = jnp.asarray(rng.randn(pages, Hkv, ps, D), jnp.float32)
    vp = jnp.asarray(rng.randn(pages, Hkv, ps, D), jnp.float32)
    bt = jnp.asarray([[0, 2, 4, 6], [1, 3, 5, 7]], jnp.int32)[:B, :S]
    kv = jnp.asarray([20, 14], jnp.int32)[:B]       # 3 / 2 pages touched
    pos = kv - 1
    return q, kn, vn, kp, vp, bt, pos, kv


def _op_run(sp, active=None):
    ctx = serving_mesh(1, sp, 1)
    q, kn, vn, kp, vp, bt, pos, kv = _op_inputs()
    attn, kpo, vpo = flash_decode_dist(ctx, q, kn, vn, kp, vp, bt, pos, kv,
                                       axis="sp", active=active)
    return (np.asarray(attn), np.asarray(kpo), np.asarray(vpo))


def test_flash_decode_dist_op_bitwise():
    """attn AND the written-back pools are bit-identical across mesh
    sizes — the n=1 route runs the same per-page partial + fold math
    and IS the golden."""
    a1, k1, v1 = _op_run(1)
    for sp in (2, 4):
        an, kn_, vn_ = _op_run(sp)
        assert np.array_equal(a1, an), f"attn diverged at sp={sp}"
        assert np.array_equal(k1, kn_), f"k pool diverged at sp={sp}"
        assert np.array_equal(v1, vn_), f"v pool diverged at sp={sp}"


def test_flash_decode_dist_active_parking():
    """Inactive rows park their k/v_new write on the scratch page in
    BOTH routes — bitwise agreement must survive the parking path."""
    active = jnp.asarray([True, False])
    a1, k1, v1 = _op_run(1, active=active)
    a4, k4, v4 = _op_run(4, active=active)
    assert np.array_equal(a1, a4)
    assert np.array_equal(k1, k4)
    assert np.array_equal(v1, v4)


def test_flash_decode_dist_pool_divisibility_refused():
    """A pool whose page count doesn't split over the SP axis is a
    loud construction error, not a silent wrong-rank walk."""
    ctx = serving_mesh(1, 2, 1)
    q, kn, vn, kp, vp, bt, pos, kv = _op_inputs(pages=9)
    with pytest.raises(AssertionError, match="not divisible"):
        flash_decode_dist(ctx, q, kn, vn, kp, vp, bt, pos, kv, axis="sp")


# ------------------------------------------------ pool layout bijection
def test_interleaved_device_row_is_a_bijection():
    pool = KVPagePool(9, 8, sp_ranks=4, layout="interleaved")
    assert pool.device_pages == 12          # padded to a multiple of 4
    rows = [pool.device_row(p) for p in range(pool.device_pages)]
    assert sorted(rows) == list(range(pool.device_pages))
    assert pool.device_row(0) == 0          # scratch page row is FIXED
    # consecutive ids round-robin across shards
    per = pool.device_pages // pool.sp_ranks
    assert [pool.page_shard(p) for p in range(4)] == [0, 1, 2, 3]
    for p in range(pool.device_pages):
        assert pool.page_shard(p) == pool.device_row(p) // per


def test_blocked_device_row_is_identity():
    pool = KVPagePool(9, 8, sp_ranks=4)     # default layout="blocked"
    assert pool.layout == "blocked"
    for p in range(pool.device_pages):
        assert pool.device_row(p) == p


def test_device_row_range_and_layout_validation():
    pool = KVPagePool(9, 8, sp_ranks=4, layout="interleaved")
    with pytest.raises(PageLedgerError):
        pool.device_row(pool.device_pages)
    with pytest.raises(PageLedgerError):
        pool.device_row(-1)
    with pytest.raises(AssertionError, match="layout"):
        KVPagePool(9, 8, layout="diagonal")


# -------------------------------------------------- workload long class
def test_workload_long_population():
    spec = parse_workload("n=40,seed=3,chat=0.5,long=0.3,plen=3:10,"
                          "mnt=2:6,lplen=64:96")
    assert spec.long == 0.3 and spec.lplen == (64, 96)
    arrivals = generate_arrivals(spec)
    longs = [a for a in arrivals if a[4] == "long"]
    assert longs, "40 draws at P(long)=0.3 produced no long arrivals"
    for _step, prompt, mnt, tenant, _cls in longs:
        assert 64 <= len(prompt) <= 96      # drawn from lplen, not plen
        assert 2 <= mnt <= 4                # chat-sized decode budget
        assert tenant.startswith("l")


def test_workload_long_validation_names_the_field():
    with pytest.raises(ValueError, match="'long'"):
        parse_workload("long=1.5")
    with pytest.raises(ValueError, match="'long'"):
        parse_workload("chat=0.8,long=0.5")          # chat + long > 1
    with pytest.raises(ValueError, match="'lplen'"):
        # lplen must sit STRICTLY above plen's HI
        parse_workload("long=0.2,plen=3:10,lplen=8:20")
    with pytest.raises(ValueError, match="'lplen'"):
        parse_workload("lplen=abc")


def test_workload_long_zero_preserves_the_rng_stream():
    """The class draw partitions the SAME uniform the two-class generator
    consumed, so adding a vanishing long share moves nothing — and a
    long=0 spec replays the pre-ISSUE-19 trace bitwise."""
    base = WorkloadSpec(n=30, seed=9, chat=0.6, long=0.0)
    eps = dataclasses.replace(base, long=1e-12, lplen=(64, 96)).validate()
    assert generate_arrivals(base) == generate_arrivals(eps)


# ---------------------------------------------------- SLO long tier
def test_parse_slo_long_tier():
    pol = parse_slo("long_chunk=2,long_weight=2,long_cap=4")
    assert [c.name for c in pol.classes] == ["chat", "long", "batch"]
    assert [c.level for c in pol.classes] == [0, 1, 2]
    spec = pol.spec("long")
    assert spec.chunk_budget == 2
    assert spec.weight == 2
    assert spec.queue_cap == 4


def test_parse_slo_without_long_fields_stays_two_class():
    pol = parse_slo("chat_weight=4,batch_cap=8")
    assert [c.name for c in pol.classes] == ["chat", "batch"]
    assert SLOPolicy.chat_batch() == SLOPolicy.chat_batch(
        long_weight=None, long_chunk_budget=None)


def test_class_spec_chunk_budget_must_be_positive():
    with pytest.raises(AssertionError):
        ClassSpec("long", chunk_budget=0)


# ----------------------------------------------- modeled attention split
def test_fd_attn_split_model_is_sublinear():
    """At real page shapes (page KV bytes ≫ partial-slab row bytes) the
    modeled total shrinks as the SP mesh grows — the property the whole
    ISSUE exists for. bench.py asserts the same thing at 8k–64k tokens;
    this is the unit-sized pin."""
    page_kv, slab_row, steps = 2_097_152, 8_192, 128
    totals = {}
    for n in (1, 2, 4):
        local, fold = fd_attn_split_us(n, 1, 1, steps, page_kv, slab_row)
        if n == 1:
            assert fold == 0.0              # nothing to fold at n=1
        totals[n] = local + fold
    assert totals[4] < totals[2] < totals[1]
    # the local half is the ∝ kv_len/n piece (steps divisible by n here)
    l1, _ = fd_attn_split_us(1, 1, 1, steps, page_kv, slab_row)
    l2, _ = fd_attn_split_us(2, 1, 1, steps, page_kv, slab_row)
    assert l2 == pytest.approx(l1 / 2)


# --------------------------------------------- per-class chunk budget
def _colocated(tiny_model, **kw):
    cfg, params = tiny_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 16)
    kw.setdefault("pages_per_seq", 6)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefill_buckets", None)
    return ServingEngine(params, cfg, **kw)


def test_long_chunk_budget_drips_without_recompiling(tiny_model):
    """A ``chunk_budget=2`` long class drips a 24-token prompt through
    the ONE compiled chunk program two real tokens at a time — the
    shrink is a runtime scalar (compile count stays 1, ``chunk_shrinks``
    counts every clamped dispatch) and the served tokens match the
    unbudgeted engine bit-for-bit."""
    rng = np.random.RandomState(11)
    arrivals = [(0, rng.randint(1, 128, size=24).tolist(), 2,
                 "l0", "long")]
    slo = SLOPolicy.chat_batch(long_weight=1, long_chunk_budget=2)
    eng = _colocated(tiny_model, slo=slo)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=list(arrivals))
    assert len(tokens) == 1
    assert eng.metrics.counters["chunk_shrinks"] >= 10   # ~12 clamped
    assert eng.compile_stats["prefill_chunk_compiles"] == 1
    base = _colocated(tiny_model)
    assert base.run(max_steps=MAX_STEPS, arrivals=list(arrivals)) == tokens
    assert base.metrics.counters.get("chunk_shrinks", 0) == 0
