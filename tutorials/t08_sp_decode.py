"""Tutorial 08 — sequence-parallel distributed flash-decode.

Analog of reference tutorials (test_sp_decode_attn) +
layers/nvidia/sp_flash_decode_layer.py. The KV cache is sequence-sharded;
each rank runs split-KV decode over its shard, then ONE fused kernel
allgathers the packed (out ‖ lse) partials and streams the online-softmax
merge as they arrive — the batch=1 decode latency path of the reference's
1→32-GPU scaling chart (README.md:161-163).

Run:  python -m tutorials.t08_sp_decode [--sim 4] [--case correctness|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context)


def _dense_golden(q, k, v, kv_lens):
    import numpy as np
    B, Hq, D = q.shape
    Hkv = k.shape[1]
    g = Hq // Hkv
    out = np.zeros((B, Hq, D), np.float32)
    qn, kn, vn = (np.asarray(x, np.float32) for x in (q, k, v))
    for b in range(B):
        L = int(kv_lens[b])
        for h in range(Hq):
            kh, vh = kn[b, h // g, :L], vn[b, h // g, :L]
            s = (qn[b, h] @ kh.T) / np.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ vh
    return out


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.layers import SpGQAFlashDecodeAttention
    ctx = world_context()
    n = ctx.num_ranks
    B, Hq, Hkv, D, s_local = 2, 4, 2, 128, 128
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_lens = jnp.array([S, S // 2 + 5], jnp.int32)
    layer = SpGQAFlashDecodeAttention(ctx, num_q_heads=Hq, num_kv_heads=Hkv,
                                      head_dim=D, axis="x")
    out = jax.jit(layer.__call__)(q, ctx.shard(k, P(None, None, "x")),
                                  ctx.shard(v, P(None, None, "x")), kv_lens)
    gold = _dense_golden(q, k, v, np.asarray(kv_lens))
    # tolerance covers the MXU's reduced-precision f32 matmul on real chips
    np.testing.assert_allclose(np.asarray(out), gold, atol=1e-2, rtol=1e-2)
    print(f"SP flash-decode over {n} KV shards (fused AG+merge) == dense "
          "attention golden")


@register_case("perf")
def perf():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
    ctx = world_context()
    n = ctx.num_ranks
    B, Hq, Hkv, D, s_local = 1, 32, 8, 128, 1024
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.bfloat16)
    kv = jnp.array([S], jnp.int32)
    ks = ctx.shard(k, P(None, None, "x"))
    vs = ctx.shard(v, P(None, None, "x"))
    for method in ("push", "fused"):
        f = jax.jit(lambda qq, m=method: sp_gqa_flash_decode(
            ctx, qq, ks, vs, kv, axis="x", ag_method=m))
        perf_report(f"sp_decode[{method}] B=1 S={S}",
                    time_op(lambda: f(q), iters=30))


if __name__ == "__main__":
    tutorial_main(__doc__)
