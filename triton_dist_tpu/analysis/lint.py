"""Determinism lint: enforce the serving trace contract on jaxprs.

The sharded-serving trace contract (docs/serving.md, CHANGES.md PR 8) is
prose today: the hot path must stay *rank-count independent* — bitwise
identical logits whichever mesh it runs on — which is why ``gemm_rs`` (a
``psum_scatter`` whose accumulation order depends on n) was refused in the
sharded engine. This module turns the prose into a rule: walk the jaxpr of
a serving program and flag any rank-count-dependent reduction or
host-sync-shaped op in it.

Flagged primitives:
- ``psum`` / ``reduce_scatter`` (``lax.psum_scatter``): cross-rank float
  accumulation whose result depends on the rank count and reduction order;
- ``pure_callback`` / ``io_callback`` / ``debug_callback`` / ``infeed`` /
  ``outfeed``: host round-trips — a host sync in the decode loop both
  breaks trace determinism (host state) and stalls the pipeline.

``all_gather`` / ``all_to_all`` / ``ppermute`` stay legal: pure data
movement, bitwise independent of arrival order.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .checker import Finding, NONDETERMINISM

BANNED_PRIMITIVES: Dict[str, str] = {
    "psum": "rank-count-dependent reduction (order/count changes the sum)",
    "reduce_scatter": "rank-count-dependent reduction (lax.psum_scatter)",
    "pure_callback": "host callback in the hot path",
    "io_callback": "host callback in the hot path",
    "debug_callback": "host callback in the hot path",
    "infeed": "host transfer in the hot path",
    "outfeed": "host transfer in the hot path",
}


def _sub_jaxprs(value: Any):
    values = value if isinstance(value, (tuple, list)) else (value,)
    for v in values:
        if hasattr(v, "eqns"):          # Jaxpr
            yield v
        elif hasattr(v, "jaxpr"):       # ClosedJaxpr
            yield v.jaxpr


def _walk(jaxpr, path: Tuple[str, ...], hits: List[Tuple[str, str]]):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in BANNED_PRIMITIVES:
            hits.append((name, "/".join(path) or "<top>"))
        for param in eqn.params.values():
            for sub in _sub_jaxprs(param):
                _walk(sub, path + (name,), hits)


def lint_jaxpr(jaxpr, op: str) -> List[Finding]:
    """Flag banned primitives anywhere in ``jaxpr`` (recursing through
    pjit/scan/cond/while/shard_map/pallas sub-jaxprs)."""
    hits: List[Tuple[str, str]] = []
    _walk(jaxpr, (), hits)
    return [Finding(NONDETERMINISM, op, None,
                    f"`{name}` under {where}: {BANNED_PRIMITIVES[name]}")
            for name, where in hits]


def lint_determinism(fn: Callable[..., Any], *example_args,
                     op: str = "fn",
                     axis_env: Optional[Tuple[Tuple[str, int], ...]] = None
                     ) -> List[Finding]:
    """Trace ``fn`` (arguments may be ShapeDtypeStructs — trace only, no
    execution) and lint the resulting jaxpr. ``axis_env`` binds named axes
    for tracing collective-bearing code outside a mesh — sizes > 1, or a
    ``psum`` over a size-1 axis constant-folds away before the lint sees
    it."""
    closed = jax.make_jaxpr(fn, axis_env=axis_env)(*example_args)
    return lint_jaxpr(closed.jaxpr, op)


# -- the three serving programs ---------------------------------------------

def _abstract(tree):
    return jax.tree_util.tree_map(
        lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype), tree)


@dataclasses.dataclass
class _ServingShapes:
    """Tiny but representative shapes for the serving-program lint."""
    chunk: int = 8
    batch: int = 2
    horizon: int = 2
    num_pages: int = 9
    page_size: int = 8
    pages_per_seq: int = 4


def lint_serving_programs(ctx=None) -> List[Finding]:
    """Lint the three serving programs the trace contract names:
    ``prefill_chunk_paged``, ``decode_multistep_paged`` (pure trace, no
    devices) and ``migrate_pages`` (traced through ``shard_map`` on a
    2-device mesh — pass ``ctx`` or have ≥ 2 local devices)."""
    from ..models.llama import (LlamaConfig, init_page_pool, init_params,
                                prefill_chunk_paged, decode_multistep_paged)

    sh = _ServingShapes()
    cfg = dataclasses.replace(LlamaConfig.tiny(n_layers=2), dtype=jnp.float32)
    params = _abstract(jax.eval_shape(
        lambda: init_params(jax.random.key(0), cfg)))
    pages = _abstract(jax.eval_shape(
        lambda: init_page_pool(cfg, sh.num_pages, sh.page_size)))
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)

    findings: List[Finding] = []
    findings += lint_determinism(
        lambda p, t, s, pl_, pg, bt: prefill_chunk_paged(
            p, t, s, pl_, cfg, pg, bt),
        params, i32(sh.chunk), i32(), i32(), pages, i32(sh.pages_per_seq),
        op="prefill_chunk_paged")
    findings += lint_determinism(
        lambda p, t, pos, pg, bt, lim: decode_multistep_paged(
            p, t, pos, cfg, pg, bt, lim, sh.horizon),
        params, i32(sh.batch), i32(sh.batch), pages,
        i32(sh.batch, sh.pages_per_seq), i32(sh.batch),
        op="decode_multistep_paged")
    findings += lint_migrate_pages(ctx)
    return findings


def lint_migrate_pages(ctx=None) -> List[Finding]:
    from ..ops import migrate_pages

    if ctx is None:
        import numpy as np
        from jax.sharding import Mesh
        from ..shmem import ShmemContext
        devices = jax.devices()
        if len(devices) < 2:
            return [Finding(
                NONDETERMINISM, "migrate_pages", None,
                "lint could not run: needs a 2-device mesh to trace "
                "through shard_map (got 1 local device)")]
        ctx = ShmemContext(mesh=Mesh(np.array(devices[:2]), ("role",)))

    sh = _ServingShapes()
    axis = ctx.axis_names[0]
    n_roles = ctx.axis_size(axis)
    L, Hkv, D, pmax = 2, 2, 64, 4
    pool = jax.ShapeDtypeStruct(
        (n_roles, L, sh.num_pages, Hkv, sh.page_size, D), jnp.float32)
    i32 = lambda *shape: jax.ShapeDtypeStruct(shape, jnp.int32)
    return lint_determinism(
        lambda kp, vp, src, dst, npg: migrate_pages(
            ctx, kp, vp, src, dst, npg, axis=axis),
        pool, pool, i32(pmax), i32(pmax), i32(1),
        op="migrate_pages")


# -- engine hook (TDT_SIGCHECK=1) -------------------------------------------

def lint_engine_programs(programs: Dict[str, Tuple[Callable, tuple]],
                         what: str) -> None:
    """Raise if any of an engine's jitted programs violates the determinism
    contract. ``programs`` maps name → (fn, example_args) with abstract
    example args; called from the engine constructors when
    ``TDT_SIGCHECK=1`` so a contract regression fails at engine build time,
    before any request is admitted."""
    findings: List[Finding] = []
    for name, (fn, example_args) in programs.items():
        findings += lint_determinism(fn, *example_args, op=f"{what}.{name}")
    if findings:
        raise RuntimeError(
            "TDT_SIGCHECK: serving trace-determinism contract violated:\n"
            + "\n".join(f"  {f}" for f in findings))
