"""Debug/printing helpers (cf. reference python/triton_dist/utils.py:201-231
``dist_print`` and :610-639 ``assert_allclose``)."""

from __future__ import annotations

import sys

import jax
import numpy as np


def interpret_race_state():
    """Best-effort handle on the interpret-mode race detector's module-level
    result state. This is a PRIVATE jax surface
    (``jax._src.pallas.mosaic.interpret.interpret_pallas_call``) that tests
    use to assert the ``TDT_DETECT_RACES`` plumbing actually ran the
    detector; a jax upgrade may move or rename it at any time. Returns the
    object exposing ``.races`` (``None`` until a detection pass ran, then a
    result with ``.races_found``), or ``None`` when the private layout is
    gone — callers should skip with a reason, not fail."""
    try:
        from jax._src.pallas.mosaic.interpret import (
            interpret_pallas_call as ipc)
    except ImportError:
        return None
    if not hasattr(ipc, "races"):
        return None
    return ipc


def dist_print(*args, allowed_ranks="all", prefix: bool = False, file=None,
               **kwargs):
    """Print from one or more host processes. In single-controller jax there
    is one host process per slice; identity is ``jax.process_index()``."""
    file = file or sys.stderr
    pid = jax.process_index()
    if allowed_ranks == "all":
        allowed = range(jax.process_count())
    else:
        allowed = allowed_ranks
    if pid in allowed:
        if prefix:
            print(f"[rank {pid}]", *args, file=file, **kwargs)
        else:
            print(*args, file=file, **kwargs)


def assert_allclose(x, y, atol: float = 1e-3, rtol: float = 1e-3, verbose: bool = True):
    """Rich allclose assert: dumps max/mean abs error and the worst offending
    indices on failure (cf. reference utils.py:610-639)."""
    x = np.asarray(x)
    y = np.asarray(y)
    assert x.shape == y.shape, f"shape mismatch {x.shape} vs {y.shape}"
    xf = x.astype(np.float64)
    yf = y.astype(np.float64)
    if np.allclose(xf, yf, atol=atol, rtol=rtol):
        return
    err = np.abs(xf - yf)
    denom = np.abs(yf) + 1e-12
    rel = err / denom
    bad = (err > atol + rtol * np.abs(yf))
    n_bad = int(bad.sum())
    msg = [
        f"assert_allclose failed: {n_bad}/{x.size} mismatched "
        f"(atol={atol}, rtol={rtol})",
        f"  max abs err {err.max():.6g}  mean abs err {err.mean():.6g}  "
        f"max rel err {rel.max():.6g}",
    ]
    if verbose:
        idx = np.unravel_index(np.argsort(err, axis=None)[::-1][:10], x.shape)
        for i in range(min(10, n_bad)):
            at = tuple(int(a[i]) for a in idx)
            msg.append(f"  at {at}: got {x[at]!r} want {y[at]!r}")
    raise AssertionError("\n".join(msg))
