"""Sharded serving (ISSUE 8): the engine's two compiled programs under
shard_map on a TP/SP/EP mesh, held to the bitwise cross-mesh contract.

THE contract (sharded.py module docstring): a 50-request forced-preemption
trace served on an n>1 interpret mesh is BIT-IDENTICAL per request to the
n=1 golden — same tokens, same preemption-survival, across decode horizons
K∈{1,4} and prefill-chunk sizes. The golden is the SAME
``ShardedServingEngine`` at mesh 1x1x1: hooks set, loops unrolled, fp8
wire round-tripped — so n>1 changes ONLY the rank count, never the code
path.

The wire dtype is PINNED to fp8 here rather than left on ``"auto"``:
auto resolves per rank count (``pick_wire_dtype``), so an n=1 golden under
auto could legitimately pick a different wire dtype than the n=4 run and
the comparison would test nothing. Pinning makes every run quantize
identically (docs/serving.md spells out the caveat).

Also covered: the one-program-per-path compile-count guard at n>1, the
replicated-decision digest guard (sensitivity + divergence injection),
constructor precondition refusals, and the ag_gemm TP impl's
allclose-only status.

Every test runs under the per-test SIGALRM watchdog (same pattern as
tests/test_chaos.py): a mesh-collective hang must kill the test loudly,
not stall the suite.
"""

import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.ops.allgather_gemm import GemmConfig, tp_column_linear
from triton_dist_tpu.serving import (ReplicatedDecisionError,
                                     ShardedServingEngine, serving_mesh)
from triton_dist_tpu.serving.kv_pool import KVPagePool
from triton_dist_tpu.serving.scheduler import ContinuousBatchingScheduler

pytestmark = [pytest.mark.mesh, pytest.mark.serving]

WATCHDOG_S = 240          # per-test wall cap — generous, CPU CI is slow
N_REQUESTS = 50
MAX_STEPS = 100_000       # engine's own stall watchdog trips far earlier
WIRE = jnp.float8_e4m3fn  # pinned (NOT "auto") — see module docstring


@pytest.fixture(autouse=True)
def mesh_watchdog():
    """Hard per-test wall-clock watchdog (test_chaos.py pattern): SIGALRM,
    not a thread, so even a wedged collective inside jax is interrupted."""
    def boom(signum, frame):
        raise TimeoutError(
            f"mesh watchdog: test exceeded {WATCHDOG_S}s wall — "
            "a mesh collective (or the engine) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def moe_model():
    """Micro MoE: smallest shape that exercises every sharded path
    (d_model=128 is the A2A wire-lane floor; 2 KV heads so GQA grouping
    is real; 4 experts / topk 2 so EP dispatch actually routes)."""
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _trace():
    """50 requests, bursty arrivals (two per step) against a 9-page pool —
    growth-driven preemption is forced, not incidental. Deterministic."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(N_REQUESTS):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        prompt = rng.randint(1, 128, size=plen).tolist()
        out.append((i // 2, prompt, mnt))
    return out


def _engine(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preemption
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _serve(moe_model, tp, sp, ep, **kw):
    eng = _engine(moe_model, tp, sp, ep, **kw)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    return {"tokens": tokens, "compiles": eng.compile_stats,
            "counters": dict(eng.metrics.counters)}


@pytest.fixture(scope="module")
def golden(moe_model):
    """The n=1 golden: the SAME sharded engine at mesh 1x1x1."""
    return _serve(moe_model, 1, 1, 1)


@pytest.fixture(scope="module")
def n2_run(moe_model):
    return _serve(moe_model, 1, 1, 2)


@pytest.fixture(scope="module")
def n4_run(moe_model):
    """n=4 with the OTHER decode horizon: SP×EP mesh, K=4 multi-token
    dispatches — trace must still replay the K=1 n=1 golden exactly."""
    return _serve(moe_model, 1, 2, 2, decode_horizon=4)


def _assert_identical(run, golden):
    assert run["tokens"].keys() == golden["tokens"].keys()
    bad = [r for r in golden["tokens"]
           if run["tokens"][r] != golden["tokens"][r]]
    assert not bad, f"token streams diverged from n=1 golden: rids {bad}"


def test_golden_trace_shape(golden):
    """The golden run actually exercised what the contract claims: every
    request finished, preemption fired, chunked prefill carried every
    prompt token, and the digest guard ran every step."""
    assert len(golden["tokens"]) == N_REQUESTS
    c = golden["counters"]
    assert c["preemptions"] >= 1, "pool sizing no longer forces preemption"
    # every prompt token entered pages through the chunk program — no
    # bucketed inline-prefill program ever compiled
    assert c["prefill_chunks"] > 0
    assert golden["compiles"]["prefill_programs"] == 0
    assert c["digest_checks"] > 0


@pytest.mark.quick
def test_trace_bit_identical_n2(n2_run, golden):
    _assert_identical(n2_run, golden)
    assert n2_run["counters"]["digest_checks"] > 0


def test_trace_bit_identical_n4_horizon4(n4_run, golden):
    _assert_identical(n4_run, golden)


def test_trace_bit_identical_chunk_variant(moe_model, golden):
    """Chunk-size invariance composes with mesh invariance: n=2 with a
    DIFFERENT prefill_chunk (4, the other row-count-specialized A2A
    layer) still replays the chunk=8 golden per request."""
    run = _serve(moe_model, 1, 1, 2, prefill_chunk=4)
    _assert_identical(run, golden)


@pytest.mark.slow
def test_trace_bit_identical_full_sweep(moe_model, golden):
    """Every axis individually plus the full 8-rank mesh."""
    for tp, sp, ep, kw in [(2, 1, 1, {}), (1, 2, 1, {}),
                           (2, 2, 2, {"decode_horizon": 4})]:
        run = _serve(moe_model, tp, sp, ep, **kw)
        _assert_identical(run, golden)


def test_one_program_per_path(golden, n2_run, n4_run):
    """Compile-count guard at n>1 (the GSPMD output-sharding flip this
    pins is real — see the out_shardings comment in engine.py): exactly
    ONE decode program and ONE chunk program per run, same as n=1."""
    for run in (golden, n2_run, n4_run):
        assert run["compiles"]["decode_compiles"] == 1, run["compiles"]
        assert run["compiles"]["prefill_chunk_compiles"] == 1, \
            run["compiles"]
        assert run["compiles"]["prefill_programs"] == 0, run["compiles"]


# -- replicated-decision digest guard -----------------------------------

def test_control_digest_sensitivity():
    """The digest moves on every control-plane decision class it claims
    to cover: allocation, free (order-sensitively), admission, ticketing."""
    pool = KVPagePool(8, 16, reserved=1)
    d0 = pool.digest()
    assert pool.alloc("r1", 2)
    d1 = pool.digest()
    assert d1 != d0
    pool.free_seq("r1")
    d2 = pool.digest()
    assert d2 != d1
    # deterministic: an identical decision history digests identically
    twin = KVPagePool(8, 16, reserved=1)
    assert twin.alloc("r1", 2)
    twin.free_seq("r1")
    assert twin.digest() == d2

    sched = ContinuousBatchingScheduler(4)
    s0 = sched.digest()
    from triton_dist_tpu.serving.scheduler import Request
    sched.submit(Request(rid=1, prompt=(1, 2, 3), max_new_tokens=2))
    assert sched.digest() != s0


@pytest.mark.quick
def test_digest_divergence_raises(moe_model):
    """Inject a per-rank digest skew (the test hook — a single-controller
    process cannot organically fork a replicated digest) and the guard
    must trip on the next productive step."""
    eng = _engine(moe_model, 1, 1, 2)
    eng.submit([1, 2, 3, 4, 5], 4)
    assert eng.step()                      # healthy step passes the check
    eng._digest_skew[1] = 1                # rank 1 now disagrees
    with pytest.raises(ReplicatedDecisionError, match="diverged"):
        while eng.step():
            pass
    eng._digest_skew[1] = 0
    eng.check_replicated_decisions()       # healthy again


def test_digest_every_disables(moe_model):
    eng = _engine(moe_model, 1, 1, 2, digest_every=0)
    eng._digest_skew[1] = 1                # would trip if checks ran
    eng.submit([1, 2, 3], 2)
    eng.run(max_steps=MAX_STEPS)
    assert eng.metrics.counters["digest_checks"] == 0


# -- constructor precondition refusals ----------------------------------

def test_requires_prefill_chunk(moe_model):
    cfg, params = moe_model
    with pytest.raises(AssertionError, match="prefill_chunk"):
        ShardedServingEngine(params, cfg, serving_mesh(1, 1, 2),
                             prefill_chunk=None, wire_dtype=WIRE)


def test_requires_ep_divisibility(moe_model):
    cfg, params = moe_model
    with pytest.raises(AssertionError, match="split evenly"):
        ShardedServingEngine(params, cfg, serving_mesh(1, 1, 2),
                             num_slots=3, prefill_chunk=8, wire_dtype=WIRE)
    with pytest.raises(AssertionError, match="split evenly"):
        ShardedServingEngine(params, cfg, serving_mesh(1, 1, 2),
                             num_slots=4, prefill_chunk=7, wire_dtype=WIRE)


def test_requires_mesh_axes(moe_model):
    cfg, params = moe_model
    from triton_dist_tpu.shmem.context import initialize_distributed
    ctx = initialize_distributed(axis_names=("role",), mesh_shape=(2,))
    with pytest.raises(AssertionError, match="missing axis"):
        ShardedServingEngine(params, cfg, ctx, prefill_chunk=8,
                             wire_dtype=WIRE)


# -- TP impl status ------------------------------------------------------

@pytest.mark.quick
def test_tp_column_linear_xla_bitwise_ag_gemm_allclose():
    """impl="xla" is bitwise-equal to the unsplit matmul (the exactness
    fact the trace contract leans on); impl="ag_gemm" — the Pallas
    overlap kernel — is allclose only, which is exactly why the engine
    defaults to xla for the bit-pinned path.

    Single-axis mesh: the Pallas DMA lowering refuses LOGICAL device ids
    on meshes with more than one named axis, so the ag_gemm impl is
    (for now) only reachable on an effectively-1-axis serving mesh
    (docs/serving.md notes this alongside its allclose-only status)."""
    from triton_dist_tpu.shmem.context import initialize_distributed
    ctx = initialize_distributed(axis_names=("tp",), mesh_shape=(2,))
    rng = np.random.RandomState(3)
    h = jnp.asarray(rng.randn(16, 128), jnp.float32)
    w = jnp.asarray(rng.randn(128, 256), jnp.float32)
    ref = h @ w
    out_xla = jax.jit(lambda h, w: tp_column_linear(
        ctx, h, w, axis="tp", impl="xla"))(h, w)
    assert jnp.array_equal(out_xla, ref)
    from triton_dist_tpu.ops.all_to_all import _interp_supports_remote_dma
    if not _interp_supports_remote_dma():
        pytest.skip("Pallas interpreter on this jax has no remote-DMA "
                    "model — the ag_gemm impl cannot execute here "
                    "(same gate the wire collectives use)")
    out_ag = jax.jit(lambda h, w: tp_column_linear(
        ctx, h, w, axis="tp", impl="ag_gemm",
        cfg=GemmConfig(block_m=8, block_n=128)))(h, w)
    np.testing.assert_allclose(np.asarray(out_ag), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
