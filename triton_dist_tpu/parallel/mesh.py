"""Mesh construction helpers.

The reference's "mesh" is implicit: one process per GPU under torchrun, with
`RANK/LOCAL_RANK/WORLD_SIZE` env (reference python/triton_dist/utils.py:91-111)
and NUMA/NVLink topology probing to pick algorithms (utils.py:504-607). On TPU
the topology is explicit — a `jax.sharding.Mesh` over named axes — and every
parallelism dimension (dp/pp/tp/ep) is an axis name. These helpers build
meshes from axis-size dicts and factorize an unknown device count into a
requested axis order (the ``prefer_inner`` axis — tp by default, the one that
most needs fast neighbours — gets the largest factor and rides ICI; outer
axes like dp get the rest and may ride DCN, per the scaling-book recipe).
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int], devices=None) -> Mesh:
    """Build a mesh from an ordered ``{axis_name: size}`` dict. A prefix
    subset of the available devices is allowed (e.g. a 4-device test mesh on
    an 8-device host)."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = int(np.prod(list(axes.values())))
    if n > devices.size:
        raise ValueError(f"mesh {axes} needs {n} devices, "
                         f"have {devices.size}")
    shape = tuple(axes.values())
    return Mesh(devices[:n].reshape(shape), tuple(axes.keys()))


def factorize_devices(n_devices: int,
                      axis_order: Sequence[str] = ("dp", "pp", "tp"),
                      prefer_inner: str | None = "tp") -> dict[str, int]:
    """Split ``n_devices`` across the named axes. The ``prefer_inner`` axis
    (innermost = fastest interconnect neighbours) takes the largest prime
    factor; the rest are dealt largest-first, round-robin inner-to-outer.
    E.g. 8 → {dp:2, pp:2, tp:2}; 4 → {dp:1, pp:2, tp:2};
    12 → {dp:2, pp:2, tp:3}; 1 → all ones."""
    axes = {a: 1 for a in axis_order}
    remaining = n_devices
    order = list(axis_order)[::-1]  # inner first
    if prefer_inner and prefer_inner in axes:
        order.remove(prefer_inner)
        order.insert(0, prefer_inner)
    factors = []
    while remaining > 1:
        f = next((p for p in range(2, remaining + 1) if remaining % p == 0))
        factors.append(f)
        remaining //= f
    # deal largest factors first so the preferred axis gets the biggest one
    for i, f in enumerate(sorted(factors, reverse=True)):
        axes[order[i % len(order)]] *= f
    return axes


__all__ = ["make_mesh", "factorize_devices"]
