"""Flash-decode tests vs dense attention goldens (parity targets: reference
test/nvidia/test_decode_attn.py and test_sp_decode_attn.py — the latter
checks the full SP pipeline against a paged-attention reference)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.flash_decode import (NEG_INF, decode_combine,
                                              gqa_decode_paged,
                                              gqa_decode_partial,
                                              sp_gqa_flash_decode)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def _dense_golden(q, k, v, kv_len):
    """Dense GQA attention golden in numpy."""
    q, k, v = (np.asarray(t, np.float64) for t in (q, k, v))
    B, Hq, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    out = np.zeros((B, Hq, D))
    for b in range(B):
        L = int(kv_len[b])
        for h in range(Hq):
            kh = h // G
            s = (k[b, kh, :L] @ q[b, h]) / math.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ v[b, kh, :L]
    return out


def test_gqa_decode_partial_full_cache():
    B, S, Hq, Hkv, D = 2, 256, 8, 2, 128
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_len = jnp.array([256, 100], jnp.int32)  # one full, one ragged
    out, lse = jax.jit(lambda *a: gqa_decode_partial(*a))(q, k, v, kv_len)
    golden = _dense_golden(q, k, v, np.asarray(kv_len))
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)
    # lse sanity: finite where kv_len > 0, lane-broadcast
    lse = np.asarray(lse)
    assert np.all(lse[..., 0] == lse[..., 1])
    assert np.all(lse[0, :, 0] > -1e29)


def test_decode_combine_matches_monolithic():
    """Splitting a cache into R chunks, decoding each, then combining must
    equal decoding the whole cache."""
    B, S, Hq, Hkv, D, R = 1, 512, 4, 1, 128, 4
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_len = jnp.array([S], jnp.int32)
    chunk = S // R
    outs, lses = [], []
    for r in range(R):
        o, l = jax.jit(lambda *a: gqa_decode_partial(*a))(
            q, k[:, :, r * chunk:(r + 1) * chunk], v[:, :, r * chunk:(r + 1) * chunk],
            jnp.array([chunk], jnp.int32))
        outs.append(o)
        lses.append(l)
    merged = jax.jit(decode_combine)(jnp.stack(outs), jnp.stack(lses))
    golden = _dense_golden(q, k, v, np.asarray(kv_len))
    assert_allclose(np.asarray(merged), golden, atol=1e-3, rtol=1e-3)


def _paged_golden(q, k_pages, v_pages, block_table, kv_len):
    """Dense paged golden: gather each row's live pages contiguously, then
    plain softmax attention. Only pages [0, ceil(kv_len/ps)) are touched —
    garbage block-table entries past that must not matter."""
    q = np.asarray(q, np.float64)
    kp = np.asarray(k_pages, np.float64)
    vp = np.asarray(v_pages, np.float64)
    bt = np.asarray(block_table)
    B, Hq, D = q.shape
    Hkv, ps = kp.shape[1], kp.shape[2]
    G = Hq // Hkv
    out = np.zeros((B, Hq, D))
    for b in range(B):
        L = int(kv_len[b])
        if L == 0:
            continue
        n_pages = -(-L // ps)
        k = np.concatenate([kp[p] for p in bt[b, :n_pages]], axis=1)[:, :L]
        v = np.concatenate([vp[p] for p in bt[b, :n_pages]], axis=1)[:, :L]
        for h in range(Hq):
            kh = h // G
            s = (k[kh] @ q[b, h]) / math.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ v[kh]
    return out


def test_paged_decode_garbage_block_table_entries():
    """Block-table entries past ceil(kv_len/page_size) may be ARBITRARY —
    even out-of-range page ids — without changing the result or faulting
    (the index map clamps and never dereferences them)."""
    B, Hq, Hkv, D, ps, pps, pool = 2, 4, 2, 64, 8, 6, 16
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    kp = jax.random.normal(jax.random.key(1), (pool, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(jax.random.key(2), (pool, Hkv, ps, D), jnp.float32)
    kv_len = jnp.array([2 * ps + 3, ps], jnp.int32)   # 3 and 1 live pages
    bt_clean = np.array([[3, 7, 1, 0, 0, 0],
                         [5, 0, 0, 0, 0, 0]], np.int32)
    out_c, lse_c = jax.jit(gqa_decode_paged)(q, kp, vp,
                                             jnp.asarray(bt_clean), kv_len)
    # poison every dead entry with garbage incl. ids far outside the pool
    bt_dirty = bt_clean.copy()
    bt_dirty[0, 3:] = [10 ** 6, -5, 2 ** 31 - 1]
    bt_dirty[1, 1:] = [-(2 ** 31), 999999, -1, 888, pool]
    out_d, lse_d = jax.jit(gqa_decode_paged)(q, kp, vp,
                                             jnp.asarray(bt_dirty), kv_len)
    np.testing.assert_array_equal(np.asarray(out_c), np.asarray(out_d))
    np.testing.assert_array_equal(np.asarray(lse_c), np.asarray(lse_d))
    golden = _paged_golden(q, kp, vp, bt_clean, np.asarray(kv_len))
    assert_allclose(np.asarray(out_d), golden, atol=1e-3, rtol=1e-3)


def test_paged_decode_kv_len_zero():
    """kv_len == 0 rows return zeros with lse = NEG_INF (the empty-shard
    convention the SP combine honors); live rows in the same batch are
    unaffected. The zero row's block table is all garbage on purpose."""
    B, Hq, Hkv, D, ps, pps, pool = 2, 4, 2, 64, 8, 4, 8
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    kp = jax.random.normal(jax.random.key(1), (pool, Hkv, ps, D), jnp.float32)
    vp = jax.random.normal(jax.random.key(2), (pool, Hkv, ps, D), jnp.float32)
    bt = jnp.asarray(np.array([[-7, 10 ** 8, -1, 4096],
                               [2, 6, 0, 0]], np.int32))
    kv_len = jnp.array([0, 2 * ps + 1], jnp.int32)
    out, lse = jax.jit(gqa_decode_paged)(q, kp, vp, bt, kv_len)
    out, lse = np.asarray(out), np.asarray(lse)
    np.testing.assert_array_equal(out[0], np.zeros_like(out[0]))
    np.testing.assert_array_equal(lse[0], np.full_like(lse[0], NEG_INF))
    golden = _paged_golden(q, kp, vp, np.asarray(bt), np.asarray(kv_len))
    assert_allclose(out[1], golden[1], atol=1e-3, rtol=1e-3)
    assert np.all(lse[1, :, 0] > -1e29)


@pytest.mark.parametrize("ag_method", ["push", "fused"])
def test_sp_flash_decode(ctx, ag_method):
    """Full SP pipeline on the mesh vs dense golden, ragged lengths —
    over the generic push AG and the fused AG+merge latency path."""
    n = ctx.num_ranks
    B, Hq, Hkv, D = 2, 4, 2, 128
    s_local = 128
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
    kv_lens = jnp.array([S, S // 2 + 17], jnp.int32)
    ks = ctx.shard(k, P(None, None, "x"))
    vs = ctx.shard(v, P(None, None, "x"))
    f = jax.jit(lambda *a: sp_gqa_flash_decode(ctx, *a, ag_method=ag_method))
    out = f(q, ks, vs, kv_lens)
    golden = _dense_golden(q, k, v, np.asarray(kv_lens))
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)
    # repeated-call safety (ws buffer addresses are reused across calls)
    out2 = f(q, ks, vs, kv_lens)
    assert_allclose(np.asarray(out2), golden, atol=1e-3, rtol=1e-3)
