"""Speculative multi-token decoding (ISSUE 20): model-free draft-verify
on the fused multistep machinery, held to the SAME bitwise trace
contract as every other serving lever.

THE claim under test: the bigram prompt-lookup drafter + the exact-match
greedy accept rule change ONLY the dispatch count — a committed token is
committed because a verify row fed the identical committed prefix
produced it, so the 50-request forced-preemption trace is BIT-IDENTICAL
to ``speculate=off`` on the colocated engine and across mesh sizes
n∈{1,2,4} at K∈{1,4}. The fast tier covers the colocated K sweep plus
the two cheapest mesh corners; the slow tier fills in the cross product.

Also covered: the one-decode-program compile guard stays pinned across K
and spec on/off; the EOS/limit accept edges ride plain int arrays
(accept-exactly-remaining, EOS-is-always-last-committed, EOS inside a
rejected suffix); mid-run preemption of slots holding speculative KV
(the tight 9-page pool forces it) rewinds cleanly; a PR 7-style chaos
schedule (seeded digest skew through the restore rung) replays
bit-identically with speculation on; and the ``serving_spec_k`` tuned
key is sigcheck-gated into the PR 15 registry (a broken protocol is
REFUSED admission) and consumed by ``speculate="auto"``.

Wire dtype pinned to fp8, never "auto" (same caveat as the sharded
suite: auto resolves per rank count, a pinned wire makes every run
quantize identically).
"""

import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.models.llama import LlamaConfig, init_params
from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
from triton_dist_tpu.serving import (ServingEngine, ShardedServingEngine,
                                     ngram_draft, serving_mesh, spec_accept)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.speculate import SPEC_K_DEFAULT, resolve_spec_k
from triton_dist_tpu.shmem import FaultPlan

pytestmark = [pytest.mark.serving, pytest.mark.spec]

WATCHDOG_S = 240
N_REQUESTS = 50
MAX_STEPS = 100_000
WIRE = jnp.float8_e4m3fn  # pinned (NOT "auto") — see module docstring
EOS = 5

# exactly one compiled program per path, regardless of K or spec on/off —
# speculation must not fork the program cache (the verify program IS the
# decode program; the drafter traces into it)
ONE_OF_EACH = {"decode_compiles": 1, "prefill_compiles": 0,
               "prefill_programs": 0, "prefill_chunk_compiles": 1}


@pytest.fixture(autouse=True)
def spec_watchdog():
    """Per-test SIGALRM wall cap (test_sharded_serving.py pattern)."""
    def boom(signum, frame):
        raise TimeoutError(
            f"spec watchdog: test exceeded {WATCHDOG_S}s wall — "
            "a mesh collective (or the engine) is hanging")
    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def llama_model():
    """Tiny-vocab Llama: greedy decode on a small model revisits states,
    so the prompt-lookup drafter lands real hits (accept > 1/dispatch)."""
    cfg = LlamaConfig(vocab_size=128, d_model=128, n_layers=1, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=128,
                      dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def moe_model():
    cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                     n_layers=1, n_heads=4, n_kv_heads=2,
                                     d_ff=128, max_seq_len=128,
                                     dtype=jnp.float32),
                    num_experts=4, topk=2, moe_d_ff=64)
    params = init_moe_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _trace(n=N_REQUESTS):
    """The sharded suite's 50-request bursty trace against a 9-page pool:
    growth-driven preemption is forced, not incidental — slots holding
    speculative KV get evicted mid-flight."""
    rng = np.random.RandomState(77)
    out = []
    for i in range(n):
        plen = int(rng.randint(3, 17))
        mnt = int(rng.randint(2, 6))
        out.append((i // 2, rng.randint(1, 128, size=plen).tolist(), mnt))
    return out


def _coloc(llama_model, **kw):
    cfg, params = llama_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)          # tight: forces preemption
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("eos_id", EOS)
    return ServingEngine(params, cfg, **kw)


def _sharded(moe_model, tp, sp, ep, **kw):
    cfg, params = moe_model
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 9)
    kw.setdefault("pages_per_seq", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("wire_dtype", WIRE)
    return ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep), **kw)


def _assert_identical(tokens, gold):
    assert tokens.keys() == gold.keys()
    bad = [r for r in gold if tokens[r] != gold[r]]
    assert not bad, f"token streams diverged from spec-off golden: rids {bad}"


# -- the accept rule on plain int arrays (the EOS/limit edges) ---------------

def _accept(inp, nxt, ract, eos=None):
    return np.asarray(spec_accept(jnp.asarray(inp, jnp.int32),
                                  jnp.asarray(nxt, jnp.int32),
                                  jnp.asarray(ract, bool), eos_id=eos))


def test_accept_full_and_partial_match():
    inp = [[7, 3, 4, 9]]          # col 0 = authentic last token
    nxt = [[3, 4, 9, 2]]          # every draft matched its argmax
    assert _accept(inp, nxt, [[True] * 4]) == [4]
    nxt2 = [[3, 4, 1, 2]]         # draft col 3 (9) != argmax of col 2 (1)
    assert _accept(inp, nxt2, [[True] * 4]) == [3]
    nxt3 = [[8, 4, 9, 2]]         # first draft already wrong
    assert _accept(inp, nxt3, [[True] * 4]) == [1]


def test_accept_position_zero_always_commits_on_active_row():
    # the verify row at position 0 consumed the AUTHENTIC last token, so
    # its argmax is exactly what speculate=off would have produced
    m = _accept([[7, 99, 99, 99]], [[1, 2, 3, 4]], [[True] * 4])
    assert m == [1]
    # a fully inactive row (parked slot) commits nothing
    assert _accept([[7, 1, 1, 1]], [[1, 1, 1, 1]], [[False] * 4]) == [0]


def test_accept_exactly_remaining():
    # limit clamps mid-slab: remaining=2 admits exactly 2 commits even
    # though every draft matches — an accept burst can never overshoot
    # max_new_tokens or write KV past the budget
    inp = [[7, 3, 4, 9]]
    nxt = [[3, 4, 9, 2]]
    ract = [[True, True, False, False]]
    assert _accept(inp, nxt, ract) == [2]
    # and remaining=K accepts the whole slab (the boundary case)
    assert _accept(inp, nxt, [[True] * 4]) == [4]


def test_accept_eos_is_always_last_committed():
    # EOS produced at position 1 with matching drafts beyond it: the
    # accept loop freezes AFTER the emitting position, so m == 2 and EOS
    # is the LAST committed token — never inside the accepted prefix
    inp = [[7, 3, EOS, 9]]
    nxt = [[3, EOS, 9, 2]]
    m = _accept(inp, nxt, [[True] * 4], eos=EOS)
    assert m == [2]
    assert nxt[0][m[0] - 1] == EOS


def test_accept_eos_inside_rejected_suffix_never_commits():
    # the draft chain breaks at position 1 (draft 8 != argmax 3); the
    # EOS the verify row hallucinated at position 2 sits in the REJECTED
    # suffix and must not terminate the request
    inp = [[7, 8, 4, 9]]
    nxt = [[3, 4, EOS, 2]]
    m = _accept(inp, nxt, [[True] * 4], eos=EOS)
    assert m == [1]
    assert EOS not in nxt[0][:m[0]]


# -- the drafter -------------------------------------------------------------

def _draft(hist, hist_len, n):
    return np.asarray(ngram_draft(jnp.asarray(hist, jnp.int32),
                                  jnp.asarray(hist_len, jnp.int32), n))


def test_draft_bigram_replays_most_recent_match():
    # window ... 5 6 9 | 5 6: the bigram (5,6) recurs; the drafter must
    # replay what followed the MOST RECENT earlier occurrence (9, 5, 6)
    hist = [[0, 0, 5, 6, 9, 5, 6]]
    assert _draft(hist, [5], 3).tolist() == [[9, 5, 6]]


def test_draft_unigram_fallback_and_no_match():
    # no earlier bigram, but the final token 6 appears earlier: unigram
    # fallback replays its continuation
    hist = [[0, 0, 6, 9, 4, 3, 6]]
    assert _draft(hist, [5], 2).tolist() == [[9, 4]]
    # no earlier occurrence at all: repeat the last token (a deliberately
    # wrong draft the verify pass rejects — never a correctness input)
    hist2 = [[0, 0, 1, 2, 3, 4, 6]]
    assert _draft(hist2, [5], 2).tolist() == [[6, 6]]


def test_draft_zero_len_window_and_n_zero():
    assert _draft([[0] * 8], [0], 2).shape == (1, 2)
    assert _draft([[1, 2, 3, 4]], [4], 0).shape == (1, 0)


# -- K resolution ------------------------------------------------------------

def test_resolve_spec_k_ladder():
    assert resolve_spec_k(3) == 3
    assert resolve_spec_k("auto") == SPEC_K_DEFAULT   # no registry
    with pytest.raises(TypeError):
        resolve_spec_k(True)
    with pytest.raises(AssertionError):
        resolve_spec_k(0)
    with pytest.raises(AssertionError):
        resolve_spec_k("fast")


# -- colocated bit-identity + compile guard ----------------------------------

@pytest.fixture(scope="module")
def coloc_golden(llama_model):
    eng = _coloc(llama_model)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    return tokens, eng.compile_stats


@pytest.mark.parametrize("k", [1, 4])
def test_spec_bit_identical_colocated(llama_model, coloc_golden, k):
    gold, gold_compiles = coloc_golden
    eng = _coloc(llama_model, speculate=k)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    _assert_identical(tokens, gold)
    # the compile guard: ONE decode program, flat across K and on/off
    assert eng.compile_stats == ONE_OF_EACH == gold_compiles
    c = eng.metrics.counters
    assert c["spec_dispatches"] == c["decode_steps"] > 0
    if k > 1:
        assert c["draft_tokens"] > 0


def test_spec_preempts_mid_verify_slot(llama_model, coloc_golden):
    """The tight 9-page pool preempts slots that hold speculative KV:
    rejected-suffix rewinds (free_tail) and whole-slot evictions compose
    — and the trace STILL matches the spec-off golden bitwise."""
    gold, _ = coloc_golden
    eng = _coloc(llama_model, speculate=4)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    _assert_identical(tokens, gold)
    c = eng.metrics.counters
    assert c["preemptions"] > 0, "pool never preempted — the test lost its bite"
    assert c["spec_rewinds"] > 0, "no draft was ever rejected at K=4"


def test_spec_accept_rate_on_repetitive_trace(llama_model):
    """On a shared-prefix trace the drafter must actually pay: accepted
    tokens per dispatch strictly above the 1.0 floor, dispatches strictly
    below the spec-off count for the SAME tokens."""
    rng = np.random.RandomState(3)
    tpl = rng.randint(1, 128, size=8).tolist()
    # one wave, landing at step 0, with long decode budgets: the dispatch
    # count is decode-bound, not arrival/prefill-bound — the axis
    # speculation moves
    arrivals = [(0, tpl + rng.randint(1, 128, size=2).tolist(), 24)
                for _ in range(4)]

    def run(spec):
        eng = _coloc(llama_model, num_pages=40, pages_per_seq=8,
                     speculate=spec)
        toks = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
        return toks, eng.metrics

    toks_off, m_off = run(None)
    toks_on, m_on = run(4)
    assert toks_on == toks_off
    acc = m_on.hist["accepted_per_dispatch"]
    assert acc.mean is not None and acc.mean > 1.0
    assert m_on.counters["dispatches"] < m_off.counters["dispatches"]
    assert m_on.counters["draft_accepted"] > 0


def test_spec_rejects_bad_knobs(llama_model):
    with pytest.raises(AssertionError, match="decode_horizon"):
        _coloc(llama_model, speculate=4, decode_horizon=2)
    with pytest.raises(AssertionError, match="spec_hist"):
        _coloc(llama_model, speculate=4, spec_hist=4)
    with pytest.raises(TypeError):
        _coloc(llama_model, speculate=True)


# -- sharded bit-identity matrix ---------------------------------------------
# fast tier: the two cheapest corners; slow tier completes n∈{1,2,4} ×
# K∈{1,4} (every combo runs the full 50-request forced-preemption trace
# against the one spec-off n=1 golden — the cross-mesh contract makes a
# single golden serve every mesh size).

_FAST = [(1, 1, 1, 4), (1, 1, 2, 4)]
_SLOW = [(1, 1, 1, 1), (1, 1, 2, 1), (1, 2, 2, 1), (1, 2, 2, 4)]


@pytest.fixture(scope="module")
def sharded_golden(moe_model):
    eng = _sharded(moe_model, 1, 1, 1)
    return eng.run(max_steps=MAX_STEPS, arrivals=_trace())


def _run_matrix_case(moe_model, sharded_golden, tp, sp, ep, k):
    eng = _sharded(moe_model, tp, sp, ep, speculate=k)
    tokens = eng.run(max_steps=MAX_STEPS, arrivals=_trace())
    _assert_identical(tokens, sharded_golden)
    assert eng.compile_stats == ONE_OF_EACH, eng.compile_stats
    assert eng.spec_k == k


@pytest.mark.mesh
@pytest.mark.parametrize("tp,sp,ep,k", _FAST)
def test_spec_bit_identical_sharded(moe_model, sharded_golden, tp, sp, ep, k):
    _run_matrix_case(moe_model, sharded_golden, tp, sp, ep, k)


@pytest.mark.mesh
@pytest.mark.slow
@pytest.mark.parametrize("tp,sp,ep,k", _SLOW)
def test_spec_bit_identical_sharded_full(moe_model, sharded_golden,
                                         tp, sp, ep, k):
    _run_matrix_case(moe_model, sharded_golden, tp, sp, ep, k)


# -- chaos replay with speculation on ----------------------------------------

@pytest.mark.mesh
def test_chaos_digest_skew_replay_with_spec(moe_model):
    """A seeded fault schedule (transient digest skew through the PR 9
    restore rung) replayed with speculation ON: the divergence is
    absorbed exactly once, the restore re-seeds every drafter window
    from the replayed prompts, and the tokens still match the spec-off
    run of the SAME schedule."""
    arrivals = _trace(20)

    def run(spec):
        eng = _sharded(moe_model, 1, 1, 2, journal=ControlJournal(),
                       checkpoint_every=4, digest_every=1, speculate=spec,
                       fault_plan=FaultPlan(seed=5, digest_skew_at=(9,)))
        toks = eng.run(max_steps=MAX_STEPS, arrivals=arrivals)
        return toks, eng.metrics.counters

    toks_off, _ = run(None)
    toks_on, c = run(4)
    assert c["digest_recoveries"] == 1
    assert c["faults_injected"] >= 1
    assert toks_on == toks_off


# -- tuned-key gate ----------------------------------------------------------

def test_spec_k_tuned_key_gated_and_consumed(moe_model):
    """The draft length is a sigcheck-gated registry key: a clean config
    admits (checked=True) and ``speculate="auto"`` consumes it; admission
    with a broken protocol runner — the seg_dropped_signal gallery
    kernel, the K-scaled EP a2a's own hazard — is REFUSED with the
    under_signal finding attached."""
    from triton_dist_tpu.analysis.gallery import GALLERY
    from triton_dist_tpu.aot.registry import (RegistryAdmissionError,
                                              TunedConfigRegistry, TunedKey,
                                              set_default_registry)

    reg = TunedConfigRegistry()
    key = TunedKey("serving_spec_k", mesh_shape=(1, 1, 1), dtype="float32",
                   shape_bucket=((2,),))
    reg.put(key, 2)                       # gate replays the 2x-row a2a
    assert reg.checked(key)

    with pytest.raises(RegistryAdmissionError) as exc:
        reg.put(TunedKey("serving_spec_k", mesh_shape=(1, 1, 2),
                         dtype="float32", shape_bucket=((2,),)), 4,
                run=GALLERY["seg_dropped_signal"].run)
    assert "under_signal" in exc.value.finding_kinds
    assert len(reg) == 1                  # the refused config never landed

    set_default_registry(reg)
    try:
        eng = _sharded(moe_model, 1, 1, 1, speculate="auto", spec_bucket=2)
        assert eng.spec_k == 2            # the tuned K won over default 4
        eng2 = _sharded(moe_model, 1, 1, 1, speculate=3, spec_bucket=2)
        assert eng2.spec_k == 3           # explicit overrides the registry
        eng3 = _sharded(moe_model, 1, 1, 1, speculate="auto", spec_bucket=0)
        assert eng3.spec_k == SPEC_K_DEFAULT   # bucket miss → default
    finally:
        set_default_registry(None)


def test_spec_bucket_of_is_pure_arithmetic():
    from triton_dist_tpu.serving.workload import (WorkloadSpec,
                                                  spec_bucket_of)
    assert spec_bucket_of(WorkloadSpec(prefixes=0)) == 0
    assert spec_bucket_of(WorkloadSpec(prefixes=4, zipf=1.1)) == 2
    assert spec_bucket_of(WorkloadSpec(prefixes=16, zipf=1.5)) == 2
    assert spec_bucket_of(WorkloadSpec(prefixes=16, zipf=1.1)) == 1
