"""Sequence-parallel GQA flash-decode attention module (analog of reference
layers/nvidia/sp_flash_decode_layer.py:43-184 ``SpGQAFlashDecodeAttention``).

The reference module owns a growable AG staging buffer and toggles between
JIT and AOT kernel paths (:111-132, :96-105). Here buffers are per-call and
the AOT path is ``jax.jit(...).lower().compile()`` (see tools.aot), so the
module reduces to configuration + the three-phase forward."""

from __future__ import annotations

import dataclasses

import jax

from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class SpGQAFlashDecodeAttention:
    ctx: ShmemContext
    num_q_heads: int
    num_kv_heads: int
    head_dim: int
    axis: str | None = None
    block_s: int = 128
    ag_method: str = "fused"  # fused partial-AG + lse-merge latency path

    def __call__(self, q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                 global_kv_lens: jax.Array) -> jax.Array:
        """q [B, Hq, D] replicated; k/v_cache [B, Hkv, S, D] sequence-sharded
        P(None, None, axis); global_kv_lens [B]. Returns [B, Hq, D] replicated
        (local split-KV decode → partial (out‖lse) allgather → lse-merge)."""
        B, Hq, D = q.shape
        assert Hq == self.num_q_heads and D == self.head_dim
        assert k_cache.shape[1] == self.num_kv_heads, (
            f"cache has {k_cache.shape[1]} kv heads, "
            f"layer configured for {self.num_kv_heads}")
        return sp_gqa_flash_decode(self.ctx, q, k_cache, v_cache,
                                   global_kv_lens, axis=self.axis,
                                   block_s=self.block_s,
                                   ag_method=self.ag_method)
