"""Two-process CPU cluster integration test.

Every other test runs the single-process simulator; the reference exercises
its multi-process model in every test via torchrun (SURVEY §4). This spawns
2 coordinator-connected ``jax.distributed`` CPU processes running
tests/mp_worker.py — the only place ``process_count() == 2`` paths execute:
the env-gated bootstrap, a cross-process XLA collective, and the autotuner's
MAX consensus. One variant launches through scripts/launch.sh to cover its
env mapping (generic COORDINATOR_ADDRESS → JAX_COORDINATOR_ADDRESS).
"""

import os
import socket
import subprocess
import sys

import jax
import pytest

# Pinned 2-process outcome per installed jax line. The overlap-kernel
# probe used to accept EITHER of its two outcomes; that either-or let a
# regression in one direction read as the other. Each supported jax line
# now pins the single outcome measured on it — an unlisted version fails
# loudly with instructions rather than guessing.
_PINNED_OUTCOME = {
    # jaxlib 0.4.x CPU client: the distributed bootstrap succeeds but ANY
    # computation spanning processes raises INVALID_ARGUMENT ("Multiprocess
    # computations aren't implemented on the CPU backend") before a kernel
    # is reached — mp_worker's capability probe turns that into one token
    # (measured 2026-08 on jax 0.4.37 / jaxlib 0.4.36).
    "0.4": "MP_BACKEND_NO_MULTIPROC",
    # jax 0.9 line: spanning XLA collectives work; the interpret-mode AG
    # kernel deadlocks on in-process semaphore state and the worker's
    # watchdog pins it (measured round 5).
    "0.9": "MP_AG_UNSUPPORTED",
}
_JAX_LINE = ".".join(jax.__version__.split(".")[:2])


def _pinned_outcome() -> str:
    try:
        return _PINNED_OUTCOME[_JAX_LINE]
    except KeyError:
        pytest.fail(
            f"no pinned 2-process outcome for jax {jax.__version__}: run "
            f"`python tests/mp_worker.py 0 2 127.0.0.1:<port>` (and id 1) "
            f"by hand, observe which MP_* token the workers print, and add "
            f'`"{_JAX_LINE}": "<token>"` to _PINNED_OUTCOME')


HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "mp_worker.py")
LAUNCH = os.path.join(REPO, "scripts", "launch.sh")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_env(pid: int, nproc: int, addr: str, generic_env: bool) -> dict:
    env = dict(os.environ)
    # a clean jax env: no axon plugin (a wedged device tunnel must not be
    # able to hang this test), no inherited XLA_FLAGS device-count forcing
    for k in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS", "JAX_PLATFORMS",
              "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS"):
        env.pop(k, None)
    env["PYTHONPATH"] = REPO
    env["JAX_NUM_PROCESSES"] = str(nproc)
    env["JAX_PROCESS_ID"] = str(pid)
    # the generic spelling exercises launch.sh's mapping
    env["COORDINATOR_ADDRESS" if generic_env
        else "JAX_COORDINATOR_ADDRESS"] = addr
    return env


def _run_cluster(via_launch_sh):
    """Launch the 2-process cluster once; returns (procs, outs) or raises
    TimeoutExpired after killing the children."""
    addr = f"127.0.0.1:{_free_port()}"
    cmd = ([LAUNCH, sys.executable, WORKER] if via_launch_sh
           else [sys.executable, WORKER])
    procs = [
        subprocess.Popen(cmd, env=_worker_env(pid, 2, addr, via_launch_sh),
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            # generous: the worker ends with a 45 s overlap-kernel
            # watchdog, and a fully loaded CI box stretches everything
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        raise
    return procs, outs


@pytest.mark.parametrize("via_launch_sh", [False, True])
def test_two_process_cluster(via_launch_sh):
    expected = _pinned_outcome()
    try:
        procs, outs = _run_cluster(via_launch_sh)
    except subprocess.TimeoutExpired:
        pytest.fail("multi-process workers timed out")
    if any(p.returncode != 0 for p in procs):
        # one retry with a FRESH port: the free-port probe releases the
        # socket before the children rebind it, and on a busy box another
        # process can grab it in between — a launch race, not a product
        # failure. A second consecutive failure is real and surfaces.
        try:
            procs, outs = _run_cluster(via_launch_sh)
        except subprocess.TimeoutExpired:
            pytest.fail(f"multi-process workers timed out on retry; "
                        f"first attempt: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        if expected == "MP_BACKEND_NO_MULTIPROC":
            # this jax line cannot execute ANY spanning computation: the
            # bootstrap + capability probe is the whole covered surface,
            # and the worker exits at the probe. Everything below (XLA
            # collective, consensus, overlap kernel) is unreachable.
            assert expected in out, (
                f"worker {pid}: expected the pinned {expected} outcome for "
                f"jax {jax.__version__} — the backend now spans processes? "
                f"re-measure and re-pin _PINNED_OUTCOME:\n{out}")
            continue
        assert f"MP_OK process={pid}/2" in out, out
        # the overlap-kernel attempt (VERDICT r4 #8) must report exactly
        # the outcome pinned for this jax version. MP_AG_WRONG_RESULT
        # (ran, corrupt data) matches no pin and fails here — as it
        # must. A flip between MP_AG_OK and MP_AG_UNSUPPORTED (runtime
        # gained/lost cross-process interpret support) also fails until
        # a human re-measures and re-pins, which is the point.
        assert expected in out, (
            f"worker {pid}: overlap-kernel outcome differs from the "
            f"pin ({expected}) for jax {jax.__version__}:\n{out}")
    if expected == "MP_BACKEND_NO_MULTIPROC":
        return
    # regex-extract: concurrent C++ (Gloo) log lines can interleave into the
    # same stdout line as the python print
    import re
    picks = {m for out in outs
             for m in re.findall(r"picked=([0-9.]+)", out)}
    assert len(picks) == 1, f"processes picked different configs: {picks}"


def test_two_process_merged_profile(tmp_path):
    """Multi-host ``group_profile``: both processes trace, process 0 merges
    one Perfetto-loadable timeline with per-host tracks (reference
    utils.py:282-501 parity)."""
    if _pinned_outcome() == "MP_BACKEND_NO_MULTIPROC":
        pytest.skip(f"jax {jax.__version__}: the CPU backend cannot span "
                    "processes, so the profiled collective cannot execute")
    import gzip
    import json

    addr = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(2):
        env = _worker_env(pid, 2, addr, generic_env=False)
        env["TDT_PROF_DIR"] = str(tmp_path)
        procs.append(subprocess.Popen(
            [sys.executable, WORKER], env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            # generous: the worker ends with a 45 s overlap-kernel
            # watchdog, and a fully loaded CI box stretches everything
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"profiled workers timed out; partial: {outs}")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
    assert any("MP_PROF_MERGED" in o for o in outs), outs

    merged = tmp_path / "mp" / "merged.trace.json.gz"
    assert merged.exists()
    with gzip.open(merged, "rt") as f:
        data = json.load(f)
    names = {ev["args"]["name"] for ev in data["traceEvents"]
             if ev.get("ph") == "M" and ev.get("name") == "process_name"}
    hosts = {n.split("/")[0] for n in names}
    assert {"host0", "host1"} <= hosts, f"per-host tracks missing: {names}"
    # both processes contributed real events, not just metadata
    pids = {ev.get("pid", 0) for ev in data["traceEvents"]}
    assert any(p >= 200000 for p in pids) and any(
        100000 <= p < 200000 for p in pids), sorted(pids)[:10]
