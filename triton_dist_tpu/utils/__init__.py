from triton_dist_tpu.utils.env import (  # noqa: F401
    on_tpu,
    on_cpu,
    interpret_params,
    default_interpret,
)
from triton_dist_tpu.utils.debug import dist_print, assert_allclose  # noqa: F401
from triton_dist_tpu.utils.perf import perf_func, group_profile  # noqa: F401
