"""Cluster-wide prefix sharing (ISSUE 17): the KV page-lending tier.

THE contract, three rungs:

- **hit rate**: on a Zipf template mix with router affinity DISABLED
  (full-prompt rendezvous — same-prefix requests scatter across the
  fleet, the adversarial placement), the lending cluster's prefix hit
  rate matches the single-replica hit rate, because a remote hit turns
  into a lend and the lend turns into an ordinary local cached hit.
- **re-warm**: a restored replica re-warms its empty cache from peers
  (kill-time tombstones → deepest-exporter lends), so post-restore
  template TTFT lands in the cached band, NOT the cold band — and
  router affinity returns to the restored home replica warm.
- **degrade, never stall**: a dead/slow/lossy lender burns its Backoff
  rungs and DEGRADES to local re-prefill (typed, audited) — tokens stay
  bit-identical to the ``expected_tokens`` closed form either way,
  because greedy-decode determinism makes lent bytes indistinguishable
  from re-prefilled ones.

Plus the kernel in isolation (``ops.lend_pages`` — the transport copy
where the LENDER KEEPS its pages, unlike migration) and the ledger /
index units underneath (``check_lendable`` sole-ownership gating,
``ReplicaPrefixIndex.prune``/``reassign``).

Every test runs under the per-test SIGALRM watchdog (test_cluster.py
pattern).
"""

import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import TEST_WORLD  # noqa: F401
from triton_dist_tpu.ops import lend_pages
from triton_dist_tpu.serving import Cluster, SimEngine, expected_tokens
from triton_dist_tpu.serving.kv_pool import KVPagePool, PageLedgerError
from triton_dist_tpu.serving.prefix_cache import ReplicaPrefixIndex
from triton_dist_tpu.shmem import FaultPlan
from triton_dist_tpu.shmem.context import initialize_distributed

pytestmark = [pytest.mark.lending, pytest.mark.serving]

WATCHDOG_S = 240
PS = 8                        # page size everywhere below
BORROWER_ROLE = 1             # 2-rank lend mesh: lender=0, borrower=1


@pytest.fixture(autouse=True)
def lending_watchdog():
    def boom(signum, frame):
        raise TimeoutError(
            f"lending watchdog: test exceeded {WATCHDOG_S}s wall — "
            "an engine (or a lend ladder) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture(scope="module")
def role_ctx():
    """One 2-rank role mesh for the kernel-in-isolation test."""
    return initialize_distributed(axis_names=("role",), mesh_shape=(2,))


def _mk_cluster(replicas=3, tmp_path=None, **kw):
    def factory(journal):
        return SimEngine(num_slots=4, page_size=PS, num_pages=33,
                         pages_per_seq=8, journal=journal,
                         prefix_cache=True, prefill_chunk=PS)

    return Cluster(factory, replicas=replicas,
                   journal_dir=None if tmp_path is None else str(tmp_path),
                   **kw)


def _templates(n=4, seed=23):
    """n distinct 24-token (3 full pages) prompt templates."""
    rng = np.random.RandomState(seed)
    return [tuple(int(t) for t in rng.randint(1, 997, size=3 * PS))
            for _ in range(n)]


def _hit_rate(cl):
    hits = sum(r.engine.metrics.counters["prefix_hits"]
               for r in cl.replicas)
    miss = sum(r.engine.metrics.counters["prefix_misses"]
               for r in cl.replicas)
    return hits / max(hits + miss, 1)


def _zipf_stream(cl, templates, n, seed):
    """Submit n Zipf-weighted template requests, draining between
    submits so the previous request's pages are CACHED (refcount-0)
    before the next may borrow them — in-flight prefill pages are not
    lendable by the sole-ownership rule. Returns {gid: (prompt, mnt)}."""
    rng = np.random.RandomState(seed)
    w = np.array([1.0 / (i + 1) ** 1.2 for i in range(len(templates))])
    w /= w.sum()
    sent = {}
    for _ in range(n):
        t = templates[int(rng.choice(len(templates), p=w))]
        prompt = t + tuple(int(x) for x in rng.randint(1, 997, size=3))
        mnt = int(rng.randint(2, 5))
        gid = cl.submit(list(prompt), mnt)
        sent[gid] = (prompt, mnt)
        cl.drain()
    return sent


def _assert_golden(cl, sent):
    res = cl.results()
    for gid, (prompt, mnt) in sent.items():
        assert res[gid] == expected_tokens(prompt, mnt), (
            f"gid {gid}: tokens diverged from the closed-form golden")


# ---------------------------------------------------------------------------
# the lend kernel, in isolation
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_lend_pages_kernel_exact_copy(role_ctx):
    """Lender-side pages land bit-exactly at the borrower's dst ids
    (every layer), padding beyond n_pages never moves, the borrower's
    landed report carries (count, tag) — and, the lend-vs-migrate
    distinction, the LENDER'S OWN PAGES ARE UNTOUCHED: a lend is a
    replication, the lender keeps serving its copies."""
    ctx = role_ctx
    L, Pg, H, ps, D = 2, 8, 2, 4, 8
    shape = (L, Pg, H, ps, D)
    host_k = np.zeros((2,) + shape, np.float32)
    host_v = np.zeros((2,) + shape, np.float32)
    for p in range(Pg):                        # distinct stamp per page
        host_k[0, :, p] = 100 + p
        host_v[0, :, p] = 200 + p
    pool_k = ctx.shard(jnp.asarray(host_k),
                       jax.sharding.PartitionSpec("role"))
    pool_v = ctx.shard(jnp.asarray(host_v),
                       jax.sharding.PartitionSpec("role"))

    src = jnp.array([3, 5, 1, 7], jnp.int32)   # entry past n is padding
    dst = jnp.array([2, 6, 4, 7], jnp.int32)
    pool_k, pool_v, landed = lend_pages(
        ctx, pool_k, pool_v, src, dst, jnp.array([3], jnp.int32),
        axis="role", lender=0, borrower=1, tag=7)
    assert int(np.asarray(landed)[BORROWER_ROLE, 0]) == 3
    assert int(np.asarray(landed)[BORROWER_ROLE, 1]) == 7
    hk, hv = np.asarray(pool_k), np.asarray(pool_v)
    for s, d in [(3, 2), (5, 6), (1, 4)]:
        assert (hk[1, :, d] == 100 + s).all()
        assert (hv[1, :, d] == 200 + s).all()
    assert not hk[1, :, 7].any(), "padding entry must not be lent"
    # the lender keeps its pages: shard 0 is untouched outside the
    # scratch page (id 0 — the interpret path mirror-writes it)
    for p in range(1, Pg):
        assert (hk[0, :, p] == 100 + p).all()
        assert (hv[0, :, p] == 200 + p).all()


# ---------------------------------------------------------------------------
# the ledger and index units underneath
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_check_lendable_sole_ownership():
    """A page is lendable iff refcount-0 AND cached-LRU-retained; the
    lendable run is the POSITIONAL PREFIX up to the first page that is
    not; out-of-range ids are ledger corruption, not a short count."""
    pool = KVPagePool(9, PS, reserved=1)
    got = pool.alloc("s", 3)
    for p in got:
        pool.mark_cacheable(p)
    # live sequence still references them — nothing is lendable yet
    assert pool.check_lendable(got) == 0
    pool.free_seq("s")          # refcount-0 + cacheable → cached LRU
    assert pool.check_lendable(got) == 3
    # a reader pins the middle page: the run stops right before it
    pool.acquire("t", [got[1]])
    assert pool.check_lendable(got) == 1
    # a refcount-0 page that is NOT index-retained is not lendable
    free = pool.alloc("u", 1)
    pool.free_seq("u")
    assert pool.check_lendable(free) == 0
    # out-of-range / reserved ids are loud
    with pytest.raises(PageLedgerError, match="check_lendable"):
        pool.check_lendable([0])
    with pytest.raises(PageLedgerError, match="check_lendable"):
        pool.check_lendable([9])


@pytest.mark.quick
def test_prefix_index_prune_and_reassign():
    """kill() prunes a dead replica's entries (returning tombstone
    paths); restore() reassigns them back — reassign OVERWRITES owners
    claimed by peers mid-death and creates missing nodes."""
    idx = ReplicaPrefixIndex(PS)
    a = tuple(range(100, 100 + 2 * PS))        # replica 0's prefix
    b = tuple(range(300, 300 + 2 * PS))        # replica 1's prefix
    idx.insert(a, 0)
    idx.insert(b, 1)
    assert idx.match(a) == (2, 0)              # (depth in runs, owner)
    tombs = idx.prune(0)
    assert tombs and all(isinstance(t, tuple) for t in tombs)
    assert {len(t) for t in tombs} <= {PS, 2 * PS}   # full token paths
    _, owner = idx.match(a)
    assert owner is None, "pruned entries must not route"
    assert idx.match(b) == (2, 1), "peer entries must survive"
    # a peer claims the prefix while 0 is dead (first-writer-wins insert)
    idx.insert(a, 1)
    assert idx.match(a) == (2, 1)
    # restore: reassign returns ownership to the re-warmed replica
    for t in tombs:
        idx.reassign(t, 0)
    assert idx.match(a) == (2, 0), "affinity did not return"
    # reassign on a never-inserted path creates it
    c = tuple(range(500, 500 + PS))
    idx.reassign(c, 2)
    assert idx.match(c) == (1, 2)


@pytest.mark.quick
def test_export_adopt_between_engines():
    """The host lend surface engine-to-engine: the lender exports its
    cached lendable prefix, the borrower adopts it as ordinary cached
    pages (classified REWARMED on first hit), tokens stay bit-identical
    to the closed form, and both ledgers audit clean."""
    lender = SimEngine(num_slots=2, page_size=PS, num_pages=17,
                       pages_per_seq=8, prefix_cache=True,
                       prefill_chunk=PS)
    borrower = SimEngine(num_slots=2, page_size=PS, num_pages=17,
                         pages_per_seq=8, prefix_cache=True,
                         prefill_chunk=PS)
    t = _templates(1)[0]
    prompt = t + (7, 8, 9)
    lender.submit(list(prompt), 3)
    lender.run()
    toks, ids, payload = lender.export_prefix(prompt)
    assert toks == 3 * PS and len(ids) == 3 and payload is None
    assert borrower.adopt_prefix(prompt, toks, payload) == 3
    # adopting again is a no-op, not an error (already as warm)
    assert borrower.adopt_prefix(prompt, toks, payload) == 0
    rid = borrower.submit(list(prompt), 3)
    out = borrower.run()
    assert out[rid] == expected_tokens(prompt, 3)
    assert borrower.metrics.hist["ttft_rewarmed_steps"].count == 1
    assert borrower.metrics.counters["prefix_hits"] == 1
    lender.alloc.check()
    borrower.alloc.check()


@pytest.mark.quick
def test_adopt_prefix_pins_local_hit_under_pool_pressure():
    """Regression: the borrower's PARTIAL local hit sits refcount-0 on
    the cached LRU, so the reclaim that makes room for the lent pages
    could evict it out from under the insert (re-popping the hit page
    into the fresh allocation → 'already indexed', or indexing a
    free-listed page). adopt_prefix must PIN the hit before reclaiming:
    under pressure the eviction takes another cached page — never the
    hit — and the lend deepens the existing prefix cleanly."""
    lender = SimEngine(num_slots=2, page_size=PS, num_pages=17,
                       pages_per_seq=8, prefix_cache=True,
                       prefill_chunk=PS)
    borrower = SimEngine(num_slots=2, page_size=PS, num_pages=17,
                         pages_per_seq=8, prefix_cache=True,
                         prefill_chunk=PS)
    t = _templates(1, seed=13)[0]
    prompt = t + (7, 8, 9)
    lender.submit(list(prompt), 3)
    lender.run()
    toks, _, payload = lender.export_prefix(prompt)
    assert toks == 3 * PS

    # the borrower caches ONLY the template's first page (the partial
    # hit, oldest on the LRU)...
    borrower.submit(list(t[:PS] + (1, 2, 3)), 2)
    borrower.run()
    assert len(borrower.prefix_cache.match(t)) == 1
    # ...then an unrelated page lands behind it on the LRU
    rng = np.random.RandomState(5)
    u = tuple(int(x) for x in rng.randint(1, 997, size=PS)) + (4, 5, 6)
    borrower.submit(list(u), 2)
    borrower.run()
    assert borrower.alloc.cached_pages == 2

    # soak the free list down to ONE page: landing the 2 missing pages
    # forces a reclaim, and the unpinned LRU victim would be the hit
    free = borrower.alloc.free_pages
    assert free >= 1
    if free > 1:
        assert borrower.alloc.alloc("soak", free - 1) is not None

    assert borrower.adopt_prefix(prompt, toks, payload) == 2
    # the hit survived (the decoy was evicted instead) and was deepened
    assert len(borrower.prefix_cache.match(t)) == 3
    assert not borrower.prefix_cache.match(u), \
        "the decoy page should have been the eviction victim"
    borrower.alloc.check()

    borrower.alloc.free_seq("soak")    # give the pool room to decode
    rid = borrower.submit(list(prompt), 2)
    out = borrower.run()
    assert out[rid] == expected_tokens(prompt, 2)


# ---------------------------------------------------------------------------
# acceptance: cluster hit rate == single-replica hit rate, affinity OFF
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_cluster_hit_rate_matches_single_replica_affinity_off():
    """The ISSUE 17 acceptance: with router affinity DISABLED (full-
    prompt rendezvous scatters same-template requests across the fleet),
    the lending cluster's hit rate matches the single-replica rate —
    every remote hit becomes a lend becomes a local hit — and beats the
    lend-less scattered baseline by a wide margin. All traces bitwise."""
    templates = _templates()
    n = 30

    single = _mk_cluster(replicas=1)
    sent_1 = _zipf_stream(single, templates, n, seed=41)
    rate_1 = _hit_rate(single)

    base = _mk_cluster(replicas=3, affinity=False)
    sent_b = _zipf_stream(base, templates, n, seed=41)
    rate_b = _hit_rate(base)

    lend = _mk_cluster(replicas=3, affinity=False, lend=True)
    sent_l = _zipf_stream(lend, templates, n, seed=41)
    rate_l = _hit_rate(lend)

    # scattering without lending costs real hits; lending wins them back
    assert rate_b < rate_1 - 0.05, (
        f"baseline not adversarial enough: {rate_b:.3f} vs {rate_1:.3f}")
    assert rate_l >= rate_b + 0.05
    assert abs(rate_l - rate_1) <= 0.02, (
        f"cluster hit rate {rate_l:.3f} != single-replica {rate_1:.3f}")
    assert lend.metrics.counters["lends"] > 0
    assert lend.metrics.counters["lent_pages"] >= \
        3 * lend.metrics.counters["lends"] - 2 * len(templates)
    assert lend.metrics.hist["lend_us_per_page"].count == \
        lend.metrics.counters["lends"]
    for cl, sent in ((single, sent_1), (base, sent_b), (lend, sent_l)):
        _assert_golden(cl, sent)
        for rep in cl.replicas:
            rep.engine.alloc.check()


# ---------------------------------------------------------------------------
# acceptance: restored replica re-warms from peers
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_restore_rewarms_from_peers(tmp_path):
    """Kill the template's home replica, serve the template elsewhere
    during the downtime, restore: the restored replica re-warms its
    cache FROM THE PEER (tombstones → deepest-exporter lend), affinity
    returns to it, and its post-restore template TTFT lands in the
    cached band — strictly below the fallback's cold band. A second
    kill/restore cycle then replays a journal that CONTAINS lend events
    (replay ignores them — re-warm re-earns the pages from peers)."""
    cl = _mk_cluster(replicas=3, tmp_path=tmp_path, lend=True)
    t = _templates(1, seed=91)[0]
    rng = np.random.RandomState(7)

    def tpl_prompt():
        return t + tuple(int(x) for x in rng.randint(1, 997, size=3))

    sent = {}

    def go(prompt, mnt=3):
        gid = cl.submit(list(prompt), mnt)
        sent[gid] = (tuple(prompt), mnt)
        cl.drain()
        return gid

    go(tpl_prompt())
    home = cl.prefix_index.match(t)[1]
    assert home is not None
    go(tpl_prompt())               # cached hit on home
    assert cl.replicas[home].engine.metrics.counters["prefix_hits"] >= 1

    cl.kill(home)
    assert cl._tombstones[home], "kill must tombstone the pruned paths"
    go(tpl_prompt())               # fallback serves the template COLD
    go(tpl_prompt())               # ... then cached
    fb = cl.prefix_index.match(t)[1]
    assert fb is not None and fb != home
    fb_m = cl.replicas[fb].engine.metrics
    cold_floor = fb_m.hist["ttft_cold_steps"].min
    cached_ceil = fb_m.hist["ttft_cached_steps"].max
    assert cold_floor is not None and cached_ceil is not None
    assert cold_floor > cached_ceil   # the bands are actually separated

    cl.restore(home)
    assert cl.metrics.counters["rewarmed_prefixes"] >= 1
    assert cl.metrics.counters["lends"] >= 1
    # affinity returned to the (re-warmed) home replica
    assert cl.route(list(tpl_prompt())).index == home
    go(tpl_prompt())               # post-restore: REWARMED, not cold
    hm = cl.replicas[home].engine.metrics
    rew = hm.hist["ttft_rewarmed_steps"]
    assert rew.count >= 1
    assert rew.max <= cached_ceil, (
        f"post-restore TTFT {rew.max} above the cached band "
        f"{cached_ceil}")
    assert rew.max < cold_floor, (
        f"post-restore TTFT {rew.max} in the cold band (floor "
        f"{cold_floor}) — the re-warm did not take")

    # second cycle: home's journal now holds "lend" events — replay must
    # ignore them (adopted pages are cache state, re-earned from peers)
    cl.kill(home)
    cl.restore(home)
    assert cl.metrics.counters["rewarmed_prefixes"] >= 2
    gid = go(tpl_prompt())
    assert cl.results()[gid] == expected_tokens(*sent[gid])

    _assert_golden(cl, sent)
    for rep in cl.replicas:
        rep.engine.alloc.check()


@pytest.mark.quick
def test_cold_restore_does_not_steal_claimed_prefixes(tmp_path):
    """With lending OFF a restored replica's cache is empty by contract
    (no re-warm ran): a prefix a peer claimed — and re-earned — during
    the downtime must STAY with that warm peer; reassigning it to the
    cold restoree would route template traffic at an empty cache. An
    UNCLAIMED tombstone still returns home: both sides are equally cold
    there, and affinity entries are never dropped."""
    cl = _mk_cluster(replicas=3, tmp_path=tmp_path)
    assert cl.lending is None
    rng = np.random.RandomState(3)
    sent = {}

    def go(t):
        prompt = t + tuple(int(x) for x in rng.randint(1, 997, size=3))
        gid = cl.submit(list(prompt), 2)
        sent[gid] = (prompt, 2)
        cl.drain()
        return gid

    # find two templates rendezvous-routed to the SAME home (pigeonhole
    # over 6 templates × 3 replicas guarantees a pair; deterministic)
    homes: dict[int, list[tuple]] = {}
    for t in _templates(6, seed=17):
        go(t)
        homes.setdefault(cl.prefix_index.match(t)[1], []).append(t)
    a, b = next(v for v in homes.values() if len(v) >= 2)[:2]
    home = cl.prefix_index.match(a)[1]

    cl.kill(home)
    go(a)                        # a fallback peer claims + re-earns `a`
    peer = cl.prefix_index.match(a)[1]
    assert peer is not None and peer != home
    assert cl.prefix_index.match(b)[1] is None, "pruned, nobody claimed"

    cl.restore(home)
    assert cl.prefix_index.match(a)[1] == peer, (
        "cold restoree stole a prefix its peer holds warm")
    assert cl.prefix_index.match(b)[1] == home, (
        "unclaimed affinity did not return to the restored replica")
    _assert_golden(cl, sent)


# ---------------------------------------------------------------------------
# acceptance: lender death mid-lend degrades, never stalls
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_lender_death_degrades_to_local_prefill():
    """A seeded dead-peer schedule kills every lend attempt in flight:
    the ladder burns its rungs, records a TYPED degradation, and the
    borrower prefills locally — tokens bit-identical to the closed-form
    golden, zero stalls. The whole drill replays from the seed: two runs
    produce identical degradation audit trails."""
    plan = FaultPlan(seed=3, dead_peer_after=0)

    def run():
        cl = _mk_cluster(replicas=3, affinity=False, lend=True,
                         lend_plan=plan)
        sent = _zipf_stream(cl, _templates(seed=61), 16, seed=5)
        _assert_golden(cl, sent)
        return (cl.metrics.counters["lends"],
                cl.metrics.counters["lend_degradations"],
                cl.metrics.counters["retries"],
                list(cl.lending.degraded))

    lends, degr, retries, audit = run()
    assert lends == 0, "a dead lender must never complete a lend"
    assert degr >= 1 and len(audit) == degr
    assert retries >= degr, "each degradation burned at least one retry"
    for lender, borrower, head in audit:
        assert lender != borrower and isinstance(head, tuple)
    assert run() == (lends, degr, retries, audit), (
        "the drill must replay from the seed alone")


@pytest.mark.quick
def test_lend_ladder_drop_delay_then_success():
    """The ladder rung by rung: total signal loss and over-deadline
    delivery both burn every rung and degrade (delay also marks the
    report stale); with the plan lifted the very same lend succeeds,
    and a repeat lend is a no-op because the borrower is already warm."""
    cl = _mk_cluster(replicas=2, lend=True)
    t = _templates(1, seed=77)[0]
    prompt = t + (5, 6, 7)
    cl.submit(list(prompt), 2)
    cl.drain()
    owner = cl.prefix_index.match(t)[1]
    borrower = cl.replicas[1 - owner]

    cl.lending._plan = FaultPlan(seed=2, p_drop=1.0)
    assert cl.lending.lend(borrower, prompt) == 0
    assert cl.metrics.counters["lend_degradations"] == 1

    cl.lending._plan = FaultPlan(seed=2, p_delay=1.0, max_delay_steps=99)
    assert cl.lending.lend(borrower, prompt) == 0
    assert cl.metrics.counters["lend_degradations"] == 2
    assert cl.metrics.counters["stale_signals"] >= 1

    cl.lending._plan = FaultPlan(seed=2)       # healthy transport
    assert cl.lending.lend(borrower, prompt) == 3
    assert cl.metrics.counters["lends"] == 1
    assert cl.lending.lend(borrower, prompt) == 0, (
        "an already-warm borrower must not borrow again")
    borrower.engine.alloc.check()
