// Host-native MoE token alignment (analog of reference
// csrc/distributed/csrc/moe_utils.cu `moe_ag_scatter_align_block_size`,
// moe_utils.cu:61-356 — there a CUDA kernel pair; here a C++ host op).
//
// On TPU the in-jit path is the vectorized jnp implementation
// (triton_dist_tpu/ops/group_gemm.py::align_tokens_by_expert); this native
// version serves the host-side datapath: routing tables that arrive from a
// CPU dataloader/serving frontend can be aligned without a device round-trip,
// then fed to the grouped GEMM as scalar-prefetch arrays.
//
// Contract (identical to align_tokens_by_expert):
//   P        = round_up(T, block_m) + E * block_m   (static packed bound)
//   n_blocks = P / block_m
//   gather_idx[P]        source row for each aligned row (0 for padding)
//   row_valid[P]         1 iff the aligned row carries a real token
//   block_expert[P/bm]   expert id owning each block (tail blocks: E-1)
// ids may contain -1 (or any out-of-range value) for padding rows.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

int64_t tdt_moe_align_padded_rows(int64_t T, int32_t E, int32_t block_m) {
  int64_t bm = block_m;
  return ((T + bm - 1) / bm) * bm + (int64_t)E * bm;
}

// Returns 0 on success, nonzero on bad arguments.
int32_t tdt_moe_align_block_size(const int32_t* ids, int64_t T, int32_t E,
                                 int32_t block_m, int32_t* gather_idx,
                                 uint8_t* row_valid, int32_t* block_expert) {
  if (T < 0 || E <= 0 || block_m <= 0) return 1;
  const int64_t bm = block_m;
  const int64_t P = tdt_moe_align_padded_rows(T, E, block_m);
  const int64_t n_blocks = P / bm;

  std::vector<int64_t> counts(E, 0);
  for (int64_t t = 0; t < T; ++t) {
    int32_t e = ids[t];
    if (e >= 0 && e < E) counts[e]++;
  }
  // block_start (in blocks) per expert; ends non-decreasing by construction
  std::vector<int64_t> row_start(E, 0), ends(E, 0);
  int64_t acc = 0;
  for (int32_t e = 0; e < E; ++e) {
    int64_t blocks_e = (counts[e] + bm - 1) / bm;
    row_start[e] = acc * bm;
    acc += blocks_e;
    ends[e] = acc;  // block index one past expert e's range
  }

  std::memset(gather_idx, 0, P * sizeof(int32_t));
  std::memset(row_valid, 0, P * sizeof(uint8_t));
  std::vector<int64_t> fill(E, 0);
  for (int64_t t = 0; t < T; ++t) {
    int32_t e = ids[t];
    if (e < 0 || e >= E) continue;  // padding row -> dropped
    int64_t dest = row_start[e] + fill[e]++;
    gather_idx[dest] = (int32_t)t;
    row_valid[dest] = 1;
  }

  // block_expert[i] = clip(#experts whose range ends at or before i, 0, E-1)
  // (two-pointer sweep over the non-decreasing `ends`)
  int64_t done = 0;
  for (int64_t i = 0; i < n_blocks; ++i) {
    while (done < E && ends[done] <= i) done++;
    block_expert[i] = (int32_t)(done < E ? done : E - 1);
  }
  return 0;
}

}  // extern "C"
