"""Shape-keyed default config selection + topology-aware method pick
(VERDICT r3 missing #3: the measured-best tile table wired into defaults,
and ``method="auto"`` consulting the mesh rather than a static rule).

Reference parity: its AG method dispatch is NVLink/NUMA-topology keyed
(allgather.py:54-69, utils.py:504-607) and its GEMM tile configs are
per-shape knobs in the perf tests (test_ag_gemm_intra_node.py:153-160).
"""

import jax
import jax.numpy as jnp
import pytest

from conftest import TEST_WORLD
from triton_dist_tpu.ops.allgather import _auto_method
from triton_dist_tpu.ops.gemm import GemmConfig, best_gemm_config
from triton_dist_tpu.shmem.context import initialize_distributed

BF16 = 2

# The reference's six perf model shapes (M=8192 rows, bf16) at the
# benchmarked n=1 geometry (docs/benchmarks.md sweep table: the GEMM tiles
# over the full [8192, K] x [K, N]). Expected picks follow the
# measured-best table.
MODEL_SHAPES = [
    # (name, N, K, expected cfg)
    ("llama-7b", 11008, 4096, GemmConfig(512, 256, 2048)),
    ("llama-3.1-8b", 14336, 4096, GemmConfig(512, 512, 2048)),
    ("llama-3.1-70b", 28672, 8192, GemmConfig(512, 512, 2048)),
    ("llama-3.1-405b", 53248, 16384, GemmConfig(512, 512, 2048)),
    ("mistral-7b", 14336, 4096, GemmConfig(512, 512, 2048)),
    ("qwen2-72b", 29568, 8192, GemmConfig(1024, 384, 1024)),
]


@pytest.mark.parametrize("name,N,K,want", MODEL_SHAPES,
                         ids=[s[0] for s in MODEL_SHAPES])
def test_best_config_model_shapes(name, N, K, want):
    got = best_gemm_config(8192, N, K, BF16)
    assert got == want, f"{name}: {got} != {want}"
    assert got.vmem_ok(K, BF16)


def test_best_config_headline_shape():
    # 4096^3 at n=1: the sweep winner (512, 512, block_k=2048)
    assert best_gemm_config(4096, 4096, 4096, BF16) == GemmConfig(
        512, 512, 2048)


def test_best_config_small_shapes_never_assert():
    # tiny/odd test shapes must fall back to something that divides
    for m, n_cols, k in [(8, 128, 64), (32, 256, 96), (24, 120, 40),
                         (1, 1, 1), (128, 384, 8192)]:
        cfg = best_gemm_config(m, n_cols, k, 4)
        assert m % cfg.block_m == 0 and n_cols % cfg.block_n == 0
        assert cfg.block_k is None or k % cfg.block_k == 0
        assert cfg.vmem_ok(k, 4)


@pytest.fixture(scope="module")
def ctx4():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


@pytest.fixture(scope="module")
def ctx2d():
    return initialize_distributed(axis_names=("node", "x"),
                                  mesh_shape=(2, TEST_WORLD // 2))


def test_auto_method_1d(ctx4):
    small = jnp.zeros((TEST_WORLD * 8, 128), jnp.float32)      # 4 KB/rank
    big = jnp.zeros((TEST_WORLD * 1024, 1024), jnp.float32)    # 4 MB/rank
    assert _auto_method(ctx4, small, "x") == "push"
    # n <= 4 keeps push even for big payloads (one hop beats 3-hop ring)
    assert _auto_method(ctx4, big, "x") == "push"


def test_auto_method_2d(ctx2d):
    small = jnp.zeros((4 * 8, 128), jnp.float32)
    big = jnp.zeros((4 * 1024, 1024), jnp.float32)
    assert _auto_method(ctx2d, small, None) == "push_2d"
    assert _auto_method(ctx2d, big, None) == "ring_2d"
    assert _auto_method(ctx2d, big, ("node", "x")) == "ring_2d"
