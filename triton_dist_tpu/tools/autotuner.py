"""Distributed thunk-level autotuner (analog of reference
python/triton_dist/autotuner.py ``contextual_autotune``).

The reference cannot use Triton's per-kernel autotuner for overlap ops — a
config change alters *multi-kernel pipelines with side effects* (symmetric
buffers, signals), and each rank must pick the SAME config or the job
deadlocks. So it tunes whole thunks by re-running full calls per config and
reaches cross-rank consensus by all-reducing MAX of the timings
(autotuner.py:225-256).

Same shape here, simpler by construction:
- a "thunk" is a pure jitted function → re-running per config is safe by
  default (no serial-mode bisection needed);
- consensus: jax is single-controller per process, but multi-host jobs still
  time differently per host — we allgather per-host timings and take the
  elementwise MAX (a config is as slow as its slowest host), exactly the
  reference's consensus rule;
- results are cached per (function, static key, arg shapes) and logged to
  ``.autotune_logs/process-N.log`` (cf. autotuner.py:57-67).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Sequence

import jax
import numpy as np

from triton_dist_tpu.utils.perf import perf_func

_CACHE: dict = {}


def _consensus_times(times: np.ndarray) -> np.ndarray:
    """Elementwise MAX of per-host timings across processes (reference
    all_reduce(MAX) consensus, autotuner.py:225-238)."""
    if jax.process_count() == 1:
        return times
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(times)  # [P, n_cfg]
    return np.max(np.asarray(gathered), axis=0)


def _log(msg: str) -> None:
    os.makedirs(".autotune_logs", exist_ok=True)
    path = f".autotune_logs/process-{jax.process_index()}.log"
    with open(path, "a") as f:
        f.write(f"[{time.strftime('%H:%M:%S')}] {msg}\n")


def contextual_autotune(configs: Sequence[Any], iters: int = 5,
                        warmup: int = 2,
                        prune: Callable[[Any, tuple, dict], bool] | None = None):
    """Decorator: ``fn(*args, cfg=<config>, **kw)`` gets its ``cfg`` picked
    by timing every candidate on the first call per arg-shape signature.

    ``prune(config, args, kw)`` may return False to skip invalid candidates
    (e.g. tile sizes that don't divide the shapes — the analog of Triton's
    early-config-prune).
    """
    configs = list(configs)

    def _sig(a):
        return ((tuple(a.shape), str(a.dtype)) if hasattr(a, "shape") else a)

    def deco(fn):
        import inspect
        fn_sig = inspect.signature(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            if kw.get("cfg") is not None:
                return fn(*args, **kw)
            # kwargs like axis/out_dtype select different code paths, so they
            # are part of the tuning signature (cfg itself is excluded).
            # Bind to the canonical parameter form so positional vs keyword
            # spelling of the same argument shares one cache entry.
            bound = fn_sig.bind(*args, **kw)
            bound.apply_defaults()
            key = (fn.__qualname__,
                   tuple((k, _sig(v)) for k, v in bound.arguments.items()
                         if k != "cfg"))
            if key not in _CACHE:
                cands = [c for c in configs
                         if prune is None or prune(c, args, kw)]
                assert cands, f"all autotune configs pruned for {key}"
                times = np.full((len(cands),), np.inf)
                for i, c in enumerate(cands):
                    try:
                        kw2 = dict(kw, cfg=c)
                        _, ms = perf_func(lambda: fn(*args, **kw2),
                                          iters=iters, warmup_iters=warmup)
                        times[i] = ms
                    except Exception as e:  # config failed to compile/run
                        _log(f"{fn.__qualname__} cfg {c}: FAILED {e!r}")
                times = _consensus_times(times)
                best = int(np.argmin(times))
                assert np.isfinite(times[best]), (
                    f"every autotune config failed for {key}")
                _CACHE[key] = cands[best]
                _log(f"{fn.__qualname__} {key[1]}: picked {cands[best]} "
                     f"({times[best]:.3f} ms; "
                     f"{np.sum(np.isfinite(times))}/{len(cands)} ok)")
            return fn(*args, **dict(kw, cfg=_CACHE[key]))

        wrapper._autotune_cache = _CACHE
        return wrapper

    return deco


__all__ = ["contextual_autotune"]
