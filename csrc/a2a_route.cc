// Host-native EP A2A routing preprocessing (analog of the reference's
// host/device token-routing helpers: per-warp atomic slot allocation in
// ep_a2a.py:64-147 and the csrc moe_utils alignment family). On TPU the
// in-jit path is the one-hot-cumsum `_slot_assign` in ops/all_to_all.py;
// this native version serves the host-side datapath (serving frontends /
// CPU dataloaders that pre-route tokens before device dispatch) and is
// cross-tested against the jnp implementation.
//
// Contract (identical to ops.all_to_all._slot_assign):
//   dc[r]   = dest[r] clipped into [0, n_dst) — out-of-range destinations
//             are NOT rejected; they route to the clipped edge rank
//             (callers that want them dropped pass valid[r]=0)
//   slot[r] = number of earlier valid rows with the same CLIPPED destination
//   ok[r]   = valid[r] && slot[r] < cap  (capacity drop; independent of
//             whether dest[r] was in range before clipping)
// Valid rows always bump the clipped destination's counter, matching the
// jnp one-hot-cumsum implementation exactly.

#include <cstdint>
#include <vector>

extern "C" {

// Returns 0 on success, nonzero on bad arguments.
int32_t tdt_a2a_slot_assign(const int32_t* dest, int64_t R, int32_t n_dst,
                            int32_t cap, const uint8_t* valid /*nullable*/,
                            int32_t* slot, uint8_t* ok) {
  if (R < 0 || n_dst <= 0 || cap < 0) return 1;
  std::vector<int64_t> counters(n_dst, 0);
  for (int64_t r = 0; r < R; ++r) {
    int32_t d = dest[r];
    int32_t dc = d < 0 ? 0 : (d >= n_dst ? n_dst - 1 : d);
    bool v = (valid == nullptr) || (valid[r] != 0);
    // jnp one-hot counts the CLIPPED destination for valid rows
    int64_t s = counters[dc];
    if (v) counters[dc]++;
    slot[r] = (int32_t)s;
    ok[r] = (v && s < cap) ? 1 : 0;
  }
  return 0;
}

// Per-destination token counts (the splits the reference ships on the wire,
// low_latency_all_to_all.py:35-118). Out-of-range destinations are dropped.
int32_t tdt_a2a_bincount(const int32_t* dest, int64_t R, int32_t n_dst,
                         int32_t* counts) {
  if (R < 0 || n_dst <= 0) return 1;
  for (int32_t i = 0; i < n_dst; ++i) counts[i] = 0;
  for (int64_t r = 0; r < R; ++r) {
    int32_t d = dest[r];
    if (d >= 0 && d < n_dst) counts[d]++;
  }
  return 0;
}

}  // extern "C"
