"""Grouped GEMM + MoE overlap op tests (parity targets: reference
test/nvidia/test_ag_moe.py, test_moe_reduce_rs.py — dense goldens)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.group_gemm import (align_tokens_by_expert,
                                            apply_grouped, grouped_gemm,
                                            grouped_gemm_gated,
                                            moe_ffn_local)
from triton_dist_tpu.ops.moe import ag_moe_group_gemm, moe_reduce_rs
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_grouped_gemm_dense_golden():
    E, H, N, bm = 4, 64, 128, 16
    T = 64
    ids = jax.random.randint(jax.random.key(0), (T,), 0, E)
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    weights = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32)
    gather_idx, row_valid, block_expert = align_tokens_by_expert(ids, E, bm)
    x = tokens[np.asarray(gather_idx)] * np.asarray(row_valid)[:, None]
    y = jax.jit(lambda x, w, be: grouped_gemm(x, w, be, block_m=bm, block_n=64))(
        x, weights, block_expert)
    # golden: each aligned row through its block's expert
    yn = np.asarray(y)
    be = np.asarray(block_expert)
    for blk in range(len(be)):
        rows = slice(blk * bm, (blk + 1) * bm)
        golden = np.asarray(x)[rows] @ np.asarray(weights)[be[blk]]
        assert_allclose(yn[rows], golden, atol=1e-3, rtol=1e-3)


def test_grouped_gemm_gated_matches_unfused():
    """The fused gate+up+act kernel == the two-launch composition it
    replaces, on both the static and runtime-bounded paths."""
    E, H, F, bm = 4, 64, 128, 16
    T = 56
    ids = jax.random.randint(jax.random.key(0), (T,), 0, E)
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    wg = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    gi, rv, be, nb = align_tokens_by_expert(ids, E, bm, with_used_count=True)
    x = tokens[np.asarray(gi)] * np.asarray(rv)[:, None]

    def unfused(x, wg, wu, be, nb):
        g = grouped_gemm(x, wg, be, block_m=bm, block_n=64,
                         n_blocks_used=nb)
        u = grouped_gemm(x, wu, be, block_m=bm, block_n=64,
                         n_blocks_used=nb)
        return jax.nn.silu(g) * u

    want = jax.jit(unfused)(x, wg, wu, be, nb)
    got_static = jax.jit(lambda *a: grouped_gemm_gated(
        *a, block_m=bm, block_n=64))(x, wg, wu, be)
    got_bounded = jax.jit(lambda *a, n=nb: grouped_gemm_gated(
        *a, block_m=bm, block_n=64, n_blocks_used=n))(x, wg, wu, be)
    valid = np.asarray(rv)[:, None]
    assert_allclose(np.asarray(got_bounded), np.asarray(want),
                    atol=1e-4, rtol=1e-4)
    # static path computes every block (padding included) — compare on
    # valid rows
    assert_allclose(np.asarray(got_static) * valid,
                    np.asarray(want) * valid, atol=1e-4, rtol=1e-4)


def test_grouped_gemm_gated_row_scale():
    """Quantized-wire rows: the per-row scale folded into both f32
    accumulators equals dequantize-then-compute."""
    E, H, F, bm = 2, 32, 64, 8
    P_rows = 4 * bm
    be = jnp.array([0, 1, 0, 1], jnp.int32)
    q = jax.random.randint(jax.random.key(0), (P_rows, H), -64, 64
                           ).astype(jnp.int8)
    scale = jax.random.uniform(jax.random.key(1), (P_rows,), jnp.float32,
                               0.01, 0.1)
    wg = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    got = jax.jit(lambda *a: grouped_gemm_gated(
        *a[:4], block_m=bm, block_n=64, row_scale=a[4],
        out_dtype=jnp.float32))(q, wg, wu, be, scale)
    xf = np.asarray(q, np.float32) * np.asarray(scale)[:, None]
    want = np.zeros((P_rows, F), np.float32)
    for blk in range(4):
        rows = slice(blk * bm, (blk + 1) * bm)
        g = xf[rows] @ np.asarray(wg)[be[blk]]
        u = xf[rows] @ np.asarray(wu)[be[blk]]
        want[rows] = g / (1 + np.exp(-g)) * u
    assert_allclose(np.asarray(got), want, atol=1e-3, rtol=1e-3)


def test_grouped_gemm_ksplit_matches():
    """block_k (K-split accumulation through the f32 VMEM scratch) matches
    the full-K strip path on both ops, row_scale included."""
    E, H, F, bm = 4, 128, 128, 16
    T = 56
    ids = jax.random.randint(jax.random.key(0), (T,), 0, E)
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    w = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    gi, rv, be, nb = align_tokens_by_expert(ids, E, bm, with_used_count=True)
    x = tokens[np.asarray(gi)] * np.asarray(rv)[:, None]
    scale = jax.random.uniform(jax.random.key(4), (x.shape[0],),
                               jnp.float32, 0.5, 1.5)

    full = jax.jit(lambda *a: grouped_gemm(
        *a[:3], block_m=bm, block_n=64, n_blocks_used=nb,
        row_scale=a[3]))(x, w, be, scale)
    split = jax.jit(lambda *a: grouped_gemm(
        *a[:3], block_m=bm, block_n=64, n_blocks_used=nb,
        row_scale=a[3], block_k=32))(x, w, be, scale)
    assert_allclose(np.asarray(split), np.asarray(full), atol=1e-4,
                    rtol=1e-4)

    full_g = jax.jit(lambda *a: grouped_gemm_gated(
        *a, block_m=bm, block_n=64, n_blocks_used=nb))(x, w, wu, be)
    split_g = jax.jit(lambda *a: grouped_gemm_gated(
        *a, block_m=bm, block_n=64, n_blocks_used=nb, block_k=32))(
        x, w, wu, be)
    assert_allclose(np.asarray(split_g), np.asarray(full_g), atol=1e-4,
                    rtol=1e-4)


@pytest.mark.quick
def test_gated_packed_matches():
    """packed=True (interleaved [g_j|u_j] single weight stream) matches
    the two-stream bounded path, with and without K-split/row_scale."""
    from triton_dist_tpu.ops.group_gemm import pack_gated_weights

    E, H, F, bm, bn = 4, 64, 128, 16, 32
    T = 56
    ids = jax.random.randint(jax.random.key(0), (T,), 0, E)
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    wg = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    gi, rv, be, nb = align_tokens_by_expert(ids, E, bm, with_used_count=True)
    x = tokens[np.asarray(gi)] * np.asarray(rv)[:, None]
    scale = jax.random.uniform(jax.random.key(4), (x.shape[0],),
                               jnp.float32, 0.5, 1.5)
    wgu = pack_gated_weights(wg, wu, block_n=bn)

    want = jax.jit(lambda *a: grouped_gemm_gated(
        *a[:4], block_m=bm, block_n=bn, n_blocks_used=nb,
        row_scale=a[4]))(x, wg, wu, be, scale)
    got = jax.jit(lambda *a: grouped_gemm_gated(
        a[0], a[1], None, a[2], block_m=bm, block_n=bn, n_blocks_used=nb,
        row_scale=a[3], packed=True))(x, wgu, be, scale)
    assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                    rtol=1e-4)
    got_ks = jax.jit(lambda *a: grouped_gemm_gated(
        a[0], a[1], None, a[2], block_m=bm, block_n=bn, n_blocks_used=nb,
        row_scale=a[3], packed=True, block_k=32))(x, wgu, be, scale)
    assert_allclose(np.asarray(got_ks), np.asarray(want), atol=1e-4,
                    rtol=1e-4)


def test_gated_quantized_convert_once():
    """Quantized-wire rows through the BOUNDED gated kernel with multiple
    n-steps (and with K-split): the per-m-step x-conversion scratch path
    must match the per-tile-convert unbounded path bit-for-bit-ish."""
    E, H, F, bm = 2, 64, 128, 8
    P_rows = 4 * bm
    be = jnp.array([0, 1, 0, 1], jnp.int32)
    nb = jnp.int32(4)
    q = jax.random.randint(jax.random.key(0), (P_rows, H), -64, 64
                           ).astype(jnp.int8)
    scale = jax.random.uniform(jax.random.key(1), (P_rows,), jnp.float32,
                               0.01, 0.1)
    wg = (jax.random.normal(jax.random.key(2), (E, H, F)) * 0.1
          ).astype(jnp.float32)
    wu = (jax.random.normal(jax.random.key(3), (E, H, F)) * 0.1
          ).astype(jnp.float32)
    want = jax.jit(lambda *a: grouped_gemm_gated(
        *a[:4], block_m=bm, block_n=32, row_scale=a[4],
        out_dtype=jnp.float32))(q, wg, wu, be, scale)
    got = jax.jit(lambda *a: grouped_gemm_gated(
        *a[:4], block_m=bm, block_n=32, row_scale=a[4],
        out_dtype=jnp.float32, n_blocks_used=nb))(q, wg, wu, be, scale)
    assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                    rtol=1e-4)
    got_ks = jax.jit(lambda *a: grouped_gemm_gated(
        *a[:4], block_m=bm, block_n=32, row_scale=a[4],
        out_dtype=jnp.float32, n_blocks_used=nb, block_k=32))(
        q, wg, wu, be, scale)
    assert_allclose(np.asarray(got_ks), np.asarray(want), atol=1e-4,
                    rtol=1e-4)


def test_apply_grouped_unmasked_ffn():
    """The masked=False fast path through apply_grouped (undefined rows
    past the bound are dropped by scatter index) matches moe_ffn_local's
    masked composition, invalid ids included."""
    E, H, F, bm = 4, 64, 128, 16
    T = 48
    ids = jax.random.randint(jax.random.key(0), (T,), -1, E)
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    wg = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wd = jax.random.normal(jax.random.key(3), (E, F, H), jnp.float32) * 0.1

    def ffn(x, be, nb):
        h = grouped_gemm_gated(x, wg, wg, be, block_m=bm, block_n=64,
                               n_blocks_used=nb, masked=False)
        return grouped_gemm(h, wd, be, block_m=bm, n_blocks_used=nb,
                            masked=False)

    got = jax.jit(lambda t, i: apply_grouped(t, i, E, ffn, block_m=bm))(
        tokens, ids)
    t, idn = np.asarray(tokens), np.asarray(ids)
    golden = np.zeros_like(t)
    for r in range(T):
        if idn[r] >= 0:
            g = t[r] @ np.asarray(wg)[idn[r]]
            h = g / (1 + np.exp(-g)) * g
            golden[r] = h @ np.asarray(wd)[idn[r]]
    assert_allclose(np.asarray(got), golden, atol=1e-3, rtol=1e-3)


def test_moe_ffn_local_golden():
    E, H, F, bm = 4, 64, 128, 16
    T = 48
    ids = jax.random.randint(jax.random.key(0), (T,), -1, E)  # some invalid
    tokens = jax.random.normal(jax.random.key(1), (T, H), jnp.float32)
    w_up = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    w_down = jax.random.normal(jax.random.key(3), (E, F, H), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, wu, wd: moe_ffn_local(t, i, wu, wd, block_m=bm))(
        tokens, ids, w_up, w_down)
    t, idn = np.asarray(tokens), np.asarray(ids)
    golden = np.zeros_like(t)
    for r in range(T):
        if idn[r] >= 0:
            h = t[r] @ np.asarray(w_up)[idn[r]]
            h = h / (1 + np.exp(-h))  # silu
            golden[r] = h @ np.asarray(w_down)[idn[r]]
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)


def test_ag_moe_group_gemm(ctx):
    n = ctx.num_ranks
    E, H, N, T = 4, 64, n * 64, n * 32
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    weights = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, w: ag_moe_group_gemm(
        ctx, ctx.shard(t, P("x")), ctx.shard(i, P("x")),
        ctx.shard(w, P(None, None, "x")), block_m=32))(tokens, ids, weights)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(weights)
    golden = np.stack([t[r] @ wn[idn[r]] for r in range(T)])
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)


def test_moe_reduce_rs_ragged_n(ctx):
    """N=192 is not a multiple of the 128-lane tile — the reduction and the
    grouped pipeline must fall back to a divisor, not drop columns."""
    n = ctx.num_ranks
    E, K, N, T, topk = 4, n * 32, 192, n * 8, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    weights = jax.random.normal(jax.random.key(3), (E, K, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, w, ww: moe_reduce_rs(
        ctx, ctx.shard(t, P(None, "x")), i, ww,
        ctx.shard(w, P(None, "x", None)), block_m=16))(tokens, ids, weights, tw)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(weights)
    rows = np.stack([t[r] @ wn[idn[r]] for r in range(T * topk)])
    golden = (rows.reshape(T, topk, N) * np.asarray(tw)[..., None]).sum(axis=1)
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)


def test_moe_reduce_rs(ctx):
    n = ctx.num_ranks
    E, K, N, T, topk = 4, n * 32, 64, n * 8, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    weights = jax.random.normal(jax.random.key(3), (E, K, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, w, ww: moe_reduce_rs(
        ctx, ctx.shard(t, P(None, "x")), i, ww,
        ctx.shard(w, P(None, "x", None)), block_m=16))(tokens, ids, weights, tw)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(weights)
    twn = np.asarray(tw)
    rows = np.stack([t[r] @ wn[idn[r]] for r in range(T * topk)])
    golden = (rows.reshape(T, topk, N) * twn[..., None]).sum(axis=1)
    assert_allclose(np.asarray(out), golden, atol=1e-3, rtol=1e-3)


def test_gated_packed_prefetch_depths():
    """The deep weight-stream DMA ring (prefetch_depth >= 2) must be
    bit-identical to the emit_pipeline weight stream it replaces
    (prefetch_depth=1 falls back) at every depth, with and without
    K-split — the ring only changes WHEN weight tiles are fetched, never
    what is computed."""
    from triton_dist_tpu.ops.group_gemm import pack_gated_weights

    E, H, F, bm, bn = 4, 64, 128, 16, 32
    ids = jax.random.randint(jax.random.key(0), (56,), 0, E)
    tokens = jax.random.normal(jax.random.key(1), (56, H), jnp.float32)
    wg = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (E, H, F), jnp.float32) * 0.1
    gi, rv, be, nb = align_tokens_by_expert(ids, E, bm, with_used_count=True)
    x = tokens[np.asarray(gi)] * np.asarray(rv)[:, None]
    wgu = pack_gated_weights(wg, wu, block_n=bn)

    ref = np.asarray(jax.jit(lambda *a: grouped_gemm_gated(
        a[0], a[1], None, a[2], block_m=bm, block_n=bn, n_blocks_used=nb,
        packed=True, prefetch_depth=1))(x, wgu, be))
    for depth in (2, 3):
        got = np.asarray(jax.jit(lambda *a, d=depth: grouped_gemm_gated(
            a[0], a[1], None, a[2], block_m=bm, block_n=bn,
            n_blocks_used=nb, packed=True, prefetch_depth=d))(x, wgu, be))
        np.testing.assert_array_equal(got, ref)
        got_ks = np.asarray(jax.jit(lambda *a, d=depth: grouped_gemm_gated(
            a[0], a[1], None, a[2], block_m=bm, block_n=bn,
            n_blocks_used=nb, packed=True, prefetch_depth=d,
            block_k=32))(x, wgu, be))
        ref_ks = np.asarray(jax.jit(lambda *a: grouped_gemm_gated(
            a[0], a[1], None, a[2], block_m=bm, block_n=bn,
            n_blocks_used=nb, packed=True, prefetch_depth=1,
            block_k=32))(x, wgu, be))
        np.testing.assert_array_equal(got_ks, ref_ks)


def test_packed_gated_weights_wrapper_contract():
    """PackedGatedWeights carries the pack width in the type: the kernel
    accepts a matching wrapper and REJECTS a mismatched one (a bare array
    only gets the divisibility check — the reason the wrapper exists)."""
    from triton_dist_tpu.ops.group_gemm import (PackedGatedWeights,
                                                pack_gated_weights)

    E, H, F, bm, bn = 2, 64, 128, 16, 32
    x = jax.random.normal(jax.random.key(0), (2 * bm, H), jnp.float32)
    wg = jax.random.normal(jax.random.key(1), (E, H, F), jnp.float32) * 0.1
    wu = jax.random.normal(jax.random.key(2), (E, H, F), jnp.float32) * 0.1
    be = jnp.zeros((2,), jnp.int32)
    nb = jnp.int32(2)
    wgu = pack_gated_weights(wg, wu, block_n=bn)
    assert isinstance(wgu, PackedGatedWeights) and wgu.block_n == bn
    # pytree roundtrip keeps the pack width (static aux data under jit)
    leaves, tree = jax.tree_util.tree_flatten(wgu)
    assert jax.tree_util.tree_unflatten(tree, leaves).block_n == bn

    ok = grouped_gemm_gated(x, wgu, None, be, block_m=bm, block_n=bn,
                            n_blocks_used=nb, packed=True)
    assert ok.shape == (2 * bm, F)
    with pytest.raises(AssertionError, match="block_n"):
        grouped_gemm_gated(x, wgu, None, be, block_m=bm, block_n=64,
                           n_blocks_used=nb, packed=True)


def test_moe_ep_overlap_expert_major(ctx):
    """The expert-major serving block: recv blocks arrive expert-segmented,
    so moe_mlp_ep_overlap takes the static block→expert fast path (no
    align gather / inverse scatter) — and must match the rank-major
    align path, with the packed weight stream and on the int8 wire."""
    from triton_dist_tpu.layers import EPAll2AllLayer
    from triton_dist_tpu.models.moe import moe_mlp_ep_overlap
    from triton_dist_tpu.ops.group_gemm import pack_gated_weights

    n = ctx.num_ranks
    T_local, D, F, E, k = 16, 128, 128, 2 * n, 2
    T = n * T_local
    x = (jax.random.normal(jax.random.key(0), (T, D), jnp.float32) * 0.3
         ).astype(jnp.bfloat16)
    router_w = jax.random.normal(jax.random.key(1), (D, E), jnp.float32) * 0.3
    wg = (jax.random.normal(jax.random.key(2), (E, D, F)) * 0.1
          ).astype(jnp.bfloat16)
    wu = (jax.random.normal(jax.random.key(3), (E, D, F)) * 0.1
          ).astype(jnp.bfloat16)
    wd = (jax.random.normal(jax.random.key(4), (E, F, D)) * 0.1
          ).astype(jnp.bfloat16)
    xs = ctx.shard(x, P("x"))

    outs = {}
    for em in (False, True):
        layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D,
                                      topk=k, num_experts=E, axis="x",
                                      expert_major=em)
        outs[em] = np.asarray(jax.jit(lambda v, l=layer: moe_mlp_ep_overlap(
            ctx, l, v, router_w, wg, wu, wd, axis="x", block_m=16))(xs),
            np.float32)
    assert_allclose(outs[True], outs[False], atol=1e-5, rtol=1e-5)

    # packed double-width weight stream on the fast path
    layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D, topk=k,
                                  num_experts=E, axis="x", expert_major=True)
    wgu = pack_gated_weights(wg, wu, block_n=64)
    got_p = np.asarray(jax.jit(lambda v: moe_mlp_ep_overlap(
        ctx, layer, v, router_w, wg, wu, wd, axis="x", block_m=16,
        block_n=64, we_gate_up_packed=wgu))(xs), np.float32)
    assert_allclose(got_p, outs[True], atol=2e-2, rtol=2e-2)

    # int8 wire, both dequant edges, still on the fast path
    for de in ("expert", "post"):
        layer = EPAll2AllLayer.create(ctx, max_tokens=T_local, hidden=D,
                                      topk=k, num_experts=E, axis="x",
                                      wire_dtype=jnp.int8, dequant_edge=de,
                                      expert_major=True)
        o = np.asarray(jax.jit(lambda v, l=layer: moe_mlp_ep_overlap(
            ctx, l, v, router_w, wg, wu, wd, axis="x", block_m=16))(xs),
            np.float32)
        assert_allclose(o, outs[True], atol=6e-2, rtol=6e-2)
