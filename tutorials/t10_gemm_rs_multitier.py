"""Tutorial 10 — hierarchical (multi-tier) GEMM-ReduceScatter.

Analog of reference tutorials/06 + 08's inter-node tier (the 2-D RS
pipeline, reduce_scatter.py:430-785). Stage 1 fuses the producer GEMM into
a fast-tier (inner-axis) reduce-scatter whose segments are strided in
outer-major block order; stage 2 ring-reduces the surviving chunk along the
slow outer axis — every row crosses the slow tier exactly once, already
reduced over the fast tier (see ops.gemm_reduce_scatter._gemm_rs_2d).

Run:  python -m tutorials.t10_gemm_rs_multitier [--sim 6]
      [--case correctness|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context_2d)


def _shapes(ctx, M=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    axes = ("node", "x")
    M = M or 128 * n
    K, N = 128 * n, 128
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)
    return a, b, ctx.shard(a, P(None, axes)), ctx.shard(b, P(axes, None))


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import gemm_rs
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context_2d()
    a, b, a_s, b_s = _shapes(ctx)
    cfg = GemmConfig(128, 128)
    c = jax.jit(lambda u, v: gemm_rs(ctx, u, v, axis=("node", "x"),
                                     cfg=cfg, out_dtype=jnp.float32)
                )(a_s, b_s)
    gold = a.astype(jnp.float32) @ b.astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(c, np.float32), gold, rtol=5e-2,
                               atol=5e-1)
    no, ni = ctx.axis_size("node"), ctx.axis_size("x")
    print(f"2-tier GEMM-RS over ({no} nodes x {ni} PEs) == "
          "dot+psum_scatter golden")


@register_case("perf")
def perf():
    import jax
    import jax.numpy as jnp

    from triton_dist_tpu.ops import gemm_rs
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context_2d()
    n = ctx.num_ranks
    _, _, a_s, b_s = _shapes(ctx, M=256 * n)
    cfg = GemmConfig(128, 128)
    f = jax.jit(lambda u, v: gemm_rs(ctx, u, v, axis=("node", "x"),
                                     cfg=cfg, out_dtype=jnp.bfloat16))
    s = time_op(lambda: f(a_s, b_s))
    M, K = a_s.shape
    N = b_s.shape[1]
    perf_report("gemm_rs_2d", s,
                f"~{2 * M * N * K / s / max(n, 1) / 1e12:.1f} TFLOP/s/chip "
                "(wall-clock; see bench.py for tunnel-corrected numbers)")


if __name__ == "__main__":
    tutorial_main(__doc__)
