"""Synthetic-trace replay through the continuous-batching serving engine
(docs/serving.md). Generates a deterministic request trace (seeded prompt
lengths / decode budgets / staggered arrivals), drives ``ServingEngine``
to completion, and prints the metrics snapshot as ONE JSON line — the same
counters/histograms bench.py's ``serving_*`` extras are built from, with
matching knobs (--slots/--page-size/--layers mirror bench_serving's).

    python scripts/serve_sim.py --sim 50
    python scripts/serve_sim.py --sim 20 --slots 8 --pages 12  # preempts
    python scripts/serve_sim.py --sim 20 --model moe --mesh 1x2x2

A deliberately small --pages forces preemption-by-eviction; the replay is
bit-deterministic (same seed => same tokens, same metrics counters), which
is also how tests/test_serving.py pins the trace down. ``--mesh TPxSPxEP``
serves the MoE model through ``ShardedServingEngine`` under shard_map
(docs/serving.md "Sharded serving"); the replay stays bit-identical across
mesh shapes when --wire is pinned (``auto`` resolves per rank count).
"""
import argparse
import json
import sys

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

import jax  # noqa: E402
import numpy as np  # noqa: E402

from triton_dist_tpu.models.llama import LlamaConfig, init_params  # noqa: E402
from triton_dist_tpu.serving import ServingEngine  # noqa: E402

p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
p.add_argument("--sim", type=int, default=50,
               help="number of synthetic requests to replay")
p.add_argument("--slots", type=int, default=4,
               help="continuous-batching slots (engine batch rows)")
p.add_argument("--page-size", type=int, default=8,
               help="KV pool page size in tokens (multiple of 8)")
p.add_argument("--pages", type=int, default=24,
               help="usable KV pool pages (small => forced preemption)")
p.add_argument("--pages-per-seq", type=int, default=8,
               help="block-table width (max pages one request may own)")
p.add_argument("--layers", type=int, default=2, help="model layers")
p.add_argument("--max-new", type=int, default=12,
               help="max decode budget per request (uniform 2..max-new)")
p.add_argument("--arrive-every", type=int, default=2,
               help="one new request submitted every N engine steps")
p.add_argument("--seed", type=int, default=0, help="trace RNG seed")
p.add_argument("--tokens", action="store_true",
               help="also print one JSON line per finished request")
p.add_argument("--decode-horizon", type=int, default=1,
               help="K: scanned decode steps per host dispatch")
p.add_argument("--prefill-buckets", default="pow2",
               help='"pow2" (default), "exact", or a comma-separated '
                    "ascending list of bucket lengths, e.g. 8,16,32")
p.add_argument("--prefill-chunk", type=int, default=None,
               help="chunked paged prefill: tokens per co-scheduled chunk "
                    "(≤1 chunk per step rides beside the decode dispatch; "
                    "omit for the bucketed inline prefill path)")
p.add_argument("--disagg", action="store_true",
               help="disaggregated prefill/decode over a 2-rank role mesh "
                    "(KV handed off by page migration; needs >= 2 devices; "
                    "--prefill-chunk defaults to 2*page_size here — chunks "
                    "ARE the migration unit)")
p.add_argument("--model", choices=("llama", "moe"), default="llama",
               help="'moe' serves MoEConfig.tiny through the sharded "
                    "engine (EP MoE FFN; defaults --mesh to 1x1x1)")
p.add_argument("--mesh", default=None, metavar="TPxSPxEP",
               help="serve under shard_map on this TP/SP/EP mesh, e.g. "
                    "2x2x2 (implies --model moe; spins up tp*sp*ep "
                    "virtual CPU devices when hardware has fewer; "
                    "--prefill-chunk defaults to 8 — the sharded engine "
                    "REQUIRES the chunked path)")
p.add_argument("--wire", choices=("auto", "fp8", "none"), default="auto",
               help="A2A wire dtype for --mesh: 'auto' (wire-fit driven, "
                    "resolves PER RANK COUNT), 'fp8' (pinned e4m3 — use "
                    "this when comparing tokens across mesh shapes), "
                    "'none' (full-width wire)")
p.add_argument("--chaos", default=None, metavar="SPEC",
               help="seeded fault injection on the migration signal plane "
                    "(implies --disagg): a bare integer seed (default "
                    "drop/delay probabilities) or a FaultPlan spec like "
                    "'seed=3,drop=0.2,dup=0.05,delay=0.3,dead=40,"
                    "rids=1|4|7'. Replays are bit-deterministic per spec; "
                    "a chaos summary line (retries / degradations / "
                    "failures / recovery latencies) is printed to stderr")
args = p.parse_args()
if args.chaos is not None:
    args.disagg = True
if args.mesh is not None:
    args.model = "moe"
elif args.model == "moe":
    args.mesh = "1x1x1"
if args.mesh is not None and args.disagg:
    # the SP-sharded pool owns page placement; disaggregation's page
    # migration is a different (single-axis) pool contract — refused,
    # see docs/serving.md "Sharded serving"
    p.error("--mesh and --disagg are mutually exclusive")

if args.prefill_buckets == "pow2":
    buckets = "pow2"
elif args.prefill_buckets == "exact":
    buckets = None
else:
    buckets = tuple(int(b) for b in args.prefill_buckets.split(","))

if args.disagg:
    # the role mesh needs 2 ranks; on fewer (e.g. plain-CPU jax) fall
    # back to the 2-device virtual CPU simulator — real chips are kept
    from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402
    force_virtual_cpu_devices(2)
elif args.mesh is not None:
    tp, sp, ep = (int(d) for d in args.mesh.lower().split("x"))
    from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402
    force_virtual_cpu_devices(tp * sp * ep)

if args.model == "moe":
    from triton_dist_tpu.models.moe import MoEConfig, init_moe_params  # noqa: E402
    cfg = MoEConfig.tiny(n_layers=args.layers)
    params = init_moe_params(jax.random.PRNGKey(args.seed), cfg)
    vocab = cfg.base.vocab_size
else:
    cfg = LlamaConfig.tiny(n_layers=args.layers)
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    vocab = cfg.vocab_size
if args.mesh is not None:
    import jax.numpy as jnp  # noqa: E402

    from triton_dist_tpu.serving import ShardedServingEngine, serving_mesh  # noqa: E402
    wire = {"auto": "auto", "fp8": jnp.float8_e4m3fn, "none": None}[args.wire]
    eng = ShardedServingEngine(params, cfg, serving_mesh(tp, sp, ep),
                               num_slots=args.slots,
                               page_size=args.page_size,
                               num_pages=args.pages,
                               pages_per_seq=args.pages_per_seq,
                               decode_horizon=args.decode_horizon,
                               prefill_chunk=args.prefill_chunk or 8,
                               wire_dtype=wire)
    print(json.dumps({"mesh": eng.mesh_desc, "wire": eng.wire_dtype}),
          file=sys.stderr)
elif args.disagg:
    from triton_dist_tpu.serving import DisaggServingEngine  # noqa: E402
    from triton_dist_tpu.shmem import FaultPlan  # noqa: E402
    plan = FaultPlan.from_spec(args.chaos) if args.chaos else None
    chunk = args.prefill_chunk or 2 * args.page_size
    eng = DisaggServingEngine(params, cfg, num_slots=args.slots,
                              page_size=args.page_size,
                              num_pages=args.pages,
                              pages_per_seq=args.pages_per_seq,
                              decode_horizon=args.decode_horizon,
                              prefill_chunk=chunk,
                              fault_plan=plan)
    if plan is not None:
        print(json.dumps({"chaos": plan.describe()}), file=sys.stderr)
else:
    eng = ServingEngine(params, cfg, num_slots=args.slots,
                        page_size=args.page_size, num_pages=args.pages,
                        pages_per_seq=args.pages_per_seq,
                        decode_horizon=args.decode_horizon,
                        prefill_buckets=buckets,
                        prefill_chunk=args.prefill_chunk)

rng = np.random.RandomState(args.seed)
max_plen = min(args.pages_per_seq * args.page_size - args.max_new, 24)
arrivals = []
for i in range(args.sim):
    plen = int(rng.randint(3, max(4, max_plen)))
    mnt = int(rng.randint(2, max(3, args.max_new + 1)))
    prompt = rng.randint(1, vocab, size=plen).tolist()
    arrivals.append((i * args.arrive_every // max(args.arrive_every, 1),
                     prompt, mnt))

results = eng.run(max_steps=200_000, arrivals=arrivals)
# run() returns FINISHED requests only. Under --chaos a request may
# instead have FAILED (typed, per-request — the ladder ran dry); those
# are accounted for, not "unfinished". Anything else absent ran out of
# steps — a real error.
failed = {r.rid: r for r in getattr(eng, "failed", [])}
unfinished = sorted(set(range(args.sim)) - set(results) - set(failed))
if unfinished:
    print(json.dumps({"error": "unfinished requests", "rids": unfinished}),
          file=sys.stderr)
    sys.exit(1)
for rid in sorted(failed):
    print(json.dumps({"failed_rid": rid,
                      "reason": type(failed[rid].failure).__name__,
                      "detail": str(failed[rid].failure)}), file=sys.stderr)

if args.tokens:
    for req in sorted(eng._finished, key=lambda r: r.rid):
        print(json.dumps({
            "rid": req.rid, "prompt_len": len(req.prompt),
            "tokens": list(req.generated),
            "preemptions": req.preemptions,
            "ttft_steps": req.first_token_step - req.submit_step,
        }))
print(json.dumps({"compile_stats": eng.compile_stats}), file=sys.stderr)

# prefill-stall / TTFT-split summary: the numbers chunked prefill moves
# (per-step decode stall bound, queue-vs-prefill TTFT split)
snap = eng.metrics.snapshot()
us = lambda v: None if v is None else round(v * 1e6, 1)
if args.disagg:
    # two panels: TTFT lives on the prefill worker, ITL/stall on the
    # decode worker — whose decode stall carries ZERO prefill work (the
    # step_prefill_tokens_max field is the proof, not a wall clock)
    snap_d = eng.metrics_decode.snapshot()
    print(json.dumps({
        "disagg": True,
        "prefill_chunks": snap["prefill_chunks"],
        "pages_migrated": snap["pages_migrated"],
        "migrate_us": {k: us(snap["migrate_s"][k])
                       for k in ("mean", "p99", "max")},
        "migrate_wait_steps_max": snap_d["migrate_wait_steps"]["max"],
        "decode_stall_us": {k: us(snap_d["decode_stall_s"][k])
                            for k in ("mean", "p50", "p99", "max")},
        "decode_step_prefill_tokens_max":
            snap_d["step_prefill_tokens"]["max"],
        "itl_us": {k: us(snap_d["tok_latency_s"][k])
                   for k in ("mean", "p99")},
        "ttft_queue_us": {k: us(snap["ttft_queue_s"][k])
                          for k in ("mean", "p99")},
        "ttft_prefill_us": {k: us(snap["ttft_prefill_s"][k])
                            for k in ("mean", "p99")},
    }), file=sys.stderr)
    if args.chaos is not None:
        # the chaos summary: what the ladder absorbed and what it cost
        print(json.dumps({
            "chaos_summary": True,
            "faults_injected": snap["faults_injected"],
            "stale_signals": snap["stale_signals"],
            "retries": snap_d["retries"],
            "degradations": snap_d["degradations"],
            "failed_requests": snap_d["failed_requests"],
            "recovered_ttft_us": {k: us(snap_d["recovered_ttft_s"][k])
                                  for k in ("mean", "p99")},
            "degraded_ttft_us": {k: us(snap_d["degraded_ttft_s"][k])
                                 for k in ("mean", "p99")},
        }), file=sys.stderr)
    eng.metrics.emit()
    eng.metrics_decode.emit()
else:
    if args.mesh is not None:
        # the replicated-decision guard's coverage for this replay
        print(json.dumps({"digest_checks": snap["digest_checks"]}),
              file=sys.stderr)
    print(json.dumps({
        "prefill_chunk": args.prefill_chunk,
        "prefill_chunks": snap["prefill_chunks"],
        "prefill_stall_us": {k: us(snap["prefill_stall_s"][k])
                             for k in ("mean", "p50", "p99", "max")},
        "decode_stall_us": {k: us(snap["decode_stall_s"][k])
                            for k in ("mean", "p50", "p99", "max")},
        "step_prefill_tokens_max": snap["step_prefill_tokens"]["max"],
        "ttft_queue_us": {k: us(snap["ttft_queue_s"][k])
                          for k in ("mean", "p99")},
        "ttft_prefill_us": {k: us(snap["ttft_prefill_s"][k])
                            for k in ("mean", "p99")},
    }), file=sys.stderr)
    eng.metrics.emit()
