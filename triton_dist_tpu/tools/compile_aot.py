"""Build a persisted AOT serving artifact from an ``ArtifactSpec`` JSON
(the parity target for the reference's ``tools/compile_aot.py`` AOT kernel
sweep — here the unit is not a kernel list but the full compiled-program
set of a declared serving fleet; see docs/serving.md "Zero-trace cold
start").

Usage::

    python -m triton_dist_tpu.tools.compile_aot --spec spec.json \
        --out /path/to/artifact [--registry tuned.json] [--devices N]

    # no --spec: build the built-in tiny smoke spec (CPU CI round trip)
    python -m triton_dist_tpu.tools.compile_aot --out /tmp/artifact --tiny

The build pays every fresh trace so no replica cold start ever does; the
resulting directory is what ``serve_sim.py --artifact`` /
``cluster_sim.py --artifact`` and ``ServingEngine(artifact=...)`` load.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

TINY_SPEC = {
    "model": {"kind": "llama", "vocab_size": 128, "d_model": 32,
              "n_layers": 1, "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
              "max_seq_len": 64, "dtype": "float32"},
    "engines": [{"kind": "colocated", "num_slots": 2, "page_size": 8,
                 "num_pages": 32, "pages_per_seq": 8, "prefill_chunk": 8}],
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="AOT-compile a serving fleet's full program set into a "
                    "persisted artifact directory")
    ap.add_argument("--spec", help="ArtifactSpec JSON file")
    ap.add_argument("--tiny", action="store_true",
                    help="use the built-in tiny colocated smoke spec")
    ap.add_argument("--out", required=True, help="artifact output directory")
    ap.add_argument("--registry",
                    help="tuned-config registry JSON to embed (the file "
                         "tools/tune_serving.py writes)")
    ap.add_argument("--devices", type=int, default=0,
                    help="force N virtual CPU devices before compiling "
                         "(0 = leave the backend alone)")
    args = ap.parse_args(argv)

    if args.devices:
        from triton_dist_tpu.utils.env import force_virtual_cpu_devices
        force_virtual_cpu_devices(args.devices, skip_if_satisfied=True)

    if args.spec:
        with open(args.spec, encoding="utf-8") as f:
            spec_doc = json.load(f)
    elif args.tiny:
        spec_doc = TINY_SPEC
    else:
        ap.error("pass --spec FILE or --tiny")

    from triton_dist_tpu.aot import (ArtifactSpec, TunedConfigRegistry,
                                     build_artifact)
    spec = ArtifactSpec.from_json(spec_doc)
    registry = (TunedConfigRegistry.load(args.registry)
                if args.registry else None)

    t0 = time.time()
    build_artifact(spec, args.out, registry=registry,
                   log=lambda s: print(s, file=sys.stderr))
    dt = time.time() - t0

    with open(os.path.join(args.out, "MANIFEST.json"),
              encoding="utf-8") as f:
        manifest = json.load(f)
    n_prog = sum(len(v) for v in manifest["programs"].values())
    print(json.dumps({
        "out": args.out,
        "spec_digest": manifest["spec_digest"],
        "engines": sorted(manifest["programs"].keys()),
        "programs": n_prog,
        "registry_entries": len(registry) if registry else 0,
        "build_s": round(dt, 3),
    }, indent=1))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
