"""Persisted tuned-config registry (analog of the reference's autotune
cache file + ML-Triton's multi-level AOT workflow, PAPERS.md).

``contextual_autotune`` reaches cross-rank consensus on a winner but the
result dies with the process; this registry is the surviving half: winners
are recorded under a ``(op, mesh_shape, dtype, shape_bucket)`` key, JSON-
serialized next to the AOT artifact (aot/artifact.py), and read back by the
autotuned op wrappers as the first candidate on the next cold start.

Admission is **sigcheck-gated**: a tuned config only enters the registry if
its kernel passes the static signal-protocol verifier at the target mesh
sizes (``analysis.api.sigcheck`` — trace-only, no device execution). A
config whose kernel sigcheck flags is refused with a typed
:class:`RegistryAdmissionError` carrying the findings; it never becomes a
persisted default someone else's replica deploys with.

On-disk integrity follows the PR 13 snapshot-audit idiom: the file carries
an FNV-1a digest over the canonical entry encoding, recomputed on load —
a torn or tampered registry raises :class:`RegistryIntegrityError` instead
of silently feeding a corrupted config into the serving path.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

# -- FNV-1a over the canonical JSON encoding (same digest family as the
# pool/scheduler digests in serving/kv_pool.py and the checkpoint audit) ----

_FNV_OFF = 0x811C9DC5
_FNV_PRIME = 0x01000193


def _fnv1a_bytes(data: bytes, h: int = _FNV_OFF) -> int:
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFF
    return h


class RegistryIntegrityError(RuntimeError):
    """The persisted registry's digest does not match its entries — the
    file is torn or tampered. Never served from."""


class RegistryAdmissionError(RuntimeError):
    """A tuned config was refused registry entry: its kernel failed the
    sigcheck admission gate (or no gate runner exists for the op and the
    registry requires one). Carries the verifier findings."""

    def __init__(self, msg: str, op: str = "", findings=()):
        super().__init__(msg)
        self.op = op
        self.findings = list(findings)

    @property
    def finding_kinds(self) -> list:
        return [getattr(f, "kind", str(f)) for f in self.findings]


# -- keys --------------------------------------------------------------------

def shape_bucket_of(*shapes) -> Tuple[Tuple[int, ...], ...]:
    """Pow2-bucket each dim of each shape — the registry's shape key. Two
    problem sizes in the same bucket share a tuned config (the autotuner's
    exact-shape cache still disambiguates within a process)."""
    def b1(d):
        d = int(d)
        if d <= 1:
            return d
        p = 1
        while p < d:
            p *= 2
        return p
    return tuple(tuple(b1(d) for d in s) for s in shapes)


@dataclasses.dataclass(frozen=True)
class TunedKey:
    """Registry key: the op name, the mesh shape the winner was tuned on
    (``()`` for single-device ops), the payload dtype, and the pow2 shape
    bucket of the array operands."""

    op: str
    mesh_shape: Tuple[int, ...] = ()
    dtype: str = "float32"
    shape_bucket: Tuple = ()

    def to_json(self) -> dict:
        return {"op": self.op, "mesh_shape": list(self.mesh_shape),
                "dtype": self.dtype,
                "shape_bucket": [list(s) for s in self.shape_bucket]}

    @classmethod
    def from_json(cls, d: dict) -> "TunedKey":
        return cls(op=d["op"], mesh_shape=tuple(d["mesh_shape"]),
                   dtype=d["dtype"],
                   shape_bucket=tuple(tuple(s) for s in d["shape_bucket"]))


# -- config codec ------------------------------------------------------------
# GemmConfig and the scalar/tuple cfg forms the autotuned wrappers use all
# round-trip through a tagged JSON encoding; anything else is refused
# loudly rather than pickled.

def _encode_config(cfg: Any) -> dict:
    from triton_dist_tpu.ops.gemm import GemmConfig
    if isinstance(cfg, GemmConfig):
        return {"kind": "GemmConfig", "block_m": cfg.block_m,
                "block_n": cfg.block_n, "block_k": cfg.block_k}
    if isinstance(cfg, bool):
        raise TypeError(f"unsupported tuned-config type: {type(cfg)}")
    if isinstance(cfg, int):
        return {"kind": "int", "value": cfg}
    if isinstance(cfg, str):
        return {"kind": "str", "value": cfg}
    if isinstance(cfg, (tuple, list)) and all(
            isinstance(v, int) for v in cfg):
        return {"kind": "ints", "value": list(cfg)}
    raise TypeError(f"unsupported tuned-config type: {type(cfg)!r} "
                    f"({cfg!r}) — add a codec in aot/registry.py")


def _decode_config(d: dict) -> Any:
    kind = d["kind"]
    if kind == "GemmConfig":
        from triton_dist_tpu.ops.gemm import GemmConfig
        return GemmConfig(d["block_m"], d["block_n"], d["block_k"])
    if kind == "int":
        return d["value"]
    if kind == "str":
        return d["value"]
    if kind == "ints":
        return tuple(d["value"])
    raise RegistryIntegrityError(
        f"unknown tuned-config kind {kind!r} in persisted registry")


# -- sigcheck gate runners ---------------------------------------------------
# Per-op factories building a ``run(ctx)`` the verifier can capture WITH the
# candidate config applied. Shapes are derived from the config so the tile
# asserts hold at every capture rank count (the idiom of
# analysis/registry.py, which instantiates each op at fixed tiny configs).

def _gate_ag_gemm(cfg) -> Callable:
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import ag_gemm
        n = ctx.num_ranks
        k = cfg.block_k or 128
        a = jnp.zeros((cfg.block_m * n, k), jnp.float32)
        b = jnp.zeros((k, cfg.block_n * n), jnp.float32)
        ag_gemm(ctx, a, b, axis="x", cfg=cfg)
    return run


def _gate_gemm_rs(cfg) -> Callable:
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import gemm_rs
        n = ctx.num_ranks
        k = cfg.block_k or 128
        a = jnp.zeros((cfg.block_m * n, k * n), jnp.float32)
        b = jnp.zeros((k * n, cfg.block_n), jnp.float32)
        gemm_rs(ctx, a, b, axis="x", cfg=cfg)
    return run


def _gate_ag_moe_group_gemm(block_m) -> Callable:
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import ag_moe_group_gemm
        n = ctx.num_ranks
        t = max(8, int(block_m))
        tokens = jnp.zeros((t * n, 128), jnp.float32)
        ids = jnp.zeros((t * n,), jnp.int32)
        weights = jnp.zeros((2, 128, 16 * n), jnp.float32)
        ag_moe_group_gemm(ctx, tokens, ids, weights, axis="x",
                          block_m=int(block_m), block_n=16)
    return run


def _gate_moe_reduce_rs(block_m) -> Callable:
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import moe_reduce_rs
        n = ctx.num_ranks
        topk = 2
        t = max(4 * n, int(block_m))
        tokens = jnp.zeros((t * topk, 128 * n), jnp.float32)
        ids = jnp.zeros((t * topk,), jnp.int32)
        moe_reduce_rs(ctx, tokens, ids, jnp.ones((t, topk), jnp.float32),
                      jnp.zeros((2, 128 * n, 16), jnp.float32), axis="x",
                      block_m=int(block_m))
    return run


def _gate_ring_attention(bqbk) -> Callable:
    # the A2A/ring signal protocol is tile-size-independent (the analysis
    # registry skips the autotuned wrappers for exactly this reason), so
    # the gate captures at the protocol-representative 128 tile — the
    # candidate's (bq, bk) only sizes on-chip blocks, never the DMA plan
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import ring_attention
        n = ctx.num_ranks
        q = jnp.zeros((1, 2, n * 128, 128), jnp.float32)
        kv = jnp.zeros((1, 2, n * 128, 128), jnp.float32)
        ring_attention(ctx, q, kv, kv, axis="x", block_q=128, block_k=128)
    return run


def _gate_overlap_microbatch(m) -> Callable:
    # the serving-overlap microbatch depth (ISSUE 16): the tuned value is
    # how many segmented a2a rounds the hot loop issues back to back, so
    # the gate replays exactly that many all_to_all_push_seg calls — the
    # counted per-segment signal protocol must stay balanced ACROSS rounds
    # (a leaked segment signal from round i poisons round i+1's gate)
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import all_to_all_push_seg
        n = ctx.num_ranks
        for _ in range(max(1, int(m))):
            all_to_all_push_seg(ctx, jnp.zeros((n * n, 16, 128),
                                               jnp.float32),
                                axis="x", segments=2)
    return run


def _gate_spec_k(k) -> Callable:
    # the speculative draft length (ISSUE 20): K multiplies the decode
    # program's row count — every verify dispatch pushes num_slots * K
    # rows through the EP a2a instead of num_slots — so the gate replays
    # the segmented a2a at the K-scaled row count the tuned value would
    # actually run. The drafter/accept logic itself is pure jnp (no
    # signals to lint); the wire protocol under the fatter payload is
    # what admission must prove out.
    def run(ctx):
        import jax.numpy as jnp
        from triton_dist_tpu.ops import all_to_all_push_seg
        n = ctx.num_ranks
        rows = n * n * max(1, int(k))
        all_to_all_push_seg(ctx, jnp.zeros((rows, 16, 128), jnp.float32),
                            axis="x", segments=2)
    return run


GATE_RUNNERS: Dict[str, Callable[[Any], Callable]] = {
    "ag_gemm": _gate_ag_gemm,
    "gemm_rs": _gate_gemm_rs,
    "ag_moe_group_gemm": _gate_ag_moe_group_gemm,
    "moe_reduce_rs": _gate_moe_reduce_rs,
    "ring_attention": _gate_ring_attention,
    "serving_overlap_mb": _gate_overlap_microbatch,
    "serving_spec_k": _gate_spec_k,
}


def _gate_meshes(mesh_shape: Tuple[int, ...]) -> Tuple[Dict[str, int], ...]:
    """Capture meshes for the admission gate: n=2 (the minimal ring) plus
    the key's own world size clamped to the verifier's supported range."""
    total = 1
    for d in mesh_shape:
        total *= int(d)
    ns = sorted({2, min(max(total, 2), 4)})
    return tuple({"x": n} for n in ns)


# -- the registry ------------------------------------------------------------

FORMAT_VERSION = 1


class TunedConfigRegistry:
    """JSON-serializable winner store keyed on
    ``(op, mesh_shape, dtype, shape_bucket)``.

    ``require_sigcheck=True`` (the default) makes :meth:`put` refuse any
    mesh-keyed config whose op has no gate runner and any config whose
    kernel the verifier flags; single-device keys (``mesh_shape=()``)
    carry no signal protocol and are admitted ungated, recorded as such.
    """

    def __init__(self, require_sigcheck: bool = True):
        self.require_sigcheck = require_sigcheck
        self._entries: Dict[TunedKey, Any] = {}
        self._checked: Dict[TunedKey, bool] = {}
        self.lookups = 0
        self.hits = 0

    # -- admission --------------------------------------------------------
    def put(self, key: TunedKey, config: Any,
            run: Optional[Callable] = None,
            meshes: Optional[Sequence[Dict[str, int]]] = None) -> None:
        """Admit ``config`` under ``key`` through the sigcheck gate.

        ``run`` overrides the built-in gate runner (``run(ctx)`` drives
        the kernel end to end on the capture context — the gallery tests
        pass intentionally-broken kernels through here)."""
        _encode_config(config)          # refuse unserializable configs NOW
        checked = False
        if key.mesh_shape:              # distributed op: protocol to verify
            runner = run
            if runner is None:
                factory = GATE_RUNNERS.get(key.op)
                runner = factory(config) if factory is not None else None
            if runner is None:
                if self.require_sigcheck:
                    raise RegistryAdmissionError(
                        f"no sigcheck gate runner for op {key.op!r} — a "
                        f"mesh-keyed config cannot enter the registry "
                        f"unverified (pass run=, or register the op in "
                        f"aot.registry.GATE_RUNNERS)", op=key.op)
            else:
                from triton_dist_tpu.analysis.api import sigcheck
                report = sigcheck(
                    runner, op=key.op,
                    meshes=meshes or _gate_meshes(key.mesh_shape))
                if not report.ok:
                    kinds = ",".join(report.finding_kinds)
                    raise RegistryAdmissionError(
                        f"sigcheck refused config {config!r} for op "
                        f"{key.op!r} at meshes {report.ns}: findings "
                        f"[{kinds}] — a flagged kernel never becomes a "
                        f"persisted default", op=key.op,
                        findings=report.findings)
                checked = True
        self._entries[key] = config
        self._checked[key] = checked

    # -- lookup -----------------------------------------------------------
    def get(self, key: TunedKey) -> Any:
        """Winner for ``key`` or None. Counts toward ``hit_rate``."""
        self.lookups += 1
        if key in self._entries:
            self.hits += 1
            return self._entries[key]
        return None

    def get_similar(self, op: str, dtype: str) -> Any:
        """Any winner for (op, dtype) ignoring mesh/shape — used by the
        autotuned wrappers to promote a near-miss winner to the FRONT of
        the candidate list (still timed, just first)."""
        for k, v in self._entries.items():
            if k.op == op and k.dtype == dtype:
                return v
        return None

    def checked(self, key: TunedKey) -> bool:
        """True when ``key``'s config passed the sigcheck gate at admission
        (single-device keys and ``require_sigcheck=False`` admits record
        False — the distinction is persisted, auditable, and honest)."""
        return self._checked.get(key, False)

    @property
    def hit_rate(self) -> float:
        return self.hits / self.lookups if self.lookups else 0.0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: TunedKey) -> bool:
        return key in self._entries

    def keys(self):
        return self._entries.keys()

    # -- persistence ------------------------------------------------------
    def _entries_json(self) -> list:
        rows = [{"key": k.to_json(), "config": _encode_config(v),
                 "checked": self._checked.get(k, False)}
                for k, v in self._entries.items()]
        rows.sort(key=lambda r: json.dumps(r["key"], sort_keys=True))
        return rows

    def to_json(self) -> dict:
        entries = self._entries_json()
        canon = json.dumps(entries, sort_keys=True).encode()
        return {"format": FORMAT_VERSION, "entries": entries,
                "digest": f"{_fnv1a_bytes(canon):08x}"}

    @classmethod
    def from_json(cls, doc: dict,
                  require_sigcheck: bool = True) -> "TunedConfigRegistry":
        if doc.get("format") != FORMAT_VERSION:
            raise RegistryIntegrityError(
                f"registry format {doc.get('format')!r} != "
                f"{FORMAT_VERSION} — refusing to guess at the layout")
        entries = doc.get("entries", [])
        canon = json.dumps(entries, sort_keys=True).encode()
        digest = f"{_fnv1a_bytes(canon):08x}"
        if digest != doc.get("digest"):
            raise RegistryIntegrityError(
                f"tuned-config registry torn or tampered: entry digest "
                f"{digest} != recorded {doc.get('digest')!r}")
        reg = cls(require_sigcheck=require_sigcheck)
        for row in entries:
            key = TunedKey.from_json(row["key"])
            # load path trusts the digest, not the gate: entries were
            # gated at put() time and the digest proves they are unedited
            reg._entries[key] = _decode_config(row["config"])
            reg._checked[key] = bool(row.get("checked", False))
        return reg

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str,
             require_sigcheck: bool = True) -> "TunedConfigRegistry":
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
        return cls.from_json(doc, require_sigcheck=require_sigcheck)


# -- process-default registry (the autotuner's write target) -----------------

_DEFAULT: Optional[TunedConfigRegistry] = None


def set_default_registry(reg: Optional[TunedConfigRegistry]) -> None:
    """Install ``reg`` as the registry ``contextual_autotune`` consults and
    records winners into (None detaches)."""
    global _DEFAULT
    _DEFAULT = reg


def get_default_registry() -> Optional[TunedConfigRegistry]:
    return _DEFAULT


__all__ = ["TunedKey", "TunedConfigRegistry", "RegistryIntegrityError",
           "RegistryAdmissionError", "shape_bucket_of", "GATE_RUNNERS",
           "set_default_registry", "get_default_registry"]
