"""Backend/environment detection.

The same kernel code runs in two modes:
- compiled Mosaic on real TPU chips (bench, production), and
- Pallas TPU *interpret mode* on a virtual CPU device mesh (tests, CI) —
  an improvement over the reference, whose tests require real GPUs
  (reference SURVEY: no single-process cluster simulator).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax 0.4.x spells this TPUCompilerParams and its dataclass predates
    # some fields (notably ``has_side_effects``). Alias it with a kwarg
    # filter: unknown fields are dropped rather than erroring, which is
    # sound everywhere this repo runs 0.4.x (CPU interpret mode executes
    # kernels unconditionally; side-effect marking only guards compiled
    # DCE). Installed once here — every module imports utils before
    # touching pltpu.
    import dataclasses as _dc

    _TPU_CP = pltpu.TPUCompilerParams
    _CP_FIELDS = {f.name for f in _dc.fields(_TPU_CP)}

    def _compat_compiler_params(**kw):
        return _TPU_CP(**{k: v for k, v in kw.items() if k in _CP_FIELDS})

    pltpu.CompilerParams = _compat_compiler_params

if not hasattr(jax.lax, "axis_size"):
    # jax 0.4.x predates ``lax.axis_size``. ``psum`` of a concrete 1 over a
    # named axis constant-folds to the axis size as a Python int, which is
    # exactly the new API's behavior (callers use it as a loop bound).
    jax.lax.axis_size = lambda name: jax.lax.psum(1, name)


def _probe_default_backend(timeout_s: float = 45.0) -> int | None:
    """Device count of the DEFAULT backend, probed in a subprocess with a
    timeout. Never call ``jax.devices()`` in-process to *discover* a backend:
    a wedged device tunnel blocks it forever (observed >2.5 h after a client
    died mid-compile — verify skill notes), which is how round 2's multichip
    dryrun timed out on plumbing while the code under test was green.
    Returns None when the backend is unreachable within ``timeout_s``."""
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print(len(jax.devices()))"],
            timeout=timeout_s, capture_output=True, text=True)
        if r.returncode == 0:
            return int(r.stdout.strip().splitlines()[-1])
    except Exception:
        pass
    return None


def force_virtual_cpu_devices(n: int, skip_if_satisfied: bool = True) -> None:
    """Re-point jax at an ``n``-device virtual CPU platform, clearing any
    live backend (the container's sitecustomize eagerly initializes a TPU
    backend at interpreter start, so env vars alone are not enough). The
    single shared copy of this order-sensitive recipe — used by
    ``__graft_entry__``, ``tests/conftest`` and the tutorials' ``--sim``.

    Order matters: drop the cached backends (including the memoized
    ``get_backend`` — ``_clear_backends`` alone does not clear it on
    jax>=0.9) BEFORE the config updates; ``jax_num_cpu_devices`` refuses to
    change once it believes backends are live.

    ``skip_if_satisfied``: no-op when the current platform already exposes
    ``n`` devices (any platform — used by dryruns that accept real chips);
    pass False to force the CPU simulator unconditionally."""
    if skip_if_satisfied:
        import jax._src.xla_bridge as xb
        if getattr(xb, "_backends", None):
            # A backend is already live in-process: enumeration completed
            # once, so devices() is a cached call that cannot hang.
            try:
                if len(jax.devices()) >= n:
                    return
            except Exception:
                pass
        else:
            # No live backend yet — probing the default one in-process can
            # hang forever on a wedged tunnel. Probe via subprocess+timeout
            # and fall through to the forced CPU mesh on timeout/shortfall.
            cnt = _probe_default_backend()
            if cnt is not None and cnt >= n:
                try:
                    if len(jax.devices()) >= n:
                        return
                except Exception:
                    pass  # backend vanished since the probe: fall through
    import jax._src.xla_bridge as xb
    try:
        xb._clear_backends()
        xb.get_backend.cache_clear()
    except Exception:
        pass
    flag = f"--xla_force_host_platform_device_count={n}"
    if flag not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") + " " + flag).strip()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:
        pass
    backend_platform.cache_clear()


@lru_cache(None)
def backend_platform() -> str:
    return jax.devices()[0].platform.lower()


def on_cpu() -> bool:
    return backend_platform() == "cpu"


def on_tpu() -> bool:
    # The axon PJRT plugin reports devices as TPU; be liberal.
    p = backend_platform()
    return ("tpu" in p) or (p == "axon")


def interpret_params(**kw):
    """TPU-interpret-mode params used when running on CPU devices.

    ``dma_execution_mode='on_wait'`` preserves the async-DMA/semaphore
    semantics closely enough to catch missing waits; set
    ``TDT_DETECT_RACES=1`` to enable the interpreter's race detector
    (the reference's analog is sleep-noise fuzzing, allgather.py:72-76).

    jax versions that predate the TPU interpreter's params class (the
    0.4.x line exposes neither ``InterpretParams`` nor its older
    ``TPUInterpretParams`` spelling) fall back to plain ``True``: the
    generic Pallas interpreter there executes local DMAs, semaphores and
    (with the generation shim below) ``emit_pipeline``, which is the
    surface the test suite needs."""
    if os.environ.get("TDT_DETECT_RACES") == "1":
        kw.setdefault("detect_races", True)
    ip = (getattr(pltpu, "InterpretParams", None)
          or getattr(pltpu, "TPUInterpretParams", None))
    if ip is None:
        return True
    try:
        return ip(**kw)
    except TypeError:
        return ip()


@lru_cache(None)
def _register_cpu_tpu_info():
    """Interpret mode runs kernels on CPU devices, but Pallas helpers that
    model the hardware (``emit_pipeline`` tiling) still query
    ``tpu_info.get_tpu_info()``. Register a v5e-like profile for the "cpu"
    device kind via the module's public ``registry`` hook so those helpers
    work in the simulator."""
    try:
        from jax._src.pallas.mosaic import tpu_info
    except ImportError:
        return  # private API moved; only emit_pipeline-style helpers notice

    def _cpu_info():  # matches jax 0.9 TpuInfo; guarded below for drift
        return tpu_info.TpuInfo(
            chip_version=tpu_info.ChipVersion.TPU_V5E,
            generation=5,
            num_cores=1,
            num_lanes=128,
            num_sublanes=8,
            mxu_column_size=128,
            vmem_capacity_bytes=128 * 1024 * 1024,
            cmem_capacity_bytes=0,
            smem_capacity_bytes=1024 * 1024,
            hbm_capacity_bytes=17_200_000_000,
            mem_bw_bytes_per_second=int(8.20e11),
            bf16_ops_per_second=int(1.97e14),
            int8_ops_per_second=int(3.94e14),
            fp8_ops_per_second=0,
            int4_ops_per_second=int(7.88e14),
        )

    try:
        _cpu_info()  # fail fast here (not inside a kernel) if TpuInfo drifted
        tpu_info.registry.setdefault("cpu", _cpu_info)
    except Exception:
        pass  # only emit_pipeline-dependent paths will then raise, with
        #       jax's own "Unsupported TPU device kind" message


@lru_cache(None)
def _patch_pipeline_tpu_generation():
    """Older jax (0.4.x) has no ``tpu_info`` registry; its pipeline helper
    reads the TPU generation straight off ``device_kind`` and asserts on
    anything that isn't a chip. Shim it to report a v5-class generation
    when the live devices are CPUs so ``emit_pipeline`` works under the
    generic interpreter (the generation only picks a DMA sublane tiling
    constant — any supported value is semantically correct in
    interpret mode)."""
    try:
        from jax._src.pallas.mosaic import pipeline as _mp
    except ImportError:
        return
    orig = getattr(_mp, "_get_tpu_generation", None)
    if orig is None:
        return

    def _gen():
        try:
            return orig()
        except Exception:
            return 5

    _mp._get_tpu_generation = _gen


def default_interpret():
    """What to pass as ``pallas_call(interpret=...)`` on this backend.

    ``TDT_FORCE_COMPILED=1`` (read at trace time) forces the compiled Mosaic
    path regardless of the live backend — used when lowering against an
    *abstract TPU topology* (AOT deployment, the CI topology-compile gate in
    tests/test_aot_topology.py) from a process whose default backend is CPU."""
    if os.environ.get("TDT_FORCE_COMPILED") == "1":
        return False
    if on_cpu():
        _register_cpu_tpu_info()
        _patch_pipeline_tpu_generation()
        return interpret_params()
    return False
