"""Elastic autoscaling (ISSUE 18): replica lifecycle, graceful drain,
lend-ahead, crash-mid-drain, the controller, and the churn bounds.

THE contract, four rungs:

- **lifecycle**: WARMING → ACTIVE → DRAINING → RETIRED, with KILLED an
  excursion any alive state may take; only ACTIVE admits, DRAINING still
  steps and lends, indices are append-only and never reused.
- **drain never changes tokens**: a draining replica requeues its queued
  requests to peers through the journal cursor (so a crash after the
  move never re-serves them), finishes its in-flight decodes in place,
  lends its hot prefixes ahead to their rendezvous successors, and
  retires — every trace bit-identical to ``expected_tokens``.
- **crash-mid-drain degrades to the PR 12 ladder**: kill of a DRAINING
  replica is legal; restore resumes the DRAIN (never admission), journal
  replay re-queues the live requests, and the fleet converges with the
  same tokens.
- **the controller is deterministic and resumable**: scaling decisions
  are a pure function of the windowed step-space attainment feed, every
  decision is journaled, and ``Autoscaler.resume`` rebuilds the fleet
  view (cursor, cooldown clock, decision log) from the journal alone.

Plus the closed-form rendezvous churn bound (a scale event at fleet size
N moves <= c/N of a fixed key population) and the units underneath
(``AttainmentWindow``, ``parse_budgets``).

Every test runs under the per-test SIGALRM watchdog (test_cluster.py
pattern).
"""

import json
import os
import signal
import subprocess
import sys
from collections import deque

import numpy as np
import pytest

from triton_dist_tpu.serving import (Autoscaler, Cluster, ReplicaState,
                                     SimEngine, expected_tokens,
                                     generate_arrivals, parse_budgets,
                                     parse_slo, parse_workload)
from triton_dist_tpu.serving.journal import ControlJournal
from triton_dist_tpu.serving.metrics import AttainmentWindow
from triton_dist_tpu.shmem import FaultPlan

pytestmark = [pytest.mark.autoscale, pytest.mark.serving]

WATCHDOG_S = 240
PS = 8                        # page size everywhere below


@pytest.fixture(autouse=True)
def autoscale_watchdog():
    def boom(signum, frame):
        raise TimeoutError(
            f"autoscale watchdog: test exceeded {WATCHDOG_S}s wall — "
            "an engine (or the controller loop) is hanging")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(WATCHDOG_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _mk_cluster(replicas=2, tmp_path=None, slots=4, **kw):
    def factory(journal):
        return SimEngine(num_slots=slots, page_size=PS, num_pages=33,
                         pages_per_seq=8, journal=journal,
                         prefix_cache=True, prefill_chunk=PS)

    return Cluster(factory, replicas=replicas,
                   journal_dir=None if tmp_path is None else str(tmp_path),
                   **kw)


def _templates(n=4, seed=23):
    rng = np.random.RandomState(seed)
    return [tuple(int(t) for t in rng.randint(1, 997, size=3 * PS))
            for _ in range(n)]


def _drain_all(cl, asc=None, max_steps=100_000):
    """Step to quiescence with the controller (if any) still ticking —
    a restore right after an idle step is not quiescence, hence the
    debounce (same loop as cluster_sim --autoscale)."""
    idle = 0
    for _ in range(max_steps):
        if idle >= 3:
            break
        idle = 0 if cl.step() else idle + 1
        if asc is not None:
            asc.step()
    return cl.results()


def _assert_golden(cl, sent):
    res = cl.results()
    for gid, (prompt, mnt) in sent.items():
        assert res[gid] == expected_tokens(list(prompt), mnt), (
            f"gid {gid} diverged from the closed-form golden")


# ---------------------------------------------------------------------------
# units: the attainment window and the budget spec
# ---------------------------------------------------------------------------

def test_attainment_window():
    w = AttainmentWindow(4)
    assert w.count(("ttft", "chat")) == 0
    for v in (1, 2, 3, 10):
        w.observe(("ttft", "chat"), v)
    assert w.count(("ttft", "chat")) == 4
    assert w.attainment(("ttft", "chat"), 3) == 0.75
    # window semantics: a 5th sample evicts the oldest (the 1)
    w.observe(("ttft", "chat"), 20)
    assert w.count(("ttft", "chat")) == 4
    assert w.attainment(("ttft", "chat"), 3) == 0.5
    # series are independent
    w.observe(("itl", "batch"), 1)
    assert w.count(("itl", "batch")) == 1
    assert w.attainment(("itl", "batch"), 1) == 1.0


def test_parse_budgets():
    assert parse_budgets("chat:8") == {"chat": (8, None)}
    assert parse_budgets(" chat:8/2 , batch:64 ") == {
        "chat": (8, 2), "batch": (64, None)}
    with pytest.raises((AssertionError, ValueError)):
        parse_budgets("chat")


# ---------------------------------------------------------------------------
# lifecycle: states, promotion, admission gating, terminal retire
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_lifecycle_transitions(tmp_path):
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path)
    assert [r.lifecycle for r in cl.replicas] == [ReplicaState.ACTIVE] * 2

    # scale-up joins WARMING: alive, not admitting, not stepped
    rep = cl.add_replica(warm_steps=2)
    assert rep.index == 2 and rep.alive and not rep.admitting
    assert cl.lifecycle_counts() == {"active": 2, "warming": 1}
    assert len(cl.admitting_replicas) == 2
    steps_before = rep.engine._steps
    cl.step()                     # warm_remaining 2 -> 1: still warming
    assert rep.lifecycle is ReplicaState.WARMING
    assert rep.engine._steps == steps_before, "WARMING must not step"
    cl.step()                     # promotion
    assert rep.lifecycle is ReplicaState.ACTIVE
    assert len(cl.admitting_replicas) == 3

    # drain: admission stops NOW, the replica still steps, then retires
    cl.begin_drain(2)
    assert rep.draining and not rep.admitting and rep.alive
    _drain_all(cl)
    assert rep.lifecycle is ReplicaState.RETIRED and not rep.alive
    assert cl.metrics.counters["retires"] == 1

    # terminal/illegal transitions are loud
    with pytest.raises(AssertionError):
        cl.begin_drain(2)         # retired replicas cannot drain
    cl.begin_drain(1)
    with pytest.raises(AssertionError):
        cl.begin_drain(0)         # never drain the last admitting replica
    # the scale history recorded every membership event in order
    kinds = [k for _, k, _ in cl.scale_history]
    assert kinds[:4] == ["scale_up", "drain_begin", "drain_done", "retire"]


# ---------------------------------------------------------------------------
# graceful drain: journal-cursor requeue, bitwise traces
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_drain_requeues_queued_bitwise(tmp_path):
    """Saturate one replica's queue, drain it: every QUEUED request moves
    to a peer under its own gid (journaled as a requeue on the source),
    in-flight slots finish in place, and every token matches the closed
    form — the drain changed the schedule, never the outputs."""
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, slots=2)
    rng = np.random.RandomState(5)
    sent = {}
    for _ in range(12):
        prompt = [int(t) for t in rng.randint(1, 997, size=6)]
        mnt = int(rng.randint(2, 5))
        sent[cl.submit(prompt, mnt)] = (tuple(prompt), mnt)
    victim = max(cl.replicas, key=lambda r: r.load).index
    moved = cl.begin_drain(victim)
    assert moved >= 1, "a saturated 2-slot replica must have had a queue"
    assert cl.metrics.counters["requeues"] == moved
    # the source journal carries one requeue event per moved request, so
    # a post-move crash replay drops them instead of re-serving them
    jpath = os.path.join(str(tmp_path), f"journal-r{victim}.jsonl")
    kinds = [json.loads(line).get("kind")
             for line in open(jpath, encoding="utf-8")]
    assert kinds.count("requeue") == moved
    res = _drain_all(cl)
    assert len(res) == len(sent) and not cl.failed_gids
    _assert_golden(cl, sent)
    assert cl.replicas[victim].lifecycle is ReplicaState.RETIRED


# ---------------------------------------------------------------------------
# crash-mid-drain: kill of DRAINING is legal, restore resumes the drain
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_crash_mid_drain_resumes_and_stays_bitwise(tmp_path):
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, slots=2)
    rng = np.random.RandomState(9)
    sent = {}
    for _ in range(10):
        prompt = [int(t) for t in rng.randint(1, 997, size=6)]
        mnt = int(rng.randint(2, 5))
        sent[cl.submit(prompt, mnt)] = (tuple(prompt), mnt)
    victim = max(cl.replicas, key=lambda r: r.load).index
    cl.begin_drain(victim)
    rep = cl.replicas[victim]
    assert rep.draining

    cl.kill(victim)               # crash MID-drain: legal
    assert rep.lifecycle is ReplicaState.KILLED
    assert rep._prekill is ReplicaState.DRAINING

    cl.restore(victim)            # comes back DRAINING, never admitting
    assert rep.draining and not rep.admitting
    res = _drain_all(cl)
    assert rep.lifecycle is ReplicaState.RETIRED
    # nothing lost, nothing doubled: the journal replay re-queued the
    # replica's live requests, the requeue events dropped the moved ones
    assert len(res) == len(sent) and not cl.failed_gids
    _assert_golden(cl, sent)


def test_autoscaler_auto_restores_crashed_drainer(tmp_path):
    """The controller's healing rung: a replica that died DRAINING is
    restored on the next tick without any policy signal — budgets never
    reach min_samples here, so the ONLY controller action is the heal."""
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, slots=2)
    asc = Autoscaler(cl, {"chat": 8}, window=8, min_samples=10**9,
                     max_replicas=4, cooldown=1)
    rng = np.random.RandomState(11)
    sent = {}
    for _ in range(8):
        prompt = [int(t) for t in rng.randint(1, 997, size=6)]
        sent[cl.submit(prompt, 3)] = (tuple(prompt), 3)
    victim = max(cl.replicas, key=lambda r: r.load).index
    cl.begin_drain(victim)
    cl.kill(victim)
    res = _drain_all(cl, asc)
    assert cl.replicas[victim].lifecycle is ReplicaState.RETIRED
    assert cl.metrics.counters["restores"] == 1
    assert len(res) == len(sent) and not cl.failed_gids
    _assert_golden(cl, sent)


# ---------------------------------------------------------------------------
# lend-ahead: push to the rendezvous successor, degrade on a dead peer,
# typed no-op on a mixed fleet
# ---------------------------------------------------------------------------

def _warm_template(cl, t, seed):
    rng = np.random.RandomState(seed)
    sent = {}
    for _ in range(3):
        prompt = list(t) + [int(x) for x in rng.randint(1, 997, size=3)]
        sent[cl.submit(prompt, 3)] = (tuple(prompt), 3)
        cl.drain()
    return sent


@pytest.mark.quick
def test_lend_ahead_lands_on_rendezvous_successor(tmp_path):
    cl = _mk_cluster(replicas=3, tmp_path=tmp_path, lend=True)
    t = _templates(1, seed=31)[0]
    sent = _warm_template(cl, t, seed=4)
    owner = cl.prefix_index.match(t)[1]
    cl.begin_drain(owner)
    _drain_all(cl)
    assert cl.replicas[owner].lifecycle is ReplicaState.RETIRED
    assert cl.metrics.counters["lend_aheads"] >= 1
    assert cl.metrics.counters["lend_ahead_pages"] >= 3
    # the index was re-pointed at the successor that adopted the pages —
    # exactly the replica the prefix's future traffic rendezvouses to
    succ = cl.prefix_index.match(t)[1]
    assert succ is not None and succ != owner
    assert succ == cl.rendezvous_owner(t)
    assert cl.replicas[succ].engine.prefix_cache.match(t), (
        "successor must hold the lent prefix warm")
    # and the next request is a warm hit there, bitwise
    prompt = list(t) + [7, 7, 7]
    gid = cl.submit(prompt, 3)
    cl.drain()
    assert cl.results()[gid] == expected_tokens(prompt, 3)
    hist = cl.replicas[succ].engine.metrics.hist
    assert (hist["ttft_cached_steps"].count
            + hist["ttft_rewarmed_steps"].count) >= 1
    _assert_golden(cl, sent)


def test_lend_ahead_dead_successor_degrades_to_cold(tmp_path):
    """A dead-peer plan kills every lend-ahead in flight: the ladder
    burns its rungs, records typed degradations, the retire is NOT
    blocked, and the successor serves the template cold — bitwise."""
    plan = FaultPlan(seed=3, dead_peer_after=0)
    cl = _mk_cluster(replicas=3, tmp_path=tmp_path, lend=True,
                     lend_plan=plan)
    t = _templates(1, seed=37)[0]
    sent = _warm_template(cl, t, seed=6)
    owner = cl.prefix_index.match(t)[1]
    degr0 = cl.metrics.counters["lend_degradations"]
    cl.begin_drain(owner)
    _drain_all(cl)
    assert cl.replicas[owner].lifecycle is ReplicaState.RETIRED, (
        "an exhausted lend-ahead ladder must never block the retire")
    assert cl.metrics.counters["lend_aheads"] == 0
    assert cl.metrics.counters["lend_degradations"] > degr0
    cl.lending._plan = FaultPlan(seed=3)       # transport heals
    prompt = list(t) + [7, 7, 7]
    gid = cl.submit(prompt, 3)
    cl.drain()
    assert cl.results()[gid] == expected_tokens(prompt, 3), (
        "cold re-prefill after a degraded lend-ahead must stay bitwise")
    _assert_golden(cl, sent)


def test_lend_ahead_mixed_fleet_is_typed_noop(tmp_path):
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, lend=True)
    t = _templates(1, seed=41)[0]
    _warm_template(cl, t, seed=8)
    owner = cl.prefix_index.match(t)[1]
    # drainee without the lend surface: the whole call is one typed no-op
    cl.replicas[owner].engine.export_prefix = None
    cl.begin_drain(owner)
    _drain_all(cl)
    assert cl.replicas[owner].lifecycle is ReplicaState.RETIRED
    assert cl.metrics.counters["lend_aheads"] == 0
    assert cl.metrics.counters["lend_ahead_noops"] == 1

    # successor without adopt: per-prefix no-ops, retire still clean
    cl2 = _mk_cluster(replicas=2, tmp_path=None, lend=True)
    _warm_template(cl2, t, seed=8)
    owner2 = cl2.prefix_index.match(t)[1]
    cl2.replicas[1 - owner2].engine.adopt_prefix = None
    cl2.begin_drain(owner2)
    _drain_all(cl2)
    assert cl2.replicas[owner2].lifecycle is ReplicaState.RETIRED
    assert cl2.metrics.counters["lend_aheads"] == 0
    assert cl2.metrics.counters["lend_ahead_noops"] >= 1


# ---------------------------------------------------------------------------
# the controller: hysteresis, cooldown, min/max clamps, journal resume
# ---------------------------------------------------------------------------

def _feed(cl, cls, ttft, n):
    for _ in range(n):
        cl._latency_feed.append((cls, ttft, None))


def test_autoscaler_up_down_cooldown_and_clamps(tmp_path):
    cl = _mk_cluster(replicas=1, tmp_path=tmp_path)
    asc = Autoscaler(cl, {"chat": 8}, window=8, min_samples=4,
                     min_replicas=1, max_replicas=2, cooldown=5,
                     warm_steps=0)
    # no samples -> no decision
    assert asc.step() is None
    # SLO misses -> ONE scale-up, then the cooldown holds the line
    _feed(cl, "chat", 50, 8)
    assert asc.step() == ("scale_up", 1)
    assert cl.replicas[1].lifecycle is ReplicaState.WARMING
    _feed(cl, "chat", 50, 8)
    for _ in range(4):
        cl.step()
        assert asc.step() is None, "cooldown must absorb the burst front"
    # still missing after cooldown, but the fleet is at max: clamped
    _feed(cl, "chat", 50, 8)
    cl.step()
    assert asc.step() is None
    assert len(cl.replicas) == 2
    # SLO comfortably met -> drain the highest-index replica... but
    # never below min_replicas
    _feed(cl, "chat", 1, 8)
    dec = None
    for _ in range(asc.cooldown + 1):
        cl.step()
        dec = dec or asc.step()
    assert dec == ("drain_begin", 1)
    _drain_all(cl, asc)
    assert cl.replicas[1].lifecycle is ReplicaState.RETIRED
    _feed(cl, "chat", 1, 8)
    for _ in range(asc.cooldown + 1):
        cl.step()
        assert asc.step() is None, "min_replicas is a floor"
    assert len(cl.admitting_replicas) == 1


def test_autoscaler_wont_drain_into_overload(tmp_path):
    """The down-side half of the dead band: attainment alone never
    drains — the survivors must also be able to SEAT the current load."""
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, slots=2)
    asc = Autoscaler(cl, {"chat": 8}, window=8, min_samples=4,
                     min_replicas=1, max_replicas=2, cooldown=1)
    rng = np.random.RandomState(13)
    for _ in range(8):     # both replicas seated + queued
        cl.submit([int(t) for t in rng.randint(1, 997, size=6)], 8)
    _feed(cl, "chat", 1, 8)
    assert asc.step() is None, (
        "perfect attainment must not drain while the load needs both "
        "replicas' slots")
    _drain_all(cl, asc)


def test_controller_journal_and_resume(tmp_path):
    jpath = Autoscaler.journal_path_for(str(tmp_path))
    cl = _mk_cluster(replicas=1, tmp_path=tmp_path)
    asc = Autoscaler(cl, {"chat": 8}, window=8, min_samples=4,
                     min_replicas=1, max_replicas=2, cooldown=3,
                     warm_steps=0, journal=jpath)
    _feed(cl, "chat", 50, 8)
    assert asc.step() == ("scale_up", 1)
    cl.step()
    _feed(cl, "chat", 1, 8)
    dec = None
    for _ in range(asc.cooldown + 1):
        cl.step()
        dec = dec or asc.step()
    assert dec == ("drain_begin", 1)
    _drain_all(cl, asc)
    assert cl.replicas[1].lifecycle is ReplicaState.RETIRED

    # the journal carries the full decision ladder in order
    kinds = [e["kind"] for e in ControlJournal.load(jpath).entries]
    assert kinds == ["scale_up", "drain_begin", "drain_done", "retire"]

    # controller crash: resume() rebuilds the fleet view from the
    # journal alone — cursor, cooldown clock, decision log — and the
    # next ticks neither re-journal old events nor re-drain retirees
    asc2 = Autoscaler.resume(cl, jpath, {"chat": 8}, window=8,
                             min_samples=4, min_replicas=1,
                             max_replicas=2, cooldown=3, warm_steps=0)
    assert asc2._hcursor == asc._hcursor
    assert [d[1:] for d in asc2.decisions] == [
        ("scale_up", 1), ("drain_begin", 1), ("drain_done", 1),
        ("retire", 1)]
    n_entries = len(ControlJournal.load(jpath).entries)
    for _ in range(3):
        cl.step()
        asc2.step()
    assert len(ControlJournal.load(jpath).entries) == n_entries, (
        "resume must not double-journal replayed history")


def test_resume_rejects_inconsistent_fleet(tmp_path):
    jpath = Autoscaler.journal_path_for(str(tmp_path))
    cl = _mk_cluster(replicas=1, tmp_path=tmp_path)
    asc = Autoscaler(cl, {"chat": 8}, window=8, min_samples=4,
                     max_replicas=2, cooldown=3, warm_steps=0,
                     journal=jpath)
    _feed(cl, "chat", 50, 8)
    asc.step()
    cl.step()
    _feed(cl, "chat", 1, 8)
    for _ in range(asc.cooldown + 1):
        cl.step()
        asc.step()
    _drain_all(cl, asc)
    # a journal that says "retired" must match the cluster it resumes
    fresh = _mk_cluster(replicas=2, tmp_path=None)
    with pytest.raises(AssertionError, match="retired"):
        Autoscaler.resume(fresh, jpath, {"chat": 8})


# ---------------------------------------------------------------------------
# churn bound: a scale event at fleet size N moves <= c/N of a fixed
# key population (closed form: only the joiner's wins / the leaver's
# keys move)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [2, 4, 8])
def test_rendezvous_churn_bound(n):
    cl = _mk_cluster(replicas=n)
    rng = np.random.RandomState(100 + n)
    keys = [tuple(int(t) for t in rng.randint(1, 32000, size=8))
            for _ in range(600)]
    before = {k: cl.rendezvous_owner(k) for k in keys}

    # scale UP: the only keys that move are those the joiner wins
    rep = cl.add_replica(warm_steps=0)
    cl.step()
    assert rep.admitting
    after_up = {k: cl.rendezvous_owner(k) for k in keys}
    moved = [k for k in keys if after_up[k] != before[k]]
    assert all(after_up[k] == rep.index for k in moved), (
        "a key that moved anywhere but the joiner breaks monotonicity")
    frac = len(moved) / len(keys)
    assert 0 < frac <= 2.0 / (n + 1), (
        f"scale-up at N={n} moved {frac:.3f} of the population — the "
        f"rendezvous bound is c/N with c=2 (ideal: {1 / (n + 1):.3f})")

    # scale DOWN: the only keys that move are the leaver's
    cl.begin_drain(rep.index)
    after_down = {k: cl.rendezvous_owner(k) for k in keys}
    for k in keys:
        if after_up[k] != rep.index:
            assert after_down[k] == after_up[k], (
                "a key not owned by the drainee must not move on drain")
    leavers = [k for k in keys if after_up[k] == rep.index]
    assert len(leavers) / len(keys) <= 2.0 / (n + 1)
    _drain_all(cl)


# ---------------------------------------------------------------------------
# end to end: scripted scale events and the policy loop on the diurnal
# workload — bitwise against the closed form AND the static-peak fleet
# ---------------------------------------------------------------------------

def _diurnal_factory(journal):
    return SimEngine(num_slots=8, page_size=PS, num_pages=129,
                     pages_per_seq=8, journal=journal, prefix_cache=True,
                     prefill_chunk=PS,
                     slo=parse_slo("chat_weight=4,batch_weight=1"))


def _run_diurnal(arrivals, n, tmp_path, elastic):
    cl = Cluster(_diurnal_factory, replicas=1 if elastic else 3,
                 journal_dir=None if tmp_path is None else str(tmp_path),
                 lend=True, spill_threshold=10)
    asc = None
    if elastic:
        asc = Autoscaler(cl, {"chat": 12, "batch": 20}, window=16,
                         min_samples=4, min_replicas=1, max_replicas=3,
                         cooldown=12, warm_steps=1)
    pend = deque(arrivals)
    reqs = {}
    i = 0
    while pend:
        while pend and pend[0][0] <= i:
            _, prompt, mnt, tenant, cls = pend.popleft()
            reqs[cl.submit(prompt, mnt, tenant=tenant,
                           cls=cls)] = (prompt, mnt)
        cl.step()
        if asc is not None:
            asc.step()
        i += 1
    res = _drain_all(cl, asc)
    assert len(res) == n and not cl.failed_gids
    for gid, toks in res.items():
        assert toks == expected_tokens(*reqs[gid])
    return cl, res


def test_diurnal_policy_loop_bitwise_vs_static_fleet(tmp_path):
    spec = parse_workload("n=400,rate=0.25,burst_every=150,burst_len=40,"
                          "burst_x=10,seed=7")
    arrivals = generate_arrivals(spec, vocab=32000, page_size=PS)
    _, res_static = _run_diurnal(arrivals, spec.n, None, elastic=False)
    cl, res_elastic = _run_diurnal(arrivals, spec.n, tmp_path,
                                   elastic=True)
    assert res_elastic == res_static, (
        "the elastic schedule changed tokens — the T3 contract is "
        "schedule-only")
    assert cl.metrics.counters["scale_ups"] >= 1
    assert cl.metrics.counters["retires"] >= 1, (
        "the diurnal swing must ride down as well as up")


def test_scripted_scale_crash_drain_bitwise(tmp_path):
    """The fully scripted ladder in ONE run: mid-stream scale-up, drain
    of a loaded replica, a forced crash mid-drain, controller-less
    manual restore — and every surviving trace bitwise."""
    cl = _mk_cluster(replicas=2, tmp_path=tmp_path, slots=2)
    rng = np.random.RandomState(17)
    sent = {}

    def pump(k):
        for _ in range(k):
            prompt = [int(t) for t in rng.randint(1, 997, size=6)]
            mnt = int(rng.randint(2, 5))
            sent[cl.submit(prompt, mnt)] = (tuple(prompt), mnt)
            cl.step()

    pump(6)
    rep = cl.add_replica(warm_steps=1)           # scale-up mid-stream
    cl.step()
    assert rep.admitting
    pump(8)
    victim = max(cl.replicas, key=lambda r: r.load).index
    cl.begin_drain(victim)
    pump(2)                                      # drain under load
    if cl.replicas[victim].draining:             # may retire in 2 steps
        cl.kill(victim)                          # crash MID-drain
        pump(3)
        cl.restore(victim)
    res = _drain_all(cl)
    assert cl.replicas[victim].lifecycle is ReplicaState.RETIRED
    assert len(res) == len(sent) and not cl.failed_gids
    _assert_golden(cl, sent)


# ---------------------------------------------------------------------------
# the CLI: cluster_sim --autoscale end to end (its own golden gate —
# exit 1 on any trace mismatch — plus the panel's acceptance rows)
# ---------------------------------------------------------------------------

def _run_cluster_sim(n, timeout=WATCHDOG_S - 30):
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "cluster_sim.py")
    proc = subprocess.run(
        [sys.executable, script, "--autoscale", "--prefix-cache",
         "--lend", "--pages", "129", "--min-replicas", "1",
         "--max-replicas", "4", "--crash-mid-drain", "--workload",
         f"n={n},rate=0.25,burst_every=300,burst_len=60,burst_x=10,"
         "seed=7"],
        capture_output=True, text=True, timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr[-2000:]
    panel = next(json.loads(line) for line in proc.stderr.splitlines()
                 if line.startswith('{"autoscale"'))
    summary = json.loads(proc.stdout.splitlines()[-1])
    return panel, summary


def test_cluster_sim_autoscale_cli():
    panel, summary = _run_cluster_sim(1500)
    assert summary["verified_bit_identical"] == 1500
    assert summary["mismatched"] == 0 and summary["missing"] == 0
    assert panel["scale_ups"] >= 1 and panel["retires"] >= 1
    assert panel["replica_steps_saved_pct"] > 0
    assert panel["crash_mid_drain"] is not None, (
        "the forced crash must actually fire on this workload")
    assert panel["ttft_chat_p99_steps"] <= 12, (
        "chat p99 TTFT must hold within the budget through every "
        "scale event")


@pytest.mark.slow
def test_cluster_sim_autoscale_100k():
    """The ISSUE 18 acceptance run at full scale: 100k requests through
    scale-ups, drains and a forced crash-mid-drain, every trace verified
    bitwise by the script's own golden gate."""
    signal.alarm(1800)            # beyond the quick-tier watchdog
    panel, summary = _run_cluster_sim(100_000, timeout=1740)
    assert summary["verified_bit_identical"] == 100_000
    assert panel["replica_steps_saved_pct"] > 0
    assert panel["ttft_chat_p99_steps"] <= 12
