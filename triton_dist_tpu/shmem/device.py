"""Device-side tpushmem primitives — usable *inside* Pallas TPU kernels.

This is the TPU-native re-creation of the reference's portability seam
``triton.language.extra.libshmem_device`` (reference
patches/triton/python/triton/language/extra/libshmem_device.py — the
vendor-neutral interface NVSHMEM/ROCSHMEM backends implement) and of its
NVIDIA implementation ``libnvshmem_device.py`` (put/get/signal/fence/quiet/
barrier device API, see reference SURVEY §2.2).

Mapping (GPU one-sided shmem → TPU):

===========================  ==============================================
reference primitive          TPU-native equivalent here
===========================  ==============================================
``my_pe()`` / ``n_pes()``    mesh axis index / size (``lax.axis_index``)
``putmem_nbi_block``         ``pltpu.make_async_remote_copy(...).start()``
``putmem_signal_nbi_block``  remote copy; the *receiver-side DMA semaphore*
                             is the delivery-ordered signal (hardware
                             signals it when data lands — stronger than
                             NVSHMEM's separate signal word)
``signal_op(SET/ADD)``       ``pltpu.semaphore_signal`` (counting ADD only;
                             SET has no TPU analog — protocols here are
                             redesigned around counted arrivals)
``signal_wait_until``        ``pltpu.semaphore_wait`` (NOTE: decrements)
``fence``/``quiet``          wait on local send semaphores (``quiet``);
                             per-destination ordering via semaphores
``barrier_all``              barrier semaphore all-to-all signal + wait
``symm_at(ptr, pe)``         not needed: remote refs are (buffer, device_id)
                             pairs — symmetric by construction
===========================  ==============================================

All functions take mesh-axis names because the "PE space" is a (possibly
multi-axis) jax mesh, not a flat rank list.
"""

from __future__ import annotations

import itertools
import os
from typing import Sequence

import jax
from jax import lax
from jax.experimental import pallas as pl  # noqa: F401  (re-exported for kernels)
from jax.experimental.pallas import tpu as pltpu

from . import faults
from . import trace


# -- producer-delay fuzzing --------------------------------------------------

_NOISE_SITE = itertools.count()


def _noise_trips() -> int:
    try:
        return int(os.environ.get("TDT_NOISE", "0") or "0")
    except ValueError:
        return 0


def producer_noise(src_ref) -> None:
    """Sync-bug fuzzing hook (analog of the reference's
    ``_add_noise_workload_debug`` sleep injection, allgather.py:72-76).

    When ``TDT_NOISE=<n>`` is set at trace time, emits ``n * (site%3 + 1)``
    effectful self-copies of ``src_ref`` before a put — per-call-site-varied
    busywork that widens producer/consumer timing windows so missing waits
    surface in interpret mode (pair with ``TDT_DETECT_RACES=1``). A no-op
    (zero emitted ops) when unset; debug knob only — it emits real DMAs if
    enabled on hardware.

    An active :class:`~triton_dist_tpu.shmem.faults.FaultPlan` with
    ``device_put_delay=k`` adds ``k`` flat extra trips on top — the
    "delay a put by extra noise trips" fault of the protocol matrix."""
    if trace.active_tracer() is not None:
        return  # busywork has no protocol meaning; skip under event capture
    trips = _noise_trips()
    plan = faults.active_plan()
    extra = plan.device_put_delay if plan is not None else 0
    if not trips and not extra:
        return
    k = next(_NOISE_SITE) % 3 + 1
    for _ in range(trips * k + extra):
        pltpu.sync_copy(src_ref, src_ref)


# -- serialized-execution bisection mode ------------------------------------

def _serial() -> bool:
    """``TDT_SERIAL=1`` (read at trace time) forces every put to complete
    synchronously at the source before the kernel proceeds — the analog of
    the reference's ``serial=True`` debug switch on its overlap ops
    (allgather_gemm.py:428,482-485), which serializes the copy/compute
    overlap to bisect hangs and races. With it set, all cross-device
    pipelining collapses to a lock-step schedule; correctness must be
    unchanged, only slower — any behavioral difference is a sync bug."""
    return os.environ.get("TDT_SERIAL") == "1"


class _CompletedDMA:
    """Stand-in descriptor returned by ``putmem_nbi`` in TDT_SERIAL mode:
    the put already completed at source, so ``quiet``/``wait_send`` become
    no-ops (a second wait on the consumed send semaphore would hang).

    ``wait()`` intentionally RAISES: on a real remote-copy descriptor it
    also waits the *receive* semaphore, which serial mode cannot have
    satisfied (delivery is signaled on the peer, not here) — silently
    no-opping would turn the bisection mode itself into a race. Kernels
    awaiting their own incoming delivery must use ``wait_recv``."""

    def wait_send(self):
        return None

    def wait(self):
        raise RuntimeError(
            "TDT_SERIAL: .wait() on a serialized put is ambiguous (the real "
            "descriptor would also wait the recv semaphore). Use wait_recv("
            "dst_ref, recv_sem) for deliveries; send completion already "
            "happened.")


_COMPLETED_DMA = _CompletedDMA()


# -- PE identity ------------------------------------------------------------

def my_pe(axis: str | Sequence[str]):
    """Rank of this device along ``axis`` (or flattened over several axes,
    major-to-minor). Analog of ``nvshmem_my_pe`` (libnvshmem_device.py:85)."""
    if isinstance(axis, str):
        return lax.axis_index(axis)
    pid = lax.axis_index(axis[0])
    for name in axis[1:]:
        pid = pid * lax.axis_size(name) + lax.axis_index(name)
    return pid


def n_pes(axis: str | Sequence[str]):
    """Number of PEs along ``axis``. Analog of ``nvshmem_n_pes``."""
    if isinstance(axis, str):
        return lax.axis_size(axis)
    n = 1
    for name in axis:
        n = n * lax.axis_size(name)
    return n


def pe_at(axis_names: Sequence[str], axis: str, index):
    """Flat LOGICAL device id of the device whose coordinate along ``axis``
    is ``index`` and whose other mesh coordinates equal ours.

    ``pltpu.make_async_remote_copy`` addresses peers by *flat* logical id
    over the whole mesh (row-major over ``axis_names``); this computes it —
    the role ``nvshmem_ptr``/``symm_at`` pointer translation plays on GPU
    (reference DistributedOps.td:135-149) without any pointer math.
    """
    pid = 0
    for name in axis_names:
        coord = index if name == axis else lax.axis_index(name)
        pid = pid * lax.axis_size(name) + coord
    return pid


def pe_at_group(mesh_axes: Sequence[str], group_axes: Sequence[str], index):
    """Flat LOGICAL device id of the device at flattened coordinate ``index``
    over ``group_axes`` (major-to-minor), other mesh coordinates equal ours.
    Generalizes ``pe_at`` to a multi-axis PE group — the addressing the
    hierarchical kernels use for their inner (fast-tier) group."""
    if isinstance(group_axes, str):
        group_axes = (group_axes,)
    rem = index
    coords = {}
    for name in reversed(tuple(group_axes)):
        sz = lax.axis_size(name)
        coords[name] = lax.rem(rem, sz)
        rem = rem // sz
    pid = 0
    for name in mesh_axes:
        coord = coords.get(name, lax.axis_index(name))
        pid = pid * lax.axis_size(name) + coord
    return pid


# -- one-sided puts ---------------------------------------------------------

def putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, pe,):
    """Non-blocking one-sided put: copy ``src_ref`` (local) into ``dst_ref``
    on device ``pe`` (flat logical id). Returns the DMA descriptor; call
    ``.wait_send()`` (quiet) locally, receiver waits ``recv_sem``.

    Analog of ``libshmem_device.putmem_nbi_block``
    (libnvshmem_device.py put family; docs/primitives.md:22-56). The
    receiving device's ``recv_sem`` (same scratch slot) is signaled by the
    DMA engine when the data has fully landed — this gives the
    "putmem_signal" delivery guarantee for free.

    An active FaultPlan with ``device_peer_dead`` swallows the put: the
    DMA never starts, the returned descriptor is already "complete" at
    source, and nothing ever arrives at the peer — the consumer's
    ``wait_recv`` hangs exactly like a dead link would (host-side
    deadlines are what bound that hang; see docs/robustness.md).
    """
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, pe)
    plan = faults.active_plan()
    if plan is not None and plan.device_peer_dead:
        return _COMPLETED_DMA
    producer_noise(src_ref)
    rdma = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=pe,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    if _serial():
        rdma.wait_send()
        return _COMPLETED_DMA
    return rdma


def putmem_block(dst_ref, src_ref, send_sem, recv_sem, pe):
    """Blocking-at-source put: start + wait local send completion.
    (Remote delivery is still signaled via ``recv_sem``.)"""
    rdma = putmem_nbi(dst_ref, src_ref, send_sem, recv_sem, pe)
    rdma.wait_send()
    return rdma


# -- signals ----------------------------------------------------------------

def signal_op(sem_ref, inc, pe=None):
    """Atomically add ``inc`` to (possibly remote) semaphore. Analog of
    ``libshmem_device.signal_op(..., NVSHMEM_SIGNAL_ADD)``
    (low_latency_all_to_all.py:96-117 uses the SET form with call_count;
    on TPU the counting form is native and protocols count arrivals).

    An active FaultPlan may drop the signal (nothing emitted — the
    consumer's counted wait starves) or duplicate it (doubled increment —
    the over-signal poison the ledger layer must detect)."""
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.signal_op(sem_ref, inc, pe)
    plan = faults.active_plan()
    if plan is not None:
        inc = plan.device_signal_inc(inc)
        if inc is None:
            return
    if pe is None:
        pltpu.semaphore_signal(sem_ref, inc=inc)
    else:
        pltpu.semaphore_signal(sem_ref, inc=inc, device_id=pe,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)


def signal_wait_until(sem_ref, value):
    """Block until the (REGULAR/barrier) semaphore has accumulated ``value``,
    then *consume* it (TPU semaphores decrement on wait — unlike NVSHMEM's
    ``signal_wait_until`` which leaves the flag set; protocols in ``ops/``
    are designed around consumption). DMA delivery waits use ``wait_recv``.
    """
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.signal_wait_until(sem_ref, value)
    pltpu.semaphore_wait(sem_ref, value)


def wait_recv(dst_ref, recv_sem):
    """Wait for delivery of a put into ``dst_ref`` tracked by ``recv_sem``
    (a DMA semaphore). DMA semaphores count transferred bytes, so the wait
    is phrased through a descriptor of the expected shape — the standard
    same-ref trick."""
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.wait_recv(dst_ref, recv_sem)
    pltpu.make_async_copy(dst_ref, dst_ref, recv_sem).wait()


def signal_read(sem_ref):
    """Non-destructive read of the semaphore count (debug/poll).

    ``semaphore_read`` moved from ``pltpu`` to ``pl`` across jax releases;
    resolve whichever this jax exposes."""
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.signal_read(sem_ref)
    read = getattr(pl, "semaphore_read", None) or getattr(
        pltpu, "semaphore_read", None)
    if read is None:
        raise NotImplementedError(
            "neither pl.semaphore_read nor pltpu.semaphore_read exists on "
            f"jax {jax.__version__}")
    return read(sem_ref)


# -- ordering ---------------------------------------------------------------

def quiet(*rdmas):
    """Wait until our outstanding puts have left this device (local send
    completion). Analog of ``libshmem_device.quiet``."""
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.quiet(*rdmas)
    for r in rdmas:
        r.wait_send()


def fence():
    """Analog of ``libshmem_device.fence`` (ordering of puts to the same PE).
    TPU remote DMAs carry their own completion semaphores; ordering is
    expressed by waiting those, so ``fence`` is a no-op kept for API parity.
    """
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.fence()
    return None


# -- barriers ---------------------------------------------------------------

def barrier_all(axis_names: Sequence[str], mesh_axes: Sequence[str] | None = None):
    """Barrier across the devices spanned by ``axis_names`` inside a kernel:
    signal every other participant's barrier semaphore, wait for n-1
    arrivals. Analog of ``libshmem_device.barrier_all`` /
    ``barrier_all_intra_node_*`` (reference kernels/nvidia/common_ops.py:88-159).

    ``mesh_axes`` is the full, ordered axis-name tuple of the enclosing mesh;
    it is required when ``axis_names`` is a *subset* of a multi-axis mesh,
    because LOGICAL device ids are flat over the whole mesh (devices outside
    the barrier group keep their own coordinates on the other axes).

    The enclosing ``pallas_call`` must set
    ``compiler_params=pltpu.CompilerParams(collective_id=...)``.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    mesh_axes = tuple(mesh_axes) if mesh_axes is not None else tuple(axis_names)
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.barrier_all(axis_names, mesh_axes)
    sem = pltpu.get_barrier_semaphore()
    npes = n_pes(axis_names)
    me = my_pe(axis_names)

    def body(i, carry):
        pid = pe_at_group(mesh_axes, axis_names, i)

        @pl.when(i != me)
        def _():
            pltpu.semaphore_signal(sem, inc=1, device_id=pid,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        return carry

    lax.fori_loop(0, npes, body, 0)
    pltpu.semaphore_wait(sem, npes - 1)


def barrier_pair(axis_names: Sequence[str], peer):
    """Two-device barrier with flat-id ``peer`` (ring neighbors etc.)."""
    tracer = trace.active_tracer()
    if tracer is not None:
        return tracer.barrier_pair(axis_names, peer)
    sem = pltpu.get_barrier_semaphore()
    pltpu.semaphore_signal(sem, inc=1, device_id=peer,
                           device_id_type=pltpu.DeviceIdType.LOGICAL)
    pltpu.semaphore_wait(sem, 1)
