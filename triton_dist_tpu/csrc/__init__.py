"""ctypes bindings for the native host ops in ``csrc/`` (analog of reference
csrc's pybind module ``libtriton_distributed`` → ``distributed.*`` ops,
op_pybind.cc:34-48 — here a C ABI + ctypes, no pybind11 in the image).

The library builds lazily on first import (g++ is in the base image); set
``TDT_NO_NATIVE=1`` to skip the native path entirely (pure-jnp fallbacks in
ops.group_gemm keep everything functional).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
_SO = os.path.join(_HERE, "_build", "libtdt_host.so")
_SRC = os.path.join(_REPO, "csrc")

_lib = None


def _build() -> None:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    srcs = [os.path.join(_SRC, "moe_align.cc"),
            os.path.join(_SRC, "a2a_route.cc")]
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-Wall",
           *srcs, "-o", _SO]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def get_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it if needed; None when disabled
    or the toolchain is unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    if os.environ.get("TDT_NO_NATIVE") == "1":
        return None
    try:
        if not os.path.exists(_SO) or any(
                os.path.getmtime(s) > os.path.getmtime(_SO)
                for s in [os.path.join(_SRC, "moe_align.cc"),
                          os.path.join(_SRC, "a2a_route.cc")]):
            _build()
        lib = ctypes.CDLL(_SO)
    except (OSError, subprocess.CalledProcessError):
        return None
    lib.tdt_moe_align_padded_rows.restype = ctypes.c_int64
    lib.tdt_moe_align_padded_rows.argtypes = [
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32]
    lib.tdt_moe_align_block_size.restype = ctypes.c_int32
    lib.tdt_moe_align_block_size.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32)]
    lib.tdt_a2a_slot_assign.restype = ctypes.c_int32
    lib.tdt_a2a_slot_assign.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8)]
    lib.tdt_a2a_bincount.restype = ctypes.c_int32
    lib.tdt_a2a_bincount.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return _lib


def moe_align_block_size(ids: np.ndarray, num_experts: int, block_m: int):
    """Native host-side twin of ops.group_gemm.align_tokens_by_expert:
    returns (gather_idx [P] i32, row_valid [P] bool, block_expert [P/bm] i32)
    for a host routing table — no device round-trip."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable "
                           "(TDT_NO_NATIVE=1 or no toolchain)")
    ids = np.ascontiguousarray(ids, dtype=np.int32)
    T = ids.shape[0]
    P = lib.tdt_moe_align_padded_rows(T, num_experts, block_m)
    gather_idx = np.zeros(P, np.int32)
    row_valid = np.zeros(P, np.uint8)
    block_expert = np.zeros(P // block_m, np.int32)
    rc = lib.tdt_moe_align_block_size(
        ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), T, num_experts,
        block_m,
        gather_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        row_valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        block_expert.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert rc == 0, f"tdt_moe_align_block_size failed: rc={rc}"
    return gather_idx, row_valid.astype(bool), block_expert


def a2a_slot_assign(dest: np.ndarray, n_dst: int, cap: int,
                    valid: np.ndarray | None = None
                    ) -> tuple[np.ndarray, np.ndarray]:
    """Host-native slot allocation (contract-identical to
    ops.all_to_all._slot_assign; cross-tested). Returns (slot, ok)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable "
                           "(TDT_NO_NATIVE=1 or no toolchain)")
    dest = np.ascontiguousarray(dest, dtype=np.int32)
    R = dest.shape[0]
    slot = np.zeros(R, np.int32)
    ok = np.zeros(R, np.uint8)
    vptr = None
    if valid is not None:
        valid = np.ascontiguousarray(valid, dtype=np.uint8)
        vptr = valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    rc = lib.tdt_a2a_slot_assign(
        dest.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), R, n_dst, cap,
        vptr, slot.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    assert rc == 0, f"tdt_a2a_slot_assign failed: rc={rc}"
    return slot, ok.astype(bool)


def a2a_bincount(dest: np.ndarray, n_dst: int) -> np.ndarray:
    """Host-native per-destination token counts (the wire `splits`)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable "
                           "(TDT_NO_NATIVE=1 or no toolchain)")
    dest = np.ascontiguousarray(dest, dtype=np.int32)
    counts = np.zeros(n_dst, np.int32)
    rc = lib.tdt_a2a_bincount(
        dest.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), dest.shape[0],
        n_dst, counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    assert rc == 0, f"tdt_a2a_bincount failed: rc={rc}"
    return counts


__all__ = ["get_lib", "moe_align_block_size", "a2a_slot_assign",
           "a2a_bincount"]


def native_or_none(fname: str, *args, **kw):
    """Named once: the host-routing-table dispatch pattern. Calls the
    native twin ``fname`` and returns its result, or None when the native
    library is unavailable (TDT_NO_NATIVE=1 / no toolchain) so the caller
    falls back to its jnp twin. Keeps the fallback policy in one place
    (a future "warn when native is missing" change lands here only)."""
    try:
        return globals()[fname](*args, **kw)
    except RuntimeError:
        return None
