"""Ring attention (training-time context parallelism) vs dense goldens.

The reference scales only decode-time sequence length (SURVEY §5.7); ring
attention generalizes its lse-merge combine to training. Forward golden:
dense softmax attention over the gathered sequence; gradient golden:
jax.grad of the dense computation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.ring_attention import (ring_attention,
                                                 ring_attention_fwd)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def _dense(q, k, v, causal, scale):
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    Hq, Hkv = q.shape[1], k.shape[1]
    kf = jnp.repeat(kf, Hq // Hkv, axis=1)
    vf = jnp.repeat(vf, Hq // Hkv, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if causal:
        S = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s,
                      -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), vf)


def _rand_qkv(n, B=1, Hq=4, Hkv=2, D=128, s_loc=128, key=0):
    S = n * s_loc
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (B, Hq, S, D), jnp.float32) * 0.5
    k = jax.random.normal(ks[1], (B, Hkv, S, D), jnp.float32) * 0.5
    v = jax.random.normal(ks[2], (B, Hkv, S, D), jnp.float32) * 0.5
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_fwd(ctx, causal):
    n = ctx.num_ranks
    q, k, v = _rand_qkv(n)
    spec = P(None, None, "x")
    out = jax.jit(lambda a, b, c: ring_attention(
        ctx, a, b, c, axis="x", causal=causal, block_q=64, block_k=64))(
        ctx.shard(q, spec), ctx.shard(k, spec), ctx.shard(v, spec))
    gold = _dense(q, k, v, causal, 1.0 / np.sqrt(q.shape[-1]))
    assert_allclose(np.asarray(out), np.asarray(gold), atol=2e-3, rtol=2e-3)


def test_ring_attention_mha_uneven_tiles(ctx):
    """MHA (Hq == Hkv) with block sizes that do not divide 512."""
    n = ctx.num_ranks
    q, k, v = _rand_qkv(n, Hq=2, Hkv=2, s_loc=96, key=7)
    spec = P(None, None, "x")
    out = jax.jit(lambda a, b, c: ring_attention(
        ctx, a, b, c, axis="x", causal=True, block_q=32, block_k=96))(
        ctx.shard(q, spec), ctx.shard(k, spec), ctx.shard(v, spec))
    gold = _dense(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))
    assert_allclose(np.asarray(out), np.asarray(gold), atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_grad(ctx, causal):
    n = ctx.num_ranks
    q, k, v = _rand_qkv(n, s_loc=64, key=3)
    scale = 1.0 / np.sqrt(q.shape[-1])
    tgt = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)
    spec = P(None, None, "x")
    qs, ks, vs = (ctx.shard(x, spec) for x in (q, k, v))

    def loss_ring(a, b, c):
        o = ring_attention(ctx, a, b, c, axis="x", causal=causal,
                           block_q=64, block_k=64)
        return jnp.sum((o.astype(jnp.float32) - tgt) ** 2)

    def loss_dense(a, b, c):
        return jnp.sum((_dense(a, b, c, causal, scale) - tgt) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        assert_allclose(np.asarray(got), np.asarray(want), atol=5e-3,
                        rtol=5e-3)


def test_ring_attention_repeated_calls(ctx):
    """Back-to-back calls reuse comm slots/semaphores — the entry barrier
    must protect cross-call delivery (cf. test_ag_gemm_repeated_calls)."""
    n = ctx.num_ranks
    spec = P(None, None, "x")
    f = jax.jit(lambda a, b, c: ring_attention(
        ctx, a, b, c, axis="x", causal=True, block_q=64, block_k=64))
    for i in range(3):
        q, k, v = _rand_qkv(n, s_loc=64, key=20 + i)
        out = f(ctx.shard(q, spec), ctx.shard(k, spec), ctx.shard(v, spec))
        gold = _dense(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))
        assert_allclose(np.asarray(out), np.asarray(gold), atol=2e-3,
                        rtol=2e-3)


def test_ring_attention_zigzag(ctx):
    """Load-balanced zigzag layout == dense golden after un-permuting
    (device r holds chunks (r, 2n-1-r); every rank computes exactly two
    chunk-pairs per causal step)."""
    from triton_dist_tpu.ops.ring_attention import zigzag_indices
    n = ctx.num_ranks
    q, k, v = _rand_qkv(n, s_loc=64, key=31)
    S = q.shape[2]
    idx, inv = zigzag_indices(S, n)
    spec = P(None, None, "x")
    qz, kz, vz = (ctx.shard(x[:, :, idx], spec) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(
        ctx, a, b, c, axis="x", causal=True, layout="zigzag",
        block_q=32, block_k=32))(qz, kz, vz)
    gold = _dense(q, k, v, True, 1.0 / np.sqrt(q.shape[-1]))
    assert_allclose(np.asarray(out)[:, :, inv], np.asarray(gold),
                    atol=2e-3, rtol=2e-3)


def test_ring_attention_zigzag_grad(ctx):
    from triton_dist_tpu.ops.ring_attention import zigzag_indices
    n = ctx.num_ranks
    q, k, v = _rand_qkv(n, s_loc=64, key=33)
    S = q.shape[2]
    scale = 1.0 / np.sqrt(q.shape[-1])
    idx, inv = zigzag_indices(S, n)
    tgt = jax.random.normal(jax.random.key(9), q.shape, jnp.float32)
    spec = P(None, None, "x")
    qz, kz, vz = (ctx.shard(x[:, :, idx], spec) for x in (q, k, v))

    def loss_ring(a, b, c):
        o = ring_attention(ctx, a, b, c, axis="x", causal=True,
                           layout="zigzag", block_q=32, block_k=32)
        return jnp.sum((o.astype(jnp.float32) - tgt[:, :, idx]) ** 2)

    def loss_dense(a, b, c):
        return jnp.sum((_dense(a, b, c, True, scale) - tgt) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qz, kz, vz)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for got, want in zip(g_ring, g_dense):
        assert_allclose(np.asarray(got)[:, :, inv], np.asarray(want),
                        atol=5e-3, rtol=5e-3)


def test_single_chip_causal_flat_walk():
    """n=1 causal contiguous takes the flat valid-tile walk (SMEM tile
    maps; fully-masked tiles never become grid steps) — must match the
    dense causal golden exactly in interpret mode, for tile shapes where
    the triangle is ragged (bq != bk)."""
    import math
    ctx1 = initialize_distributed(axis_names=("x",), mesh_shape=(1,))
    B, Hq, Hkv, S, D = 1, 4, 2, 512, 128
    q = jax.random.normal(jax.random.key(0), (B, Hq, S, D), jnp.float32) * 0.5
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32) * 0.5
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32) * 0.5
    for bq, bk in ((128, 128), (256, 128), (128, 256)):
        out, lse = ring_attention_fwd(ctx1, q, k, v, axis="x", causal=True,
                                      block_q=bq, block_k=bk)
        g = Hq // Hkv
        kf = np.repeat(np.asarray(k), g, 1)
        vf = np.repeat(np.asarray(v), g, 1)
        s = np.einsum("bhqd,bhkd->bhqk", np.asarray(q), kf) / math.sqrt(D)
        s = np.where(np.tril(np.ones((S, S), bool)), s, -1e30)
        m = s.max(-1, keepdims=True)
        p = np.exp(s - m)
        l = p.sum(-1, keepdims=True)
        gold = np.einsum("bhqk,bhkd->bhqd", p / l, vf)
        gold_lse = (m + np.log(l))[..., 0]
        assert_allclose(np.asarray(out), gold, atol=2e-3, rtol=2e-3)
        assert_allclose(np.asarray(lse), gold_lse, atol=2e-3, rtol=2e-3)
