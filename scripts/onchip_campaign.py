"""Round-4 on-chip campaign — ONE command for the moment the tunnel heals.

The entire round-3/4 perf story is code-complete but unmeasured (the
device tunnel has been wedged since round 2's killed dispatch_2d run).
This script executes the full measurement campaign in order, each step a
subprocess with its own timeout, logging everything under
``docs/onchip_r4/`` so the results survive a mid-campaign wedge:

    python scripts/onchip_campaign.py             # everything
    python scripts/onchip_campaign.py bench sweep # specific steps

Steps (in order; later steps run even if an earlier one fails, EXCEPT
that everything stops if the preflight finds the tunnel wedged):

    bench       python bench.py — headline AG-GEMM + a2a/decode/attn/moe
                extras incl. the fp8 wire model (VERDICT r4 #1/#6)
    a2a         python bench.py a2a — the DeepEP-comparison line
    sweep       python bench.py --sweep — six model shapes
    attn_sweep  python bench.py --attn-sweep — ring-attention tiles after
                the dtype-preserving matmul change (VERDICT r4 #7)
    bisect      scripts/bisect_a2a_onchip.py — serial twins first,
                client-side compile, narrows the dispatch_2d hang
                (VERDICT r4 #2)

ORDER MATTERS: the bench/sweep steps exercise only the 1-axis kernels
that already ran clean on-chip in round 2 — they are banked FIRST. The
bisect's 2-tier dispatch graphs are the ones whose round-2 execution
wedged the device for >30 h; running them last means a re-wedge costs
the remaining bisect stages, not the scoreboard numbers. (The bisect
itself uses client-side compile + per-stage subprocess timeouts, so a
compile hang stays local — but execution-side wedges remain possible.)

After a full green run: paste the numbers into docs/benchmarks.md
(replace every "awaiting re-measurement"), update the autotable in
ops/gemm.py::_MEASURED_BEST if a sweep winner moved, and commit.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(REPO, "docs", "onchip_r4")

STEPS = [
    # (name, argv, timeout_s) — safe 1-axis measurements first, the
    # wedge-risky 2-tier bisect LAST (see module docstring)
    ("bench", [sys.executable, os.path.join(REPO, "bench.py")], 3600),
    ("a2a", [sys.executable, os.path.join(REPO, "bench.py"), "a2a"], 3600),
    ("sweep", [sys.executable, os.path.join(REPO, "bench.py"),
               "--sweep"], 5400),
    ("attn_sweep", [sys.executable, os.path.join(REPO, "bench.py"),
                    "--attn-sweep"], 5400),
    ("bisect", [sys.executable, os.path.join(REPO, "scripts",
                                             "bisect_a2a_onchip.py")], 7200),
]


def preflight(timeout_s: int = 240) -> bool:
    """Reachable AND an accelerator: a CPU-fallback backend would run the
    whole campaign in interpret smoke mode and stamp simulator numbers
    'ALL GREEN' — that must read as unreachable here."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(len(d), d[0].platform); "
             "raise SystemExit(1 if d[0].platform == 'cpu' else 0)"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> int:
    want = set(sys.argv[1:])
    known = {name for name, _, _ in STEPS}
    unknown = want - known
    if unknown:
        print(f"unknown step(s) {sorted(unknown)}; choose from "
              f"{sorted(known)}", file=sys.stderr)
        return 2
    print("[campaign] preflight: backend reachability ...", flush=True)
    if not preflight():
        print("[campaign] BACKEND UNREACHABLE — tunnel still wedged; "
              "re-run when it heals.", flush=True)
        return 3
    print("[campaign] preflight OK", flush=True)
    os.makedirs(OUT_DIR, exist_ok=True)
    summary = {}
    for name, argv, timeout_s in STEPS:
        if want and name not in want:
            continue
        log_path = os.path.join(OUT_DIR, f"{name}.log")
        print(f"[campaign] {name} -> {log_path} ...", flush=True)
        t0 = time.time()
        try:
            with open(log_path, "w") as log:
                r = subprocess.run(argv, cwd=REPO, timeout=timeout_s,
                                   stdout=log, stderr=subprocess.STDOUT)
            rc = r.returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
        dt = time.time() - t0
        tail = ""
        try:
            with open(log_path) as f:
                lines = [ln.rstrip() for ln in f if ln.strip()]
            tail = lines[-1] if lines else ""
        except OSError:
            pass
        summary[name] = {"rc": rc, "secs": round(dt, 1), "tail": tail[:400]}
        print(f"[campaign] {name}: rc={rc} in {dt:.0f}s", flush=True)
        # a bench/bisect failure is data, not a reason to skip the rest —
        # but if the tunnel wedged mid-campaign, everything after would
        # just burn its timeout in backend discovery
        if rc != 0 and not preflight(120):
            print("[campaign] tunnel wedged mid-campaign; stopping.",
                  flush=True)
            break
    # merge into any prior summary so a subset rerun (e.g. after a
    # mid-campaign wedge) doesn't clobber the earlier steps' record
    summary_path = os.path.join(OUT_DIR, "summary.json")
    merged = {}
    try:
        with open(summary_path) as f:
            merged = json.load(f)
    except (OSError, ValueError):
        pass
    merged.update(summary)
    with open(summary_path, "w") as f:
        json.dump(merged, f, indent=2)
    print("\n=== campaign summary ===")
    for k, v in summary.items():
        print(f"{k:11s} rc={v['rc']} {v['secs']}s  {v['tail'][:120]}")
    ok = summary and all(v["rc"] == 0 for v in summary.values())
    print(f"\nartifacts: {OUT_DIR}/  " +
          ("ALL GREEN — update docs/benchmarks.md and commit."
           if ok else "some steps failed; see logs."))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
