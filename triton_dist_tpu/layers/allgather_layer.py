"""AllGather module layer (analog of reference
layers/nvidia/low_latency_allgather_layer.py:31-195 — a stage-buffered
wrapper exposing one ``forward_*`` per AG algorithm)."""

from __future__ import annotations

import dataclasses

import jax

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.shmem.context import ShmemContext


@dataclasses.dataclass(frozen=True)
class AllGatherLayer:
    """Method-per-algorithm wrapper. The reference stages inputs into
    persistent symmetric buffers keyed by a rotating stage index
    (low_latency_allgather_layer.py:44-62); jax allocates per-call output
    buffers, so no stage bookkeeping is needed."""
    ctx: ShmemContext
    axis: str | None = None

    def forward_push(self, x: jax.Array) -> jax.Array:
        """Full-mesh one-hop push (≈ forward_pull/push 1-stage variants)."""
        return all_gather(self.ctx, x, axis=self.axis, method="push")

    def forward_ring(self, x: jax.Array) -> jax.Array:
        """1-D bandwidth-optimal ring (≈ forward_push_2d)."""
        return all_gather(self.ctx, x, axis=self.axis, method="ring")

    def forward_ring_2d(self, x: jax.Array) -> jax.Array:
        """Hierarchical 2-D ring for multi-axis meshes (≈ forward_push_numa_2d
        / the multinode variants) — bandwidth-oriented."""
        return all_gather(self.ctx, x, method="ring_2d")

    def forward_push_2d(self, x: jax.Array) -> jax.Array:
        """Single-kernel hierarchical push (outer same-inner-index relay +
        inner push) — the latency-oriented multi-axis path
        (≈ forward_push_2d/push_3d, low_latency_allgather_layer.py:63-125)."""
        return all_gather(self.ctx, x, method="push_2d")

    def __call__(self, x: jax.Array) -> jax.Array:
        return all_gather(self.ctx, x, axis=self.axis, method="auto")
