"""Multi-tier (DCN-story) collectives on a 2-axis mesh.

Parity targets: the reference's 2-D hierarchical reduce-scatter
(reduce_scatter.py:430-785) and 2-tier EP A2A dispatch/combine
(ep_a2a.py:35-147). The (2, 3) asymmetric mesh catches major/minor swaps,
matching test_all_gather_2d."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import reduce_scatter
from triton_dist_tpu.ops.all_to_all import (combine_2d,
                                            create_all_to_all_context_2d,
                                            dispatch_2d)
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx2d():
    return initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))


def test_reduce_scatter_2d(ctx2d):
    n = 6
    M = 24  # per-device contribution rows; 24 % 6 == 0
    x = jnp.round(jax.random.normal(jax.random.key(0), (n * M, 128)) * 4)
    xs = ctx2d.shard(x.astype(jnp.float32), P(("a", "b")))
    y = jax.jit(lambda v: reduce_scatter(ctx2d, v))(xs)

    def g(shard):
        return jax.lax.psum_scatter(shard, ("a", "b"), scatter_dimension=0,
                                    tiled=True)
    golden = jax.jit(ctx2d.shard_map(g, in_specs=P(("a", "b")),
                                     out_specs=P(("a", "b"))))(xs)
    assert_allclose(np.asarray(y), np.asarray(golden))


def test_reduce_scatter_2d_repeated(ctx2d):
    f = jax.jit(lambda v: reduce_scatter(ctx2d, v, method="ring_2d"))
    g = jax.jit(ctx2d.shard_map(
        lambda s: jax.lax.psum_scatter(s, ("a", "b"), scatter_dimension=0,
                                       tiled=True),
        in_specs=P(("a", "b")), out_specs=P(("a", "b"))))
    for it in range(3):
        x = jnp.round(jax.random.normal(jax.random.key(it), (6 * 12, 128)) * 4)
        xs = ctx2d.shard(x.astype(jnp.float32), P(("a", "b")))
        assert_allclose(np.asarray(f(xs)), np.asarray(g(xs)))


def _dense_moe_golden(tokens, ids, w, scale):
    """Expert e multiplies a token by scale[e]; topk-weighted sum."""
    t = np.asarray(tokens, np.float32)
    out = np.zeros_like(t)
    idn, wn = np.asarray(ids), np.asarray(w, np.float32)
    for i in range(t.shape[0]):
        acc = 0.0
        for j in range(idn.shape[1]):
            acc = acc + wn[i, j] * (t[i] * scale[idn[i, j]])
        out[i] = acc
    return out


@pytest.mark.quick
def test_dispatch_combine_2d_roundtrip(ctx2d):
    """Full 2-tier dispatch → per-expert scaling → combine vs dense golden."""
    n, T, H, topk = 6, 8, 128, 2
    E = 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.float32)
    epr = E // n
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, topk), 0, E)
    w = jax.nn.softmax(jax.random.normal(jax.random.key(2), (n * T, topk)), -1)
    scale = np.linspace(0.5, 2.0, E).astype(np.float32)
    scale_j = jnp.asarray(scale)

    def run(t, i, ww):
        recv, recv_ids, layouts = dispatch_2d(a2a, t, i)

        def process(r_shard, id_shard):
            me0 = jax.lax.axis_index("a")
            me1 = jax.lax.axis_index("b")
            rank = me0 * a2a.n_minor + me1
            gid = jnp.where(id_shard >= 0, rank * epr + id_shard, 0)
            s = jnp.take(scale_j, gid)
            s = jnp.where(id_shard >= 0, s, 0.0)
            return r_shard * s[..., None]

        both = P(("a", "b"))
        proc = ctx2d.shard_map(process, in_specs=(both, both),
                               out_specs=both)(recv, recv_ids)
        return combine_2d(a2a, proc, layouts, ww)

    out = jax.jit(run)(ctx2d.shard(tokens, P(("a", "b"))),
                       ctx2d.shard(ids, P(("a", "b"))),
                       ctx2d.shard(w, P(("a", "b"))))
    golden = _dense_moe_golden(tokens, ids, w, scale)
    assert_allclose(np.asarray(out, np.float32), golden, rtol=2e-2,
                    atol=2e-2)


def test_dispatch_2d_placement(ctx2d):
    """Every routed (token, k) pair lands exactly once on its expert's rank
    with the right local expert id."""
    n, T, H, topk, E = 6, 4, 128, 2, 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.float32)
    epr = E // n
    # token value encodes (rank, t) so placement is checkable
    tokens = jnp.arange(n * T, dtype=jnp.float32)[:, None] * jnp.ones((1, H))
    ids = jax.random.randint(jax.random.key(3), (n * T, topk), 0, E)
    recv, recv_ids = jax.jit(lambda t, i: dispatch_2d(a2a, t, i)[:2])(
        ctx2d.shard(tokens, P(("a", "b"))), ctx2d.shard(ids, P(("a", "b"))))

    recv_n = np.asarray(recv)      # [n * n_minor, cap2, H]
    ids_n = np.asarray(recv_ids)   # [n * n_minor, cap2]
    nm, cap2 = a2a.n_minor, a2a.cap2
    recv_n = recv_n.reshape(n, nm, cap2, H)
    ids_n = ids_n.reshape(n, nm, cap2)
    got = []  # (expert_rank, local_eid, token_value)
    for r in range(n):
        for src in range(nm):
            for c in range(cap2):
                if ids_n[r, src, c] >= 0:
                    got.append((r, int(ids_n[r, src, c]),
                                float(recv_n[r, src, c, 0])))
    expect = []
    idn = np.asarray(ids)
    for row in range(n * T):
        for j in range(topk):
            e = int(idn[row, j])
            expect.append((e // epr, e % epr, float(row)))
    assert sorted(got) == sorted(expect)


@pytest.fixture(scope="module")
def ctx3d():
    return initialize_distributed(axis_names=("a", "b", "c"),
                                  mesh_shape=(2, 2, 2))


def test_all_gather_3d(ctx3d):
    """3-tier hierarchical AG on a (2,2,2) mesh (slice, torus-y, torus-x)."""
    from triton_dist_tpu.ops import all_gather
    x = jnp.arange(8 * 8 * 128, dtype=jnp.float32).reshape(8 * 8, 128)
    xs = ctx3d.shard(x, P(("a", "b", "c")))
    y = jax.jit(lambda v: all_gather(ctx3d, v, method="ring_2d"))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))


def test_reduce_scatter_3d(ctx3d):
    x = jnp.round(jax.random.normal(jax.random.key(5), (8 * 16, 128)) * 4)
    xs = ctx3d.shard(x.astype(jnp.float32), P(("a", "b", "c")))
    got = jax.jit(lambda v: reduce_scatter(ctx3d, v))(xs)
    gold = jax.jit(ctx3d.shard_map(
        lambda s: jax.lax.psum_scatter(s, ("a", "b", "c"),
                                       scatter_dimension=0, tiled=True),
        in_specs=P(("a", "b", "c")), out_specs=P(("a", "b", "c"))))(xs)
    assert_allclose(np.asarray(got), np.asarray(gold))


# -- hierarchical overlap ops (inter-node AG-GEMM / GEMM-RS analogs) --------

def _ag_gemm_golden(ctx, a, b, axes):
    def g(a_shard, b_shard):
        a_full = jax.lax.all_gather(a_shard, axes, axis=0, tiled=True)
        return jnp.dot(a_full, b_shard, preferred_element_type=jnp.float32)
    sm = ctx.shard_map(g, in_specs=(P(axes), P(None, axes)),
                       out_specs=P(None, axes))
    return jax.jit(sm)(a, b)


def test_ag_gemm_2d(ctx2d):
    """2-tier AG-GEMM on the (2,3) mesh vs all_gather+dot golden (parity:
    ag_gemm_inter_node, reference allgather_gemm.py:938-975)."""
    from triton_dist_tpu.ops.allgather_gemm import GemmConfig, ag_gemm
    n = 6
    axes = ("a", "b")
    M, K, N = n * 16, 128, n * 32
    a = ctx2d.shard(jax.random.normal(jax.random.key(0), (M, K)), P(axes))
    b = ctx2d.shard(jax.random.normal(jax.random.key(1), (K, N)),
                    P(None, axes))
    cfg = GemmConfig(block_m=16, block_n=32)
    c = jax.jit(lambda a, b: ag_gemm(ctx2d, a, b, axis=axes, cfg=cfg,
                                     out_dtype=jnp.float32))(a, b)
    assert_allclose(np.asarray(c), np.asarray(_ag_gemm_golden(ctx2d, a, b,
                                                              axes)),
                    atol=1e-4, rtol=1e-4)


def test_ag_gemm_2d_repeated_ws(ctx2d):
    """Persistent-workspace hierarchical AG-GEMM, repeated calls (entry
    barrier must protect slot/semaphore reuse across calls)."""
    from triton_dist_tpu.ops.allgather_gemm import (GemmConfig, ag_gemm_ws,
                                                    create_ag_gemm_workspace)
    n = 6
    axes = ("a", "b")
    M, K, N = n * 16, 128, n * 16
    cfg = GemmConfig(block_m=16, block_n=16)
    ws = create_ag_gemm_workspace(ctx2d, M // n, K, jnp.float32, axis=axes)
    f = jax.jit(lambda a, b, w: ag_gemm_ws(ctx2d, a, b, w, axis=axes,
                                           cfg=cfg))
    for i in range(3):
        a = ctx2d.shard(jax.random.normal(jax.random.key(i), (M, K)),
                        P(axes))
        b = ctx2d.shard(jax.random.normal(jax.random.key(100 + i), (K, N)),
                        P(None, axes))
        c, ws = f(a, b, ws)
        assert_allclose(np.asarray(c),
                        np.asarray(_ag_gemm_golden(ctx2d, a, b, axes)),
                        atol=1e-4, rtol=1e-4)


def test_gemm_rs_2d(ctx2d):
    """2-tier GEMM-RS on the (2,3) mesh vs dot+psum_scatter golden (parity:
    inter-node GEMM-RS, reference reduce_scatter.py:430-785)."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import GemmConfig, gemm_rs
    n = 6
    axes = ("a", "b")
    M, K, N = n * 16, n * 32, 64
    a = ctx2d.shard(jax.random.normal(jax.random.key(0), (M, K)),
                    P(None, axes))
    b = ctx2d.shard(jax.random.normal(jax.random.key(1), (K, N)),
                    P(axes, None))
    cfg = GemmConfig(block_m=16, block_n=32)
    c = jax.jit(lambda a, b: gemm_rs(ctx2d, a, b, axis=axes, cfg=cfg,
                                     out_dtype=jnp.float32))(a, b)

    def g(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, axes, scatter_dimension=0,
                                    tiled=True)
    golden = jax.jit(ctx2d.shard_map(g, in_specs=(P(None, axes),
                                                  P(axes, None)),
                                     out_specs=P(axes)))(a, b)
    assert_allclose(np.asarray(c), np.asarray(golden), atol=1e-4, rtol=1e-4)


def test_gemm_rs_2d_repeated(ctx2d):
    from triton_dist_tpu.ops.gemm_reduce_scatter import GemmConfig, gemm_rs
    n = 6
    axes = ("a", "b")
    M, K, N = n * 16, n * 16, 32
    cfg = GemmConfig(block_m=16, block_n=32)
    f = jax.jit(lambda a, b: gemm_rs(ctx2d, a, b, axis=axes, cfg=cfg))

    def g(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, axes, scatter_dimension=0,
                                    tiled=True)
    gold = jax.jit(ctx2d.shard_map(g, in_specs=(P(None, axes), P(axes, None)),
                                   out_specs=P(axes)))
    for i in range(3):
        a = ctx2d.shard(jax.random.normal(jax.random.key(i), (M, K),
                                          jnp.float32), P(None, axes))
        b = ctx2d.shard(jax.random.normal(jax.random.key(50 + i), (K, N),
                                          jnp.float32), P(axes, None))
        assert_allclose(np.asarray(f(a, b)), np.asarray(gold(a, b)),
                        atol=1e-4, rtol=1e-4)


def test_ag_moe_group_gemm_2d(ctx2d):
    """Hierarchical fused MoE AG+GroupGEMM on the (2,3) mesh (inter-node
    analog: allgather_group_gemm.py:171-228)."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    n, axes = 6, ("a", "b")
    T, H, E = n * 8, 128, 4
    Nw = n * 16
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    w = jax.random.normal(jax.random.key(2), (E, H, Nw), jnp.float32) * 0.2
    ts = ctx2d.shard(tokens, P(axes))
    ws = ctx2d.shard(w, P(None, None, axes))
    y = jax.jit(lambda t, w_: ag_moe_group_gemm(ctx2d, t, ids, w_,
                                                axis=axes, block_m=8)
                )(ts, ws)
    golden = np.stack([np.asarray(tokens)[i] @ np.asarray(w)[int(ids[i])]
                       for i in range(T)])
    assert_allclose(np.asarray(y), golden, atol=1e-3, rtol=1e-3)


def test_moe_reduce_rs_2d(ctx2d):
    """Hierarchical fused GroupGEMM+RS on the (2,3) mesh (inter-node
    analog: moe_reduce_rs.py:590-670)."""
    from triton_dist_tpu.ops.moe import moe_reduce_rs
    n, axes = 6, ("a", "b")
    T, topk, K, Nw, E = n * 4, 2, n * 32, 64, 4
    Tk = T * topk
    tokens = jax.random.normal(jax.random.key(0), (Tk, K), jnp.float32) * 0.3
    ids = jax.random.randint(jax.random.key(1), (Tk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    w = jax.random.normal(jax.random.key(3), (E, K, Nw), jnp.float32) * 0.2
    ts = ctx2d.shard(tokens, P(None, axes))
    wsh = ctx2d.shard(w, P(None, axes, None))
    y = jax.jit(lambda t, w_: moe_reduce_rs(ctx2d, t, ids, tw, w_,
                                            axis=axes, block_m=8))(ts, wsh)
    rows = np.stack([np.asarray(tokens)[i] @ np.asarray(w)[int(ids[i])]
                     for i in range(Tk)]).reshape(T, topk, Nw)
    golden = np.sum(rows * np.asarray(tw)[..., None], axis=1)
    assert_allclose(np.asarray(y), golden, atol=1e-3, rtol=1e-3)


def test_gemm_rs_2d_repeated_ws(ctx2d):
    """Persistent fast-tier workspace threaded through repeated 2-tier
    GEMM-RS calls (entry barrier protects reuse)."""
    from triton_dist_tpu.ops.gemm_reduce_scatter import (
        GemmConfig, create_gemm_rs_workspace, gemm_rs_ws)
    n, axes = 6, ("a", "b")
    M, K, N = n * 16, n * 16, 32
    cfg = GemmConfig(block_m=16, block_n=32)
    ws, stage = create_gemm_rs_workspace(ctx2d, M // n, N, jnp.float32,
                                         axis=axes)
    f = jax.jit(lambda a, b, w, s: gemm_rs_ws(ctx2d, a, b, w, s, axis=axes,
                                              cfg=cfg))

    def g(a_s, b_s):
        part = jnp.dot(a_s, b_s, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, axes, scatter_dimension=0,
                                    tiled=True)
    gold = jax.jit(ctx2d.shard_map(g, in_specs=(P(None, axes), P(axes, None)),
                                   out_specs=P(axes)))
    for i in range(3):
        a = ctx2d.shard(jax.random.normal(jax.random.key(i), (M, K),
                                          jnp.float32), P(None, axes))
        b = ctx2d.shard(jax.random.normal(jax.random.key(70 + i), (K, N),
                                          jnp.float32), P(axes, None))
        c, ws, stage = f(a, b, ws, stage)
        assert_allclose(np.asarray(c), np.asarray(gold(a, b)),
                        atol=1e-4, rtol=1e-4)


def test_moe_ep_overlap_2tier(ctx2d):
    """End-to-end MoE EP block over the hierarchical dispatch/combine
    (router → 2-tier A2A → grouped FFN on local experts → combine)."""
    from triton_dist_tpu.layers import EPAll2AllLayer
    from triton_dist_tpu.models.moe import moe_mlp_ep_overlap
    n, axes = 6, ("a", "b")
    T_local, D, F, k = 8, 128, 128, 2
    E = 2 * n
    T = n * T_local
    x = (jax.random.normal(jax.random.key(0), (T, D), jnp.float32)
         * 0.3).astype(jnp.bfloat16)
    router_w = jax.random.normal(jax.random.key(1), (D, E),
                                 jnp.float32) * 0.3
    mk = lambda key, s: (jax.random.normal(jax.random.key(key), s)
                         * 0.1).astype(jnp.bfloat16)
    wg, wu, wd = mk(2, (E, D, F)), mk(3, (E, D, F)), mk(4, (E, F, D))
    layer = EPAll2AllLayer.create(ctx2d, max_tokens=T_local, hidden=D,
                                  topk=k, num_experts=E, axis=axes)
    xs = ctx2d.shard(x, P(axes))
    got = jax.jit(lambda v: moe_mlp_ep_overlap(
        ctx2d, layer, v, router_w, wg, wu, wd))(xs)

    x32, wg32, wu32, wd32 = (a.astype(jnp.float32) for a in (x, wg, wu, wd))
    logits = x32 @ router_w
    gv, gi = jax.lax.top_k(jax.nn.softmax(logits, -1), k)
    gv = gv / jnp.sum(gv, -1, keepdims=True)
    h = jax.nn.silu(jnp.einsum("td,edf->tef", x32, wg32)) \
        * jnp.einsum("td,edf->tef", x32, wu32)
    ye = jnp.einsum("tef,efd->ted",
                    h.astype(jnp.bfloat16).astype(jnp.float32), wd32)
    sel = jnp.take_along_axis(ye, gi[..., None], axis=1)
    golden = jnp.sum(sel * gv[..., None], axis=1)
    assert_allclose(np.asarray(got, np.float32), np.asarray(golden),
                    atol=8e-2, rtol=8e-2)


def test_dispatch_combine_2d_fp8_roundtrip(ctx2d):
    """2-tier dispatch/combine on the quantized wire (int8 on the CPU sim;
    same protocol as fp8): quantize once at the edge, scales ride both
    tiers, dequant at the edges — the reference's inter-node fp8 showcase
    configuration (README.md:55) on the hierarchical path."""
    n, T, H, topk, E = 6, 8, 128, 2, 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.float32,
                                       wire_dtype=jnp.int8)
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, topk), 0, E)
    w = jnp.full((n * T, topk), 1.0 / topk)
    spec = P(("a", "b"))
    ts, is_, ws = (ctx2d.shard(t, spec) for t in (tokens, ids, w))
    recv_tok, recv_ids, layouts = dispatch_2d(a2a, ts, is_)
    # identity experts: combine returns each token (mean of k copies),
    # up to two int8 quantization round-trips
    out = combine_2d(a2a, recv_tok, layouts, ws)
    err = np.abs(np.asarray(out) - np.asarray(tokens))
    scale = np.abs(np.asarray(tokens)).max(axis=-1, keepdims=True)
    assert np.max(err / (scale + 1e-6)) < 0.03, np.max(err / (scale + 1e-6))


def test_dispatch_combine_2d_fp8_aligned_cap(ctx2d):
    """cap1=128 (⇒ cap2=256, both 128-aligned): tier 2 takes the IN-KERNEL
    per-arrival dequant, not the post-kernel fallback — the fused path must
    be numerically indistinguishable from it."""
    n, T, H, topk, E = 6, 8, 128, 2, 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       cap1=128, dtype=jnp.float32,
                                       wire_dtype=jnp.int8,
                                       dequant_edge="kernel")
    assert a2a.cap1 == 128 and a2a.cap2 % 128 == 0, (a2a.cap1, a2a.cap2)
    assert a2a._dequant_in_kernel()
    tokens = jax.random.normal(jax.random.key(4), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(5), (n * T, topk), 0, E)
    w = jnp.full((n * T, topk), 1.0 / topk)
    spec = P(("a", "b"))
    ts, is_, ws = (ctx2d.shard(t, spec) for t in (tokens, ids, w))
    recv_tok, recv_ids, layouts = dispatch_2d(a2a, ts, is_)
    out = combine_2d(a2a, recv_tok, layouts, ws)
    err = np.abs(np.asarray(out) - np.asarray(tokens))
    scale = np.abs(np.asarray(tokens)).max(axis=-1, keepdims=True)
    assert np.max(err / (scale + 1e-6)) < 0.03, np.max(err / (scale + 1e-6))


def test_dispatch_2d_quant_edge_parity(ctx2d):
    """"pre" (quantize source rows, gather wire-dtype) and "fused" (gather
    then quantize per slot) build bit-identical tier-1 wire buffers — the
    per-slot amax is the same reduction over the same row — so the 2-tier
    roundtrip must agree exactly between the two, and both must reproduce
    the tokens through identity experts up to quantization error."""
    n, T, H, topk, E = 6, 8, 128, 2, 12
    mk = lambda qe: create_all_to_all_context_2d(
        ctx2d, max_tokens=T, hidden=H, topk=topk, num_experts=E,
        dtype=jnp.float32, wire_dtype=jnp.int8, quant_edge=qe,
        dequant_edge="post")
    tokens = jax.random.normal(jax.random.key(7), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(8), (n * T, topk), 0, E)
    w = jnp.full((n * T, topk), 1.0 / topk)
    spec = P(("a", "b"))
    ts, is_, ws = (ctx2d.shard(t, spec) for t in (tokens, ids, w))

    outs = {}
    for qe in ("pre", "fused"):
        a2a = mk(qe)
        recv_tok, _, layouts = dispatch_2d(a2a, ts, is_)
        outs[qe] = np.asarray(combine_2d(a2a, recv_tok, layouts, ws))
    np.testing.assert_array_equal(outs["fused"], outs["pre"])
    err = np.abs(outs["pre"] - np.asarray(tokens))
    scale = np.abs(np.asarray(tokens)).max(axis=-1, keepdims=True)
    assert np.max(err / (scale + 1e-6)) < 0.03, np.max(err / (scale + 1e-6))


def test_dispatch_2d_expert_edge(ctx2d):
    """2-tier expert-edge protocol: dispatch_2d returns QuantTokens (the
    scale side-channel that rode both tiers), and applying the scale once
    reproduces the "post"-edge dequantized tokens exactly — same wire
    bits, same scales, one deferred multiply."""
    from triton_dist_tpu.ops.all_to_all import QuantTokens
    n, T, H, topk, E = 6, 8, 128, 2, 12
    mk = lambda de: create_all_to_all_context_2d(
        ctx2d, max_tokens=T, hidden=H, topk=topk, num_experts=E,
        dtype=jnp.float32, wire_dtype=jnp.int8, dequant_edge=de)
    tokens = jax.random.normal(jax.random.key(12), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(13), (n * T, topk), 0, E)
    spec = P(("a", "b"))
    ts, is_ = ctx2d.shard(tokens, spec), ctx2d.shard(ids, spec)

    qt, ids_e, lay_e = dispatch_2d(mk("expert"), ts, is_)
    assert isinstance(qt, QuantTokens)
    deq = np.asarray(qt.q, np.float32) * np.asarray(qt.scale)[..., None]
    post, ids_p, _ = dispatch_2d(mk("post"), ts, is_)
    np.testing.assert_allclose(deq, np.asarray(post, np.float32),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ids_e), np.asarray(ids_p))
