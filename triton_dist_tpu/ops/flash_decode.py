"""Distributed Flash-Decoding (analog of reference
python/triton_dist/kernels/nvidia/flash_decode.py + the SP layer
sp_flash_decode_layer.py).

Reference structure: per-rank split-KV GQA decode kernel (flash_decode.py
:129-280) + intra-rank combine (:392-480), then a low-latency allgather of
each rank's partial (out ‖ lse) and an inter-rank lse-weighted combine
(:481-566). Sequence parallelism = KV cache sharded over ranks
(SURVEY §5.7); batch=1 decode is the target.

TPU-native mapping:

- GPU split-KV exists to fill SMs with (batch × head × split) blocks. A TPU
  core runs its grid sequentially, so the *intra-rank* split is pointless —
  the kernel is a single-pass online-softmax walk over the local KV shard
  (the grid's S dimension pipelines KV blocks HBM→VMEM instead). The
  *inter-rank* split IS the SP sharding, and the partial-merge math
  (m/l/lse bookkeeping) is identical to the reference's combine kernels.
- lse rides the wire lane-broadcast ([…, 128]) so every DMA slice stays
  tiling-aligned.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather import all_gather
from triton_dist_tpu.ops.common import collective_id_for
from triton_dist_tpu.shmem import device as shd
from triton_dist_tpu.shmem.context import ShmemContext
from triton_dist_tpu.utils import default_interpret

NEG_INF = -1e30


def _online_softmax_body(s, kv_len, q_ref, k_ref, v_ref, out_ref, lse_ref,
                         acc, m_i, l_i, *, block_s: int, sm_scale: float,
                         n_kv_heads: int):
    """Shared grid-step body for the decode kernels: init at s==0, one
    online-softmax update per KV block, finalize (incl. lse) at the last
    step. All Hq query heads are processed per step as a [Hkv, G, ·] batched
    contraction (Mosaic needs the last-two block dims full/aligned, so heads
    are not split). Analog of kernel_gqa_fwd_batch_decode_split_kv
    (flash_decode.py:129-280) with the split-KV dimension replaced by
    sequential KV-block pipelining."""
    n_s = pl.num_programs(1)

    @pl.when(s == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_i[...] = jnp.full_like(m_i, NEG_INF)
        l_i[...] = jnp.zeros_like(l_i)

    @pl.when(s * block_s < kv_len)
    def _():
        Hq, D = acc.shape
        G = Hq // n_kv_heads
        # operands stay in the input dtype (f32 accumulate): upcasting
        # bf16 first would run the MXU at its slower f32 rate (see the
        # ring-attention pipeline note)
        q = q_ref[0].reshape(n_kv_heads, G, D)
        k = k_ref[0]                                 # [Hkv, block_s, D]
        v = v_ref[0]                                 # [Hkv, block_s, D]
        scores = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * sm_scale  # [Hkv, G, bs]
        scores = scores.reshape(Hq, block_s)
        pos = s * block_s + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(pos < kv_len, scores, NEG_INF)
        m_new = jnp.maximum(m_i[...], jnp.max(scores, axis=1, keepdims=True))
        alpha = jnp.exp(m_i[...] - m_new)
        p = jnp.exp(scores - m_new)                  # [Hq, block_s]
        l_i[...] = l_i[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.reshape(n_kv_heads, G, block_s).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).reshape(Hq, D)
        acc[...] = acc[...] * alpha + pv
        m_i[...] = m_new

    @pl.when(s == n_s - 1)
    def _():
        l_safe = jnp.where(l_i[...] > 0, l_i[...], 1.0)
        out_ref[0] = (acc[...] / l_safe).astype(out_ref.dtype)
        # lse = m + log(l); empty shard -> NEG_INF so combine ignores it
        lse = jnp.where(l_i[...] > 0, m_i[...] + jnp.log(l_safe), NEG_INF)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _decode_kernel(kv_len_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
                   acc, m_i, l_i, *, block_s: int, sm_scale: float,
                   n_kv_heads: int):
    """Grid (B, S//block_s) over a contiguous KV shard."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    _online_softmax_body(s, kv_len_ref[b], q_ref, k_ref, v_ref, out_ref,
                         lse_ref, acc, m_i, l_i, block_s=block_s,
                         sm_scale=sm_scale, n_kv_heads=n_kv_heads)


def _decode_paged_kernel(kv_len_ref, bt_ref, q_ref, k_ref, v_ref, out_ref,
                         lse_ref, acc, m_i, l_i, *, block_s: int,
                         sm_scale: float, n_kv_heads: int):
    """Grid (B, pages_per_seq) over a paged KV pool; ``bt_ref`` is the
    block table (scalar-prefetch — the index_map streams page
    ``bt[b, s]``). Analog of the reference's block_table-driven split-KV
    kernel (flash_decode.py:129-280 `page` indexing)."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    _online_softmax_body(s, kv_len_ref[b], q_ref, k_ref, v_ref, out_ref,
                         lse_ref, acc, m_i, l_i, block_s=block_s,
                         sm_scale=sm_scale, n_kv_heads=n_kv_heads)


def gqa_decode_partial(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                       kv_len: jax.Array, block_s: int = 128,
                       sm_scale: float | None = None):
    """Single-device split-KV decode over a (possibly partial) KV shard.
    q [B, Hq, D]; k_cache/v_cache [B, Hkv, S, D] (head-major layout so KV
    blocks are tiling-aligned DMA slices); kv_len [B] valid keys. Returns
    (out [B, Hq, D] in q.dtype, lse [B, Hq, 128] f32 lane-broadcast).
    Entry analog: gqa_fwd_batch_decode_intra_rank (flash_decode.py:847-930).
    """
    B, Hq, D = q.shape
    _, Hkv, S, _ = k_cache.shape
    assert Hq % Hkv == 0
    block_s = min(block_s, S)
    if S % block_s != 0:
        # fall back to the largest common divisor so ragged shard lengths
        # (e.g. S=192 with block_s=128) still work; kv_len masking handles
        # the tail either way
        block_s = math.gcd(S, block_s)
    assert block_s % 8 == 0 or block_s == S, (
        f"KV shard length {S} has no tiling-aligned block size; pad the "
        f"cache (second-minor DMA dims must be multiples of 8)")
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    kernel = functools.partial(_decode_kernel, block_s=block_s,
                               sm_scale=sm_scale, n_kv_heads=Hkv)
    grid = (B, S // block_s)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, s, kl: (b, 0, 0)),
                pl.BlockSpec((1, Hkv, block_s, D),
                             lambda b, s, kl: (b, 0, s, 0)),
                pl.BlockSpec((1, Hkv, block_s, D),
                             lambda b, s, kl: (b, 0, s, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, s, kl: (b, 0, 0)),
                pl.BlockSpec((1, Hq, 128), lambda b, s, kl: (b, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((Hq, D), jnp.float32),
                pltpu.VMEM((Hq, 1), jnp.float32),
                pltpu.VMEM((Hq, 1), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 128), jnp.float32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * Hq * S * D,
            bytes_accessed=(q.size + k_cache.size + v_cache.size) * 2,
            transcendentals=B * Hq * S),
        interpret=default_interpret(),
    )(kv_len, q, k_cache, v_cache)


def gqa_decode_paged(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                     block_table: jax.Array, kv_len: jax.Array,
                     sm_scale: float | None = None):
    """Paged-attention decode over a shared KV page pool (the serving-side
    cache layout; parity with the reference's block_table path and its
    ``ref_paged_attn`` golden, test_sp_decode_attn.py:81-134).

    q [B, Hq, D]; k_pages/v_pages [P, Hkv, page_size, D] (page-major pool);
    block_table [B, pages_per_seq] int32 page ids — entries past
    ceil(kv_len/page_size) may be ARBITRARY values (even out of range):
    the index map never dereferences them. kv_len [B] (0 allowed: the row
    returns zeros with lse = NEG_INF, the "empty shard" convention the SP
    combine already honors). Returns (out [B, Hq, D], lse [B, Hq, 128] f32).

    Dead pages are free twice over: their grid steps revisit the LAST
    valid page (same block index as the previous step ⇒ the pipeline
    skips the HBM→VMEM DMA entirely — the causal-attention kv-clamp
    trick, docs/benchmarks.md) and their compute is skipped by the
    ``s * page_size < kv_len`` mask, so a short sequence in a long
    ``pages_per_seq`` batch costs its own length, not the batch max.

    Nothing here assumes distinct batch rows mean distinct sequences:
    rows are (block_table, kv_len) pairs, so several rows may walk the
    SAME pages at staggered ``kv_len`` — the speculative verify dispatch
    (ISSUE 20) runs B*K rows this way, row (b, i) attending its slot's
    pages at ``kv_len = pos_b + i + 1``, exactly like the chunked-prefill
    C-rows-of-decode idiom.
    """
    B, Hq, D = q.shape
    P_pool, Hkv, page_size, _ = k_pages.shape
    assert Hq % Hkv == 0
    assert page_size % 8 == 0, f"page_size {page_size} must be 8-aligned"
    pages_per_seq = block_table.shape[1]
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    def page_index(b, s, kl, bt):
        # last valid page for row b (0 when kv_len == 0 — any real page
        # works, the compute mask kills its contribution); steps past it
        # revisit it (DMA-free), and the clamp keeps even garbage block-
        # table entries inside the pool so the DMA can never read OOB
        last = jnp.maximum((kl[b] + page_size - 1) // page_size - 1, 0)
        page = bt[b, jnp.minimum(s, last)]
        return (jnp.clip(page, 0, P_pool - 1), 0, 0, 0)

    kernel = functools.partial(_decode_paged_kernel, block_s=page_size,
                               sm_scale=sm_scale, n_kv_heads=Hkv)
    grid = (B, pages_per_seq)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, s, kl, bt: (b, 0, 0)),
                pl.BlockSpec((1, Hkv, page_size, D), page_index),
                pl.BlockSpec((1, Hkv, page_size, D), page_index),
            ],
            out_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, s, kl, bt: (b, 0, 0)),
                pl.BlockSpec((1, Hq, 128), lambda b, s, kl, bt: (b, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((Hq, D), jnp.float32),
                pltpu.VMEM((Hq, 1), jnp.float32),
                pltpu.VMEM((Hq, 1), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, Hq, D), q.dtype),
            jax.ShapeDtypeStruct((B, Hq, 128), jnp.float32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * Hq * pages_per_seq * page_size * D,
            bytes_accessed=(q.size
                            + B * pages_per_seq * Hkv * page_size * D * 2),
            transcendentals=B * Hq * pages_per_seq * page_size),
        interpret=default_interpret(),
    )(kv_len, block_table, q, k_pages, v_pages)


def paged_kv_write(k_pages: jax.Array, v_pages: jax.Array,
                   k_new: jax.Array, v_new: jax.Array,
                   block_table: jax.Array, pos: jax.Array,
                   active: jax.Array | None = None
                   ) -> tuple[jax.Array, jax.Array]:
    """Scatter one new (k, v) row per batch slot into the page pool:
    page ``block_table[b, pos_b // page_size]``, row ``pos_b % page_size``.

    k/v_pages [P, Hkv, page_size, D]; k/v_new [B, Hkv, D]; pos [B] int32.
    ``active`` [B] bool (optional) PARKS the write of masked-off rows on
    the scratch page (page 0, the id the serving engine reserves): a slot
    frozen mid-scan by the multi-token decode loop (done on EOS/budget, or
    clamped by page capacity) keeps computing, but its writes can never
    land on a live sequence's page — the device-side twin of the engine's
    host-side slot parking. Rows whose block-table lookup walks past the
    owned pages hit the row's fill id (0, same scratch page) either way.

    The speculative verify dispatch (ISSUE 20) reuses both behaviors
    with B*K rows per slot: row (b, i) writes its draft's KV at
    ``pos_b + i`` (beyond-limit rows park on the scratch page), and a
    rejected suffix's rows simply become garbage past the accepted
    cursor — overwritten by the next dispatch's writes before any read,
    the same argument that makes in-page padding tails safe.
    """
    B = pos.shape[0]
    page_size = k_pages.shape[2]
    rows = jnp.arange(B)
    page = block_table[rows, pos // page_size]              # [B]
    if active is not None:
        page = jnp.where(active, page, 0)
    slot = pos % page_size                                  # [B]
    # advanced indices (page, slot) around the head slice put the batch
    # dim in front — [B, Hkv, D] rows
    return (k_pages.at[page, :, slot].set(k_new),
            v_pages.at[page, :, slot].set(v_new))


def _combine_kernel(outs_ref, lses_ref, out_ref):
    """Inter-rank lse-weighted merge (analog of
    kernel_inter_rank_gqa_fwd_batch_decode_combine_kv,
    flash_decode.py:481-566). Grid (B,): merge R partials for one batch."""
    outs = outs_ref[:, 0].astype(jnp.float32)       # [R, Hq, D]
    lses = lses_ref[:, 0, :, 0:1].astype(jnp.float32)  # [R, Hq, 1]
    m = jnp.max(lses, axis=0)                        # [Hq, 1]
    w = jnp.exp(lses - m[None])                      # [R, Hq, 1]
    denom = jnp.sum(w, axis=0)                       # [Hq, 1]
    denom = jnp.where(denom > 0, denom, 1.0)
    merged = jnp.sum(outs * w, axis=0) / denom       # [Hq, D]
    out_ref[0] = merged.astype(out_ref.dtype)


def decode_combine(partial_outs: jax.Array, partial_lses: jax.Array):
    """partial_outs [R, B, Hq, D], partial_lses [R, B, Hq, 128] →
    merged [B, Hq, D]."""
    R, B, Hq, D = partial_outs.shape
    return pl.pallas_call(
        _combine_kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((R, 1, Hq, D), lambda b: (0, b, 0, 0)),
            pl.BlockSpec((R, 1, Hq, 128), lambda b: (0, b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hq, D), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Hq, D), partial_outs.dtype),
        interpret=default_interpret(),
    )(partial_outs, partial_lses)


def _ll_ag_merge_kernel(axis, mesh_axes, D, out_dtype,
                        part_ref, out_ref, ws_ref, bufs, obuf,
                        csems, send_sems, recv_sems):
    """Fused low-latency partial-AG + lse-merge (the decode critical path).

    Replaces the generic AG kernel + separate combine kernel with ONE
    kernel: put my packed partial (out ‖ lse, f32) to every peer (my own
    segment reads part_ref directly — no ws round-trip), then stream the
    online lse-merge over partials in CANONICAL rank order (seg 0..n-1),
    each segment waited once and prefetched into a VMEM double buffer
    behind the previous segment's merge math. Canonical order makes the fp32 accumulation identical on every
    rank, so the P(None) "replicated" output is bitwise consistent across
    devices (a swizzled start-local order would merge in a different order
    per rank and drift in the low bits, compounding across autoregressive
    steps). The merge math is the running (max, denom, acc) rescaling —
    the same online softmax the reference's inter-rank combine uses
    (kernel_inter_rank_gqa_fwd_batch_decode_combine_kv,
    flash_decode.py:481-566), fused behind the transport like the
    reference's LL allgather layer (low_latency_allgather.py:531-621,
    sp_flash_decode_layer.py:108-125).

    The entry barrier is required: the ws arrival buffer address is reused
    across calls by XLA, so without it a fast peer's call-k+1 put could
    overwrite a slot this rank's call-k merge has not read yet.
    """
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(ws_ref.at[me], part_ref,
                                    send_sems.at[dst], recv_sems.at[me], pid))

    # Double-buffered VMEM prefetch with own-segment bypass: segment `me`
    # reads part_ref directly (our ws slot is never written — the ws
    # round-trip the first version paid is gone), and segment seg+1's
    # HBM→VMEM fetch rides behind segment seg's VPU merge.
    def fetch(seg, slot):
        @pl.when(seg == me)
        def _():
            pltpu.make_async_copy(part_ref, bufs.at[slot],
                                  csems.at[slot]).start()

        @pl.when(seg != me)
        def _():
            shd.wait_recv(ws_ref.at[seg], recv_sems.at[seg])
            pltpu.make_async_copy(ws_ref.at[seg], bufs.at[slot],
                                  csems.at[slot]).start()

    fetch(0, 0)
    acc = m = denom = None
    for seg in range(n):
        slot = seg % 2
        if seg + 1 < n:
            fetch(seg + 1, (seg + 1) % 2)
        pltpu.make_async_copy(bufs.at[slot], bufs.at[slot],
                              csems.at[slot]).wait()
        x = bufs[slot]
        o, lse = x[..., :D], x[..., D:D + 1]   # [B*Hq,D], [B*Hq,1]
        if seg == 0:
            acc, m, denom = o, lse, jnp.ones_like(lse)
        else:
            new_m = jnp.maximum(m, lse)
            scale = jnp.exp(m - new_m)
            w = jnp.exp(lse - new_m)
            acc = acc * scale + o * w
            denom = denom * scale + w
            m = new_m

    obuf[...] = (acc / jnp.where(denom > 0, denom, 1.0)).astype(out_dtype)
    pltpu.sync_copy(obuf, out_ref)   # ANY-space outputs need a DMA store
    shd.quiet(*rdmas)


def ll_ag_merge(ctx: ShmemContext, packed: jax.Array, D: int,
                out_dtype, axis: str):
    """Host wrapper for the fused partial-AG + merge. ``packed`` is
    [n, B, Hq, D+128] f32 sharded P(axis) (rank dim leading); returns
    merged [B, Hq, D] replicated."""
    if not default_interpret() and D % 128:
        raise ValueError(
            f"fused SP decode on compiled TPU needs a lane-multiple head "
            f"dim: head_dim={D} (Mosaic tiles lanes by 128 — the packed "
            "(out ‖ lse) wire slices would be unaligned; the interpret-"
            "mode simulator does not enforce this)")
    n = ctx.axis_size(axis)
    mesh_axes = ctx.axis_names

    def f(pk):
        B, Hq, W = pk.shape[1:]
        # flatten to 2-D rows: [B*Hq, W] keeps the sublane (second-minor)
        # dim a row count Mosaic tiles cleanly; a 3-D [B, Hq<8, W] buffer
        # silently mislays rows in VMEM↔HBM DMAs on real chips
        R = B * Hq
        kernel = lambda *refs: _ll_ag_merge_kernel(
            axis, mesh_axes, D, out_dtype, *refs)
        out, _ws = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((R, D), out_dtype),
                jax.ShapeDtypeStruct((n, R, W), pk.dtype),  # arrival ws
            ),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),
                       pl.BlockSpec(memory_space=pl.ANY)),
            scratch_shapes=[
                pltpu.VMEM((2, R, W), pk.dtype),   # prefetch double buffer
                pltpu.VMEM((R, D), out_dtype),
                pltpu.SemaphoreType.DMA((2,)),     # prefetch copy sems
                pltpu.SemaphoreType.DMA((n,)),
                pltpu.SemaphoreType.DMA((n,)),
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"ll_ag_merge_{axis}")),
            interpret=default_interpret(),
        )(pk[0].reshape(R, W))  # local block is [1, B, Hq, W]
        return out.reshape(B, Hq, D)

    sm = ctx.shard_map(f, in_specs=P(axis), out_specs=P(None))
    return sm(packed)


def sp_gqa_flash_decode(ctx: ShmemContext, q: jax.Array, k_cache: jax.Array,
                        v_cache: jax.Array, global_kv_lens: jax.Array,
                        axis: str | None = None, block_s: int = 128,
                        ag_method: str = "fused") -> jax.Array:
    """Sequence-parallel distributed flash-decode
    (analog of SpGQAFlashDecodeAttention.forward,
    sp_flash_decode_layer.py:78-184):

    1. per-rank split-KV decode over the local KV shard,
    2. low-latency AllGather of the partial (out ‖ lse),
    3. inter-rank lse-weighted combine.

    q [B, Hq, D] replicated; k_cache/v_cache [B, Hkv, n*S_local, D] sharded
    P(None, None, axis) on S; global_kv_lens [B] total valid keys. Returns
    [B, Hq, D] replicated. Golden: dense softmax attention over the full
    cache."""
    axis = axis or ctx.axis_names[0]
    n = ctx.axis_size(axis)
    B, Hq, D = q.shape
    S = k_cache.shape[2]
    assert S % n == 0
    s_local = S // n

    def local(q, k_shard, v_shard, kv_lens):
        me = lax.axis_index(axis)
        local_len = jnp.clip(kv_lens - me * s_local, 0, s_local)
        out_p, lse_p = gqa_decode_partial(q, k_shard, v_shard,
                                          local_len.astype(jnp.int32),
                                          block_s=block_s)
        return out_p[None], lse_p[None]   # add rank dim for the gather

    def local_packed(q, k_shard, v_shard, kv_lens):
        out_p, lse_p = local(q, k_shard, v_shard, kv_lens)
        # one wire payload (out ‖ lse), f32, like the reference's fused
        # partial buffer (sp_flash_decode_layer.py:134-137)
        return jnp.concatenate(
            [out_p.astype(jnp.float32), lse_p], axis=-1)

    sm = ctx.shard_map(local_packed,
                       in_specs=(P(), P(None, None, axis),
                                 P(None, None, axis), P()),
                       out_specs=P(axis))
    packed = sm(q, k_cache, v_cache, global_kv_lens)   # [n, B, Hq, D+128]

    if ag_method == "fused":
        # latency path: one kernel does the partial AG and the streaming
        # lse-merge (no gathered HBM round-trip, no second kernel launch)
        return ll_ag_merge(ctx, packed, D, q.dtype, axis)

    g = all_gather(ctx, packed, axis=axis, method=ag_method)

    def merge(pk):
        return decode_combine(pk[..., :D].astype(q.dtype), pk[..., D:])

    smc = ctx.shard_map(merge, in_specs=P(None), out_specs=P(None))
    return smc(g)


def _pool_ag_kernel(axis, mesh_axes, k_ref, v_ref, kf_ref, vf_ref,
                    send_sems, recv_sems, sig):
    """Signal-gated start-local pool allgather (the SP half of the ISSUE 16
    overlap schedule — the reference ``allgather_gemm.py`` tile-swizzle
    "start local" idiom, restricted to the transport).

    The rank's OWN pool slice is copied into its canonical slot of the full
    pool FIRST, with no gate — it is ready while every remote shard is
    still in flight, so the consumer's paged-attention walk can begin
    issuing its earliest (local-page) reads immediately after this kernel.
    Remote shards are put to each peer's canonical slot and announced with
    one counted ``signal_op`` (``ops/page_migrate.py``'s protocol); the
    consumer gates on the aggregate count and drains arrivals in FIXED
    rank order. The assembled pool is a pure page-order concatenation —
    bitwise identical to ``lax.all_gather(tiled=True)`` — so the attention
    walk that follows keeps its single-device reduction order untouched.
    Overlap moves the SCHEDULE (local slice never waits on the wire),
    never the reduction order."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    p_local = k_ref.shape[0]
    shd.barrier_all((axis,), mesh_axes=mesh_axes)
    # start local: own slice lands while the remote puts are in flight
    lk = pltpu.make_async_copy(
        k_ref, kf_ref.at[pl.ds(me * p_local, p_local)], recv_sems.at[0, me])
    lv = pltpu.make_async_copy(
        v_ref, vf_ref.at[pl.ds(me * p_local, p_local)], recv_sems.at[1, me])
    lk.start()
    lv.start()
    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(
            kf_ref.at[pl.ds(me * p_local, p_local)], k_ref,
            send_sems.at[0, dst], recv_sems.at[0, me], pid))
        rdmas.append(shd.putmem_nbi(
            vf_ref.at[pl.ds(me * p_local, p_local)], v_ref,
            send_sems.at[1, dst], recv_sems.at[1, me], pid))
        # announce my shard to the peer the moment its puts are in flight
        shd.signal_op(sig, 1, pe=pid)
    lk.wait()
    lv.wait()
    if n > 1:
        shd.signal_wait_until(sig, n - 1)
        for p in range(1, n):
            src = lax.rem(me + p, n)
            shd.wait_recv(kf_ref.at[pl.ds(src * p_local, p_local)],
                          recv_sems.at[0, src])
            shd.wait_recv(vf_ref.at[pl.ds(src * p_local, p_local)],
                          recv_sems.at[1, src])
    shd.quiet(*rdmas)


def pool_ag_start_local(ctx: ShmemContext, k_pages: jax.Array,
                        v_pages: jax.Array, axis: str = "sp"):
    """Host wrapper for the start-local pool allgather: global pools
    [P, Hkv, page_size, D] sharded P(axis) on the page dim in; FULL pools
    (replicated) out, assembled in canonical page order — bitwise identical
    to the tiled ``lax.all_gather`` concatenation the non-overlapped SP
    path uses (the DCN/CPU fallback IS that all_gather). One kernel moves
    both pools so K and V ride the wire together."""
    from triton_dist_tpu.ops.all_to_all import _xla_wire
    n = ctx.axis_size(axis)
    if n == 1:
        return k_pages, v_pages
    mesh_axes = ctx.axis_names

    if _xla_wire(ctx, axis):
        def f(kp_l, vp_l):
            return (lax.all_gather(kp_l, axis, axis=0, tiled=True),
                    lax.all_gather(vp_l, axis, axis=0, tiled=True))
        return ctx.shard_map(f, in_specs=(P(axis), P(axis)),
                             out_specs=(P(None), P(None)))(k_pages, v_pages)

    def f(kp_l, vp_l):
        kernel = lambda *refs: _pool_ag_kernel(axis, mesh_axes, *refs)
        full_k = jax.ShapeDtypeStruct((n * kp_l.shape[0],) + kp_l.shape[1:],
                                      kp_l.dtype)
        full_v = jax.ShapeDtypeStruct((n * vp_l.shape[0],) + vp_l.shape[1:],
                                      vp_l.dtype)
        return pl.pallas_call(
            kernel,
            out_shape=(full_k, full_v),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 2,
            out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2, n)),
                pltpu.SemaphoreType.DMA((2, n)),
                pltpu.SemaphoreType.REGULAR,
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True,
                collective_id=collective_id_for(f"pool_ag_{axis}")),
            interpret=default_interpret(),
        )(kp_l, vp_l)

    return ctx.shard_map(f, in_specs=(P(axis), P(axis)),
                         out_specs=(P(None), P(None)))(k_pages, v_pages)


def sp_paged_attend_write(ctx: ShmemContext, q: jax.Array,
                          k_new: jax.Array, v_new: jax.Array,
                          k_pages: jax.Array, v_pages: jax.Array,
                          block_table: jax.Array, pos: jax.Array,
                          kv_len: jax.Array, axis: str = "sp",
                          active: jax.Array | None = None,
                          overlap: bool = False):
    """Sequence-parallel paged write + paged GQA decode attention: the page
    pool is sharded over ``axis`` on the PAGE dim (``page_pool_pspec``),
    rank r owning pages ``[r*Pl, (r+1)*Pl)``.

    Per rank: scatter the new (k, v) rows that land on LOCALLY-owned pages
    (non-local rows drop via an out-of-bounds index with ``mode="drop"`` —
    every row is written by exactly one rank), then allgather the pool
    shards back to the full pool and run the replicated ``gqa_decode_paged``
    walk over it. The allgather is a pure concatenation in page order, so
    the gathered pool — and therefore the attention output — is BITWISE
    identical to the single-device ``paged_kv_write`` + ``gqa_decode_paged``
    composition at any mesh size (tests/test_sharded_serving.py pins this).
    The write bandwidth is what shards; attention reads stay replicated —
    the regime where pool residency, not attention FLOPs, is the scaling
    limit (one new KV row per slot per step).

    ``active`` parks masked-off rows on the scratch page (page 0, rank 0's
    shard) exactly like ``paged_kv_write``. q [B, Hq, D]; k/v_new
    [B, Hkv, D]; k/v_pages [P, Hkv, page_size, D] GLOBAL views sharded
    P(axis); pos/kv_len [B]. Returns (attn [B, Hq, D], k_pages, v_pages)
    with the pools still P(axis)-sharded.

    ``overlap=True`` swaps the tiled ``lax.all_gather`` for the
    signal-gated start-local assembly (``pool_ag_start_local``): the
    rank's own pool slice lands in the full pool without waiting on the
    wire and remote slices are gated per-shard by counted signals —
    ISSUE 16's SP overlap. The assembled pool is a page-order
    concatenation either way, so the attention output is BITWISE identical
    to ``overlap=False`` (only the transport schedule differs).
    """
    n = ctx.axis_size(axis)
    if n == 1:
        kp, vp = paged_kv_write(k_pages, v_pages, k_new, v_new,
                                block_table, pos, active=active)
        out, _ = gqa_decode_paged(q, kp, vp, block_table, kv_len)
        return out, kp, vp

    assert k_pages.shape[0] % n == 0, (
        f"pool pages {k_pages.shape[0]} not divisible by |{axis}|={n} — "
        "pad the pool to a multiple of the SP axis (the sharded engine "
        "does this; the allocator never hands out the padding pages)")
    has_active = active is not None

    def write_shard(kp_l, vp_l, kn, vn, bt, pos, *act):
        r = lax.axis_index(axis)
        p_local = kp_l.shape[0]
        page_size = kp_l.shape[2]
        rows = jnp.arange(pos.shape[0])
        page = bt[rows, pos // page_size]                   # [B] global ids
        if has_active:
            page = jnp.where(act[0], page, 0)
        loc = page - r * p_local
        ok = (loc >= 0) & (loc < p_local)
        idx = jnp.where(ok, loc, p_local)    # OOB sentinel → dropped write
        slot = pos % page_size
        kp_l = kp_l.at[idx, :, slot].set(kn, mode="drop")
        vp_l = vp_l.at[idx, :, slot].set(vn, mode="drop")
        return kp_l, vp_l

    if overlap:
        smw = ctx.shard_map(
            write_shard,
            in_specs=(P(axis), P(axis)) + (P(),) * (4 + int(has_active)),
            out_specs=(P(axis), P(axis)))
        wargs = (k_pages, v_pages, k_new, v_new, block_table, pos)
        if has_active:
            wargs += (active,)
        kp, vp = smw(*wargs)
        kf, vf = pool_ag_start_local(ctx, kp, vp, axis=axis)
        smo = ctx.shard_map(
            lambda q, kf, vf, bt, kl: gqa_decode_paged(q, kf, vf, bt, kl)[0],
            in_specs=(P(),) * 5, out_specs=P())
        return smo(q, kf, vf, block_table, kv_len), kp, vp

    def body(kp_l, vp_l, q, kn, vn, bt, pos, kv_lens, *act):
        kp_l, vp_l = write_shard(kp_l, vp_l, kn, vn, bt, pos, *act)
        # tiled page-dim allgather = exact concatenation of the shards
        kf = lax.all_gather(kp_l, axis, axis=0, tiled=True)
        vf = lax.all_gather(vp_l, axis, axis=0, tiled=True)
        out, _ = gqa_decode_paged(q, kf, vf, bt, kv_lens)
        return out, kp_l, vp_l

    sm = ctx.shard_map(
        body,
        in_specs=(P(axis), P(axis)) + (P(),) * (6 + int(has_active)),
        out_specs=(P(), P(axis), P(axis)))
    args = (k_pages, v_pages, q, k_new, v_new, block_table, pos, kv_len)
    if has_active:
        args += (active,)
    return sm(*args)


# -- distributed flash-decode: one request's KV sharded over the SP mesh ----
#
# `sp_paged_attend_write` shards the pool across REQUESTS: every rank
# allgathers the whole pool and attends over all of it, so one long
# request's attention cost is replicated n times. `flash_decode_dist`
# shards ONE request's pages: each rank walks only the block-table pages
# resident in its pool slice, computes an independent softmax partial PER
# PAGE, announces the partial slab with one-sided puts + a counted
# `signal_op`, and every rank folds all slabs in a single FIXED order.
#
# Why per-PAGE partials (not one per-rank online-softmax partial): the
# fold must be bitwise identical at every mesh size n. A per-rank running
# (m, l, acc) partial bakes the rank's page count into its rounding, so
# merging two ranks' partials ≠ one rank's partial over both slices at the
# last bit. A per-page partial is a pure function of (q, that page's K/V)
# — identical floats no matter which rank computed it — and the fold
# visits pages in block-table order with ranks 0..n-1 interleaved at each
# page, where at most ONE rank's entry per page is real and every other
# entry is the neutral (out=0, lse=NEG_INF) element applied as an EXACT
# no-op (a `where` select of the untouched carry, never an arithmetic
# identity — `acc*1 + 0` can still flip a -0.0). The carry therefore
# walks the same float sequence at n=1, 2, 4, ... for ANY page→rank
# placement, which is also what makes the pool layout (blocked vs
# round-robin interleaved) a pure balance knob. A psum/lse-psum would
# re-associate by rank count — exactly what sigcheck's rank-count-
# dependent-reduction lint rejects — so it is refused by construction.

_FD_EMPTY = NEG_INF / 2  # "no entry" threshold: real lse never gets here


def _fd_partial_kernel(kl_ref, bt_ref, rk_ref, q_ref, k_ref, v_ref,
                       out_ref, lse_ref, *, page_size: int, p_local: int,
                       sm_scale: float, n_kv_heads: int):
    """Grid (B, pages_per_seq): one INDEPENDENT softmax partial per
    block-table page — no carry between steps, so any rank (or any
    distribution of pages over ranks) produces bit-identical entries for
    the pages it owns. Non-local / dead pages emit the neutral element."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    page = bt_ref[b, s]
    base = rk_ref[0] * p_local
    mine = jnp.logical_and(page >= base, page < base + p_local)
    live = jnp.logical_and(mine, s * page_size < kl_ref[b])

    Hq, D = out_ref.shape[2], out_ref.shape[3]
    G = Hq // n_kv_heads
    q = q_ref[0].reshape(n_kv_heads, G, D)
    k = k_ref[0]                                   # [Hkv, page_size, D]
    v = v_ref[0]
    scores = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * sm_scale   # [Hkv, G, ps]
    scores = scores.reshape(Hq, page_size)
    pos = s * page_size + jax.lax.broadcasted_iota(
        jnp.int32, scores.shape, 1)
    scores = jnp.where(pos < kl_ref[b], scores, NEG_INF)
    m = jnp.max(scores, axis=1, keepdims=True)     # [Hq, 1]
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=1, keepdims=True)          # [Hq, 1]
    pv = jax.lax.dot_general(
        p.reshape(n_kv_heads, G, page_size).astype(v.dtype), v,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).reshape(Hq, D)
    l_safe = jnp.where(l > 0, l, 1.0)
    # keep: a live page always has ≥1 unmasked key, but garbage pool rows
    # under a dead step may be anything — the select (not a multiply)
    # guarantees the neutral entry regardless
    keep = jnp.logical_and(live, l > 0)
    out_ref[0, 0] = jnp.where(keep, pv / l_safe, 0.0)
    lse_ref[0, 0] = jnp.broadcast_to(
        jnp.where(keep, m + jnp.log(l_safe), NEG_INF), lse_ref.shape[2:])


def _fd_page_partials(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                      block_table: jax.Array, kv_len: jax.Array,
                      rank: jax.Array, sm_scale: float | None = None):
    """Per-page partial slab for one rank's pool slice: returns packed
    (out ‖ lse) [B, S, Hq, D+128] f32. ``k_pages``/``v_pages`` are the
    LOCAL slice [p_local, Hkv, page_size, D]; ``block_table`` holds GLOBAL
    device rows — rank r owns rows [r*p_local, (r+1)*p_local)."""
    B, Hq, D = q.shape
    p_local, Hkv, page_size, _ = k_pages.shape
    S = block_table.shape[1]
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)

    def page_index(b, s, kl, bt, rk):
        # clamp into the local slice: non-local steps fetch an arbitrary
        # in-bounds page (their compute is discarded by the select)
        loc = bt[b, s] - rk[0] * p_local
        return (jnp.clip(loc, 0, p_local - 1), 0, 0, 0)

    kernel = functools.partial(_fd_partial_kernel, page_size=page_size,
                               p_local=p_local, sm_scale=sm_scale,
                               n_kv_heads=Hkv)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(B, S),
            in_specs=[
                pl.BlockSpec((1, Hq, D), lambda b, s, kl, bt, rk: (b, 0, 0)),
                pl.BlockSpec((1, Hkv, page_size, D), page_index),
                pl.BlockSpec((1, Hkv, page_size, D), page_index),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, Hq, D),
                             lambda b, s, kl, bt, rk: (b, s, 0, 0)),
                pl.BlockSpec((1, 1, Hq, 128),
                             lambda b, s, kl, bt, rk: (b, s, 0, 0)),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, S, Hq, D), jnp.float32),
            jax.ShapeDtypeStruct((B, S, Hq, 128), jnp.float32),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * Hq * S * page_size * D,
            bytes_accessed=q.size + B * S * Hkv * page_size * D * 2,
            transcendentals=B * Hq * S * page_size),
        interpret=default_interpret(),
    )(kv_len, block_table, rank, q, k_pages, v_pages)
    return jnp.concatenate([out, lse], axis=-1)


def _fd_fold(stacked: jax.Array, D: int, out_dtype):
    """Fixed-order fold of page partials: ``stacked`` [T, rows, D+128] in
    fold order (page-major, rank-minor — T = S at n=1, S*n otherwise).
    Neutral entries (lse == NEG_INF) are EXACT no-ops: the carry is passed
    through a select untouched, so the float sequence the carry walks is
    the n=1 page-order sequence at every mesh size. Never a psum."""
    init = (jnp.zeros(stacked.shape[1:-1] + (D,), jnp.float32),
            jnp.full(stacked.shape[1:-1] + (1,), NEG_INF, jnp.float32),
            jnp.zeros(stacked.shape[1:-1] + (1,), jnp.float32))

    def step(carry, x):
        acc, m, denom = carry
        xo, xl = x[..., :D], x[..., D:D + 1]
        empty = xl <= _FD_EMPTY
        new_m = jnp.maximum(m, xl)
        scale = jnp.exp(m - new_m)
        w = jnp.exp(xl - new_m)
        return (jnp.where(empty, acc, acc * scale + xo * w),
                jnp.where(empty, m, new_m),
                jnp.where(empty, denom, denom * scale + w)), None

    (acc, _m, denom), _ = lax.scan(step, init, stacked)
    return (acc / jnp.where(denom > 0, denom, 1.0)).astype(out_dtype)


def _fd_fold_kernel(axis, mesh_axes, S, BH, D, out_dtype,
                    part_ref, out_ref, ws_ref, bufs, obuf,
                    csems, send_sems, recv_sems, sig):
    """One-sided partial exchange + fixed-order page fold (the
    `paged_transport` seg-push idiom): put my page-partial slab to every
    peer and announce it with one counted ``signal_op``; consume peers'
    slabs in CANONICAL rank order, each gated by exactly one announcement
    count plus that slab's delivery credits. My own slab's VMEM fetch is
    UNGATED — local partials land while remote slabs are still in flight
    (overlap the schedule). The fold itself then walks (page s, rank r)
    in the one fixed order shared with the XLA/CPU path — at each page
    exactly one rank's entry is real, the rest are exact no-ops — so the
    reduction order never changes with n (never a psum).

    The entry barrier is required for the same reason as
    ``_ll_ag_merge_kernel``: the ws arrival buffer is reused across calls.
    VMEM note: all n slabs are resident during the fold (n*S*B*Hq*(D+128)
    f32) — fine for decode batches; streaming a per-page double buffer is
    the round-7 lever for 100k-context on-chip runs."""
    me = shd.my_pe(axis)
    n = shd.n_pes(axis)
    shd.barrier_all((axis,), mesh_axes=mesh_axes)

    rdmas = []
    for p in range(1, n):
        dst = lax.rem(me + p, n)
        pid = shd.pe_at(mesh_axes, axis, dst)
        rdmas.append(shd.putmem_nbi(ws_ref.at[me], part_ref,
                                    send_sems.at[dst], recv_sems.at[me],
                                    pid))
        # announce my partial slab the moment its put is in flight
        shd.signal_op(sig, 1, pe=pid)

    def fetch(r):
        @pl.when(r == me)
        def _():
            # own slab: no gate — it never rides the wire
            pltpu.make_async_copy(part_ref, bufs.at[r], csems.at[r]).start()

        @pl.when(r != me)
        def _():
            # exactly the signals this fold step consumes: one partial
            # announcement, then the slab's delivery credits
            shd.signal_wait_until(sig, 1)
            shd.wait_recv(ws_ref.at[r], recv_sems.at[r])
            pltpu.make_async_copy(ws_ref.at[r], bufs.at[r],
                                  csems.at[r]).start()

    # page 0 of the fold touches every rank's slab, so full residency is
    # the minimal wait set; gate in canonical order, fetches overlapping
    fetch(0)
    for r in range(n):
        if r + 1 < n:
            fetch(r + 1)
        pltpu.make_async_copy(bufs.at[r], bufs.at[r], csems.at[r]).wait()

    def fold_step(t, carry):
        acc, m, denom = carry
        r = lax.rem(t, n)
        s = t // n
        x = bufs[r, pl.ds(s * BH, BH), :]
        xo, xl = x[..., :D], x[..., D:D + 1]
        empty = xl <= _FD_EMPTY
        new_m = jnp.maximum(m, xl)
        scale = jnp.exp(m - new_m)
        w = jnp.exp(xl - new_m)
        return (jnp.where(empty, acc, acc * scale + xo * w),
                jnp.where(empty, m, new_m),
                jnp.where(empty, denom, denom * scale + w))

    init = (jnp.zeros((BH, D), jnp.float32),
            jnp.full((BH, 1), NEG_INF, jnp.float32),
            jnp.zeros((BH, 1), jnp.float32))
    acc, _m, denom = lax.fori_loop(0, S * n, fold_step, init)
    obuf[...] = (acc / jnp.where(denom > 0, denom, 1.0)).astype(out_dtype)
    pltpu.sync_copy(obuf, out_ref)   # ANY-space outputs need a DMA store
    shd.quiet(*rdmas)


def flash_decode_dist(ctx: ShmemContext, q: jax.Array,
                      k_new: jax.Array, v_new: jax.Array,
                      k_pages: jax.Array, v_pages: jax.Array,
                      block_table: jax.Array, pos: jax.Array,
                      kv_len: jax.Array, axis: str = "sp",
                      active: jax.Array | None = None):
    """Distributed flash-decode over a page pool sharded on ``axis``: the
    single-request SP axis (ROADMAP item 2). Same contract as
    ``sp_paged_attend_write`` — q [B, Hq, D]; k/v_new [B, Hkv, D];
    k/v_pages [P, Hkv, page_size, D] GLOBAL views sharded P(axis) on the
    page dim; block_table [B, S] DEVICE rows; pos/kv_len [B] — returns
    (attn [B, Hq, D], k_pages, v_pages) with the pools still sharded.

    Unlike ``sp_paged_attend_write`` (pool allgather + replicated walk:
    per-rank attention cost ∝ FULL kv_len), each rank here walks only the
    block-table pages resident in its own slice and ships one packed
    partial slab — per-rank attention compute ∝ kv_len/n, the property
    that makes 64k–100k-token contexts servable. The combine is the
    fixed-order page fold (see the section comment above): bitwise
    identical at any n and any page→rank placement, so the n=1 route —
    which runs the SAME per-page partial + fold math — IS the golden.
    """
    n = ctx.axis_size(axis)
    B, Hq, D = q.shape
    S = block_table.shape[1]

    if n == 1:
        kp, vp = paged_kv_write(k_pages, v_pages, k_new, v_new,
                                block_table, pos, active=active)
        packed = _fd_page_partials(q, kp, vp, block_table, kv_len,
                                   jnp.zeros((1,), jnp.int32))
        stacked = packed.transpose(1, 0, 2, 3).reshape(S, B * Hq, D + 128)
        return _fd_fold(stacked, D, q.dtype).reshape(B, Hq, D), kp, vp

    assert k_pages.shape[0] % n == 0, (
        f"pool pages {k_pages.shape[0]} not divisible by |{axis}|={n} — "
        "pad the pool to a multiple of the SP axis (the sharded engine "
        "does this; the allocator never hands out the padding pages)")
    from triton_dist_tpu.ops.all_to_all import _xla_wire
    wire_xla = _xla_wire(ctx, axis)
    if not wire_xla and not default_interpret() and D % 128:
        raise ValueError(
            f"flash_decode_dist on compiled TPU needs a lane-multiple "
            f"head dim: head_dim={D} (the packed (out ‖ lse) slab slices "
            "would be unaligned on the wire)")
    mesh_axes = ctx.axis_names
    has_active = active is not None
    BH = B * Hq
    W = D + 128

    def f(kp_l, vp_l, q, kn, vn, bt, pos, kl, *act):
        r = lax.axis_index(axis)
        p_local = kp_l.shape[0]
        page_size = kp_l.shape[2]
        # scatter the new rows that land on locally-owned pages (the
        # sp_paged_attend_write OOB-drop idiom: every row written once)
        rows = jnp.arange(pos.shape[0])
        page = bt[rows, pos // page_size]
        if has_active:
            page = jnp.where(act[0], page, 0)
        loc = page - r * p_local
        ok = (loc >= 0) & (loc < p_local)
        idx = jnp.where(ok, loc, p_local)   # OOB sentinel → dropped write
        slot = pos % page_size
        kp_l = kp_l.at[idx, :, slot].set(kn, mode="drop")
        vp_l = vp_l.at[idx, :, slot].set(vn, mode="drop")

        packed = _fd_page_partials(q, kp_l, vp_l, bt, kl,
                                   r.astype(jnp.int32)[None])
        slab = packed.transpose(1, 0, 2, 3).reshape(S * BH, W)

        if wire_xla:
            g = lax.all_gather(slab, axis, axis=0, tiled=False)
            # reorder to the ONE fold order: page-major, rank-minor
            stacked = g.reshape(n, S, BH, W).transpose(1, 0, 2, 3)
            out = _fd_fold(stacked.reshape(S * n, BH, W), D, q.dtype)
        else:
            kernel = lambda *refs: _fd_fold_kernel(
                axis, mesh_axes, S, BH, D, q.dtype, *refs)
            out, _ws = pl.pallas_call(
                kernel,
                out_shape=(
                    jax.ShapeDtypeStruct((BH, D), q.dtype),
                    jax.ShapeDtypeStruct((n, S * BH, W), slab.dtype),
                ),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 2,
                scratch_shapes=[
                    pltpu.VMEM((n, S * BH, W), jnp.float32),
                    pltpu.VMEM((BH, D), q.dtype),
                    pltpu.SemaphoreType.DMA((n,)),   # slab VMEM fetches
                    pltpu.SemaphoreType.DMA((n,)),   # send credits
                    pltpu.SemaphoreType.DMA((n,)),   # delivery credits
                    pltpu.SemaphoreType.REGULAR,     # counted announces
                ],
                compiler_params=pltpu.CompilerParams(
                    has_side_effects=True,
                    collective_id=collective_id_for(f"fd_fold_{axis}")),
                interpret=default_interpret(),
            )(slab)
        return out.reshape(B, Hq, D), kp_l, vp_l

    sm = ctx.shard_map(
        f,
        in_specs=(P(axis), P(axis)) + (P(),) * (6 + int(has_active)),
        out_specs=(P(), P(axis), P(axis)))
    args = (k_pages, v_pages, q, k_new, v_new, block_table, pos, kv_len)
    if has_active:
        args += (active,)
    return sm(*args)


__all__ = ["gqa_decode_partial", "gqa_decode_paged", "paged_kv_write",
           "decode_combine", "ll_ag_merge", "sp_gqa_flash_decode",
           "sp_paged_attend_write", "pool_ag_start_local",
           "flash_decode_dist"]
