"""Sanity probe for the wire-only A2A timing (bench.bench_a2a_wire):
scale payload bytes and dtype, confirm time scales with bytes. A flat
line (or super-HBM GB/s) means the chain is being optimized away — which
is exactly what the first self-chained version of this probe caught: a
bare copy chain is a fixed point XLA collapses (0.4 µs for 7 MiB). The
current inner-K differencing holds the eps feedback constant and
differences K=5 vs K=1 pushes per iteration."""
import json
import sys

import jax
import jax.numpy as jnp

REPO = __file__.rsplit("/", 2)[0]
sys.path.insert(0, REPO)

from bench import bench_a2a_wire  # noqa: E402
from triton_dist_tpu.shmem.context import initialize_distributed  # noqa: E402
from triton_dist_tpu.utils import on_cpu  # noqa: E402

ctx = initialize_distributed(axis_names=("x",),
                             mesh_shape=(len(jax.devices()),))
i1, i2 = (1, 3) if on_cpu() else (10, 810)

# (wire_dtype, tokens_per_rank, hidden) -> capacity = tokens * topk
CASES = [
    (None, 128, 7168),      # bf16, cap 1024: 14 MiB
    (None, 64, 7168),       # bf16, cap 512:   7 MiB
    (None, 128, 3584),      # bf16, cap 1024:  7 MiB
    (jnp.float8_e4m3fn, 128, 7168),   # fp8, cap 1024: 7 MiB
    (jnp.float8_e4m3fn, 64, 7168),    # fp8, cap 512: 3.5 MiB
    (jnp.int8, 128, 7168),
]
if on_cpu():
    CASES = [(None, 8, 256), (jnp.int8, 8, 256)]

for wire, tok, H in CASES:
    s = bench_a2a_wire(ctx, tokens_per_rank=tok, hidden=H, topk=8,
                       num_experts=64, i1=i1, i2=i2, wire_dtype=wire)
    itemsize = jnp.dtype(wire).itemsize if wire else 2
    mb = tok * 8 * H * itemsize / 2**20
    print(json.dumps({
        "wire": str(jnp.dtype(wire)) if wire else "bf16", "cap": tok * 8,
        "H": H, "payload_mib": round(mb, 1), "wire_us": round(s * 1e6, 1),
        "gbps_rw": round(2 * mb / 1024 / max(s, 1e-9), 1)}), flush=True)
