"""Sync-protocol hardening: repeated-call safety, interpret-mode race
detection, and producer-delay noise fuzzing.

Parity targets: the reference's sync-bug tooling — sleep-noise injection
``_add_noise_workload_debug`` (allgather.py:72-76), ``serial`` bisection mode
(allgather_gemm.py:482-485), and its implicit repeated-call coverage (every
perf loop reruns ops against live semaphores). Here the interpreter's
vector-clock race detector (``TDT_DETECT_RACES=1``) replaces sleep-fuzzing
as the primary tool, and ``TDT_NOISE`` perturbs producer timing on top.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops import all_gather, reduce_scatter
from triton_dist_tpu.ops.all_to_all import (combine,
                                            create_all_to_all_context,
                                            dispatch)
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


@pytest.fixture(scope="module")
def ctx2d():
    return initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))


def _assert_detector_ran_clean(what: str):
    """The detector must have RUN (ipc.races populated — guards against the
    env-flag plumbing silently breaking) and found nothing. The state lives
    on a private jax module; ``interpret_race_state`` version-guards the
    import so a jax bump turns these asserts into skips, not failures."""
    from triton_dist_tpu.utils.debug import interpret_race_state
    ipc = interpret_race_state()
    if ipc is None:
        pytest.skip("jax moved the private interpret-mode race-detector "
                    "state (jax._src.pallas.mosaic.interpret) — cannot "
                    "assert the detector ran on this jax version")
    assert ipc.races is not None, (
        f"race detector never ran for {what} — TDT_DETECT_RACES plumbing "
        "broken?")
    assert not ipc.races.races_found, f"race detected in {what}"


# -- repeated calls: semaphores are physical registers shared across calls --
# (entry barriers make back-to-back calls safe; these tests pin the protocol
# by reusing ONE jitted callable so state genuinely crosses calls)

@pytest.mark.parametrize("method", ["push", "ring"])
def test_all_gather_repeated_calls(ctx, method):
    n = ctx.num_ranks
    f = jax.jit(lambda v: all_gather(ctx, v, axis="x", method=method))
    for it in range(3):
        x = jax.random.normal(jax.random.key(it), (n * 8, 128), jnp.float32)
        xs = ctx.shard(x, P("x"))
        assert_allclose(np.asarray(f(xs)), np.asarray(x))


def test_all_gather_2d_repeated_calls(ctx2d):
    f = jax.jit(lambda v: all_gather(ctx2d, v, method="ring_2d"))
    for it in range(3):
        x = jax.random.normal(jax.random.key(it), (6 * 8, 128), jnp.float32)
        xs = ctx2d.shard(x, P(("a", "b")))
        assert_allclose(np.asarray(f(xs)), np.asarray(x))


def test_reduce_scatter_repeated_calls(ctx):
    n = ctx.num_ranks
    f = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))
    g = jax.jit(ctx.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))
    for it in range(3):
        x = jnp.round(jax.random.normal(jax.random.key(it), (n * 16, 128)) * 4)
        xs = ctx.shard(x.astype(jnp.float32), P("x"))
        assert_allclose(np.asarray(f(xs)), np.asarray(g(xs)))


def test_a2a_dispatch_combine_repeated_calls(ctx):
    n = ctx.num_ranks
    T, H, topk = n * 8, 128, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x")

    def roundtrip(t, i, w):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, w)

    f = jax.jit(roundtrip)
    for it in range(3):
        t = jax.random.normal(jax.random.key(3 * it), (T, H), jnp.float32
                              ).astype(jnp.bfloat16)
        ids = jax.random.randint(jax.random.key(3 * it + 1), (T, topk), 0,
                                 2 * n)
        w = jnp.ones((T, topk), jnp.float32) / topk
        ts = ctx.shard(t, P("x"))
        out = f(ts, ctx.shard(ids, P("x")), ctx.shard(w, P("x")))
        # combine sums the same token back topk times with weight 1/topk
        assert_allclose(np.asarray(out, np.float32), np.asarray(t, np.float32),
                        rtol=3e-2, atol=3e-2)


# (gemm_rs repeated-call coverage lives in tests/test_gemm_rs.py)


# -- race detector CI slice (TDT_DETECT_RACES=1) ----------------------------

def test_collectives_race_free_under_detector(ctx, monkeypatch):
    monkeypatch.setenv("TDT_DETECT_RACES", "1")
    n = ctx.num_ranks
    # fresh lambdas → fresh traces → the env flag is honored
    x = jax.random.normal(jax.random.key(7), (n * 8, 128), jnp.float32)
    xs = ctx.shard(x, P("x"))
    for method in ("push", "ring"):
        y = jax.jit(lambda v, m=method: all_gather(ctx, v, axis="x",
                                                   method=m))(xs)
        assert_allclose(np.asarray(y), np.asarray(x))
        _assert_detector_ran_clean(f"all_gather {method}")

    r = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))(xs)
    jax.block_until_ready(r)
    _assert_detector_ran_clean("reduce_scatter")


def test_ag_gemm_race_free_under_detector(ctx, monkeypatch):
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    monkeypatch.setenv("TDT_DETECT_RACES", "1")
    n = ctx.num_ranks
    M = K = 64
    N = 128 * n
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    cfg = GemmConfig(M // n, 128)
    out = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis="x", cfg=cfg))(
        ctx.shard(a, P("x")), ctx.shard(b, P(None, "x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)
    _assert_detector_ran_clean("ag_gemm")


def test_fused_moe_race_free_under_detector(ctx, monkeypatch):
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    monkeypatch.setenv("TDT_DETECT_RACES", "1")
    n = ctx.num_ranks
    E, H, N, T = 4, 128, n * 128, n * 32
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    w = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, ww: ag_moe_group_gemm(
        ctx, ctx.shard(t, P("x")), ctx.shard(i, P("x")),
        ctx.shard(ww, P(None, None, "x")), block_m=32))(tokens, ids, w)
    jax.block_until_ready(out)
    _assert_detector_ran_clean("ag_moe_group_gemm")


def test_a2a_and_fused_decode_race_free_under_detector(ctx, monkeypatch):
    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode
    monkeypatch.setenv("TDT_DETECT_RACES", "1")
    n = ctx.num_ranks
    T, H, topk = n * 8, 128, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x")
    t = jax.random.normal(jax.random.key(0), (T, H), jnp.float32
                          ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(1), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk

    def roundtrip(tt, ii, ww):
        recv, _, layout = dispatch(a2a, tt, ii)
        return combine(a2a, recv, layout, ww)

    out = jax.jit(roundtrip)(ctx.shard(t, P("x")), ctx.shard(ids, P("x")),
                             ctx.shard(w, P("x")))
    jax.block_until_ready(out)
    _assert_detector_ran_clean("a2a dispatch/combine")

    B, Hq, Hkv, D, s_local = 1, 4, 2, 128, 64
    S = n * s_local
    q = jax.random.normal(jax.random.key(2), (B, Hq, D), jnp.float32)
    k = jax.random.normal(jax.random.key(3), (B, Hkv, S, D), jnp.float32)
    v = jax.random.normal(jax.random.key(4), (B, Hkv, S, D), jnp.float32)
    out2 = jax.jit(lambda *a: sp_gqa_flash_decode(ctx, *a,
                                                  ag_method="fused"))(
        q, ctx.shard(k, P(None, None, "x")), ctx.shard(v, P(None, None, "x")),
        jnp.array([S], jnp.int32))
    jax.block_until_ready(out2)
    _assert_detector_ran_clean("fused sp decode")


# -- producer-delay noise fuzzing (TDT_NOISE) -------------------------------

def test_all_gather_correct_under_noise(ctx, monkeypatch):
    monkeypatch.setenv("TDT_NOISE", "2")
    n = ctx.num_ranks
    x = jax.random.normal(jax.random.key(9), (n * 8, 128), jnp.float32)
    xs = ctx.shard(x, P("x"))
    for method in ("push", "ring"):
        y = jax.jit(lambda v, m=method: all_gather(ctx, v, axis="x",
                                                   method=m))(xs)
        assert_allclose(np.asarray(y), np.asarray(x))


def test_rs_correct_under_noise(ctx, monkeypatch):
    monkeypatch.setenv("TDT_NOISE", "2")
    n = ctx.num_ranks
    x = jnp.round(jax.random.normal(jax.random.key(10), (n * 16, 128)) * 4)
    xs = ctx.shard(x.astype(jnp.float32), P("x"))
    got = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))(xs)
    gold = jax.jit(ctx.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))(xs)
    assert_allclose(np.asarray(got), np.asarray(gold))


def test_a2a_roundtrip_correct_under_noise(ctx, monkeypatch):
    monkeypatch.setenv("TDT_NOISE", "2")
    n = ctx.num_ranks
    T, H, topk = n * 8, 128, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x")

    def roundtrip(t, i, w):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, w)

    t = jax.random.normal(jax.random.key(11), (T, H), jnp.float32
                          ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(12), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk
    out = jax.jit(roundtrip)(ctx.shard(t, P("x")), ctx.shard(ids, P("x")),
                             ctx.shard(w, P("x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(t, np.float32),
                    rtol=3e-2, atol=3e-2)


# -- serialized-execution bisection mode (TDT_SERIAL) -----------------------
# (reference parity: serial=True on its overlap ops, allgather_gemm.py:482-485
#  — forces puts synchronous so overlap collapses to lock-step; results must
#  be bit-identical to the pipelined schedule.)

def test_collectives_correct_under_serial(ctx, monkeypatch):
    n = ctx.num_ranks
    x = jax.random.normal(jax.random.key(21), (n * 8, 128), jnp.float32)
    xs = ctx.shard(x, P("x"))
    pipelined = {m: np.asarray(jax.jit(
        lambda v, m=m: all_gather(ctx, v, axis="x", method=m))(xs))
        for m in ("push", "ring")}
    monkeypatch.setenv("TDT_SERIAL", "1")
    from triton_dist_tpu.shmem import device as shd
    assert shd._serial()
    for m in ("push", "ring"):
        y = jax.jit(lambda v, m=m: all_gather(ctx, v, axis="x", method=m))(xs)
        np.testing.assert_array_equal(np.asarray(y), pipelined[m])

    r = jax.jit(lambda v: reduce_scatter(ctx, v, axis="x"))(xs)
    gold = jax.jit(ctx.shard_map(
        lambda s: jax.lax.psum_scatter(s, "x", scatter_dimension=0,
                                       tiled=True),
        in_specs=P("x"), out_specs=P("x")))(xs)
    assert_allclose(np.asarray(r), np.asarray(gold))


def test_overlap_ops_correct_under_serial(ctx, monkeypatch):
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    monkeypatch.setenv("TDT_SERIAL", "1")
    n = ctx.num_ranks
    M = K = 64
    N = 128 * n
    a = jax.random.normal(jax.random.key(22), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(23), (K, N), jnp.float32)
    out = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis="x",
                                       cfg=GemmConfig(M // n, 128)))(
        ctx.shard(a, P("x")), ctx.shard(b, P(None, "x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)

    T, H, topk = n * 8, 128, 2
    a2a = create_all_to_all_context(ctx, max_tokens=T // n, hidden=H,
                                    topk=topk, num_experts=2 * n, axis="x")

    def roundtrip(t, i, w):
        recv, _, layout = dispatch(a2a, t, i)
        return combine(a2a, recv, layout, w)

    t = jax.random.normal(jax.random.key(24), (T, H), jnp.float32
                          ).astype(jnp.bfloat16)
    ids = jax.random.randint(jax.random.key(25), (T, topk), 0, 2 * n)
    w = jnp.ones((T, topk), jnp.float32) / topk
    out = jax.jit(roundtrip)(ctx.shard(t, P("x")), ctx.shard(ids, P("x")),
                             ctx.shard(w, P("x")))
    assert_allclose(np.asarray(out, np.float32), np.asarray(t, np.float32),
                    rtol=3e-2, atol=3e-2)


def test_hierarchical_race_free_under_detector(ctx2d, monkeypatch):
    """Race-detector slice over the 2-tier protocols: relay AG-GEMM,
    hierarchical push AG, 2-tier A2A on the quantized wire."""
    from triton_dist_tpu.ops.all_to_all import (combine_2d,
                                                create_all_to_all_context_2d,
                                                dispatch_2d)
    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    monkeypatch.setenv("TDT_DETECT_RACES", "1")
    n, axes = 6, ("a", "b")

    M, K, N = n * 8, 128, n * 16
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    out = jax.jit(lambda u, v: ag_gemm(ctx2d, u, v, axis=axes,
                                       cfg=GemmConfig(8, 16)))(
        ctx2d.shard(a, P(axes)), ctx2d.shard(b, P(None, axes)))
    assert_allclose(np.asarray(out, np.float32), np.asarray(a @ b),
                    rtol=5e-2, atol=5e-1)
    _assert_detector_ran_clean("ag_gemm 2-tier")

    y = jax.jit(lambda v: all_gather(ctx2d, v, method="push_2d"))(
        ctx2d.shard(a, P(axes)))
    assert_allclose(np.asarray(y), np.asarray(a))
    _assert_detector_ran_clean("push_2d all_gather")

    T, H, topk, E = 8, 128, 2, 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.float32,
                                       wire_dtype=jnp.int8)
    tokens = jax.random.normal(jax.random.key(2), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(3), (n * T, topk), 0, E)
    w = jnp.full((n * T, topk), 1.0 / topk)
    spec = P(axes)
    rt, ri, lay = dispatch_2d(a2a, ctx2d.shard(tokens, spec),
                              ctx2d.shard(ids, spec))
    back = combine_2d(a2a, rt, lay, ctx2d.shard(w, spec))
    jax.block_until_ready(back)
    _assert_detector_ran_clean("2-tier quantized a2a")
