"""Test bootstrap: force an 8-device virtual CPU mesh.

The distributed kernels run in Pallas TPU interpret mode on CPU devices —
this is the single-process cluster simulator the reference lacks (its tests
need real GPUs + torchrun; see SURVEY.md §4).

The container's axon sitecustomize eagerly initializes the single-chip TPU
backend at interpreter start, so setting JAX_PLATFORMS=cpu in the
environment is not enough — we re-point jax at CPU and drop the cached
backend before any test imports run.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import jax  # noqa: E402

from triton_dist_tpu.utils.env import force_virtual_cpu_devices  # noqa: E402

_N_DEVICES = int(os.environ.get("TDT_TEST_DEVICES", "12"))
force_virtual_cpu_devices(_N_DEVICES, skip_if_satisfied=False)

# Per-run XLA compile cache: many tests build fresh engines/kernels whose
# programs lower to byte-identical HLO (each engine owns its own jax.jit
# objects, so the trace-level cache cannot share them). A content-keyed
# persistent cache dedupes those XLA compiles within one suite run — it
# does NOT affect the compile-count guards, which count trace-cache
# entries, not XLA compiles. Fresh temp dir per run: nothing persists
# across runs, so the first run's numbers are every run's numbers. The
# 0.3 s threshold keeps the flood of tiny eager-op compiles out of the
# cache (caching those costs more in serialization than it saves).
import tempfile  # noqa: E402

_cache_dir = tempfile.mkdtemp(prefix="tdt_xla_cache_")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

assert jax.device_count() == _N_DEVICES, (
    f"expected {_N_DEVICES} virtual CPU devices, got {jax.devices()}"
)

# Most tests use a 4-way mesh for speed; TEST_WORLD_WIDE exercises the
# driver's exact 8-way configuration (tests/test_eight_way.py, and the
# full-participation 8-of-8 sweep in test_full_participation.py via
# TDT_TEST_DEVICES=8). The default keeps 12 devices so the wide tests also
# cover the participants-<-devices subset shape users hit on real pods.
TEST_WORLD = 4
TEST_WORLD_WIDE = 8
