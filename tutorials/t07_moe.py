"""Tutorial 07 — fused MoE overlap ops: AG+GroupGEMM and GroupGEMM+RS.

Analog of reference tutorials (test_ag_moe / test_moe_reduce_rs) +
allgather_group_gemm.py / moe_reduce_rs.py. Both are single
arrival-driven kernels: token blocks are expert-aligned on the SENDER so
wire blocks are expert-pure, and the consumer streams each arrived
segment through an in-kernel grouped GEMM whose weight tiles follow a
scalar-prefetch block→expert table.

Run:  python -m tutorials.t07_moe [--sim 4] [--case ag_group_gemm|reduce_rs]
"""

from tutorials.common import register_case, tutorial_main, world_context


@register_case("ag_group_gemm")
def ag_group_gemm():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    ctx = world_context()
    n = ctx.num_ranks
    E, H, N, T = 4, 128, n * 128, n * 32
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    w = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, ww: ag_moe_group_gemm(
        ctx, ctx.shard(t, P("x")), ctx.shard(i, P("x")),
        ctx.shard(ww, P(None, None, "x")), block_m=32))(tokens, ids, w)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(w)
    gold = np.stack([t[r] @ wn[idn[r]] for r in range(T)])
    np.testing.assert_allclose(np.asarray(out), gold, atol=3e-2, rtol=3e-2)
    print(f"fused AG+GroupGEMM over {n} PEs, {E} experts == dense golden")


@register_case("reduce_rs")
def reduce_rs():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_dist_tpu.ops.moe import moe_reduce_rs
    ctx = world_context()
    n = ctx.num_ranks
    E, K, N, T, topk = 4, n * 128, 128, n * 8, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    w = jax.random.normal(jax.random.key(3), (E, K, N), jnp.float32) * 0.1
    out = jax.jit(lambda t, i, ww, tww: moe_reduce_rs(
        ctx, ctx.shard(t, P(None, "x")), i, tww,
        ctx.shard(ww, P(None, "x", None)), block_m=16))(tokens, ids, w, tw)
    t, idn, wn = np.asarray(tokens), np.asarray(ids), np.asarray(w)
    rows = np.stack([t[r] @ wn[idn[r]] for r in range(T * topk)])
    gold = (rows.reshape(T, topk, N) * np.asarray(tw)[..., None]).sum(axis=1)
    np.testing.assert_allclose(np.asarray(out), gold, atol=3e-2, rtol=3e-2)
    print(f"fused GroupGEMM+topk-reduce+RS over {n} PEs == dense golden")


@register_case("correctness")
def correctness():
    ag_group_gemm()
    reduce_rs()


if __name__ == "__main__":
    tutorial_main(__doc__)
