"""Headline benchmark — prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline", "extras"}``.

Primary metric: AG-GEMM TFLOPS/chip at the Llama shape [4096, 4096, 4096]
bf16 (BASELINE.json / reference tutorial 07), running the REAL overlapping
``ag_gemm`` Pallas kernel compiled by Mosaic (not interpret mode) — on a
multi-chip mesh with remote DMA, and on a single chip as the n=1 degenerate
case (entry barrier + swizzled segment GEMM; the local segment reads its
input directly, so no DMA remains at n=1 — see ops/allgather_gemm.py).

Extras: MoE A2A dispatch/combine latency at the DeepSeek-infer shape
(128 tok/rank, topk=8, hidden=7168 — BASELINE.md second target, reference
README.md:55: 137 µs on 32 GPUs vs DeepEP's 182 µs). The A2A kernel's
local-copy DMA + semaphore waits DO execute compiled on the chip even at
n=1, covering the Mosaic lowering of the shmem machinery.

Timing methodology: the device sits behind an async tunnel where
``block_until_ready`` can return before remote execution finishes, so naive
event timing over-reports by ~100x. We therefore time a chain of kernels
ending in a scalar pulled to the host (a D2H transfer cannot complete
early), at two chain lengths, and difference them to cancel the fixed
round-trip (cf. the reference's CUDA-event ``perf_func``,
python/triton_dist/utils.py:186-198 — same warmup+iters idea, adapted to a
remote-execution runtime).

Baseline: FLUX-class efficiency = 60% of the chip's peak dense bf16 FLOPs
(the reference claims "comparable to FLUX" for AG-GEMM, README.md:146-150).
``vs_baseline`` = measured / baseline; 1.0 = FLUX-parity efficiency.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# dense bf16 peak TFLOP/s per chip by device kind (public specs)
_PEAKS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5", 197.0),     # v5e / v5 lite
    ("v4", 275.0),
    ("cpu", 0.15),     # virtual device smoke-run; irrelevant to the driver
)


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAKS:
        if key in kind:
            return peak
    return 197.0


def _best_of(measure, n: int = 2, stat=min) -> float:
    """Best over ``n`` full re-measurements. The shared dev chip's
    interference is heavy-tailed ONE-SIDED noise (other tenants only ever
    slow us down), so "best" is the right statistic — the same treatment
    the headline gets via its config loop + `_plausible` (VERDICT r4 Weak
    #4: extras that feed claims must not be single samples). ``stat`` is
    ``min`` for durations and MUST be ``max`` for throughputs (TFLOP/s —
    interference only ever lowers them)."""
    return stat(measure() for _ in range(n))


def _per_iter(timer, i1: int, i2: int, trials: int = 6) -> float:
    """Differenced per-iteration seconds: run ``timer(iters)`` at two chain
    lengths, INTERLEAVED (the tunnel's fixed round-trip drifts over tens of
    ms, so paired sampling + best-of beats two separate best-ofs), and
    difference the minima to cancel the fixed round-trip."""
    timer(i1), timer(i2)  # compile + warm both lengths
    t1 = t2 = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        timer(i1)
        t1 = min(t1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        timer(i2)
        t2 = min(t2, time.perf_counter() - t0)
    return (t2 - t1) / (i2 - i1)


def make_chain_timer(step_fn, a, b):
    """Timer over a data-dependent scan of ``step_fn`` ending in a scalar
    pull (a D2H transfer cannot complete early)."""
    cache = {}

    def timer(iters: int):
        if iters not in cache:
            def chain(a, b):
                def body(c, _):
                    return (step_fn(c, b) * jnp.asarray(0.01, c.dtype), None)
                c, _ = lax.scan(body, a, None, length=iters)
                return jnp.sum(c.astype(jnp.float32))
            cache[iters] = jax.jit(chain)
        return float(cache[iters](a, b))

    return timer


def bench_ag_gemm(ctx, n_dev: int, M: int, N: int, K: int, configs,
                  i1: int, i2: int) -> float:
    """Best per-call seconds for the overlapping ``ag_gemm`` kernel, using
    the persistent-workspace form (``ag_gemm_ws`` — context-owned symmetric
    workspace threaded through the timing loop; zero per-call workspace
    allocation, matching the reference's create-context-once usage).

    At n=1 the kernel degenerates to barrier_all + the segment-GEMM
    pipeline reading the input directly (the local segment bypasses the
    workspace by design); remote DMA paths only exist at n>1.
    """
    from triton_dist_tpu.ops.allgather_gemm import (ag_gemm_ws,
                                                    create_ag_gemm_workspace)

    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)
    a_s = ctx.shard(a, P("x"))
    b_s = ctx.shard(b, P(None, "x"))
    ws0 = create_ag_gemm_workspace(ctx, M // n_dev, K, jnp.bfloat16,
                                   axis="x")

    best_s, best_cfg = float("inf"), None
    first_err = [None]
    for cfg in configs:
        if (M // n_dev) % cfg.block_m or (N // n_dev) % cfg.block_n:
            continue
        if not cfg.vmem_ok(K, 2):
            continue
        try:
            # self-chain for ANY shape: feed an epsilon-scaled element of
            # the output back into the activation — a real data dependency
            # that lets the scan manage buffers (reused in place, no
            # dispatch-pileup memory cap, no host-dispatch noise)
            cache = {}

            def timer(iters: int, c=cfg):
                if iters not in cache:
                    def chain(a, b, ws):
                        def body(carry, _):
                            x, w = carry
                            y, w = ag_gemm_ws(ctx, x, b, w, axis="x",
                                              cfg=c, out_dtype=jnp.bfloat16)
                            eps = (y[0, 0].astype(jnp.float32)
                                   * 1e-30).astype(x.dtype)
                            return (x + eps, w), None
                        (x, _), _ = lax.scan(body, (a, ws), None,
                                             length=iters)
                        return jnp.sum(x.astype(jnp.float32))
                    cache[iters] = jax.jit(chain)
                return float(cache[iters](a_s, b_s, ws0))

            s = _per_iter(timer, i1, i2)
            if s < best_s:
                best_s, best_cfg = s, cfg
        except Exception as e:
            # keep the FIRST error so an all-configs failure (e.g. a
            # transient remote-compile outage) is diagnosable — a bare
            # best_s=inf assert hides the cause entirely
            first_err[0] = first_err[0] or f"{type(e).__name__}: {e}"[:200]
            continue
    if best_s == float("inf") and first_err[0]:
        raise RuntimeError(
            f"bench_ag_gemm: every config failed; first error: "
            f"{first_err[0]}")
    return best_s, best_cfg


def bench_a2a(ctx, tokens_per_rank: int, hidden: int, topk: int,
              num_experts: int, i1: int, i2: int,
              wire_dtype=None, dequant_edge: str = "post"
              ) -> tuple[float, float]:
    """(dispatch_s, roundtrip_s) per call at the DeepSeek-infer A2A shape —
    the BASELINE.md second target (reference low_latency_all_to_all.py,
    README.md:55; the reference's 137 µs number is fp8+scales, which
    ``wire_dtype=jnp.float8_e4m3fn`` matches). ``roundtrip`` = dispatch +
    combine chained."""
    from triton_dist_tpu.ops.all_to_all import (combine,
                                                create_all_to_all_context,
                                                dispatch)

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    a2a = create_all_to_all_context(ctx, max_tokens=tokens_per_rank,
                                    hidden=hidden, topk=topk,
                                    num_experts=num_experts, axis=axis,
                                    wire_dtype=wire_dtype,
                                    dequant_edge=dequant_edge)
    T = n * tokens_per_rank
    tokens = ctx.shard(jax.random.normal(jax.random.key(0), (T, hidden),
                                         jnp.float32).astype(jnp.bfloat16),
                       P(axis))
    ids = ctx.shard(jax.random.randint(jax.random.key(1), (T, topk), 0,
                                       num_experts), P(axis))
    w = ctx.shard(jax.nn.softmax(jax.random.normal(jax.random.key(2),
                                                   (T, topk)), axis=-1),
                  P(axis))

    # dispatch alone does not self-chain ([T,H] → [n,cap,H]), so feed an
    # epsilon-scaled summary of the output back into the input: a real data
    # dependency (not constant-foldable) that lets the scan-based chain
    # timer manage buffers (XLA reuses them across iterations — hundreds of
    # un-executed dispatches would otherwise hold [n,cap,H] each)
    def disp_step(t, i):
        recv_tokens, _, _ = dispatch(a2a, t, i)
        # expert-edge dispatch returns QuantTokens — anchor on the raw q
        rq = getattr(recv_tokens, "q", recv_tokens)
        eps = (jnp.sum(rq.astype(jnp.float32)) * 1e-20).astype(t.dtype)
        return t + eps

    disp_timer = make_chain_timer(disp_step, tokens, ids)
    dispatch_s = _per_iter(disp_timer, i1, i2)
    # the MXU-gather dispatch is ~25 µs: i2=1610 puts only ~40 ms of
    # differenced signal against the tunnel's ~50 ms jitter, which can
    # return a noise-floor artifact (0.2 µs observed). Re-measure with a
    # 4x chain when the reading is implausibly low (< 5 µs covers kernel
    # launch + the wire copy alone).
    if dispatch_s < 5e-6 and i2 > i1 + 100:
        dispatch_s = _per_iter(disp_timer, i1, (i2 - i1) * 4 + i1)

    # dispatch→combine roundtrip self-chains ([T,H] → [T,H]), so it can be
    # timed as a data-dependent scan — immune to host-dispatch noise
    def roundtrip(t, _ids):
        recv_tokens, _, layout = dispatch(a2a, t, _ids)
        if hasattr(recv_tokens, "q"):
            # expert-edge identity "expert": apply the scale once, as the
            # real expert GEMM's accumulator would (one fused pass straight
            # to the compute dtype — never materialize f32 rows)
            recv_tokens = (recv_tokens.q.astype(a2a.dtype)
                           * recv_tokens.scale[..., None].astype(a2a.dtype))
        return combine(a2a, recv_tokens, layout, w)

    roundtrip_s = _per_iter(make_chain_timer(roundtrip, tokens, ids), i1, i2)
    return dispatch_s, roundtrip_s


def bench_a2a_edges(ctx, tokens_per_rank: int, hidden: int, topk: int,
                    num_experts: int, i1: int, i2: int,
                    wire_dtype=None, quant_edge: str = "fused",
                    expert_major: bool = False) -> dict:
    """Per-edge timings for the quantized wire: dispatch alone, combine
    alone, and the chained roundtrip, at a given send-edge strategy.
    ``quant_edge="fused"`` quantizes tile-by-tile inside the collective
    (no standalone qpack pass on either edge); ``"pre"`` keeps the
    separate XLA pre-pass for comparison — the difference IS the fusion
    win. Each edge self-chains through an epsilon summary of its output
    (cf. ``bench_a2a``'s buffer-management note)."""
    from triton_dist_tpu.ops.all_to_all import (combine,
                                                create_all_to_all_context,
                                                dispatch)

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    a2a = create_all_to_all_context(ctx, max_tokens=tokens_per_rank,
                                    hidden=hidden, topk=topk,
                                    num_experts=num_experts, axis=axis,
                                    wire_dtype=wire_dtype,
                                    quant_edge=quant_edge,
                                    expert_major=expert_major)
    T = n * tokens_per_rank
    tokens = ctx.shard(jax.random.normal(jax.random.key(0), (T, hidden),
                                         jnp.float32).astype(jnp.bfloat16),
                       P(axis))
    ids = ctx.shard(jax.random.randint(jax.random.key(1), (T, topk), 0,
                                       num_experts), P(axis))
    w = ctx.shard(jax.nn.softmax(jax.random.normal(jax.random.key(2),
                                                   (T, topk)), axis=-1),
                  P(axis))

    def disp_step(t, i):
        recv_tokens, _, _ = dispatch(a2a, t, i)
        rq = getattr(recv_tokens, "q", recv_tokens)
        eps = (jnp.sum(rq.astype(jnp.float32)) * 1e-20).astype(t.dtype)
        return t + eps

    dispatch_s = _per_iter(make_chain_timer(disp_step, tokens, ids), i1, i2)

    # combine alone: freeze one dispatch's layout/payload outside the
    # timer, chain on an epsilon summary of the combined output
    recv0, _, layout0 = jax.jit(lambda t, i: dispatch(a2a, t, i))(tokens,
                                                                  ids)
    if hasattr(recv0, "q"):
        recv0 = (recv0.q.astype(a2a.dtype)
                 * recv0.scale[..., None].astype(a2a.dtype))

    def comb_step(r, _w):
        out = combine(a2a, r, layout0, _w)
        eps = (jnp.sum(out.astype(jnp.float32)) * 1e-20).astype(r.dtype)
        return r + eps

    combine_s = _per_iter(make_chain_timer(comb_step, recv0, w), i1, i2)

    def roundtrip(t, _ids):
        recv_tokens, _, layout = dispatch(a2a, t, _ids)
        if hasattr(recv_tokens, "q"):
            recv_tokens = (recv_tokens.q.astype(a2a.dtype)
                           * recv_tokens.scale[..., None].astype(a2a.dtype))
        return combine(a2a, recv_tokens, layout, w)

    roundtrip_s = _per_iter(make_chain_timer(roundtrip, tokens, ids), i1, i2)
    return {
        "dispatch_us": round(dispatch_s * 1e6, 1),
        "combine_us": round(combine_s * 1e6, 1),
        "roundtrip_us": round(roundtrip_s * 1e6, 1),
    }


def bench_a2a_wire(ctx, tokens_per_rank: int, hidden: int, topk: int,
                   num_experts: int, i1: int, i2: int,
                   wire_dtype=None, clamp: bool = True) -> float:
    """Wire-collective-only dispatch seconds — the REFERENCE's timed
    region. Its 137 µs times ``fast_all_to_all`` alone: token
    scatter/duplication, routing, and quantization are built OUTSIDE the
    timed loop ("will not be included in the e2e time measurement",
    test_all_to_all.py:313-329, timed region :331-348) and the scales are
    never applied in a standalone pass (post_process only slices,
    low_latency_all_to_all.py:251-270 — dequant rides the expert GEMM).
    So the apples-to-apples number is ``all_to_all_push`` on pre-built
    wire buffers: payload + ids (+ scale side-channel), no dequant. The
    full routing+gather+quant+wire+dequant path stays reported as
    ``a2a_dispatch_us`` (a strictly wider scope than the reference's)."""
    from triton_dist_tpu.ops.all_to_all import (_id_cols, all_to_all_push,
                                                create_all_to_all_context)

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    a2a = create_all_to_all_context(ctx, max_tokens=tokens_per_rank,
                                    hidden=hidden, topk=topk,
                                    num_experts=num_experts, axis=axis,
                                    wire_dtype=wire_dtype)
    cap, idc = a2a.capacity, _id_cols(a2a.capacity)
    wdt = a2a.wire_dtype or a2a.dtype
    payload = ctx.shard(
        jax.random.normal(jax.random.key(0), (n * n, cap, hidden),
                          jnp.float32).astype(wdt), P(axis))
    ids = ctx.shard(jnp.zeros((n * n, idc // 128, 128), jnp.int32), P(axis))
    arrays = (payload, ids)
    if wire_dtype is not None:
        arrays += (ctx.shard(jnp.ones((n * n, idc // 128, 128),
                                      jnp.float32), P(axis)),)

    # The chain carries an eps feedback like every other bench (a bare
    # self-chained copy is a fixed point whose measurement collapses into
    # noise), and since that eps pass would dominate the wire time, the
    # wire cost is measured by a SECOND difference: K=9 vs K=1 pushes per
    # iteration (identical eps work in both) → (t9 - t1) / 8 per push.
    # At the DeepSeek shape the buffers are VMEM-resident and the true
    # marginal push is only ~1-4 µs — at or below what 8×1600 differenced
    # iterations can resolve against the tunnel's ~50 ms drift, hence the
    # floor clamp below. K=9 still earns its keep on HBM-resident
    # payloads, where the push is ~100 µs and the estimator measures true
    # (scripts/wire_probe.py: cost scales with bytes at ~1 TB/s r+w).
    def timer_for(K: int):
        cache = {}

        def timer(iters: int):
            if iters not in cache:
                def chain(*arrs):
                    def body(c, _):
                        p = c[0]
                        for _k in range(K):
                            out = all_to_all_push(ctx, p, *c[1:], axis=axis)
                            p = out[0]
                        eps = (jnp.max(p.astype(jnp.float32)) * 1e-20
                               ).astype(c[0].dtype)
                        return (c[0] + eps,) + c[1:], None
                    c, _ = lax.scan(body, arrs, None, length=iters)
                    return jnp.sum(c[0].astype(jnp.float32))
                cache[iters] = jax.jit(chain)
            return float(cache[iters](*arrays))

        return timer

    t1 = _per_iter(timer_for(1), i1, i2)
    t9 = _per_iter(timer_for(9), i1, i2)
    if not clamp:
        # raw differenced marginal push — may be noise-negative at small
        # payloads; the payload-scaling FIT (bench_a2a_wire_fit) is the
        # seed path, this raw form is its per-point measurement
        return (t9 - t1) / 8
    # at the DeepSeek shape the wire buffers are VMEM-resident and the
    # marginal push (~1-2 µs: launch + barrier + VMEM copy) sits BELOW the
    # tunnel's differencing noise floor — clamp to the separately measured
    # per-kernel overhead so a noise-negative difference can't report a
    # zero-cost wire (scripts/wire_probe.py and the 56 MiB scaling run
    # establish both the floor and that larger payloads measure true)
    return max((t9 - t1) / 8, _WIRE_FLOOR_US * 1e-6)


def _wire_bytes(n: int, tokens_per_rank: int, hidden: int, topk: int,
                wire_dtype) -> int:
    """Total bytes one ``all_to_all_push`` moves PER DEVICE at this shape:
    the local wire arrays are [n, cap, …] (one slot per peer — global
    [n·n, …] sharded over the n devices), each read once and written once
    (payload + id wire + optional f32 scale wire)."""
    from triton_dist_tpu.ops.all_to_all import _cap_round, _id_cols
    itemsize = jnp.dtype(wire_dtype or jnp.bfloat16).itemsize
    cap = _cap_round(tokens_per_rank * topk, itemsize)
    idc = _id_cols(cap)
    b = n * (cap * hidden * itemsize + idc * 4)
    if wire_dtype is not None:
        b += n * idc * 4
    return 2 * b


def bench_a2a_wire_fit(ctx, tokens_per_rank: int, hidden: int, topk: int,
                       num_experts: int, i1: int, i2: int,
                       wire_dtype=None,
                       multipliers=(1, 2, 4, 8)) -> dict:
    """Wire seed WITHOUT the noise-floor clamp (VERDICT r4 #5): measure the
    marginal push at 1×/2×/4×/8× payload (the larger points resolve real
    traffic — the 56 MiB scaling run showed cost scales with bytes) and
    fit a TWO-SEGMENT model

        t(bytes) = max(t_lat, t0 + bytes/BW)

    — a flat launch/sync latency floor meeting an affine bandwidth segment
    at the knee. A single affine through all points couldn't serve both
    regimes (round-5 residuals 0.19/0.17: the latency-floored 1× point
    dragged the slope); here the first ``k`` points may sit on the floor
    (every split is tried, the single-affine ``k = 0`` included, and the
    one with the smallest worst-case relative residual wins). BOTH segment
    residuals are reported — ``fit_residual_small`` over the floor points
    and ``fit_residual_big`` at the largest (best-resolved) point — plus
    the raw least-squares terms and every pin reason, so a multi-chip run
    can falsify the model from the recorded artifacts."""
    import numpy as np

    n = ctx.axis_size(ctx.axis_names[0])
    ts, bs = [], []
    for m in multipliers:
        # keep the differenced signal duration roughly constant: bigger
        # payloads need fewer chain iterations to clear the tunnel jitter
        scale = max(1, m // 2)
        t = bench_a2a_wire(ctx, tokens_per_rank * m, hidden, topk,
                           num_experts, i1, max(i1 + 20, i2 // scale),
                           wire_dtype=wire_dtype, clamp=False)
        ts.append(t)
        bs.append(_wire_bytes(n, tokens_per_rank * m, hidden, topk,
                              wire_dtype))

    def _affine(pb, pt):
        A = np.vstack([np.ones(len(pb)), np.asarray(pb, np.float64)]).T
        (t0_f, slope_f), *_ = np.linalg.lstsq(
            A, np.asarray(pt, np.float64), rcond=None)
        return float(t0_f), float(slope_f)

    def _pin(t0_f, slope_f):
        # Report the fit HONESTLY: the raw least-squares terms are
        # recorded as-is so a later run can see exactly what the data
        # said. The *used* terms are pinned to the physics floor only when
        # the fit crosses it (a negative intercept means the small-payload
        # points sat below the launch/sync latency the big points imply —
        # measurement noise won, not negative wire cost), and every pin
        # states its reason.
        t0, per_byte, reason = t0_f, slope_f, None
        if per_byte < 0.0:
            # slope is the better-conditioned term (big payloads
            # dominate); a negative slope means the segment is noise —
            # fall back to a pure marginal-cost model through the
            # largest point
            per_byte = ts[-1] / bs[-1]
            t0 = 0.0
            reason = ("negative per-byte slope: points do not resolve "
                      "traffic; using bytes/t at the largest payload")
        elif t0 < 0.0:
            t0 = 0.0
            reason = ("negative intercept: launch latency below the "
                      "fit's noise floor; pinned to 0 so the seed never "
                      "credits negative wire cost")
        return t0, per_byte, reason

    best = None
    for k in range(len(bs) - 1):   # k floor points; >=2 bandwidth points
        t0_fit, pb_fit = _affine(bs[k:], ts[k:])
        t0, per_byte, reason = _pin(t0_fit, pb_fit)
        t_lat = float(np.mean(ts[:k])) if k else None

        def model(b, _tl=t_lat, _t0=t0, _pb=per_byte):
            aff = _t0 + _pb * b
            return max(_tl, aff) if _tl is not None else aff

        rel = [abs(model(b) - t) / max(abs(t), 1e-12)
               for b, t in zip(bs, ts)]
        cand = {"k": k, "t0_fit": t0_fit, "pb_fit": pb_fit, "t0": t0,
                "per_byte": per_byte, "reason": reason, "t_lat": t_lat,
                "model": model, "score": max(rel),
                "resid_small": max(rel[:k]) if k else None,
                "resid_big": rel[-1]}
        # strict improvement required: ties keep the simpler split
        # (k = 0 is the plain single-affine fit, tried first)
        if best is None or cand["score"] < best["score"] - 1e-12:
            best = cand

    t0, per_byte, t_lat = best["t0"], best["per_byte"], best["t_lat"]
    seed_s = best["model"](bs[0])
    knee_b = None
    if t_lat is not None and per_byte > 0:
        knee_b = max(0.0, (t_lat - t0) / per_byte)
    return {
        "wire_us": round(seed_s * 1e6, 2),
        "t0_us": round(t0 * 1e6, 2),
        "t0_fit_us": round(best["t0_fit"] * 1e6, 2),
        "t0_pinned_reason": best["reason"],
        "t_lat_us": (round(t_lat * 1e6, 2) if t_lat is not None else None),
        "knee_mb": (round(knee_b / 1e6, 2) if knee_b is not None else None),
        "latency_points": best["k"],
        "gb_per_s": (round(1e-9 / per_byte, 1) if per_byte > 0 else None),
        "gb_per_s_fit": (round(1e-9 / best["pb_fit"], 1)
                         if best["pb_fit"] > 0 else None),
        "points_us": [round(t * 1e6, 2) for t in ts],
        "points_mb": [round(b / 1e6, 1) for b in bs],
        "fit_residual_small": (round(best["resid_small"], 3)
                               if best["resid_small"] is not None else None),
        "fit_residual_big": round(best["resid_big"], 3),
    }


def bench_moe(ctx, i1: int, i2: int, tokens_rows: int = 1024,
              hidden: int = 1024, n_out: int = 1024,
              num_experts: int = 64) -> dict[str, float]:
    """Fused AG+GroupGEMM latency at an expert-heavy shape, uniform vs
    skewed routing. Skewed (most tokens on few experts) is where the
    runtime block bound pays: the static layout always computed
    ``round_up(T,bm) + E*bm`` rows; the bounded walk does
    ``sum_e ceil(count_e/bm)`` blocks (reference num_tokens_post_padded
    parity, allgather_group_gemm.py:278-285)."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    T = tokens_rows
    toks = ctx.shard(jax.random.normal(jax.random.key(0), (T, hidden),
                                       jnp.float32).astype(jnp.bfloat16),
                     P(axis))
    w = ctx.shard(jax.random.normal(jax.random.key(1),
                                    (num_experts, hidden, n_out),
                                    jnp.float32).astype(jnp.bfloat16) * 0.1,
                  P(None, None, axis))
    ids_u = jax.random.randint(jax.random.key(2), (T,), 0, num_experts)
    # skewed: 90% of tokens on 4 experts (decode-time MoE reality)
    ids_s = jnp.where(jax.random.uniform(jax.random.key(3), (T,)) < 0.9,
                      jax.random.randint(jax.random.key(4), (T,), 0, 4),
                      ids_u)
    from triton_dist_tpu.utils import on_cpu
    out = {}
    for name, ids in (("uniform", ids_u), ("skewed", ids_s)):
        ids_sh = ctx.shard(ids, P(axis))
        if on_cpu():
            # API smoke only: a shard_map'd interpret-mode kernel inside the
            # chain timer's lax.scan deadlocks the simulator's device
            # threads (see the scan+interpret note in the verify skill)
            jax.block_until_ready(jax.jit(
                lambda t, i: ag_moe_group_gemm(ctx, t, i, w))(toks, ids_sh))
            out[f"moe_ag_gg_{name}_us"] = None
            continue

        # block_m sweep over the autotuned entry's candidate list (ONE
        # source of truth — the bench must not diverge from what the
        # shipped op would pick), best-of like the headline's config loop
        from triton_dist_tpu.ops.autotuned import _MOE_BLOCK_CANDIDATES
        best = float("inf")
        first_err = None
        for bm in _MOE_BLOCK_CANDIDATES:
            def step(t, i, _bm=bm):
                y = ag_moe_group_gemm(ctx, t, i, w, block_m=_bm)
                eps = (jnp.sum(y.astype(jnp.float32)) * 1e-20
                       ).astype(t.dtype)
                return t + eps

            try:
                best = min(best, _per_iter(
                    make_chain_timer(step, toks, ids_sh), i1, i2))
            except Exception as e:
                first_err = first_err or f"{type(e).__name__}: {e}"[:120]
                continue
        if best == float("inf"):
            # every candidate failed: fail LOUDLY (a silent Infinity
            # would corrupt the JSON line and hide the regression)
            raise RuntimeError(
                f"moe_ag_gg: every block_m candidate failed; first error: "
                f"{first_err}")
        out[f"moe_ag_gg_{name}_us"] = round(best * 1e6, 1)
    return out


def bench_ep_block(ctx, i1: int, i2: int, T: int = 128, D: int = 7168,
                   F: int = 512, E: int = 16, topk: int = 8,
                   wire_dtype=None, dequant_edge: str = "post",
                   expert_major: bool = False) -> float:
    """Full EP MoE serving block per-call seconds: router → dispatch →
    grouped gated FFN over local experts → combine (the reference's
    end-to-end inference workload, test_ep_moe_inference.py). Weights ride
    the chain as arguments — closing over them would bake multi-hundred-MB
    constants into the remote compile payload (HTTP 413)."""
    from triton_dist_tpu.layers import EPAll2AllLayer
    from triton_dist_tpu.models.moe import moe_mlp_ep_overlap

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    # expert count must divide over the ranks: round the requested E up to
    # a multiple of n so the block measures on any mesh size
    E = max(n, (E + n - 1) // n * n)
    kw = {} if wire_dtype is None else dict(wire_dtype=wire_dtype,
                                            dequant_edge=dequant_edge)
    layer = EPAll2AllLayer.create(ctx, max_tokens=T, hidden=D, topk=topk,
                                  num_experts=E, axis=axis,
                                  expert_major=expert_major, **kw)
    x = ctx.shard(jax.random.normal(jax.random.key(0), (n * T, D),
                                    jnp.float32).astype(jnp.bfloat16),
                  P(axis))
    rw = jax.random.normal(jax.random.key(1), (D, E), jnp.float32) * 0.3
    wg = (jax.random.normal(jax.random.key(2), (E, D, F)) * 0.05
          ).astype(jnp.bfloat16)
    wu = (jax.random.normal(jax.random.key(3), (E, D, F)) * 0.05
          ).astype(jnp.bfloat16)
    wd = (jax.random.normal(jax.random.key(4), (E, F, D)) * 0.05
          ).astype(jnp.bfloat16)

    # serving deployment: gate+up pre-packed ONCE into the interleaved
    # single-stream layout. Measured for the gated kernel alone:
    # two-stream (128,128) 538.9 µs → packed full-K (128,128) 381.5 µs
    # (K-split variants re-read the x strip per n-step and lose in-block).
    # Weight prep is one-time, like any serving weight layout.
    from triton_dist_tpu.ops.group_gemm import pack_gated_weights
    bn_pack = min(128, F)
    wgu = pack_gated_weights(wg, wu, block_n=bn_pack)

    def step(c, w):
        # tokens stay STATIC (+ a vanishing carry term): the chain timer
        # decays its carry by 0.01/iter, and a decaying token carry would
        # collapse the router to all-tie logits — the bounded grouped GEMM
        # then measures a degenerate concentrated routing, not the
        # balanced serving block. The scalar carry keeps the data
        # dependency without perturbing the top-k picks.
        toks = w[4] + c.astype(jnp.bfloat16)
        y = moe_mlp_ep_overlap(ctx, layer, toks, w[0], w[1], w[2], w[3],
                               axis=axis, block_n=bn_pack,
                               we_gate_up_packed=w[5])
        return jnp.max(y.astype(jnp.float32)) * 1e-20

    return _per_iter(make_chain_timer(
        step, jnp.zeros((), jnp.float32), (rw, wg, wu, wd, x, wgu)),
        i1, i2)


def bench_small_ag(ctx, i1: int, i2: int) -> dict:
    """Small-message AG latency rows (VERDICT r4 Missing #3 / Next #9):
    XLA ``all_gather`` vs the Pallas ``push`` AG vs the barrier-free LL AG
    at 4/16/64 KB per-rank payloads (f32, 128 lanes). At n=1 the wire
    degenerates and the rows measure per-call overhead (launch + barrier
    vs launch only) — the regime where the LL design pays; real
    multi-chip runs measure the full story."""
    from triton_dist_tpu.ops import (all_gather, all_gather_ll,
                                     create_ag_ll_workspace)

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    out = {}
    # these ops are single-digit µs: one call per scan iteration leaves
    # the differenced signal far below the tunnel's ~50 ms jitter (a
    # first attempt read 0.1 to NEGATIVE µs). Like bench_a2a_wire, run K
    # calls per iteration and difference K vs 1 — (t_K - t_1)/(K-1) is
    # the marginal per-call cost with the chain bookkeeping cancelled.
    K = 33

    def marginal(make_chain):
        cache = {}

        def timer_for(k):
            def timer(iters):
                key = (k, iters)
                if key not in cache:
                    cache[key] = jax.jit(make_chain(k, iters))
                return float(cache[key]())
            return timer

        t1 = _per_iter(timer_for(1), i1, i2)
        tk = _per_iter(timer_for(K), i1, i2)
        return max((tk - t1) / (K - 1), 0.0)

    for kb in (4, 16, 64):
        rows = max(8, kb * 1024 // (128 * 4))
        x = ctx.shard(jax.random.normal(jax.random.key(kb),
                                        (n * rows, 128), jnp.float32),
                      P(axis))

        sm = ctx.shard_map(
            lambda s: lax.all_gather(s, axis, axis=0, tiled=True),
            in_specs=P(axis), out_specs=P(None, None))

        def make_xla(k, iters, x=x):
            def chain():
                def body(c, _):
                    v = c
                    for _j in range(k):
                        y = sm(v)
                        v = v + (jnp.sum(y.astype(jnp.float32))[None, None]
                                 * 1e-20).astype(v.dtype)
                    return v, None
                v, _ = lax.scan(body, x, None, length=iters)
                return jnp.sum(v.astype(jnp.float32))
            return chain

        out[f"ag_xla_{kb}kb_us"] = round(marginal(make_xla) * 1e6, 2)

        def make_push(k, iters, x=x):
            def chain():
                def body(c, _):
                    v = c
                    for _j in range(k):
                        y = all_gather(ctx, v, axis=axis, method="push")
                        v = v + (jnp.sum(y.astype(jnp.float32))[None, None]
                                 * 1e-20).astype(v.dtype)
                    return v, None
                v, _ = lax.scan(body, x, None, length=iters)
                return jnp.sum(v.astype(jnp.float32))
            return chain

        out[f"ag_push_{kb}kb_us"] = round(marginal(make_push) * 1e6, 2)

        ws0 = create_ag_ll_workspace(ctx, rows, (128,), jnp.float32,
                                     axis=axis)

        def make_ll(k, iters, x=x, ws0=ws0):
            def chain():
                def body(c, it):
                    v, w = c
                    for _j in range(k):
                        y, w = all_gather_ll(
                            ctx, v, w,
                            ((it * k + _j) % 2)[None].astype(jnp.int32),
                            axis=axis)
                        v = v + (jnp.sum(y.astype(jnp.float32)) * 1e-20
                                 ).astype(v.dtype)
                    return (v, w), None
                (v, _), _ = lax.scan(body, (x, ws0),
                                     jnp.arange(iters))
                return jnp.sum(v.astype(jnp.float32))
            return chain

        out[f"ag_ll_{kb}kb_us"] = round(marginal(make_ll) * 1e6, 2)
    return out


def bench_baselines(ctx, n_dev: int, M: int, N: int, K: int, cfg,
                    i1: int, i2: int) -> dict:
    """Non-overlap baselines at the headline shape (VERDICT r4 Missing #1 —
    every reference perf claim is a comparison against torch+NCCL / FLUX
    non-overlapped rows, README.md:146-163):

    - ``xla_ag_dot``: plain XLA `all_gather` + `dot` under jit (GSPMD) —
      what a user gets with sharding annotations and no custom kernel. At
      n=1 the all_gather is the identity, so this row is XLA's own dense
      matmul.
    - ``pallas_matmul``: the bare Pallas GEMM pipeline (``ops.gemm.matmul``)
      with the same tile config the overlap kernel picked — isolates the
      GEMM engine from the overlap protocol (n=1 only: the row exists to
      show the ag_gemm number is not "just a good matmul" hiding comm).
    - ``ag_gemm_serial``: the overlap kernel with ``TDT_SERIAL=1`` (every
      put completes inline before compute proceeds — comm serialized
      against compute). At n=1 there are no remote puts, so this row
      documents the degenerate equality; at n>1 it is the
      overlap-disabled twin the reference plots against.
    """
    import os

    from triton_dist_tpu.ops.gemm import matmul

    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)
    out = {}

    def tflops(s):
        return round(2.0 * M * N * K / s / max(n_dev, 1) / 1e12, 1)

    # 1. plain XLA all_gather + dot (GSPMD) — the no-custom-kernel row
    a_s = ctx.shard(a, P("x"))
    b_s = ctx.shard(b, P(None, "x"))

    def f(xs, ws):
        xg = lax.all_gather(xs, "x", axis=0, tiled=True)
        return (xg @ ws).astype(jnp.bfloat16)

    sm = ctx.shard_map(f, in_specs=(P("x"), P(None, "x")),
                       out_specs=P(None, "x"))

    def xla_step(x, w):
        y = sm(x, w)
        # full-reduction feedback: a y[0,0] probe would let XLA's
        # algebraic simplifier shrink the dead matmul to one output
        # element (the Pallas rows are opaque custom calls; this row is
        # pure XLA and needs every output live)
        return x + (jnp.sum(y.astype(jnp.float32)) * 1e-30).astype(x.dtype)

    # same plausibility guard as the headline: a baseline row above 95%
    # of dense peak is an interference artifact, and an inflated
    # non-overlap row would understate the overlap delta this bench
    # exists to measure
    v, artifact = _plausible(lambda: tflops(
        _per_iter(make_chain_timer(xla_step, a_s, b_s), i1, i2)),
        frac=0.95)
    out["xla_ag_dot_tflops"] = v
    if artifact:
        out["xla_ag_dot_artifact"] = True

    # 2. bare Pallas GEMM, same tile config as the overlap kernel
    if n_dev == 1:
        def mm_step(x, w):
            y = matmul(x, w, cfg=cfg, out_dtype=jnp.bfloat16)
            return x + (y[0, 0].astype(jnp.float32) * 1e-30).astype(x.dtype)

        v, artifact = _plausible(lambda: tflops(
            _per_iter(make_chain_timer(mm_step, a, b), i1, i2)), frac=0.95)
        out["pallas_matmul_tflops"] = v
        if artifact:
            out["pallas_matmul_artifact"] = True

    # 3. overlap kernel with comm serialized (TDT_SERIAL read at trace
    # time; fresh timers inside bench_ag_gemm retrace under the flag).
    # Same plausibility guard: a same-day serial row read 192.3 = 97.6%
    # of dense peak — an interference artifact, not a measurement.
    old = os.environ.get("TDT_SERIAL")
    os.environ["TDT_SERIAL"] = "1"
    try:
        def serial_row():
            s, _ = bench_ag_gemm(ctx, n_dev, M, N, K, [cfg], i1, i2)
            return tflops(s) if s < float("inf") else 0.0

        v, artifact = _plausible(serial_row, frac=0.95)
        if v:
            out["ag_gemm_serial_tflops"] = v
            if artifact:
                out["ag_gemm_serial_artifact"] = True
    finally:
        if old is None:
            del os.environ["TDT_SERIAL"]
        else:
            os.environ["TDT_SERIAL"] = old
    return out


def attn_sweep():
    """Ring-attention tile sweep at the bench shape (VERDICT r3 #7: the
    42%-MFU sweep stopped at the VMEM cliff; re-sweep after the
    dtype-preserving matmul change). One JSON line per tile config.

    The shared dev chip shows heavy-tailed interference: differenced
    readings occasionally come out ABOVE the chip's dense peak (an
    impossible artifact of drift landing inside the differencing window).
    Such readings are re-measured up to twice and, if still impossible,
    reported with ``"artifact": true`` so a table consumer never banks
    them."""
    from triton_dist_tpu.shmem.context import initialize_distributed
    from triton_dist_tpu.utils import on_cpu
    n_dev = len(jax.devices())
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n_dev,))
    peak = chip_peak_tflops()
    smoke = on_cpu()   # interpret mode: API smoke at a tiny shape only
    if smoke:
        tiles = [(128, 128)]
    else:
        # the autotuner's candidate list, plus over-budget probes so the
        # sweep validates the VMEM-prune boundary empirically (expected
        # to fail compile; a probe that RUNS means the prune is too tight)
        from triton_dist_tpu.ops.autotuned import _ATTN_CANDIDATES
        tiles = list(_ATTN_CANDIDATES) + [(2048, 1024), (4096, 512)]
    shape = dict(s_loc=256, Hq=4, Hkv=2) if smoke else {}
    for bq, bk in tiles:
        try:
            t, artifact = _plausible(
                lambda bq=bq, bk=bk: bench_attn(
                    ctx, i1=1 if smoke else 10, i2=3 if smoke else 210,
                    block_q=bq, block_k=bk, **shape
                )["attn_tflops_per_chip"],
                frac=0.98, skip=smoke)
            line = {"block_q": bq, "block_k": bk,
                    "attn_tflops_per_chip": t,
                    "mfu_pct": round(100 * t / peak, 1)}
            if artifact:
                line["artifact"] = True
            print(json.dumps(line))
        except Exception as e:
            print(json.dumps({"block_q": bq, "block_k": bk,
                              "error": f"{type(e).__name__}: {e}"[:120]}))


def bench_attn(ctx, i1: int, i2: int, B: int = 1, Hq: int = 16,
               Hkv: int = 4, D: int = 128, s_loc: int = 4096,
               block_q: int = 1024, block_k: int = 1024
               ) -> dict[str, float]:
    """Causal ring-attention forward TFLOP/s per chip (at n=1: the blockwise
    flash kernel itself — MXU efficiency of the per-step inner loop)."""
    from triton_dist_tpu.ops.ring_attention import ring_attention
    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    S = n * s_loc
    q = (jax.random.normal(jax.random.key(0), (B, Hq, S, D), jnp.float32)
         * 0.5).astype(jnp.bfloat16)
    k = (jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32)
         * 0.5).astype(jnp.bfloat16)
    v = (jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32)
         * 0.5).astype(jnp.bfloat16)
    spec = P(None, None, axis)
    ks_, vs_ = ctx.shard(k, spec), ctx.shard(v, spec)

    def step(qq, _):
        o = ring_attention(ctx, qq, ks_, vs_, axis=axis, causal=True,
                           block_q=block_q, block_k=block_k)
        return qq + (o * jnp.asarray(1e-20, o.dtype))

    s = _per_iter(make_chain_timer(step, ctx.shard(q, spec),
                                   jnp.zeros((), jnp.bfloat16)), i1, i2)
    flops = 2 * 2 * B * Hq * S * S * D / 2  # 2 matmuls; causal halves
    return {"attn_tflops_per_chip": round(flops / s / max(n, 1) / 1e12, 2)}


def bench_decode(ctx, i1: int, i2: int, B: int = 1, Hq: int = 32,
                 Hkv: int = 8, D: int = 128, s_local: int = 1024
                 ) -> dict[str, float]:
    """SP flash-decode latency (batch=1, the reference's scaling-chart
    workload, README.md:161-163) for the generic push AG + separate combine
    vs the fused AG+merge latency paths."""
    from triton_dist_tpu.ops.flash_decode import sp_gqa_flash_decode

    axis = ctx.axis_names[0]
    n = ctx.axis_size(axis)
    S = n * s_local
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32
                          ).astype(jnp.bfloat16)
    k = jax.random.normal(jax.random.key(1), (B, Hkv, S, D), jnp.float32
                          ).astype(jnp.bfloat16)
    v = jax.random.normal(jax.random.key(2), (B, Hkv, S, D), jnp.float32
                          ).astype(jnp.bfloat16)
    kv = jnp.array([S] * B, jnp.int32)
    ks = ctx.shard(k, P(None, None, axis))
    vs = ctx.shard(v, P(None, None, axis))

    res = {}
    for method in ("push", "fused"):
        # decode output [B,Hq,D] feeds back as next q: self-chains
        def step(qq, _m=method):
            out = sp_gqa_flash_decode(ctx, qq, ks, vs, kv, axis=axis,
                                      ag_method=_m)
            return qq + (out * jnp.asarray(1e-20, out.dtype))

        timer = make_chain_timer(lambda c, _b, s=step: s(c), q,
                                 jnp.zeros((), jnp.bfloat16))
        res[f"decode_{method}_us"] = round(
            _per_iter(timer, i1, i2) * 1e6, 1)
    return res


def bench_flash_decode_dist(Hq: int = 8, Hkv: int = 4, D: int = 128,
                            page_size: int = 512) -> dict:
    """Distributed flash-decode rows (ISSUE 19): ONE request's pages
    sharded over an SP rank sweep n ∈ {1, 2, 4} at context lengths
    {8k, 32k, 64k} tokens.

    - ``flash_decode_dist_us``: measured per-call wall latency per
      (n, length). On the CPU interpret mesh ranks run SERIALIZED, so
      this wall clock is an API smoke number, not the scaling story.
    - the scaling story is the wire-fit model — ``fd_attn_split_us``,
      the SAME model the engine metrics and serve_sim panels quote:
      local partial walk ∝ ceil(pages/n) vs fixed-order fold wait
      ∝ (n−1) partial-slab rows. ``attn_model_total_us`` is ASSERTED
      sublinear in rank count at every length: a page's KV bytes
      (2·Hkv·ps·D·itemsize) dwarf its slab row (Hq·(D+128)·4), so
      halving the local walk always buys more than the extra fold
      slabs cost. The assertion covers the full {1,2,4} sweep even
      when the device count caps the measured runs (the model is pure
      host math).
    - bit-identity vs the n=1 golden is ASSERTED per length: per-page
      partials + the one fixed (page, rank) fold order mean the output
      cannot move with the mesh — the op-level twin of the engine's
      cross-mesh trace contract.
    """
    import numpy as _np

    from triton_dist_tpu.ops.flash_decode import flash_decode_dist
    from triton_dist_tpu.serving.sharded import fd_attn_split_us
    from triton_dist_tpu.shmem.context import initialize_distributed
    from triton_dist_tpu.utils import on_cpu

    n_dev = len(jax.devices())
    ns = [n for n in (1, 2, 4) if n <= n_dev]
    page_kv = 2 * Hkv * page_size * D * 4           # f32 pool
    slab_row = Hq * (D + 128) * 4
    rows = {}
    for s_tok in (8192, 32768, 65536):
        pages = s_tok // page_size
        q = jax.random.normal(jax.random.key(0), (1, Hq, D), jnp.float32)
        kp = jax.random.normal(jax.random.key(1),
                               (pages, Hkv, page_size, D), jnp.float32)
        vp = jax.random.normal(jax.random.key(2),
                               (pages, Hkv, page_size, D), jnp.float32)
        kn = jax.random.normal(jax.random.key(3), (1, Hkv, D), jnp.float32)
        vn = jax.random.normal(jax.random.key(4), (1, Hkv, D), jnp.float32)
        bt = jnp.arange(pages, dtype=jnp.int32)[None]
        pos = jnp.array([s_tok - 1], jnp.int32)
        kv = jnp.array([s_tok], jnp.int32)

        key = f"{s_tok // 1024}k"
        rows[key] = {}
        golden = None
        model_total = {}
        for n in ns:
            ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n,))
            fn = jax.jit(lambda q_, kn_, vn_, kp_, vp_, _c=ctx:
                         flash_decode_dist(_c, q_, kn_, vn_, kp_, vp_,
                                           bt, pos, kv, axis="x")[0])
            kps, vps = ctx.shard(kp, P("x")), ctx.shard(vp, P("x"))
            out = jax.block_until_ready(fn(q, kn, vn, kps, vps))  # compile

            def measure(fn=fn, kps=kps, vps=vps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(q, kn, vn, kps, vps))
                return time.perf_counter() - t0

            s = _best_of(measure, n=2)
            if golden is None:
                golden = _np.asarray(out)
            else:
                assert _np.array_equal(_np.asarray(out), golden), (
                    f"flash_decode_dist at n={n}, {key} tokens changed "
                    "bits vs the n=1 golden — the fixed-order fold "
                    "contract broke")
            local, fold = fd_attn_split_us(n, 1, 1, pages, page_kv,
                                           slab_row)
            model_total[n] = local + fold
            rows[key][f"n{n}"] = {
                "flash_decode_dist_us": round(s * 1e6, 1),
                "attn_local_model_us": round(local, 2),
                "attn_fold_wait_model_us": round(fold, 2),
                "attn_model_total_us": round(local + fold, 2),
            }
        rows[key]["bit_identical"] = True
        for n in (1, 2, 4):
            if n not in model_total:
                local, fold = fd_attn_split_us(n, 1, 1, pages, page_kv,
                                               slab_row)
                model_total[n] = local + fold
        assert model_total[4] < model_total[2] < model_total[1], (
            f"modeled per-step attention not sublinear in rank count at "
            f"{key}: {model_total} — the fold-slab wire cost outweighs "
            "the local-walk savings at this shape")
        rows[key]["model_sublinear"] = True
    return {
        "flash_decode_dist": rows,
        "flash_decode_dist_knobs": {
            "Hq": Hq, "Hkv": Hkv, "head_dim": D, "page_size": page_size,
            "pool_dtype": "float32", "page_kv_bytes": page_kv,
            "slab_row_bytes": slab_row,
            "wall_clock": "interpret-smoke" if on_cpu() else "device",
            "model": "wire-fit (serving/sharded.py fd_attn_split_us)"},
    }


def bench_serving(ctx, i1: int, i2: int, B: int = 1, Hq: int = 32,
                  Hkv: int = 8, D: int = 128, S: int = 4096,
                  page_size: int = 128, num_slots: int = 4,
                  n_layers: int = 2, decode_horizon: int = 4,
                  prefill_chunk: int = 16) -> dict:
    """Serving-runtime extras (ISSUE 2 paged parity + ISSUE 4
    device-resident hot loop):

    - ``serving_decode_step_us``: the jitted ``gqa_decode_paged`` attention
      step at the SAME (B, Hq, Hkv, D, S) as ``bench_decode``'s contiguous
      ``decode_push_us``/``decode_fused_us`` rows — the apples-to-apples
      parity target (same bytes streamed; the block table is the only
      extra traffic).
    - ``serving_step_us``: one DISPATCH of the fused device chain
      (``decode_multistep_paged`` at horizon K: K sample-fused model steps
      per launch, tokens leave the device as one int32 slab), timed as a
      data-dependent chain exactly like ``ServingEngine.step``'s hot path.
      ``serving_step_tok_us`` divides by K.
    - real-engine rows from a small seeded trace through ``ServingEngine``
      at horizon K and again at K=1: ``serving_tok_per_s``,
      ``serving_device_us``/``serving_host_us`` (the per-dispatch
      device/host split from the engine's own histograms),
      ``serving_dispatches`` vs ``serving_dispatches_k1`` (the >=K-times
      launch-count win), ``serving_host_syncs``, ``serving_compiles``.

    - chunked-prefill rows (ISSUE 5) from the same trace replayed with
      ``prefill_chunk``: ``serving_prefill_stall_us`` (per-chunk dispatch
      latency), ``serving_decode_stall_us`` vs ``_inline_us`` (admission
      time ahead of the decode dispatch, chunk-bounded vs whole-prompt),
      ``serving_ttft_split_us`` (queue wait vs prefill latency, both
      paths), ``serving_prefill_chunks``, ``serving_compiles_chunked``.

    Knobs mirror ``scripts/serve_sim.py``
    (--slots/--page-size/--layers/--decode-horizon/--prefill-chunk).
    """
    from triton_dist_tpu.models.llama import (LlamaConfig,
                                              decode_multistep_paged,
                                              init_page_pool, init_params)
    from triton_dist_tpu.ops.flash_decode import gqa_decode_paged
    from triton_dist_tpu.serving import ServingEngine

    out = {}
    # 1. paged attention step at the contiguous-bench shape -----------------
    n_pages = S // page_size
    q = jax.random.normal(jax.random.key(0), (B, Hq, D), jnp.float32
                          ).astype(jnp.bfloat16)
    kp = jax.random.normal(jax.random.key(1), (n_pages, Hkv, page_size, D),
                           jnp.float32).astype(jnp.bfloat16)
    vp = jax.random.normal(jax.random.key(2), (n_pages, Hkv, page_size, D),
                           jnp.float32).astype(jnp.bfloat16)
    bt = jnp.tile(jnp.arange(n_pages, dtype=jnp.int32)[None], (B, 1))
    kv = jnp.array([S] * B, jnp.int32)

    def attn_step(qq, _):
        o, _lse = gqa_decode_paged(qq, kp, vp, bt, kv)
        return qq + (o * jnp.asarray(1e-20, o.dtype))

    timer = make_chain_timer(attn_step, q, jnp.zeros((), jnp.bfloat16))
    out["serving_decode_step_us"] = round(_per_iter(timer, i1, i2) * 1e6, 1)

    # 2. fused device chain at batch = num_slots, horizon K -----------------
    # one timed iteration == one DISPATCH (K sample-fused steps on device)
    K = decode_horizon
    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(3), cfg)
    pages_per_seq = -(-(i2 * K + 2) // page_size)
    pool = init_page_pool(cfg, num_slots * pages_per_seq + 1, page_size)
    bt2 = jnp.asarray(
        1 + jnp.arange(num_slots * pages_per_seq, dtype=jnp.int32
                       ).reshape(num_slots, pages_per_seq))
    tok0 = jnp.zeros((num_slots,), jnp.int32)
    lim = jnp.full((num_slots,), K, jnp.int32)

    cache = {}

    def step_timer(iters: int):
        if iters not in cache:
            def chain(params, tok0, kp0, vp0, bt2, lim):
                def body(c, _):
                    tok, pos, pages = c
                    _toks, tok, pos, pages = decode_multistep_paged(
                        params, tok, pos, cfg, pages, bt2, lim, K)
                    return (tok, pos, pages), None
                c0 = (tok0, jnp.zeros((num_slots,), jnp.int32),
                      {"k": kp0, "v": vp0})
                (tok, pos, _), _ = lax.scan(body, c0, None, length=iters)
                return (jnp.sum(tok.astype(jnp.float32))
                        + jnp.sum(pos.astype(jnp.float32)))
            cache[iters] = jax.jit(chain)
        return float(cache[iters](params, tok0, pool["k"], pool["v"], bt2,
                                  lim))

    step_s = _per_iter(step_timer, i1, i2)
    out["serving_step_us"] = round(step_s * 1e6, 1)
    out["serving_step_tok_us"] = round(step_s / K * 1e6, 1)

    # 3. real engine on a seeded trace: horizon K vs the K=1 baseline -------
    import numpy as _np

    def _engine_trace(horizon: int, chunk: int | None = None):
        rng = _np.random.RandomState(0)
        eng = ServingEngine(params, cfg, num_slots=num_slots, page_size=16,
                            num_pages=8 * num_slots + 8, pages_per_seq=8,
                            decode_horizon=horizon, prefill_chunk=chunk)
        for _ in range(3 * num_slots):
            plen = int(rng.randint(4, 24))
            prompt = [int(t) for t in
                      rng.randint(1, cfg.vocab_size, size=plen)]
            eng.submit(prompt, int(rng.randint(8, 24)))
        t0 = time.perf_counter()
        res = eng.run(max_steps=100_000)
        wall = time.perf_counter() - t0
        assert len(res) == 3 * num_slots
        return eng, eng.metrics.snapshot(), wall

    eng, snap, wall = _engine_trace(K)
    _, snap1, _ = _engine_trace(1)
    out["serving_tok_per_s"] = round(snap["tokens_generated"] / wall, 1)
    dev, host = snap["step_device_s"], snap["step_host_s"]
    out["serving_device_us"] = round((dev["mean"] or 0.0) * 1e6, 1)
    out["serving_host_us"] = round((host["mean"] or 0.0) * 1e6, 1)
    out["serving_dispatches"] = snap["dispatches"]
    out["serving_dispatches_k1"] = snap1["dispatches"]
    out["serving_host_syncs"] = snap["host_syncs"]
    out["serving_compiles"] = eng.compile_stats

    # 4. chunked paged prefill (ISSUE 5): same trace with admission split
    # into co-scheduled chunks — the stall rows are the point: per-step
    # decode stall bounded by one chunk, TTFT split into queue wait vs
    # prefill latency, zero contiguous-cache converter traffic
    eng_c, snap_c, wall_c = _engine_trace(K, chunk=prefill_chunk)
    us = lambda h, k="mean": round((h[k] or 0.0) * 1e6, 1)
    out["serving_tok_per_s_chunked"] = round(
        snap_c["tokens_generated"] / wall_c, 1)
    out["serving_prefill_chunks"] = snap_c["prefill_chunks"]
    out["serving_prefill_stall_us"] = us(snap_c["prefill_stall_s"])
    out["serving_prefill_stall_p99_us"] = us(snap_c["prefill_stall_s"], "p99")
    # decode stall: admission+prefill time ahead of the decode dispatch,
    # chunked vs the inline-prefill baseline (same trace, same horizon)
    out["serving_decode_stall_us"] = us(snap_c["decode_stall_s"])
    out["serving_decode_stall_inline_us"] = us(snap["decode_stall_s"])
    out["serving_step_prefill_tokens_max"] = (
        snap_c["step_prefill_tokens"]["max"])
    out["serving_ttft_split_us"] = {
        "queue": us(snap_c["ttft_queue_s"]),
        "prefill": us(snap_c["ttft_prefill_s"]),
        "queue_inline": us(snap["ttft_queue_s"]),
        "prefill_inline": us(snap["ttft_prefill_s"]),
    }
    out["serving_compiles_chunked"] = eng_c.compile_stats
    out["serving_knobs"] = {"num_slots": num_slots, "page_size": page_size,
                            "n_layers": n_layers, "attn_B": B, "attn_S": S,
                            "decode_horizon": K,
                            "prefill_chunk": prefill_chunk}
    return out


def bench_disagg(ctx, num_slots: int = 4, page_size: int = 16,
                 n_layers: int = 2, prefill_chunk: int = 16) -> dict:
    """Disaggregated prefill/decode rows (ISSUE 6) vs the colocated
    ``serving_*`` baselines, from the SAME seeded trace run through both
    engines:

    - ``disagg_ttft_us`` vs ``disagg_ttft_colocated_us``: time-to-first-
      token, measured on the PREFILL worker's panel (the decode worker
      never sees a prompt token).
    - ``disagg_itl_us`` vs ``disagg_itl_colocated_us``: per-token decode
      latency from the DECODE worker's panel — in the colocated engine
      this number carries the co-scheduled chunk stall; disaggregated it
      cannot (``step_prefill_tokens`` max is pinned 0 by test).
    - ``disagg_migrate_us_per_page``: page-migration kernel cost
      (total migrate wall / pages moved) — the price of the handoff the
      colocated engine does not pay.
    - ``disagg_decode_stall_us`` vs colocated: host admission work ahead
      of the decode dispatch.

    Knobs mirror ``scripts/serve_sim.py --disagg``.
    """
    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import DisaggServingEngine, ServingEngine

    if len(jax.devices()) < 2:
        return {"disagg_skipped": "needs >= 2 devices for the role mesh"}

    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(3), cfg)
    import numpy as _np

    def _trace():
        rng = _np.random.RandomState(0)
        return [([int(t) for t in rng.randint(1, cfg.vocab_size,
                                              size=int(rng.randint(4, 24)))],
                 int(rng.randint(8, 24)))
                for _ in range(3 * num_slots)]

    kw = dict(num_slots=num_slots, page_size=page_size,
              num_pages=8 * num_slots + 8, pages_per_seq=8,
              prefill_chunk=prefill_chunk)
    us = lambda h, k="mean": round((h[k] or 0.0) * 1e6, 1)

    base = ServingEngine(params, cfg, **kw)
    for p, m in _trace():
        base.submit(p, m)
    t0 = time.perf_counter()
    res = base.run(max_steps=100_000)
    base_wall = time.perf_counter() - t0
    assert len(res) == 3 * num_slots
    snap_b = base.metrics.snapshot()

    eng = DisaggServingEngine(params, cfg, **kw)
    for p, m in _trace():
        eng.submit(p, m)
    t0 = time.perf_counter()
    res = eng.run(max_steps=100_000)
    wall = time.perf_counter() - t0
    assert len(res) == 3 * num_slots
    snap_p = eng.metrics.snapshot()            # prefill worker's panel
    snap_d = eng.metrics_decode.snapshot()     # decode worker's panel

    out = {
        "disagg_ttft_us": us(snap_p["ttft_s"]),
        "disagg_ttft_colocated_us": us(snap_b["ttft_s"]),
        "disagg_itl_us": us(snap_d["tok_latency_s"]),
        "disagg_itl_colocated_us": us(snap_b["tok_latency_s"]),
        "disagg_decode_stall_us": us(snap_d["decode_stall_s"]),
        "disagg_decode_stall_colocated_us": us(snap_b["decode_stall_s"]),
        "disagg_tok_per_s": round(snap_d["tokens_generated"] / wall, 1),
        "disagg_tok_per_s_colocated": round(
            snap_b["tokens_generated"] / base_wall, 1),
        "disagg_pages_migrated": snap_p["pages_migrated"],
        "disagg_migrate_chunks": snap_p["migrate_chunks"],
        "disagg_compiles": eng.compile_stats,
        "disagg_knobs": {"num_slots": num_slots, "page_size": page_size,
                         "n_layers": n_layers,
                         "prefill_chunk": prefill_chunk},
    }
    mig = snap_p["migrate_s"]
    if snap_p["pages_migrated"]:
        out["disagg_migrate_us_per_page"] = round(
            (mig["mean"] or 0.0) * mig["count"] * 1e6
            / snap_p["pages_migrated"], 1)
    # the isolation headline, restated as data: the decode worker
    # processed ZERO prompt tokens over the whole trace
    out["disagg_decode_prefill_tokens_max"] = (
        snap_d["step_prefill_tokens"]["max"])
    return out


def bench_chaos(ctx, num_slots: int = 4, page_size: int = 16,
                n_layers: int = 2, prefill_chunk: int = 16) -> dict:
    """Recovery-ladder cost rows (ISSUE 7): the same seeded disagg trace
    replayed under two seeded fault schedules —

    - ``chaos_recovery_us``: mean TTFT of requests that lost at least one
      migration signal and were saved by the RETRY rung (deadline expiry
      → re-issued ``migrate_pages`` send), under a drop-heavy plan.
    - ``chaos_degraded_ttft_us``: mean TTFT of requests rescued by
      decode-local re-prefill after the peer went DEAD mid-trace — the
      worst-case rung short of failure.
    - the fault/retry/degradation counts behind both, so a regression in
      the ladder shows up as a count shift even when CPU wall noise
      drowns the latencies.

    Token streams under both schedules are asserted bit-identical to the
    fault-free run — these rows price recovery, they must not change
    output.
    """
    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import DisaggServingEngine
    from triton_dist_tpu.shmem import FaultPlan

    if len(jax.devices()) < 2:
        return {"chaos_skipped": "needs >= 2 devices for the role mesh"}

    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(3), cfg)
    import numpy as _np

    def _trace():
        rng = _np.random.RandomState(5)
        return [([int(t) for t in rng.randint(1, cfg.vocab_size,
                                              size=int(rng.randint(4, 24)))],
                 int(rng.randint(4, 12)))
                for _ in range(3 * num_slots)]

    kw = dict(num_slots=num_slots, page_size=page_size,
              num_pages=8 * num_slots + 8, pages_per_seq=8,
              prefill_chunk=prefill_chunk)
    us = lambda h, k="mean": round((h[k] or 0.0) * 1e6, 1)

    def _run(plan, **ekw):
        eng = DisaggServingEngine(params, cfg, fault_plan=plan,
                                  **kw, **ekw)
        for p, m in _trace():
            eng.submit(p, m)
        res = eng.run(max_steps=100_000)
        assert not eng.failed, [str(r.failure) for r in eng.failed]
        return eng, res

    _, gold = _run(None)
    drop, res_drop = _run(FaultPlan(seed=9, p_drop=0.4),
                          signal_deadline_steps=4, max_retries=6)
    dead, res_dead = _run(FaultPlan(seed=9, dead_peer_after=8),
                          signal_deadline_steps=2, max_retries=1)
    for res in (res_drop, res_dead):
        assert res == gold, "recovery changed tokens — ladder regression"
    snap_drop = drop.metrics_decode.snapshot()
    snap_dead = dead.metrics_decode.snapshot()
    return {
        "chaos_recovery_us": us(snap_drop["recovered_ttft_s"]),
        "chaos_recovered_requests": snap_drop["recovered_ttft_s"]["count"],
        "chaos_retries": snap_drop["retries"],
        "chaos_faults_injected":
            drop.metrics.snapshot()["faults_injected"],
        "chaos_degraded_ttft_us": us(snap_dead["degraded_ttft_s"]),
        "chaos_degradations": snap_dead["degradations"],
        "chaos_knobs": {"num_slots": num_slots, "page_size": page_size,
                        "n_layers": n_layers,
                        "prefill_chunk": prefill_chunk},
    }


def bench_recovery(ctx, num_requests: int = 20, num_slots: int = 4,
                   page_size: int = 8, n_layers: int = 1,
                   prefill_chunk: int = 8,
                   checkpoint_every: int = 8) -> dict:
    """Crash-consistency cost rows (ISSUE 9): what the journal/checkpoint/
    restore machinery costs, priced on the same seeded traces the recovery
    tests pin —

    - ``checkpoint_us``: mean control-plane snapshot cost at an
      every-``checkpoint_every``-steps cadence (pure host work, zero
      dispatches — the number that bounds journaled-run overhead).
    - ``recovery_replay_us``: one full restore on a freshly built engine —
      checkpoint load + WAL-suffix replay + mirror re-upload (the
      crash-to-serving gap, minus the re-prefill the trace contract makes
      free).
    - ``digest_recovery_us``: the sharded digest-divergence rung end to
      end — quarantine, restore from the last agreed step, re-admission —
      under a seeded transient ``digest_skew`` on the n=2 mesh.

    Every row is priced on a run whose tokens are asserted BIT-IDENTICAL
    to its fault-free golden: these rows price recovery, they must not
    change output.
    """
    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import ControlJournal, ServingEngine
    from triton_dist_tpu.shmem import FaultPlan
    from triton_dist_tpu.shmem.faults import InjectedCrash
    import numpy as _np

    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(3), cfg)
    kw = dict(num_slots=num_slots, page_size=page_size,
              num_pages=3 * num_slots, pages_per_seq=6,
              prefill_chunk=prefill_chunk)
    us = lambda h, k="mean": round((h[k] or 0.0) * 1e6, 1)

    def _trace():
        rng = _np.random.RandomState(5)
        return [(i, [int(t) for t in rng.randint(
                    1, cfg.vocab_size, size=int(rng.randint(4, 17)))],
                 int(rng.randint(2, 8))) for i in range(num_requests)]

    gold_eng = ServingEngine(params, cfg, **kw)
    gold = gold_eng.run(max_steps=100_000, arrivals=_trace())
    journal = ControlJournal()
    crash_at = gold_eng._steps // 2
    eng = ServingEngine(params, cfg, journal=journal,
                        checkpoint_every=checkpoint_every,
                        fault_plan=FaultPlan(seed=7, crash_at=(crash_at,)),
                        **kw)
    try:
        eng.run(max_steps=100_000, arrivals=_trace())
        raise AssertionError("injected crash never fired")
    except InjectedCrash:
        pass
    done = sum(1 for e in journal.entries if e["kind"] == "submit")
    eng2 = ServingEngine(params, cfg, journal=journal,
                         checkpoint_every=checkpoint_every, **kw)
    res = eng2.run(max_steps=100_000, arrivals=_trace()[done:],
                   recover=True)
    assert res == gold, "crash recovery changed tokens — replay regression"
    snap = eng2.metrics.snapshot()
    rows = {
        "checkpoint_us": us(eng.metrics.snapshot()["checkpoint_s"]),
        "checkpoints": eng.metrics.counters["checkpoints"],
        "recovery_replay_us": us(snap["restore_s"]),
        "recovery_journal_entries": len(journal),
        "recovery_knobs": {"num_slots": num_slots, "page_size": page_size,
                           "n_layers": n_layers, "crash_at": crash_at,
                           "checkpoint_every": checkpoint_every},
    }

    # the sharded digest rung needs a 2-rank mesh
    if len(jax.devices()) >= 2:
        from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
        from triton_dist_tpu.serving import (ShardedServingEngine,
                                             serving_mesh)
        mcfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                          n_layers=1, n_heads=4,
                                          n_kv_heads=2, d_ff=128,
                                          max_seq_len=128,
                                          dtype=jnp.float32),
                         num_experts=4, topk=2, moe_d_ff=64)
        mparams = init_moe_params(jax.random.key(3), mcfg)
        skw = dict(num_slots=num_slots, page_size=page_size, num_pages=9,
                   pages_per_seq=4, prefill_chunk=prefill_chunk,
                   wire_dtype=jnp.float8_e4m3fn)

        def _mtrace():
            rng = _np.random.RandomState(5)
            return [(i // 2, [int(t) for t in rng.randint(
                        1, 128, size=int(rng.randint(4, 17)))],
                     int(rng.randint(2, 8))) for i in range(12)]

        mgold = ShardedServingEngine(
            mparams, mcfg, serving_mesh(1, 2, 1), **skw).run(
                max_steps=100_000, arrivals=_mtrace())
        meng = ShardedServingEngine(
            mparams, mcfg, serving_mesh(1, 2, 1), journal=ControlJournal(),
            checkpoint_every=4, digest_every=1,
            fault_plan=FaultPlan(seed=5, digest_skew_at=(7,)), **skw)
        mres = meng.run(max_steps=100_000, arrivals=_mtrace())
        assert meng.metrics.counters["digest_recoveries"] == 1
        assert mres == mgold, ("digest recovery changed tokens — "
                               "divergence rung regression")
        msnap = meng.metrics.snapshot()
        rows["digest_recovery_us"] = us(msnap["digest_recovery_s"])
        rows["digest_recoveries"] = meng.metrics.counters[
            "digest_recoveries"]
    else:
        rows["digest_recovery_skipped"] = "needs >= 2 devices"
    return rows


def bench_serving_sharded(ctx, num_requests: int = 24, num_slots: int = 4,
                          page_size: int = 8, num_pages: int = 24,
                          pages_per_seq: int = 4, prefill_chunk: int = 8,
                          decode_horizon: int = 1,
                          flagship: bool = False) -> dict:
    """Sharded serving rows (ISSUE 8): the EP MoE config served end to end
    through ``ShardedServingEngine`` over a MESH-SIZE SWEEP —
    ``serving_tok_per_s`` / ``serving_step_us`` per mesh shape, from the
    same seeded trace every shape replays bit-identically (asserted; a
    sweep that changed tokens would be pricing a broken engine).

    On the CPU interpret mesh the sweep runs the micro MoE shape at
    1x1x1 / 1x1x2 / 1x2x2 (TPxSPxEP). With ``flagship=True`` and >= 8
    real devices it serves ``MoEConfig.deepseek_infer()`` on the 2x2x2
    mesh instead — the reference's A2A benchmark shape through the whole
    runtime. The wire dtype is PINNED to fp8 (e4m3) for the sweep:
    ``"auto"`` resolves per rank count from the wire-fit model, so the
    1x1x1 golden could legitimately skip the quant round trip that the
    multi-rank shapes take — pinning keeps every shape on the identical
    per-row quant/dequant fold and makes the bitwise assertion fair
    (same caveat docs/serving.md spells out for the trace tests).

    Knobs mirror ``scripts/serve_sim.py --mesh/--model moe``.
    """
    from triton_dist_tpu.models.llama import LlamaConfig
    from triton_dist_tpu.models.moe import MoEConfig, init_moe_params
    from triton_dist_tpu.serving import ShardedServingEngine, serving_mesh
    import numpy as _np

    n_dev = len(jax.devices())
    if flagship and n_dev >= 8:
        cfg = MoEConfig.deepseek_infer()
        meshes = [(1, 1, 1), (2, 2, 2)]
    else:
        cfg = MoEConfig(base=LlamaConfig(vocab_size=128, d_model=128,
                                         n_layers=1, n_heads=4,
                                         n_kv_heads=2, d_ff=128,
                                         max_seq_len=128,
                                         dtype=jnp.float32),
                        num_experts=4, topk=2, moe_d_ff=64)
        meshes = [m for m in [(1, 1, 1), (1, 1, 2), (1, 2, 2)]
                  if m[0] * m[1] * m[2] <= n_dev]
    params = init_moe_params(jax.random.key(3), cfg)

    def _trace():
        rng = _np.random.RandomState(0)
        return [(i // 2,
                 [int(t) for t in rng.randint(1, cfg.base.vocab_size,
                                              size=int(rng.randint(4, 17)))],
                 int(rng.randint(2, 8)))
                for i in range(num_requests)]

    rows, golden = {}, None
    # overlap sweep (ISSUE 16): every multi-rank mesh runs twice —
    # overlap=off (the PR 8 baseline) and overlap=ep+sp (microbatched EP
    # dispatch + start-local SP pool assembly). BOTH rows are asserted
    # bitwise against the n=1 golden: overlap moves the schedule, never
    # the reduction order. The exposed/overlapped split is the wire-fit
    # model (serving/sharded.py _comm_split_us) — CPU wall clock
    # serializes ranks, so the modeled split is the honest number here.
    for tp, sp, ep in meshes:
        variants = [("off", "")]
        if tp * sp * ep > 1:
            variants.append(("ep+sp", ":overlap=on"))
        for overlap, tag in variants:
            eng = ShardedServingEngine(
                params, cfg, serving_mesh(tp, sp, ep), num_slots=num_slots,
                page_size=page_size, num_pages=num_pages,
                pages_per_seq=pages_per_seq, decode_horizon=decode_horizon,
                prefill_chunk=prefill_chunk,
                wire_dtype=jnp.float8_e4m3fn, overlap=overlap)
            t0 = time.perf_counter()
            res = eng.run(max_steps=100_000, arrivals=_trace())
            wall = time.perf_counter() - t0
            assert len(res) == num_requests
            if golden is None:
                golden = res
            else:
                assert res == golden, (
                    f"mesh {tp}x{sp}x{ep} overlap={overlap} changed "
                    "tokens — the bitwise cross-mesh contract broke")
            snap = eng.metrics.snapshot()
            rows[eng.mesh_desc + tag] = {
                "serving_tok_per_s": round(
                    snap["tokens_generated"] / wall, 1),
                "serving_step_us": round(
                    (snap["step_device_s"]["mean"] or 0.0) * 1e6, 1),
                "exposed_comm_us": round(
                    snap["exposed_comm_us"]["mean"] or 0.0, 2),
                "overlapped_comm_us": round(
                    snap["overlapped_comm_us"]["mean"] or 0.0, 2),
                "dispatches": snap["dispatches"],
                "digest_checks": snap["digest_checks"],
                "compiles": eng.compile_stats,
            }
    return {
        "serving_sharded": rows,
        "serving_sharded_wire": eng.wire_dtype,
        "serving_sharded_knobs": {
            "model": "deepseek_infer" if flagship and n_dev >= 8
            else "micro_moe",
            "num_requests": num_requests, "num_slots": num_slots,
            "page_size": page_size, "prefill_chunk": prefill_chunk,
            "decode_horizon": decode_horizon,
            "overlap_microbatches": eng.overlap_microbatches},
    }


def bench_cluster(ctx, num_requests: int = 2000, templates: int = 32,
                  zipf: float = 1.1, max_new: int = 8, num_slots: int = 8,
                  page_size: int = 8, num_pages: int = 48,
                  pages_per_seq: int = 8) -> dict:
    """Cluster serving rows (ISSUE 12): the deterministic prefix-affinity
    router over N ``SimEngine`` replicas on a Zipf template workload —
    ``cluster_tok_per_s`` / ``cluster_ttft_p50_us`` / ``cluster_ttft_p99_us``
    per replica count in {1, 2, 4}, EVERY trace asserted bit-identical to
    the closed-form ``expected_tokens`` golden (a scaling row that changed
    tokens would be pricing a broken router), plus ``cluster_failover_us``:
    wall time of a full kill → journal-reload → fresh-engine →
    checkpoint-restore → replay cycle on the 4-replica cluster.

    The SimEngine is the honest vehicle here: the rows price the CONTROL
    plane (routing, admission, paged growth/preemption, journaling,
    harvest) without the device dispatch noise — exactly what changes
    with replica count. Knobs mirror ``scripts/cluster_sim.py``.
    """
    import numpy as _np

    from triton_dist_tpu.serving import (Cluster, SimEngine,
                                         expected_tokens)

    rng0 = _np.random.RandomState(0)
    max_plen = pages_per_seq * page_size - max_new
    tpls = [rng0.randint(1, 32000,
                         size=int(rng0.randint(3, min(max_plen - 4, 17)))
                         ).tolist()
            for _ in range(templates)]
    ranks = _np.arange(1, templates + 1, dtype=_np.float64)
    zp = ranks ** -zipf
    zp /= zp.sum()

    def _workload():
        rng = _np.random.RandomState(1)
        out = []
        for _ in range(num_requests):
            t = int(rng.choice(templates, p=zp))
            tail = rng.randint(1, 32000,
                               size=int(rng.randint(1, 5))).tolist()
            out.append(((tpls[t] + tail)[:max_plen],
                        int(rng.randint(2, max_new + 1))))
        return out

    def factory(journal):
        return SimEngine(num_slots=num_slots, page_size=page_size,
                         num_pages=num_pages, pages_per_seq=pages_per_seq,
                         journal=journal)

    rows = {}
    for n_rep in (1, 2, 4):
        cl = Cluster(factory, replicas=n_rep)
        reqs = {}
        arrive = 2 * n_rep
        t0 = time.perf_counter()
        for i, (prompt, mnt) in enumerate(_workload()):
            reqs[cl.submit(prompt, mnt)] = (prompt, mnt)
            if i % arrive == arrive - 1:
                cl.step()
        res = cl.drain()
        wall = time.perf_counter() - t0
        assert len(res) == num_requests and not cl.failed_gids
        for gid, toks in res.items():
            assert toks == expected_tokens(*reqs[gid]), (
                f"gid {gid} diverged from the closed-form golden at "
                f"{n_rep} replicas — the router added nondeterminism")
        ttft = cl.metrics.hist["ttft_s"]
        toks_total = sum(len(t) for t in res.values())
        rows[f"replicas={n_rep}"] = {
            "cluster_tok_per_s": round(toks_total / wall, 1),
            "cluster_ttft_p50_us": round(
                (ttft.percentile(50) or 0.0) * 1e6, 1),
            "cluster_ttft_p99_us": round(
                (ttft.percentile(99) or 0.0) * 1e6, 1),
        }

    # failover: kill replica 1 mid-run on the 4-replica cluster (journals
    # on disk this time — the reload path is part of what's being timed),
    # run a while longer, then time the restore ladder end to end
    import tempfile as _tf
    with _tf.TemporaryDirectory(prefix="bench-cluster-") as jdir:
        cl = Cluster(factory, replicas=4, journal_dir=jdir)
        reqs = {}
        failover_s = None
        for i, (prompt, mnt) in enumerate(_workload()):
            reqs[cl.submit(prompt, mnt)] = (prompt, mnt)
            if i == num_requests // 2:
                cl.kill(1)
            if i == num_requests // 2 + num_requests // 10:
                tk = time.perf_counter()
                stats = cl.restore(1)
                failover_s = time.perf_counter() - tk
            if i % 8 == 7:
                cl.step()
        res = cl.drain()
        assert len(res) == num_requests and not cl.failed_gids
        for gid, toks in res.items():
            assert toks == expected_tokens(*reqs[gid]), (
                f"gid {gid} diverged across the kill/restore cycle")
    return {
        "cluster": rows,
        "cluster_failover_us": round(failover_s * 1e6, 1),
        "cluster_failover_replayed": stats["replayed"],
        "cluster_knobs": {
            "num_requests": num_requests, "templates": templates,
            "zipf": zipf, "num_slots": num_slots,
            "page_size": page_size, "num_pages": num_pages},
    }


def bench_lending(ctx, num_requests: int = 240, templates: int = 8,
                  zipf: float = 1.2, replicas: int = 4,
                  num_slots: int = 4, page_size: int = 8,
                  num_pages: int = 33, pages_per_seq: int = 8) -> dict:
    """Cluster-wide prefix sharing rows (ISSUE 17): the page-lending
    tier on a Zipf template workload with router affinity DISABLED —
    full-prompt rendezvous scatters same-template requests across the
    fleet, the adversarial placement lending exists to absorb.

    - ``lend_hit_rate_single`` / ``lend_hit_rate_scattered`` /
      ``lend_hit_rate_cluster``: the acceptance sandwich — one replica's
      hit rate (the ceiling), the scattered fleet without lending (the
      floor), and the scattered fleet WITH lending, asserted within 0.02
      of the ceiling: every remote radix hit became a lend became an
      ordinary local cached hit.
    - ``lend_us_per_page``: mean wall cost of one lent page through the
      export → ladder → adopt path (host control plane; the device-mesh
      byte movement is ``ops.lend_pages``, priced by its own sigcheck-
      registered kernel).
    - ``lend_rewarm_ttft_steps`` vs ``lend_cold_ttft_steps``: post-
      restore template TTFT (step space) after the re-warm-from-peers
      path vs the fallback's cold prefill during the owner's downtime —
      the restore acceptance is rewarmed ≈ cached, NOT cold.

    Every trace in every configuration is asserted bit-identical to the
    closed-form ``expected_tokens`` golden — lending that changed tokens
    would be pricing a broken tier. Submissions are drained serially so
    the lender's pages are CACHED (refcount-0, the sole-ownership lend
    precondition) before a peer may borrow them; the rows price warm
    steady-state lending, not the racy in-flight window it refuses.
    """
    import numpy as _np

    from triton_dist_tpu.serving import (Cluster, SimEngine,
                                         expected_tokens)

    rng0 = _np.random.RandomState(0)
    tpls = [tuple(rng0.randint(1, 32000, size=3 * page_size).tolist())
            for _ in range(templates)]
    ranks = _np.arange(1, templates + 1, dtype=_np.float64)
    zp = ranks ** -zipf
    zp /= zp.sum()

    def factory(journal):
        return SimEngine(num_slots=num_slots, page_size=page_size,
                         num_pages=num_pages, pages_per_seq=pages_per_seq,
                         journal=journal, prefix_cache=True,
                         prefill_chunk=page_size)

    def run(n_rep, **kw):
        cl = Cluster(factory, replicas=n_rep, **kw)
        rng = _np.random.RandomState(1)
        reqs = {}
        for _ in range(num_requests):
            t = tpls[int(rng.choice(templates, p=zp))]
            prompt = list(t) + rng.randint(1, 32000, size=3).tolist()
            mnt = int(rng.randint(2, 5))
            reqs[cl.submit(prompt, mnt)] = (prompt, mnt)
            cl.drain()
        res = cl.results()
        assert len(res) == num_requests and not cl.failed_gids
        for gid, toks in res.items():
            assert toks == expected_tokens(*reqs[gid]), (
                f"gid {gid} diverged from the closed-form golden — "
                f"lending changed tokens")
        hits = sum(r.engine.metrics.counters["prefix_hits"]
                   for r in cl.replicas)
        miss = sum(r.engine.metrics.counters["prefix_misses"]
                   for r in cl.replicas)
        return cl, hits / max(hits + miss, 1)

    _, rate_single = run(1)
    _, rate_scattered = run(replicas, affinity=False)
    cl, rate_lend = run(replicas, affinity=False, lend=True)
    assert rate_lend >= rate_single - 0.02, (
        f"cluster hit rate {rate_lend:.3f} fell below the single-replica "
        f"ceiling {rate_single:.3f} — the lending tier is leaking misses")
    lp = cl.metrics.hist["lend_us_per_page"]
    lend_count = cl.metrics.counters["lends"]

    # the restore rung: kill a template's home, serve it elsewhere (cold,
    # then cached), restore — the re-warm makes post-restore TTFT land in
    # the cached band, and the step-space split is the witness
    cl = Cluster(factory, replicas=replicas, lend=True)
    rng = _np.random.RandomState(2)
    t = tpls[0]

    def go(c):
        prompt = list(t) + rng.randint(1, 32000, size=3).tolist()
        gid = c.submit(prompt, 3)
        c.drain()
        assert c.results()[gid] == expected_tokens(prompt, 3)

    go(cl)
    home = cl.prefix_index.match(t)[1]
    cl.kill(home)
    go(cl)          # fallback pays the cold prefill
    go(cl)          # ... then serves cached
    fb = cl.prefix_index.match(t)[1]
    cl.restore(cl.replicas[home].index)
    go(cl)          # home again (reassign) — REWARMED, not cold
    hm = cl.replicas[home].engine.metrics.hist
    cold = cl.replicas[fb].engine.metrics.hist["ttft_cold_steps"]
    rew = hm["ttft_rewarmed_steps"]
    assert rew.count >= 1 and rew.max < cold.min, (
        f"post-restore TTFT {rew.max} steps in the cold band "
        f"({cold.min}) — the re-warm did not take")
    return {
        "lend_hit_rate_single": round(rate_single, 3),
        "lend_hit_rate_scattered": round(rate_scattered, 3),
        "lend_hit_rate_cluster": round(rate_lend, 3),
        "lend_us_per_page": round(lp.mean, 1) if lp.mean else None,
        "lend_count": lend_count,
        "lend_rewarm_ttft_steps": rew.max,
        "lend_cold_ttft_steps": cold.min,
        "lend_knobs": {
            "num_requests": num_requests, "templates": templates,
            "zipf": zipf, "replicas": replicas, "page_size": page_size,
            "num_pages": num_pages},
    }


def bench_prefix_cache(ctx, num_requests: int = 40, templates: int = 4,
                       zipf: float = 1.1, num_slots: int = 4,
                       page_size: int = 8, num_pages: int = 14,
                       pages_per_seq: int = 8, n_layers: int = 2) -> dict:
    """Prefix-cache rows (ISSUE 13): the same Zipf template workload run
    through ``ServingEngine`` twice — cache OFF (the golden) and cache ON
    — with every token asserted bit-identical between the two runs and
    the compiled-program counts asserted equal (the cache adds zero
    programs: adoption and COW are host ledger ops plus eager copies).

    - ``serving_cache_hit_rate``: admissions that adopted >=1 cached page
      over all admissions; the Zipf head templates should push this past
      0.5 even at 4 templates.
    - ``serving_ttft_cached_us`` vs ``serving_ttft_cold_us``: the split
      the cache exists to move — adopted prompts skip whole pages of
      prefill compute.
    - ``serving_prefix_evictions`` / ``serving_cow_copies``: LRU
      reclaim + divergence-copy traffic at a pool deliberately too small
      to hold every template resident.
    """
    import numpy as _np

    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import ServingEngine

    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(7), cfg)

    # page-aligned Zipf-ranked template prefixes + tiny unique tails, the
    # serve_sim --prompt-zipf shape: full-page runs are what the radix
    # index can actually share
    rng0 = _np.random.RandomState(0)
    tpls = [rng0.randint(1, cfg.vocab_size, size=3 * page_size).tolist()
            for _ in range(templates)]
    ranks = _np.arange(1, templates + 1, dtype=_np.float64)
    zp = ranks ** -zipf
    zp /= zp.sum()

    def _workload():
        rng = _np.random.RandomState(1)
        out = []
        for _ in range(num_requests):
            t = int(rng.choice(templates, p=zp))
            tail = rng.randint(1, cfg.vocab_size,
                               size=int(rng.randint(1, 5))).tolist()
            out.append((tpls[t] + tail, int(rng.randint(2, 7))))
        return out

    def _run(cache_on: bool):
        eng = ServingEngine(params, cfg, num_slots=num_slots,
                            page_size=page_size, num_pages=num_pages,
                            pages_per_seq=pages_per_seq,
                            prefill_chunk=2 * page_size,
                            prefix_cache=cache_on)
        res = {}
        # waves of num_slots: finished requests park their pages on the
        # cached list before the next wave admits, so the hit-rate row
        # measures the cache, not the arrival overlap
        work = _workload()
        for i in range(0, len(work), num_slots):
            for prompt, mnt in work[i:i + num_slots]:
                eng.submit(prompt, mnt)
            res.update(eng.run(max_steps=100_000))
        return eng, res, eng.metrics.snapshot()

    eng_off, res_off, _ = _run(False)
    eng_on, res_on, snap = _run(True)
    assert res_on == res_off, (
        "prefix cache changed tokens — adoption/COW broke bit-identity")
    assert eng_on.compile_stats == eng_off.compile_stats, (
        f"prefix cache compiled extra programs: {eng_on.compile_stats} "
        f"vs {eng_off.compile_stats}")
    hits, misses = snap["prefix_hits"], snap["prefix_misses"]
    us = lambda h: round((h["mean"] or 0.0) * 1e6, 1)  # noqa: E731
    return {
        "serving_cache_hit_rate": round(hits / max(hits + misses, 1), 3),
        "serving_cache_hit_tokens": snap["prefix_hit_tokens"],
        "serving_ttft_cached_us": us(snap["ttft_cached_s"]),
        "serving_ttft_cold_us": us(snap["ttft_cold_s"]),
        "serving_prefix_evictions": snap["prefix_evictions"],
        "serving_cow_copies": snap["cow_copies"],
        "serving_cache_bit_identical": len(res_on),
        "serving_cache_knobs": {
            "num_requests": num_requests, "templates": templates,
            "zipf": zipf, "num_slots": num_slots, "page_size": page_size,
            "num_pages": num_pages, "n_layers": n_layers},
    }


def bench_slo(ctx, n: int = 48, num_slots: int = 4, page_size: int = 8,
              num_pages: int = 16, pages_per_seq: int = 8,
              n_layers: int = 2) -> dict:
    """Multi-tenant SLO rows (ISSUE 14): the bursty two-class workload
    (``serving/workload.py``) through ``ServingEngine`` under the
    chat/batch WFQ policy, twice — chat arrivals alone (the uncontended
    golden) and the full trace with the batch burst riding along — with
    every admitted chat token asserted bit-identical between the runs
    (isolation is a correctness claim here, not just a latency one).

    - ``serving_ttft_p99_us{class=...}`` / ``serving_itl_p99_us{class=...}``
      (and p50s): the per-class split the policy exists to separate —
      chat latency under flood vs the batch tier absorbing the damage.
    - ``serving_slo_shed{class=batch}``: typed batch terminals
      (REJECTED + TtlExpired) while chat sheds nothing.
    - ``serving_slo_quota_throttled`` / ``serving_slo_chunk_shrinks``:
      token-bucket skips and deadline-aware prefill-chunk shrinks — both
      through the already-compiled chunk program (compile_stats is
      asserted flat across policy-off/policy-on).
    """
    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import ServingEngine, SLOPolicy
    from triton_dist_tpu.serving.workload import (generate_arrivals,
                                                  parse_workload)

    cfg = LlamaConfig.tiny(n_layers=n_layers)
    params = init_params(jax.random.key(7), cfg)
    spec = parse_workload(
        f"n={n},seed=11,chat=0.6,rate=0.8,burst_every=32,burst_len=8,"
        "burst_x=4,zipf=1.2,prefixes=4,tenants=2,plen=4:20,mnt=2:8")
    trace = generate_arrivals(spec, vocab=cfg.vocab_size,
                              page_size=page_size)
    slo = SLOPolicy.chat_batch(chat_weight=4, batch_weight=1,
                               batch_queue_cap=8, batch_ttl_steps=60,
                               chat_stall_budget=4, quotas={"b0": (1, 4)})

    def _run(arrivals, policy):
        eng = ServingEngine(params, cfg, num_slots=num_slots,
                            page_size=page_size, num_pages=num_pages,
                            pages_per_seq=pages_per_seq,
                            prefill_chunk=page_size, slo=policy)
        eng.run(max_steps=100_000, arrivals=arrivals)
        chat = {tuple(r.prompt): list(r.generated)
                for r in eng._finished if r.cls == "chat"}
        return eng, chat

    chat_only = [a for a in trace if a[4] == "chat"]
    golden_eng, golden = _run(chat_only, slo)
    eng, flooded_chat = _run(trace, slo)
    assert flooded_chat == golden, (
        "batch burst changed admitted chat tokens — WFQ isolation broke")
    assert eng.compile_stats == golden_eng.compile_stats, (
        f"policy compiled extra programs: {eng.compile_stats} vs "
        f"{golden_eng.compile_stats}")
    shed = eng._rejected
    assert all(r.cls == "batch" for r in shed), "chat was shed under flood"

    us = lambda v: None if v is None else round(v * 1e6, 1)  # noqa: E731
    out = {}
    for cls, row in sorted(eng.metrics.per_class().items()):
        out[f"serving_ttft_p50_us{{class={cls}}}"] = us(row["ttft_p50_s"])
        out[f"serving_ttft_p99_us{{class={cls}}}"] = us(row["ttft_p99_s"])
        out[f"serving_itl_p50_us{{class={cls}}}"] = us(row["itl_p50_s"])
        out[f"serving_itl_p99_us{{class={cls}}}"] = us(row["itl_p99_s"])
        out[f"serving_slo_shed{{class={cls}}}"] = (
            row["rejections"] + row["expirations"])
    out.update({
        "serving_slo_chat_bit_identical": len(flooded_chat),
        "serving_slo_quota_throttled":
            eng.metrics.counters["quota_throttled"],
        "serving_slo_chunk_shrinks":
            eng.metrics.counters["chunk_shrinks"],
        "serving_slo_knobs": {
            "n": n, "num_slots": num_slots, "page_size": page_size,
            "num_pages": num_pages, "n_layers": n_layers,
            "workload": "bursty chat/batch, seed 11",
            "policy": "chat:4 batch:1, batch cap 8 ttl 60, "
                      "chat stall 4, quota b0=1/4"},
    })
    return out


# --- EP-dispatch wire model (the DeepEP-comparison analog) -----------------
#
# The reference's headline 137 µs dispatch (README.md:55) is 32 H800 ranks,
# fp8 wire, 128 tok/rank, topk 8, hidden 7168 — multi-rank hardware this
# environment does not have. The honest substitute (VERDICT r3 #6/#7):
# measure the n=1 kernel (routing + slot compute + local copy, no wire
# benefit) and extrapolate with an explicit, checkable per-link model:
#
#   t(n) = t_kernel(n=1)                      measured
#        + bytes_out * (n-1)/n / ICI_EGRESS   wire serialization
#        + (n-1) * HOP_US                     per-peer put issue/latency
#
#   bytes_out = tok/rank * topk * (hidden * wire_bytes + 4)   (f32 scale
#   channel rides per token-slot; worst case all-remote routing)
#
# v5e public figures: 4 ICI links/chip x ~45 GB/s one-way = ~180 GB/s
# egress; sub-µs per-hop latency, rounded up to 1 µs per remote peer to
# absorb semaphore-signal cost. Multi-chip measurements must replace the
# model terms; until then vs_baseline for the a2a metric is
# reference_137us / t_model(32) — i.e. >1 means the model predicts beating
# the reference's published number on same-scale hardware.
def _plausible(measure, frac: float, skip: bool = False,
               attempts: int = 3) -> tuple[float, bool]:
    """Re-measure a per-chip TFLOP/s reading that exceeds ``frac`` of the
    dense peak — the shared dev chip's heavy-tailed interference
    occasionally lands a differenced reading ABOVE the hardware peak
    (observed 98-102% "MFU"), which is an artifact, not a measurement.
    Returns (value, artifact_flag); the flag is True only if every attempt
    was impossible. One guard for both the headline and the attention
    sweep (``frac`` differs: 0.95 headline — legit peak ≈ 91% MFU — vs
    0.98 attention)."""
    cap = frac * chip_peak_tflops()
    for _ in range(attempts):
        t = measure()
        if skip or t <= cap:
            return t, False
    return t, True


_ICI_EGRESS_GBS = 180.0
_HOP_US = 1.0
_REFERENCE_DISPATCH_US = 137.0   # 32x H800 (reference README.md:55)
_WIRE_FLOOR_US = 2.0   # measured marginal per-push overhead (launch +
                       # barrier + VMEM-resident copy), scripts/wire_probe.py


def a2a_dispatch_model_us(measured_n1_us: float, n: int,
                          tokens_per_rank: int = 128, topk: int = 8,
                          hidden: int = 7168, wire_bytes: int = 1) -> float:
    """Model-extrapolated dispatch latency at ``n`` ranks from the measured
    n=1 kernel time (see module comment above for the model and its
    parameters). The egress term counts the actual token bytes
    (tok·topk rows, worst-case all-remote) — i.e. it assumes per-pair
    ``capacity`` is sized to the expected tokens-per-peer (the context
    takes explicit ``capacity``); a worst-case capacity of tok·topk per
    PAIR would pad the wire n× beyond this."""
    bytes_out = tokens_per_rank * topk * (hidden * wire_bytes + 4)
    wire_us = bytes_out * (n - 1) / n / (_ICI_EGRESS_GBS * 1e3)
    return measured_n1_us + wire_us + (n - 1) * _HOP_US


def bench_autoscale(ctx, n: int = 1500, num_slots: int = 8,
                    page_size: int = 8, num_pages: int = 129,
                    pages_per_seq: int = 8, max_replicas: int = 4) -> dict:
    """Elastic autoscaling rows (ISSUE 18): the diurnal two-class
    workload served twice — a static fleet pinned at ``max_replicas``
    (the peak-provisioned golden) and an elastic fleet starting at ONE
    replica under the ``Autoscaler`` — with the two result dicts
    asserted EQUAL token for token: every scale-up, graceful drain and
    lend-ahead changed the schedule, never the outputs.

    - ``autoscale_replica_steps_saved_pct``: engine steps the elastic
      fleet did NOT pay vs the static peak (both MEASURED runs, not a
      counterfactual), asserted > 0 alongside >= 1 scale-up and >= 1
      retire — a run that never scaled would price nothing.
    - ``autoscale_chat_p99_ttft_steps``: whole-run chat TTFT tail under
      the chat-priority WFQ policy, asserted within the chat budget —
      elasticity must not cost the interactive class its SLO.
    - ``autoscale_*_attainment``: the controller's own windowed per-class
      attainment at end of run (its scaling signal, newest window only).
    - ``scale_up_ttft_us``: wall time for ONE mid-run scale-up of the
      real jitted engine — ``EngineReplica`` build seeded from a
      persisted AOT artifact through first token — with
      ``aot_programs`` asserted > 0 and fresh traces asserted ZERO:
      scale-up latency is artifact load, not compilation.
    """
    import tempfile as _tf
    from collections import deque as _dq

    import numpy as _np  # noqa: F401  (parity with sibling benches)

    from triton_dist_tpu.serving import (Autoscaler, Cluster, SimEngine,
                                         expected_tokens, generate_arrivals,
                                         parse_slo, parse_workload)

    budgets = {"chat": 12, "batch": 20}
    wspec = parse_workload(f"n={n},rate=0.25,burst_every=300,"
                           "burst_len=60,burst_x=10,seed=7")
    arrivals = generate_arrivals(wspec, vocab=32000, page_size=page_size)

    def factory(journal):
        # chat-priority WFQ keeps chat TTFT flat through burst fronts,
        # so BATCH is the binding scaling class — reactive TTFT sensing
        # lags by the TTFT itself, and the class that can wait carries it
        return SimEngine(num_slots=num_slots, page_size=page_size,
                         num_pages=num_pages, pages_per_seq=pages_per_seq,
                         journal=journal, prefix_cache=True,
                         prefill_chunk=page_size,
                         slo=parse_slo("chat_weight=4,batch_weight=1"))

    def run(jdir, elastic):
        cl = Cluster(factory, replicas=1 if elastic else max_replicas,
                     journal_dir=jdir, lend=True, spill_threshold=10)
        asc = None
        if elastic:
            asc = Autoscaler(cl, budgets, window=32, min_samples=6,
                             cooldown=20, warm_steps=1, min_replicas=1,
                             max_replicas=max_replicas,
                             journal=Autoscaler.journal_path_for(jdir))
        pend = _dq(arrivals)
        reqs = {}
        i = 0
        while pend:
            while pend and pend[0][0] <= i:
                _, prompt, mnt, tenant, cls = pend.popleft()
                reqs[cl.submit(prompt, mnt, tenant=tenant,
                               cls=cls)] = (prompt, mnt)
            cl.step()
            if asc is not None:
                asc.step()
            i += 1
        idle = 0
        while idle < 3:
            idle = 0 if cl.step() else idle + 1
            if asc is not None:
                asc.step()
        res = cl.results()
        assert len(res) == wspec.n and not cl.failed_gids, (
            f"{len(res)}/{wspec.n} finished, {len(cl.failed_gids)} failed")
        for gid, toks in res.items():
            assert toks == expected_tokens(*reqs[gid]), (
                f"gid {gid} diverged from the closed-form golden")
        return cl, asc, res

    with _tf.TemporaryDirectory(prefix="bench-autoscale-s-") as jd:
        cl_s, _, res_static = run(jd, elastic=False)
        static_steps = cl_s.metrics.counters["replica_steps"]
    with _tf.TemporaryDirectory(prefix="bench-autoscale-e-") as jd:
        cl_e, asc, res_elastic = run(jd, elastic=True)
    assert res_elastic == res_static, (
        "elastic fleet results diverged from the static-peak golden — "
        "a scale event changed tokens")
    cm = cl_e.metrics
    rsteps = cm.counters["replica_steps"]
    assert cm.counters["scale_ups"] >= 1 and cm.counters["retires"] >= 1, (
        f"the diurnal run must ride the swing (ups "
        f"{cm.counters['scale_ups']}, retires {cm.counters['retires']})")
    saved = 100.0 * (1 - rsteps / max(static_steps, 1))
    assert saved > 0, (
        f"elastic fleet paid {rsteps} replica steps vs static "
        f"{static_steps} — autoscaling must save engine time")
    chat_p99 = cm.hist[cm.class_key("ttft_steps", "chat")].percentile(99)
    assert chat_p99 <= budgets["chat"], (
        f"chat p99 TTFT {chat_p99} steps blew the {budgets['chat']}-step "
        f"budget — elasticity cost the interactive class its SLO")
    out = {
        "autoscale_scale_ups": cm.counters["scale_ups"],
        "autoscale_retires": cm.counters["retires"],
        "autoscale_requeues": cm.counters["requeues"],
        "autoscale_lend_aheads": cm.counters["lend_aheads"],
        "autoscale_replica_steps": rsteps,
        "autoscale_static_replica_steps": static_steps,
        "autoscale_replica_steps_saved_pct": round(saved, 1),
        "autoscale_chat_p99_ttft_steps": chat_p99,
        "autoscale_batch_p99_ttft_steps":
            cm.hist[cm.class_key("ttft_steps", "batch")].percentile(99),
        "autoscale_verified_requests": len(res_elastic),
    }
    for _cls, b_ttft in sorted(budgets.items()):
        if asc.attain.count(("ttft", _cls)):
            out[f"autoscale_{_cls}_attainment"] = round(
                asc.attain.attainment(("ttft", _cls), b_ttft), 3)

    # -- scale-up-to-first-token off the AOT artifact (real engine) ---------
    from triton_dist_tpu.aot import (ArtifactSpec, build_artifact,
                                     load_artifact, make_engine)
    from triton_dist_tpu.serving.cluster import EngineReplica

    spec = ArtifactSpec(
        model={"kind": "llama", "vocab_size": 128, "d_model": 64,
               "n_layers": 2, "n_heads": 4, "n_kv_heads": 2,
               "d_ff": 128, "max_seq_len": 64, "dtype": "float32"},
        engines=[{"kind": "colocated", "num_slots": 4, "page_size": 8,
                  "num_pages": 9, "pages_per_seq": 4, "prefill_chunk": 8}])
    cfg = spec.model_config()
    params = spec.init_params()
    with _tf.TemporaryDirectory(prefix="bench-autoscale-a-") as tdir:
        art = load_artifact(build_artifact(spec, f"{tdir}/artifact"),
                            spec=spec)

        def cfactory(journal, artifact=None):
            return make_engine(spec.engines[0], params, cfg,
                               artifact=artifact)

        # exactly what Cluster.add_replica builds mid-run, timed from
        # construction (artifact seeding included) through first token
        t0 = time.perf_counter()
        rep = EngineReplica(1, cfactory, None, artifact=art)
        rep.engine.submit(list(range(1, 12)), 2)
        while not rep.engine._finished:
            rep.engine.step()
        su_s = time.perf_counter() - t0
        stats = rep.engine.compile_stats
        fresh = {k: v for k, v in stats.items()
                 if k.endswith("_compiles") and v}
        assert stats["aot_programs"] > 0 and not fresh, (
            f"scale-up must seed from the artifact, not compile: {stats}")
        out["scale_up_ttft_us"] = round(su_s * 1e6, 1)
        out["scale_up_build_us"] = round(rep.build_s * 1e6, 1)
        out["scale_up_aot_programs"] = stats["aot_programs"]
    return out


def bench_speculate(ctx, num_requests: int = 16, templates: int = 4,
                    zipf: float = 1.5, num_slots: int = 4,
                    page_size: int = 8, num_pages: int = 40,
                    pages_per_seq: int = 8, spec_k: int = 4,
                    max_new: int = 32) -> dict:
    """Speculative-decoding rows (ISSUE 20): a high-Zipf shared-prefix
    workload run through ``ServingEngine`` twice — speculate OFF (the
    golden) and speculate ON at K — with every token asserted
    bit-identical, the compiled-program counts asserted EQUAL (the
    verify dispatch IS the one decode program; drafting adds zero), and
    the draft economics asserted to actually pay:

    - ``serving_spec_accepted_per_dispatch`` asserted > 1: every point
      above 1.0 is a decode dispatch the host never launched. This is
      the deterministic uplift row — on launch-latency-bound serving
      each saved dispatch is a saved host round trip, while the CPU
      interpret wall clock pays real compute for all K verify rows and
      so UNDERSTATES the win (same caveat as the overlap rows).
    - ``serving_spec_dispatch_uplift``: dispatches-off over
      dispatches-on on the identical trace, asserted > 1.
    - ``serving_spec_tok_per_s`` / ``serving_spec_tok_per_s_off``:
      interpret-mode wall clock, reported for trend, not asserted.

    The tiny-vocab config (greedy decode on a small model revisits
    states, so the bigram prompt-lookup drafter lands real hits) plays
    the role the paper's repetition-heavy serving traces play at scale.
    """
    import numpy as _np

    from triton_dist_tpu.models.llama import LlamaConfig, init_params
    from triton_dist_tpu.serving import ServingEngine

    cfg = LlamaConfig(vocab_size=128, d_model=128, n_layers=1, n_heads=4,
                      n_kv_heads=2, d_ff=128, max_seq_len=256,
                      dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)

    rng0 = _np.random.RandomState(0)
    tpls = [rng0.randint(1, cfg.vocab_size, size=2 * page_size).tolist()
            for _ in range(templates)]
    ranks = _np.arange(1, templates + 1, dtype=_np.float64)
    zp = ranks ** -zipf
    zp /= zp.sum()
    rng = _np.random.RandomState(1)
    work = []
    for _ in range(num_requests):
        t = int(rng.choice(templates, p=zp))
        tail = rng.randint(1, cfg.vocab_size,
                           size=int(rng.randint(1, 4))).tolist()
        work.append((tpls[t] + tail,
                     int(rng.randint(max_new // 2, max_new + 1))))

    def _run(speculate):
        eng = ServingEngine(params, cfg, num_slots=num_slots,
                            page_size=page_size, num_pages=num_pages,
                            pages_per_seq=pages_per_seq,
                            prefill_chunk=2 * page_size,
                            speculate=speculate)
        for prompt, mnt in work:
            eng.submit(list(prompt), mnt)
        t0 = time.perf_counter()
        res = eng.run(max_steps=200_000)
        wall = time.perf_counter() - t0
        assert len(res) == num_requests
        return eng, res, eng.metrics.snapshot(), wall

    eng_off, res_off, snap_off, wall_off = _run(None)
    eng_on, res_on, snap_on, wall_on = _run(spec_k)
    assert res_on == res_off, (
        "speculation changed tokens — the exact-match-greedy accept rule "
        "broke bit-identity")
    assert eng_on.compile_stats == eng_off.compile_stats, (
        f"speculation compiled extra programs: {eng_on.compile_stats} "
        f"vs {eng_off.compile_stats}")
    acc = snap_on["accepted_per_dispatch"]["mean"]
    assert acc is not None and acc > 1.0, (
        f"speculation accepted nothing beyond the mandatory token "
        f"(accepted_per_dispatch mean = {acc}) — drafting never paid")
    d_on, d_off = snap_on["dispatches"], snap_off["dispatches"]
    assert d_on < d_off, (
        f"speculation saved no dispatches ({d_off} -> {d_on})")
    return {
        "serving_spec_accepted_per_dispatch": round(acc, 3),
        "serving_spec_dispatch_uplift": round(d_off / d_on, 3),
        "serving_spec_dispatches": d_on,
        "serving_spec_dispatches_off": d_off,
        "serving_spec_draft_hit_rate": snap_on["draft_hit_rate"],
        "serving_spec_rewinds": snap_on["spec_rewinds"],
        "serving_spec_tok_per_s": round(
            snap_on["tokens_generated"] / wall_on, 1),
        "serving_spec_tok_per_s_off": round(
            snap_off["tokens_generated"] / wall_off, 1),
        "serving_spec_bit_identical": len(res_on),
        "serving_spec_knobs": {
            "num_requests": num_requests, "templates": templates,
            "zipf": zipf, "num_slots": num_slots, "page_size": page_size,
            "spec_k": spec_k, "max_new": max_new,
            "vocab": cfg.vocab_size},
    }


# The reference's perf-shape table (test_ag_gemm_intra_node.py:153-160):
# AG-GEMM M/N/K per model family, M = 8192 token rows.
MODEL_SHAPES = {
    "LLaMA-7B": (8192, 11008, 4096),
    "LLaMA-3.1-8B": (8192, 14336, 4096),
    "LLaMA-3.1-70B": (8192, 28672, 8192),
    "LLaMA-3.1-405B": (8192, 53248, 16384),
    "Mistral-7B": (8192, 14336, 4096),
    "Qwen2-72B": (8192, 29568, 8192),
}


def bench_sigcheck() -> dict:
    """Static verifier throughput: one full-registry ``scripts/sigcheck.py``
    sweep in a CPU subprocess (the capture layer monkeypatches global jax
    surfaces — it must never share a process with live-chip benchmarks),
    amortized per checked op. Tracks the wall cost of the dryrun gate's
    rung 0 so a registry growth or capture slowdown shows up on the
    scoreboard; also re-asserts zero findings on the shipping registry."""
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "scripts", "sigcheck.py")
    proc = subprocess.run(
        [sys.executable, script, "--all", "--quiet"],
        capture_output=True, text=True, timeout=580,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    if proc.returncode != 0:
        raise RuntimeError(f"sigcheck rc={proc.returncode}: "
                           f"{proc.stderr[-300:]}")
    doc = json.loads(proc.stdout)
    checked = sum(1 for r in doc["ops"].values() if not r.get("skipped"))
    return {
        "sigcheck_us_per_op": round(doc["elapsed_s"] * 1e6
                                    / max(checked, 1), 1),
        "sigcheck_ops_checked": checked,
        "sigcheck_findings": doc["n_findings"],
    }


def bench_aot(ctx, n_layers: int = 2, num_requests: int = 12) -> dict:
    """AOT cold-start rows (ISSUE 15): wall time from a cold process state
    to the FIRST TOKEN out of a colocated engine, fresh-trace vs seeded
    from a persisted artifact (``aot_cold_start_to_first_token_us`` both
    ways + the speedup, asserted >= 10x on CPU where XLA compiles dwarf
    dispatch), then a preemption trace asserted BIT-IDENTICAL artifact-on
    vs artifact-off with compile parity (0 fresh traces, every program
    accounted to the artifact).

    Registry rows: the contextual autotuner's persisted-winner loop on the
    two CPU-executable r6 levers (``grouped_gemm`` / ``moe_ffn_gated``) —
    first-process sweep cost vs second-process ``registry_hit`` cost,
    the registry hit rate, and tuned-vs-default kernel latency at the
    swept shape.
    """
    import tempfile as _tf

    import numpy as _np

    from triton_dist_tpu.aot import (ArtifactSpec, build_artifact,
                                     load_artifact, make_engine)
    from triton_dist_tpu.aot.registry import (TunedConfigRegistry,
                                              set_default_registry)
    from triton_dist_tpu.utils import on_cpu
    from triton_dist_tpu.utils.perf import perf_func

    spec = ArtifactSpec(
        model={"kind": "llama", "vocab_size": 128, "d_model": 64,
               "n_layers": n_layers, "n_heads": 4, "n_kv_heads": 2,
               "d_ff": 128, "max_seq_len": 64, "dtype": "float32"},
        engines=[{"kind": "colocated", "num_slots": 4, "page_size": 8,
                  "num_pages": 9, "pages_per_seq": 4, "prefill_chunk": 8}])
    cfg = spec.model_config()
    params = spec.init_params()

    def first_token_s(artifact=None):
        t0 = time.perf_counter()
        eng = make_engine(spec.engines[0], params, cfg, artifact=artifact)
        eng.submit(list(range(1, 12)), 2)
        while not eng._finished:
            eng.step()
        return time.perf_counter() - t0

    # fresh side FIRST: once the artifact's XLA cache is installed, later
    # compiles in this process would hit it and the baseline would lie
    fresh_s = _best_of(lambda: first_token_s(), n=2)

    out = {}
    with _tf.TemporaryDirectory(prefix="bench-aot-") as tdir:
        t0 = time.perf_counter()
        art_dir = build_artifact(spec, f"{tdir}/artifact")
        out["aot_build_s"] = round(time.perf_counter() - t0, 3)

        art_s = _best_of(
            lambda: first_token_s(load_artifact(art_dir, spec=spec)), n=2)
        speedup = fresh_s / art_s
        out["aot_cold_start_fresh_us"] = round(fresh_s * 1e6, 1)
        out["aot_cold_start_artifact_us"] = round(art_s * 1e6, 1)
        out["aot_cold_start_speedup"] = round(speedup, 1)
        if on_cpu():
            assert speedup >= 10.0, (
                f"artifact cold start must be >= 10x a fresh trace on CPU "
                f"(fresh {fresh_s:.3f}s vs artifact {art_s:.3f}s = "
                f"{speedup:.1f}x) — is the persisted XLA cache being hit?")

        # bit-identity + compile parity on a preemption trace (9-page pool)
        rng = _np.random.RandomState(77)
        trace = [(i // 2, rng.randint(1, 128, size=int(rng.randint(3, 17))
                                      ).tolist(), int(rng.randint(2, 6)))
                 for i in range(num_requests)]
        eng_f = make_engine(spec.engines[0], params, cfg)
        golden = eng_f.run(max_steps=100_000, arrivals=list(trace))
        eng_a = make_engine(spec.engines[0], params, cfg,
                            artifact=load_artifact(art_dir, spec=spec))
        tokens = eng_a.run(max_steps=100_000, arrivals=list(trace))
        assert tokens == golden, "artifact-on trace diverged from fresh"
        stats = eng_a.compile_stats
        fresh_traces = {k: v for k, v in stats.items()
                        if k.endswith("_compiles") and v}
        assert not fresh_traces and stats["aot_programs"] == 2, stats

    # -- persisted-registry loop on the CPU-executable levers ---------------
    from triton_dist_tpu.ops import autotuned as at
    key = jax.random.PRNGKey(0)
    T, H, N, E = 256, 128, 256, 4
    tokens_a = jax.random.normal(key, (T, H), jnp.float32)
    ids = jnp.arange(T, dtype=jnp.int32) % E
    w = jax.random.normal(key, (E, H, N), jnp.float32)
    wd = jax.random.normal(key, (E, N, H), jnp.float32)
    calls = {
        "grouped_gemm": lambda **kw: at.grouped_gemm_autotuned(
            tokens_a, ids, w, **kw),
        "moe_ffn_gated": lambda **kw: at.moe_ffn_gated_autotuned(
            tokens_a, ids, w, w, wd, **kw),
    }

    def _drop_cached(op):
        # simulate the next process: the in-memory winner cache is empty,
        # only the registry survives
        fn = getattr(at, f"{op}_autotuned")
        for k in [k for k in fn._autotune_cache
                  if k[0] == fn.__wrapped__.__qualname__]:
            del fn._autotune_cache[k]

    reg = TunedConfigRegistry()
    set_default_registry(reg)
    try:
        for op, call in calls.items():
            _drop_cached(op)
            _, sweep_ms = perf_func(call, iters=1, warmup_iters=0)
            _drop_cached(op)
            _, hit_ms = perf_func(call, iters=1, warmup_iters=0)
            out[f"aot_{op}_sweep_ms"] = round(sweep_ms, 1)
            out[f"aot_{op}_registry_hit_ms"] = round(hit_ms, 1)
            winner = reg.get_similar(op, "float32")
            _, tuned_ms = perf_func(lambda: call(cfg=winner),
                                    iters=5, warmup_iters=2)
            _, default_ms = perf_func(lambda: call(cfg=(128, 128)),
                                      iters=5, warmup_iters=2)
            out[f"aot_{op}_tuned_us"] = round(tuned_ms * 1e3, 1)
            out[f"aot_{op}_default_us"] = round(default_ms * 1e3, 1)
            out[f"aot_{op}_winner"] = str(winner)
    finally:
        set_default_registry(None)
    out["aot_registry_hit_rate"] = round(reg.hit_rate, 3)
    out["aot_registry_entries"] = len(reg)
    return out


def sweep():
    """Per-model-family AG-GEMM sweep at the reference's perf shapes; one
    JSON line per shape (informational — the driver parses main()'s single
    line, so this runs only with --sweep)."""
    from triton_dist_tpu.ops.gemm import GemmConfig
    from triton_dist_tpu.shmem.context import initialize_distributed

    n_dev = len(jax.devices())
    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n_dev,))
    peak = chip_peak_tflops()
    # K-split candidates cover 405B-class K=16384 (full-K strips exceed the
    # scoped-VMEM budget) and amortize B-strip reloads at large N via tall
    # block_m (B traffic scales with M/block_m)
    configs = [GemmConfig(128, 128), GemmConfig(256, 256),
               GemmConfig(256, 256, 4096), GemmConfig(512, 256, 2048),
               GemmConfig(1024, 256, 1024), GemmConfig(1024, 512, 1024),
               GemmConfig(512, 512, 2048), GemmConfig(512, 1024, 1024),
               # block_n=384 tall variants for N divisible by 3*128 but not
               # 256 (e.g. Qwen2-72B's 29568; measured 169 vs 89 TFLOP/s
               # against the narrow-tile fallback)
               GemmConfig(512, 384, 2048), GemmConfig(1024, 384, 1024)]
    for name, (M, N, K) in MODEL_SHAPES.items():
        try:
            # dedupe by effective tiling (block_k == K is the full-K path)
            eff = {(c.block_m, c.block_n, min(c.block_k or K, K)): c
                   for c in configs}
            best_s, _ = bench_ag_gemm(ctx, n_dev, M, N, K,
                                      list(eff.values()), 10, 110)
            if best_s == float("inf"):
                raise RuntimeError("no candidate config fits this shape")
            tflops = (2.0 * M * N * K / best_s) / max(n_dev, 1) / 1e12
            print(json.dumps({
                "model": name, "M": M, "N": N, "K": K,
                "ag_gemm_tflops_per_chip": round(tflops, 2),
                "mfu_pct": round(100 * tflops / peak, 1),
            }))
        except Exception as e:
            print(json.dumps({"model": name,
                              "error": f"{type(e).__name__}: {e}"[:150]}))


def main(a2a_primary: bool = False):
    import math

    from triton_dist_tpu.ops.gemm import GemmConfig
    from triton_dist_tpu.shmem.context import initialize_distributed
    from triton_dist_tpu.utils import on_cpu

    if on_cpu():
        # smoke shape; interpret mode is only reliable at <=6 sim devices
        # on one host core, and needs SPARE non-participating device
        # threads or kernel barriers deadlock (see tests/conftest.py)
        M = N = K = 512
        n_dev = max(1, min(4, len(jax.devices()) - 2))
        configs = [GemmConfig(math.gcd(128, M // n_dev),
                              math.gcd(128, N // n_dev))]
        i1, i2 = 1, 3
        a2a_shape = dict(tokens_per_rank=16, hidden=256, topk=2,
                         num_experts=4 * n_dev)
    else:
        M = N = K = 4096
        n_dev = len(jax.devices())
        # (512, 512, 2048) / (512, 1024, 1024) measured best at 4096^3 on
        # v5e: 171 vs 158 TFLOP/s for the earlier K-split candidates
        configs = [GemmConfig(128, 128), GemmConfig(256, 256),
                   GemmConfig(512, 256, 2048), GemmConfig(1024, 256, 1024),
                   GemmConfig(512, 512, 2048), GemmConfig(512, 1024, 1024)]
        # the tunnel's fixed round-trip jitters by ~50 ms; a wide iteration
        # spread keeps the differenced signal well above it
        i1, i2 = 10, 410
        # BASELINE.md: 128 tok/rank, topk=8, hidden=7168 (DeepSeek-infer,
        # models/moe.py MoEConfig.deepseek_infer)
        a2a_shape = dict(tokens_per_rank=128, hidden=7168, topk=8,
                         num_experts=64)

    ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n_dev,))

    headline_cfg = {}

    def measure_headline():
        best_s, best_cfg = bench_ag_gemm(ctx, n_dev, M, N, K, configs,
                                         i1, i2)
        assert best_s < float("inf") and best_s > 0, (
            f"no benchmark config ran (best_s={best_s})")
        headline_cfg["cfg"] = best_cfg
        return (2.0 * M * N * K / best_s) / max(n_dev, 1) / 1e12

    tflops, artifact = _plausible(measure_headline, frac=0.95,
                                  skip=on_cpu())
    baseline = 0.6 * chip_peak_tflops()

    extras = {}

    def attempt(label, fn):
        """Run a sub-benchmark; retry ONCE iff the failure matches the
        remote-compile service's transient HTTP 5xx signature (seen twice
        on 2026-07-31 — one retry must not blemish the round record).
        Deterministic failures surface immediately with the FIRST error;
        a double transient records the first error too."""
        try:
            fn()
            return
        except Exception as e:
            first = f"{type(e).__name__}: {e}"[:200]
            # transient = the remote-compile service's HTTP 5xx signature
            # specifically (observed form: "remote_compile: HTTP 500:
            # tpu_compile_helper subprocess exit code 1") — a
            # deterministic compile error also mentions remote_compile,
            # and re-running that would double its cost; bare substring
            # digits would false-match byte counts in error text
            import re
            s = str(e)
            transient = ("remote_compile" in s
                         and re.search(r"HTTP 5\d\d", s) is not None)
            if not transient:
                extras[f"{label}_error"] = first
                return
        try:
            fn()
        except Exception:
            extras[f"{label}_error"] = first

    # per-call a2a/decode latencies are tens of µs; the chain spread must be
    # wider than the GEMM bench's for the differenced signal to clear the
    # ~50 ms tunnel jitter
    ai1, ai2 = (i1, i2) if on_cpu() else (10, 1610)

    def _a2a():
        dispatch_s, roundtrip_s = bench_a2a(ctx, i1=ai1, i2=ai2, **a2a_shape)
        extras["a2a_dispatch_us"] = round(dispatch_s * 1e6, 1)
        extras["a2a_roundtrip_us"] = round(roundtrip_s * 1e6, 1)

    attempt("a2a", _a2a)

    def _decode():
        # decode per-call latency is tens of µs, so the spread must be wider
        # than the GEMM bench's for the differenced signal to clear the
        # ~50 ms tunnel jitter (target ≥ ~100 ms of differenced signal)
        dec_shape = (dict(s_local=256, Hq=8, Hkv=2)
                     if on_cpu() else dict(s_local=4096))
        di1, di2 = (i1, i2) if on_cpu() else (10, 3610)
        extras.update(bench_decode(ctx, i1=di1, i2=di2, **dec_shape))

    attempt("decode", _decode)

    def _flash_decode_dist():
        # one-request KV sharded over the SP axis (ISSUE 19): rank sweep
        # at {8k, 32k, 64k}-token contexts, bit-identity vs the n=1
        # golden asserted, modeled attention split asserted sublinear
        extras.update(bench_flash_decode_dist())

    attempt("flash_decode_dist", _flash_decode_dist)

    def _serving():
        # paged-decode serving extras at the SAME attention shape as
        # _decode's contiguous rows (the <=10% parity acceptance); the
        # engine-step throughput row uses the single-device paged step, so
        # it is scan-safe even on the CPU simulator (no shard_map inside)
        ssh = (dict(S=256, Hq=8, Hkv=2, page_size=128, n_layers=1)
               if on_cpu() else dict(S=4096 * len(jax.devices())
                                     if len(jax.devices()) > 1 else 4096))
        si1, si2 = (i1, i2) if on_cpu() else (10, 410)
        extras.update(bench_serving(ctx, i1=si1, i2=si2, **ssh))

    attempt("serving", _serving)

    def _disagg():
        # disaggregated prefill/decode vs the colocated rows above; the
        # role mesh is its own 2-rank context (first two devices)
        dsh = (dict(page_size=8, n_layers=1, prefill_chunk=8)
               if on_cpu() else {})
        extras.update(bench_disagg(ctx, **dsh))

    attempt("disagg", _disagg)

    def _chaos():
        # recovery-ladder cost under seeded fault schedules (ISSUE 7)
        csh = (dict(page_size=8, n_layers=1, prefill_chunk=8)
               if on_cpu() else {})
        extras.update(bench_chaos(ctx, **csh))

    attempt("chaos", _chaos)

    def _recovery():
        # crash-consistency cost: checkpoint cadence, restore/replay, and
        # the sharded digest-divergence rung (ISSUE 9); every row asserts
        # token bit-identity against its fault-free golden
        extras.update(bench_recovery(ctx))

    attempt("recovery", _recovery)

    def _serving_sharded():
        # whole-engine mesh-size sweep for the EP MoE config (ISSUE 8);
        # the CPU simulator runs the micro shape on interpret meshes up
        # to 1x2x2, real hardware with >= 8 chips serves deepseek_infer
        # on the 2x2x2 mesh
        extras.update(bench_serving_sharded(
            ctx, flagship=not on_cpu(),
            **(dict(num_requests=24) if on_cpu() else {})))

    attempt("serving_sharded", _serving_sharded)

    def _cluster():
        # router + replica control plane vs replica count, and the full
        # kill/restore failover cycle, all bit-identity-asserted against
        # the closed-form SimEngine golden (ISSUE 12)
        extras.update(bench_cluster(ctx))

    attempt("cluster", _cluster)

    def _prefix_cache():
        # ref-counted prefix cache vs the cache-off golden on a Zipf
        # template workload: hit rate, cached/cold TTFT split, eviction
        # and COW traffic, tokens asserted bit-identical (ISSUE 13)
        psh = dict(n_layers=1) if on_cpu() else {}
        extras.update(bench_prefix_cache(ctx, **psh))

    attempt("prefix_cache", _prefix_cache)

    def _lending():
        # cluster-wide prefix sharing: the hit-rate sandwich (single-
        # replica ceiling vs scattered floor vs lending fleet, affinity
        # off), per-lent-page cost, and the post-restore re-warm TTFT
        # band — every trace bit-identity-asserted (ISSUE 17)
        extras.update(bench_lending(ctx))

    attempt("lending", _lending)

    def _slo():
        # multi-tenant WFQ isolation under the bursty two-class workload:
        # per-class TTFT/ITL rows, typed batch shedding, chat tokens
        # asserted bit-identical to the uncontended golden (ISSUE 14)
        ssh = dict(n_layers=1) if on_cpu() else {}
        extras.update(bench_slo(ctx, **ssh))

    attempt("slo", _slo)

    def _autoscale():
        # elastic fleet vs the static-peak golden on the diurnal swing:
        # result dicts asserted equal, replica-steps saved, per-class
        # attainment, and the scale-up-to-first-token split off the AOT
        # artifact with aot_programs > 0 asserted (ISSUE 18)
        extras.update(bench_autoscale(ctx))

    attempt("autoscale", _autoscale)

    def _speculate():
        # model-free draft-verify decoding vs the speculate-off golden on
        # a high-Zipf workload: accepted-per-dispatch asserted > 1,
        # dispatch-count uplift asserted, tokens asserted bit-identical,
        # compiled-program counts asserted equal (ISSUE 20)
        extras.update(bench_speculate(ctx))

    attempt("speculate", _speculate)

    def _aot():
        # persisted-artifact cold start vs fresh traces (>=10x on CPU,
        # bit-identity + compile parity asserted) and the tuned-config
        # registry's sweep-once/hit-forever loop (ISSUE 15)
        extras.update(bench_aot(ctx))

    attempt("aot", _aot)

    def _attn():
        ash = dict(s_loc=256, Hq=4, Hkv=2) if on_cpu() else {}
        if on_cpu():
            extras.update(bench_attn(ctx, i1=i1, i2=i2, **ash))
            return
        # best-of-2: single samples measured 96.6-110.8 TFLOP/s across
        # same-day runs on the shared chip (one-sided interference;
        # stat=max — this is a throughput, min would pick the WORST run)
        extras["attn_tflops_per_chip"] = _best_of(
            lambda: bench_attn(ctx, i1=i1, i2=i2,
                               **ash)["attn_tflops_per_chip"], stat=max)

    attempt("attn", _attn)

    def _moe():
        msh = (dict(tokens_rows=64, hidden=256, n_out=256, num_experts=8)
               if on_cpu() else {})
        mi1, mi2 = (i1, i2) if on_cpu() else (10, 1610)
        extras.update(bench_moe(ctx, i1=mi1, i2=mi2, **msh))

    attempt("moe", _moe)

    def _ep_block():
        # end-to-end EP MoE serving block (reference test_ep_moe_inference
        # parity: router → dispatch → grouped gated FFN → combine)
        if on_cpu():
            esh = dict(T=16, D=256, F=128, E=8, topk=2)
            ei1, ei2 = i1, i2
        else:
            esh = {}
            ei1, ei2 = 10, 210
        if on_cpu():
            s = bench_ep_block(ctx, i1=ei1, i2=ei2, **esh)
            se = bench_ep_block(ctx, i1=ei1, i2=ei2, expert_major=True,
                                **esh)
        else:
            # best-of-2 (851-1033 µs across same-day single samples)
            s = _best_of(lambda: bench_ep_block(ctx, i1=ei1, i2=ei2,
                                                **esh))
            se = _best_of(lambda: bench_ep_block(ctx, i1=ei1, i2=ei2,
                                                 expert_major=True, **esh))
        extras["moe_ep_block_us"] = round(s * 1e6, 1)
        # expert-major capacity layout: per-expert slot budgets at the
        # source, expert-segmented arrivals, no align gather/scatter in
        # the serving FFN — the receiver-side ragged-alignment share of
        # the roofline gap, measured head-to-head
        extras["moe_ep_block_em_us"] = round(se * 1e6, 1)

    attempt("ep_block", _ep_block)

    def _fp8():
        # fp8 wire + scale side-channel — the reference's showcase protocol.
        # At n=1 this measures pure quantize/dequant overhead (no wire to
        # shrink); the halved wire bytes only pay off multi-chip.
        # Dispatch best-of-2: this number SEEDS the DeepEP-model e2e
        # bracket, and single samples measured 47.6-71.3 µs same-day
        if on_cpu():
            d8, r8 = bench_a2a(ctx, i1=ai1, i2=ai2,
                               wire_dtype=jnp.float8_e4m3fn, **a2a_shape)
        else:
            runs = [bench_a2a(ctx, i1=ai1, i2=ai2,
                              wire_dtype=jnp.float8_e4m3fn, **a2a_shape)
                    for _ in range(2)]
            d8 = min(r[0] for r in runs)
            r8 = min(r[1] for r in runs)
        extras["a2a_dispatch_fp8_us"] = round(d8 * 1e6, 1)
        extras["a2a_roundtrip_fp8_us"] = round(r8 * 1e6, 1)
        # expert-edge protocol: dispatch hands QuantTokens to the expert
        # GEMM (no dequant pass anywhere) — the reference's architecture
        d8e, r8e = bench_a2a(ctx, i1=ai1, i2=ai2,
                             wire_dtype=jnp.float8_e4m3fn,
                             dequant_edge="expert", **a2a_shape)
        extras["a2a_dispatch_fp8_expert_us"] = round(d8e * 1e6, 1)
        extras["a2a_roundtrip_fp8_expert_us"] = round(r8e * 1e6, 1)
        # per-edge fp8 timings: fused in-collective quantization on BOTH
        # edges vs the standalone qpack pre-pass — the difference is the
        # send-edge fusion win, stated per edge so each side's share of
        # the roundtrip is auditable
        for qe in ("fused", "pre"):
            edges = bench_a2a_edges(ctx, i1=ai1, i2=ai2,
                                    wire_dtype=jnp.float8_e4m3fn,
                                    quant_edge=qe, **a2a_shape)
            extras[f"a2a_edges_fp8_{qe}"] = edges
        # reference-scope wire-only numbers (its 137 µs excludes routing,
        # token scatter, quant and dequant — see bench_a2a_wire docstring).
        # Seeds come from the payload-scaling FIT (no noise-floor clamp,
        # VERDICT r4 #5): the 4×/8× points resolve real traffic and the
        # fit extrapolates down; every term + the residual is emitted.
        fit16 = bench_a2a_wire_fit(ctx, i1=ai1, i2=ai2, **a2a_shape)
        fit8 = bench_a2a_wire_fit(ctx, i1=ai1, i2=ai2,
                                  wire_dtype=jnp.float8_e4m3fn, **a2a_shape)
        w16 = fit16["wire_us"] * 1e-6
        w8 = fit8["wire_us"] * 1e-6
        extras["a2a_wire_us"] = round(w16 * 1e6, 1)
        extras["a2a_wire_fp8_us"] = round(w8 * 1e6, 1)
        extras["a2a_wire_fit"] = {"bf16": fit16, "fp8": fit8}
        if not on_cpu() and n_dev == 1:
            # first-class DeepEP-comparison metric: model-extrapolated 8-
            # and 32-rank dispatch from the measured n=1 fp8 kernel (see
            # the wire-model comment above MODEL_SHAPES). n=1 only — a
            # multi-chip measurement already contains real wire/hop cost,
            # and adding the modeled terms would double-count them (real
            # multi-chip numbers supersede the model entirely).
            # model seeded with the WIRE-scope fp8 time — the same timed
            # region as the reference's 137 µs (kernel only; routing,
            # scatter, quant, dequant excluded there too) — plus a
            # conservative variant seeded with the full e2e dispatch (every
            # edge pass included), bracketing the claim
            shp = {k: v for k, v in a2a_shape.items() if k != "num_experts"}
            m8 = a2a_dispatch_model_us(w8 * 1e6, 8, **shp)
            m32 = a2a_dispatch_model_us(w8 * 1e6, 32, **shp)
            m32_e2e = a2a_dispatch_model_us(d8 * 1e6, 32, **shp)
            extras["a2a_model"] = {
                "n8_us": round(m8, 1), "n32_us": round(m32, 1),
                "n32_e2e_us": round(m32_e2e, 1),
                "vs_reference_137us": round(_REFERENCE_DISPATCH_US / m32, 3),
                "vs_reference_137us_e2e": round(
                    _REFERENCE_DISPATCH_US / m32_e2e, 3),
                "ici_egress_gbs": _ICI_EGRESS_GBS, "hop_us": _HOP_US,
                "scope": "kernel-only seed = reference timed region "
                         "(test_all_to_all.py:313-348); _e2e seed adds "
                         "routing+gather+quant+dequant edges",
            }

    attempt("a2a_fp8", _fp8)

    def _baselines():
        # non-overlap rows (VERDICT r4 Missing #1): XLA ag+dot, bare
        # Pallas matmul, comm-serialized ag_gemm — the overlap delta as a
        # measurement instead of an assertion, at the HEADLINE's winning
        # tile config so the delta isolates overlap, not tile choice
        cfg = headline_cfg.get("cfg") or configs[-1]
        extras.update(bench_baselines(ctx, n_dev, M, N, K, cfg, i1, i2))

    attempt("baselines", _baselines)

    def _small_ag():
        # small-message AG latency family (LL vs push vs XLA); chip only —
        # interpret-mode kernels inside the scan chain deadlock the
        # simulator (see the scan+interpret note in tests/conftest.py)
        if not on_cpu():
            extras.update(bench_small_ag(ctx, i1=10, i2=1610))

    attempt("small_ag", _small_ag)

    def _sigcheck():
        # static-verifier throughput (rung 0 of the validation ladder);
        # CPU subprocess, so the row rides along on chip runs too
        extras.update(bench_sigcheck())

    attempt("sigcheck", _sigcheck)

    if artifact:
        # three impossible readings in a row: report, but flagged so no
        # consumer banks a >peak number as a measurement
        extras["artifact"] = ("reading exceeds 95% of dense peak after 3 "
                              "attempts (interference artifact)")
    result = {
        "metric": "ag_gemm_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / baseline, 3),
        "extras": extras,
    }
    if a2a_primary:
        # `a2a` argv mode: the DeepEP-comparison line (BASELINE.md second
        # target: beat 137 µs at 32 ranks). value = the model-extrapolated
        # 32-rank fp8 dispatch, seeded with the measured wire-scope n=1
        # time — the reference's timed region (its 137 µs excludes
        # routing, token scatter, quant and dequant; see bench_a2a_wire).
        # Every model term is stated in extras; a real multi-chip run
        # supersedes the model (at n>1 extras carry measurements only).
        import sys
        am = extras.get("a2a_model", {})
        # n=1: model-extrapolated 32-rank figure; n>1: the measured wire
        # time at this rank count (real ICI cost, no model)
        value = am.get("n32_us", extras.get("a2a_wire_fp8_us"))
        a2a_extras = {**extras, "ag_gemm_tflops_per_chip": round(tflops, 2)}
        if value is None:
            # fail loudly: a null metric with rc 0 would be recorded as a
            # vacuous success by any harness reading this line
            a2a_extras["status"] = "unavailable"
            a2a_extras.setdefault(
                "error", extras.get("a2a_fp8_error",
                                    "fp8 dispatch not measured"))
        print(json.dumps({
            "metric": "a2a_dispatch_us",
            "value": value,
            "unit": "us",
            "vs_baseline": am.get("vs_reference_137us"),
            "extras": a2a_extras,
        }))
        if value is None:
            sys.exit(1)
        return
    _record_healthy(result)
    print(json.dumps(result))


def _last_healthy_path():
    import os.path
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        ".bench_last_healthy.json")


def _record_healthy(result: dict) -> None:
    """Persist the latest healthy result so an unreachable-device run can
    report it from a recorded artifact rather than a hardcoded string.
    Skipped when the run captured any sub-benchmark error (a partially
    failed run must not become the 'healthy' reference); stamped so a
    consumer can tell how stale the fallback is."""
    import time
    from triton_dist_tpu.utils import on_cpu
    if on_cpu():
        return  # a CPU smoke must not clobber the chip reference
    if any(k.endswith("error") for k in result.get("extras", {})):
        return
    if "artifact" in result.get("extras", {}):
        return  # an impossible reading must not become the reference
    try:
        with open(_last_healthy_path(), "w") as f:
            json.dump({**result, "recorded_unix_time": int(time.time())}, f)
    except OSError:
        pass


def _device_reachable(timeout_s: int = 240) -> bool:
    """Probe backend init in a subprocess: a wedged device tunnel hangs
    ``jax.devices()`` forever (observed after a client was killed
    mid-compile — see the verify skill notes), and an eternally-hanging
    bench is worse than a recorded failure. One shared probe
    implementation lives in utils.env."""
    from triton_dist_tpu.utils.env import _probe_default_backend
    return _probe_default_backend(timeout_s=timeout_s) is not None


if __name__ == "__main__":
    import sys
    if not _device_reachable():
        # Not a measurement: value stays null so a metrics consumer cannot
        # ingest it as a real 0.0-TFLOP/s regression data point.
        extras = {"status": "device_unreachable",
                  "error": "device backend unreachable (tunnel/device "
                           "wedged; jax.devices() hung >240s)"}
        try:
            with open(_last_healthy_path()) as f:
                extras["last_healthy"] = json.load(f)
        except (OSError, ValueError):
            pass
        print(json.dumps({
            "metric": "ag_gemm_tflops_per_chip", "value": None,
            "unit": "TFLOP/s", "vs_baseline": None, "extras": extras,
        }))
        sys.exit(0)
    if "--sweep" in sys.argv:
        sweep()
    elif "--attn-sweep" in sys.argv:
        attn_sweep()
    else:
        main(a2a_primary="a2a" in sys.argv)
