"""Headline benchmark — prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``.

Metric: AG-GEMM TFLOPS/chip at the Llama shape [4096, 4096, 4096] bf16
(BASELINE.json / reference tutorial 07). On a multi-chip mesh this runs the
overlapping AG-GEMM kernel; on a single chip it runs the same consumer GEMM
pipeline (n=1 degenerate case — all communication vanishes, leaving the MXU
GEMM whose efficiency the overlap must preserve).

Timing methodology: the device sits behind an async tunnel where
``block_until_ready`` can return before remote execution finishes, so naive
event timing over-reports by ~100x. We therefore time a *data-dependent
chain* of GEMMs ending in a scalar pulled to the host (a D2H transfer cannot
complete early), at two chain lengths, and difference them to cancel the
fixed round-trip (cf. the reference's CUDA-event ``perf_func``,
python/triton_dist/utils.py:186-198 — same warmup+iters idea, adapted to a
remote-execution runtime).

Baseline: FLUX-class efficiency = 60% of the chip's peak dense bf16 FLOPs
(the reference claims "comparable to FLUX" for AG-GEMM, README.md:146-150).
``vs_baseline`` = measured / baseline; 1.0 = FLUX-parity efficiency.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# dense bf16 peak TFLOP/s per chip by device kind (public specs)
_PEAKS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5", 197.0),     # v5e / v5 lite
    ("v4", 275.0),
    ("cpu", 0.15),     # virtual device smoke-run; irrelevant to the driver
)


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAKS:
        if key in kind:
            return peak
    return 197.0


def _timed_pull(fn, *args, trials: int = 3) -> float:
    """Best-of wall time of ``float(fn(*args))`` — the scalar D2H pull is the
    synchronization point."""
    float(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_chain(step_fn, a, b, iters: int) -> float:
    """Seconds for ``iters`` data-dependent applications of ``step_fn`` plus
    one fixed pull (differenced away by the caller)."""

    def chain(a, b):
        def body(c, _):
            return (step_fn(c, b) * jnp.asarray(0.01, c.dtype), None)
        c, _ = lax.scan(body, a, None, length=iters)
        return jnp.sum(c.astype(jnp.float32))

    return _timed_pull(jax.jit(chain), a, b)


def bench_calls(fn, args, iters: int) -> float:
    """Seconds for ``iters`` back-to-back dispatches plus one final pull —
    in-order device execution makes the pull wait for every prior kernel.
    Used for the multi-chip ag_gemm path (its output sharding differs from
    its input's, so it does not self-chain)."""
    pull = jax.jit(lambda x: jnp.sum(x.astype(jnp.float32)))
    float(pull(fn(*args)))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = fn(*args)
        float(pull(out))
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    import math

    from triton_dist_tpu.ops.allgather_gemm import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig, matmul
    from triton_dist_tpu.shmem.context import initialize_distributed
    from triton_dist_tpu.utils import on_cpu

    if on_cpu():
        # smoke shape; interpret mode is only reliable at <=6 sim devices
        # on one host core (see tests/conftest.py)
        M = N = K = 512
        n_dev = min(len(jax.devices()), 4)
        configs = [GemmConfig(math.gcd(128, M // n_dev),
                              math.gcd(128, N // n_dev))]
        i1, i2 = 1, 3
    else:
        M = N = K = 4096
        n_dev = len(jax.devices())
        configs = [GemmConfig(128, 128), GemmConfig(256, 256),
                   GemmConfig(512, 256)]
        i1, i2 = 10, 50

    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)

    best_s = float("inf")
    if n_dev > 1:
        ctx = initialize_distributed(axis_names=("x",), mesh_shape=(n_dev,))
        a_s = ctx.shard(a, P("x"))
        b_s = ctx.shard(b, P(None, "x"))
        for cfg in configs:
            if (M // n_dev) % cfg.block_m or (N // n_dev) % cfg.block_n:
                continue
            if not cfg.vmem_ok(K, 2):
                continue
            try:
                f = jax.jit(lambda a, b, c=cfg: ag_gemm(
                    ctx, a, b, axis="x", cfg=c, out_dtype=jnp.bfloat16))
                t1 = bench_calls(f, (a_s, b_s), i1)
                t2 = bench_calls(f, (a_s, b_s), i2)
                best_s = min(best_s, (t2 - t1) / (i2 - i1))
            except Exception:
                continue
    else:
        for cfg in configs:
            if M % cfg.block_m or N % cfg.block_n or not cfg.vmem_ok(K, 2):
                continue
            try:
                step = lambda x, y, c=cfg: matmul(x, y, c)
                t1 = bench_chain(step, a, b, i1)
                t2 = bench_chain(step, a, b, i2)
                best_s = min(best_s, (t2 - t1) / (i2 - i1))
            except Exception:
                continue

    assert best_s < float("inf") and best_s > 0, (
        f"no benchmark config ran (best_s={best_s})")
    tflops = (2.0 * M * N * K / best_s) / max(n_dev, 1) / 1e12
    baseline = 0.6 * chip_peak_tflops()
    print(json.dumps({
        "metric": "ag_gemm_tflops_per_chip",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(tflops / baseline, 3),
    }))


if __name__ == "__main__":
    main()
