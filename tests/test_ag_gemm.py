"""AG-GEMM overlap op vs golden (parity target: reference
test/nvidia/test_ag_gemm_intra_node.py — correctness case :128-148 builds the
golden with all_gather + matmul; odd-ish shapes deliberately)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.gemm import GemmConfig, matmul
from conftest import TEST_WORLD
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def _golden(ctx, a, b):
    def g(a_shard, b_shard):
        a_full = jax.lax.all_gather(a_shard, "x", axis=0, tiled=True)
        return jnp.dot(a_full, b_shard, preferred_element_type=jnp.float32)
    sm = ctx.shard_map(g, in_specs=(P("x"), P(None, "x")),
                       out_specs=P(None, "x"))
    return jax.jit(sm)(a, b)


def test_matmul_local():
    a = jax.random.normal(jax.random.key(0), (64, 256), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (256, 128), jnp.float32)
    c = jax.jit(lambda a, b: matmul(a, b, GemmConfig(block_m=32, block_n=64)))(a, b)
    assert_allclose(c, np.asarray(a) @ np.asarray(b), atol=1e-2, rtol=1e-2)


@pytest.mark.quick
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_ag_gemm(ctx, dtype):
    n = ctx.num_ranks
    M, K, N = n * 32, 128, n * 64  # tiny Llama-shaped TP slice
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32).astype(dtype)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32).astype(dtype)
    a = ctx.shard(a, P("x"))
    b = ctx.shard(b, P(None, "x"))
    cfg = GemmConfig(block_m=32, block_n=64)
    c = jax.jit(lambda a, b: ag_gemm(ctx, a, b, axis="x", cfg=cfg,
                                     out_dtype=jnp.float32))(a, b)
    golden = _golden(ctx, a, b)
    tol = 1e-2 if dtype == jnp.bfloat16 else 1e-4
    assert_allclose(np.asarray(c), np.asarray(golden), atol=tol, rtol=tol)


def test_ag_gemm_repeated_calls(ctx):
    """Back-to-back calls reuse workspace slots — the entry barrier must
    prevent cross-call races (cf. local_copy_and_barrier_all)."""
    n = ctx.num_ranks
    M, K, N = n * 32, 128, n * 32
    cfg = GemmConfig(block_m=32, block_n=32)
    f = jax.jit(lambda a, b: ag_gemm(ctx, a, b, axis="x", cfg=cfg))
    for i in range(3):
        a = ctx.shard(jax.random.normal(jax.random.key(i), (M, K)), P("x"))
        b = ctx.shard(jax.random.normal(jax.random.key(100 + i), (K, N)),
                      P(None, "x"))
        c = f(a, b)
        golden = _golden(ctx, a, b)
        assert_allclose(np.asarray(c), np.asarray(golden), atol=1e-4, rtol=1e-4)
