"""Prefix cache over ``KVPagePool`` (ISSUE 13): a token-keyed radix index
mapping FULL-PAGE token runs to the page ids holding their computed KV.

The million-user workload is dominated by shared prefixes (system
prompts, few-shot headers). Greedy decode makes KV a pure function of
the token prefix, so a page that holds the KV of tokens
``[i*page_size, (i+1)*page_size)`` for one request holds it for EVERY
request whose prompt starts with the same ``(i+1)*page_size`` tokens —
repeated prefills become page-table pointer swaps. In the paper's
producer/consumer-over-pages framing, a cached page is simply a page
whose producer already ran.

Division of labor:

- ``KVPagePool`` (kv_pool.py) owns the refcount mechanics: ``acquire``
  bumps a shared page's count, release parks the last reference of an
  index-retained page on the cached LRU list instead of the free list,
  ``cow_page`` swaps a fresh page under a would-be writer of a shared
  one, and ``check()``/``digest()`` audit all of it.
- ``PrefixCache`` (this module) owns the token-keyed index: a radix
  trie whose edges are page-sized token runs, ``match`` walks the
  longest cached prefix, ``insert`` registers a finished prefill's
  pages, and ``evict`` reclaims refcount-0 cached pages in LRU order
  (dropping each victim's whole subtree — a child run's KV is
  meaningless without its parent's pages).

First-writer-wins: if two requests compute the same prefix before
either is indexed, the first ``insert`` claims the trie edge and the
second request's duplicate pages free normally at finish — greedy
determinism guarantees their bytes were identical anyway, which is also
why adopting cached pages preserves the bit-identical trace contract.

``ReplicaPrefixIndex`` is the cluster-router variant of the same trie
(ISSUE 13 satellite): runs map to replica indices instead of page ids,
so the router can send a prompt to the replica whose cache most likely
holds its prefix — radix-hit routing with rendezvous-hash fallback.
"""

from __future__ import annotations

from .kv_pool import KVPagePool, PageLedgerError, _fnv1a


class _Node:
    """One radix-trie node: the page holding the KV of ``run`` (the
    page-sized token run on the edge above), its parent, and children
    keyed by the NEXT run. Insertion-ordered children keep every walk
    deterministic."""
    __slots__ = ("page", "run", "parent", "children")

    def __init__(self, page=None, run=None, parent=None):
        self.page = page
        self.run = run
        self.parent = parent
        self.children: dict[tuple, "_Node"] = {}


class PrefixCache:
    """Token-run radix index over one ``KVPagePool``.

    Only FULL pages are indexed: a partially-filled last page is still
    being written by its owner (decode appends there), so it can never
    be shared. ``match`` therefore returns whole-page hits only, and the
    engine resumes chunked prefill at the first missing token.
    """

    def __init__(self, pool: KVPagePool, page_size: int):
        assert page_size >= 1
        self.pool = pool
        self.page_size = page_size
        self._root = _Node()
        self._node_of: dict[int, _Node] = {}

    # -- token-run helpers ------------------------------------------------
    def _runs(self, prompt) -> list[tuple]:
        ps = self.page_size
        return [tuple(prompt[i:i + ps])
                for i in range(0, (len(prompt) // ps) * ps, ps)]

    @property
    def indexed_pages(self) -> int:
        return len(self._node_of)

    @property
    def evictable(self) -> int:
        """Refcount-0 cached pages reclaimable right now — the headroom
        admission adds to the free-page count."""
        return self.pool.cached_pages

    # -- lookup / registration --------------------------------------------
    def match(self, prompt) -> list[int]:
        """Page ids of the longest indexed full-page prefix of
        ``prompt``, in position order (may be empty)."""
        node, out = self._root, []
        for run in self._runs(prompt):
            child = node.children.get(run)
            if child is None:
                break
            out.append(child.page)
            node = child
        return out

    def insert(self, prompt, pages) -> int:
        """Index ``pages[i]`` as holding the KV of ``prompt``'s i-th
        full-page run. Existing mappings win (first-writer-wins); newly
        indexed pages are marked cacheable on the pool so their last
        release parks them on the cached LRU list. Returns how many
        pages were newly indexed."""
        runs = self._runs(prompt)
        if len(pages) > len(runs):
            raise PageLedgerError(
                f"insert: {len(pages)} pages for only {len(runs)} "
                f"full-page runs of a {len(prompt)}-token prompt")
        node, new = self._root, 0
        for run, page in zip(runs, pages):
            child = node.children.get(run)
            if child is None:
                if page in self._node_of:
                    raise PageLedgerError(
                        f"page {page} is already indexed under a "
                        "different token run")
                child = _Node(page, run, node)
                node.children[run] = child
                self._node_of[page] = child
                self.pool.mark_cacheable(page)
                new += 1
            node = child
        return new

    # -- eviction (LRU, subtree-consistent) -------------------------------
    def evict(self, want: int) -> int:
        """Reclaim at least ``want`` pages for the free list by retiring
        cached (refcount-0) pages in LRU order. Each victim's ENTIRE
        subtree leaves the index — a child run's KV is unreachable
        without its parent's pages — so one eviction may free several
        cached pages (all counted). Subtree pages still referenced by
        running sequences merely lose their retention mark: they free
        normally on their last release. Returns pages actually freed;
        less than ``want`` means the cache is out of evictable pages."""
        freed = 0
        while freed < want:
            lru = self.pool.lru_cached()
            if not lru:
                break
            node = self._node_of.get(lru[0])
            if node is None:        # cached without an index entry —
                raise PageLedgerError(   # uncache() should have run
                    f"cached page {lru[0]} has no index node")
            freed += self._drop_subtree(node)
        return freed

    def _drop_subtree(self, node: _Node) -> int:
        if node.parent is not None:
            del node.parent.children[node.run]
            node.parent = None
        freed, stack = 0, [node]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children = {}
            if n.page is not None:
                self._node_of.pop(n.page, None)
                if self.pool.uncache(n.page):
                    freed += 1
        return freed

    def clear(self) -> int:
        """Drop the whole index (restore path: a rebuilt pool re-earns
        every page via re-prefill, so no pre-crash KV may be adopted)."""
        return self._drop_subtree(self._root) if self._root.children \
            else 0

    # -- checkpoint audit (ISSUE 9 satellite) -----------------------------
    def snapshot(self) -> list:
        """JSON-able preorder edge list ``[parent_page, run, page]``
        (root parent encoded as -1), deterministic given the insertion
        history. Checkpoints record it next to the pool snapshot purely
        as an integrity artifact: restore re-earns KV via re-prefill and
        starts with an EMPTY cache, but a torn/tampered snapshot must
        still fail the digest audit loudly."""
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            for c in n.children.values():
                out.append([-1 if n.page is None else n.page,
                            list(c.run), c.page])
                stack.append(c)
        return out

    def digest(self) -> int:
        return self.snapshot_digest(self.snapshot())

    @staticmethod
    def snapshot_digest(entries) -> int:
        """32-bit FNV-1a over a ``snapshot()`` edge list — order,
        tokens, parentage and page ids all fold in, so any single-field
        tamper shifts the digest."""
        h = 0x811C9DC5
        for parent, run, page in entries:
            h = _fnv1a(h, parent, len(run), *run, page)
        return h


class ReplicaPrefixIndex:
    """The cluster's authoritative prefix radix index (ISSUE 13
    satellite, promoted to the lending tier's source of truth in ISSUE
    17): block-sized token runs map to the replica that last served that
    prefix. Pure host-side control plane — no pool, no refcounts — but
    the same full-run granularity as ``PrefixCache`` so an index hit
    predicts an engine-side cache hit. Two consumers now share it: the
    router (radix-hit affinity) and the page-lending tier
    (serving/lending.py), which on a borrower-side miss asks the owner
    replica to lend the prefix pages. First-writer-wins keeps both
    sticky and deterministic. A dead replica's entries are PRUNED by the
    cluster's ``kill()`` (stale entries would route — and worse, lend —
    against pages that no longer exist); the pruned prefixes come back
    via ``insert`` when the replica is restored and re-warmed."""

    def __init__(self, block: int):
        assert block >= 1
        self.block = block
        self._root: dict = {}

    def _runs(self, prompt) -> list[tuple]:
        b = self.block
        return [tuple(prompt[i:i + b])
                for i in range(0, (len(prompt) // b) * b, b)]

    def match(self, prompt) -> tuple[int, int | None]:
        """(hit depth in runs, replica index of the DEEPEST hit node) —
        ``(0, None)`` on a miss."""
        node, depth, owner = self._root, 0, None
        for run in self._runs(prompt):
            child = node.get(run)
            if child is None:
                break
            depth += 1
            owner = child[0]
            node = child[1]
        return depth, owner

    def insert(self, prompt, replica: int) -> None:
        node = self._root
        for run in self._runs(prompt):
            child = node.get(run)
            if child is None:
                child = (replica, {})
                node[run] = child
            node = child[1]

    def reassign(self, prompt, replica: int) -> None:
        """Set ``replica`` as owner of EVERY node along ``prompt``'s
        full-run path, creating missing nodes — the restore-path inverse
        of ``prune`` (ISSUE 17). Unlike ``insert`` this overwrites: a
        restored replica reclaims its tombstoned prefixes (it just
        re-warmed exactly those pages from peers, so routing them back is
        warm), which is the "affinity returns the moment the replica is
        restored" contract the kill/restore test pins."""
        node = self._root
        for run in self._runs(prompt):
            child = node.get(run)
            if child is None:
                child = (replica, {})
            elif child[0] != replica:
                child = (replica, child[1])
            node[run] = child
            node = child[1]

    def prune(self, replica: int) -> list[tuple[int, ...]]:
        """Drop every node owned by ``replica`` — with its WHOLE subtree,
        like ``PrefixCache`` eviction: a child run's claim is meaningless
        once its parent's entry is gone (ISSUE 17 satellite). Foreign-
        owned descendants inside a dropped subtree are acceptable
        collateral — they re-register on their owners' next submits.
        Returns the full token paths of every ``replica``-owned node
        removed (deepest included), insertion-ordered: the tombstone
        list the cluster re-warms from peers and re-registers once the
        restored replica verifies."""
        tombstones: list[tuple[int, ...]] = []

        def collect(children: dict, path: tuple) -> None:
            for run, (owner, sub) in children.items():
                p = path + run
                if owner == replica:
                    tombstones.append(p)
                collect(sub, p)

        def walk(children: dict, path: tuple) -> None:
            for run in list(children):
                owner, sub = children[run]
                p = path + run
                if owner == replica:
                    tombstones.append(p)
                    collect(sub, p)
                    del children[run]
                else:
                    walk(sub, p)

        walk(self._root, ())
        return tombstones


__all__ = ["PrefixCache", "ReplicaPrefixIndex"]
