"""Model-free speculative decoding primitives (ISSUE 20).

The serving hot loop earns one committed token per active slot per
dispatch — decode throughput is bounded by sequential sampling, not the
hardware. Draft-verify breaks the bound WITHOUT a draft model:

- **draft** (``ngram_draft``): propose K-1 continuation tokens per slot by
  prompt-lookup over the token history the engine already keeps device-
  resident — find the most recent earlier occurrence of the current
  bigram and replay what followed it. Pure jnp over an int32 ``[B, H]``
  ring of recent tokens: no host sync, no extra model, no new weights.
- **verify** (``models.llama.decode_speculate_paged``): ONE paged-
  attention pass scores all K positions as K batch rows (the
  ``prefill_chunk_paged`` C-rows-of-decode idiom), greedy-argmaxes each,
  and ``spec_accept`` keeps the longest prefix where draft == argmax.
- **rewind**: rejected positions' KV is already past the accepted
  cursor; the engine frees whole rejected pages via the existing
  ``KVPagePool.free_tail`` and the next dispatch overwrites in-page
  remainders before any read (the same argument that makes in-page
  padding tails safe).

Acceptance is EXACT-MATCH against the greedy argmax, which is what keeps
the bitwise trace contract: a committed token is committed because the
verify row — fed the identical committed prefix — produced it, so the
sequence is bit-identical to ``speculate=off``; only the dispatch count
shrinks. A bad drafter can only cost speed, never change a token.

This module is deliberately free of model/engine imports (``llama.py``
imports it function-locally at trace time) so the drafter and the accept
rule stay unit-testable host-side — the EOS/limit edge cases ride plain
int arrays here instead of a 50-request engine run.
"""

from __future__ import annotations

import jax.numpy as jnp

from triton_dist_tpu.aot.registry import TunedKey, get_default_registry

SPEC_K_DEFAULT = 4


def ngram_draft(hist: jnp.ndarray, hist_len: jnp.ndarray,
                n: int) -> jnp.ndarray:
    """Propose ``n`` draft tokens per row by bigram prompt-lookup.

    ``hist`` [B, H] int32 is the right-aligned recent-token window
    (newest token at column H-1, zero left-padding); ``hist_len`` [B]
    int32 counts the valid suffix. For each row, find the MOST RECENT
    earlier position whose (previous, current) token pair equals the
    window's final bigram and return the ``n`` tokens that followed it;
    fall back to a unigram match on the final token, then to repeating
    the final token (a deliberately wrong draft the verify pass simply
    rejects — drafting can never affect correctness, only speed).

    Pure jnp, shape-static in (B, H, n): traces into the one compiled
    decode program. Most-recent-match (not first) because generation
    loops — n-gram cycles in the generated suffix — are exactly the
    repetitive structure speculation wins on.
    """
    B, H = hist.shape
    if n <= 0:
        return jnp.zeros((B, 0), jnp.int32)
    idx = jnp.arange(H, dtype=jnp.int32)[None, :]             # [1, H]
    lo = (H - hist_len)[:, None].astype(jnp.int32)            # [B, 1]
    last = hist[:, -1][:, None]                               # [B, 1]
    prev = jnp.concatenate([jnp.zeros((B, 1), hist.dtype),
                            hist[:, :-1]], axis=1)            # [B, H]
    second = prev[:, -1][:, None]                             # hist[:, -2]
    # candidates strictly before the newest position, inside the valid
    # window (the bigram additionally needs its PREVIOUS position valid)
    in_win = jnp.logical_and(idx >= lo, idx < H - 1)
    m1 = jnp.logical_and(hist == last, in_win)
    m2 = jnp.logical_and(m1, jnp.logical_and(prev == second,
                                             idx - 1 >= lo))
    j2 = jnp.max(jnp.where(m2, idx, -1), axis=1)              # [B]
    j1 = jnp.max(jnp.where(m1, idx, -1), axis=1)
    j = jnp.where(j2 >= 0, j2, j1)                            # [B]
    cols = j[:, None] + 1 + jnp.arange(n, dtype=jnp.int32)[None, :]
    cols = jnp.clip(cols, 0, H - 1)
    out = jnp.take_along_axis(hist, cols, axis=1)
    return jnp.where((j >= 0)[:, None], out, last).astype(jnp.int32)


def spec_accept(inp: jnp.ndarray, nxt: jnp.ndarray, ract: jnp.ndarray,
                eos_id: int | None = None) -> jnp.ndarray:
    """Accepted-count per row for one draft-verify dispatch.

    ``inp`` [B, K] are the tokens the verify rows CONSUMED (column 0 the
    real last token, columns 1..K-1 the drafts); ``nxt`` [B, K] the
    greedy argmax each row PRODUCED; ``ract`` [B, K] the per-row
    ``limit`` mask. Returns ``m`` [B] int32, the number of committed
    tokens ``nxt[:, :m]`` — the longest prefix where:

    - position 0 always commits on an active row (``inp[:, 0]`` is the
      authentic last token, so ``nxt[:, 0]`` IS the greedy next token);
    - position i > 0 commits iff position i-1 committed AND the draft
      matched its verified argmax (``inp[:, i] == nxt[:, i-1]`` — the
      row consumed the token greedy decoding would have) AND the limit
      admits it AND position i-1 did not emit EOS.

    The EOS clause freezes AFTER the emitting position, mirroring
    ``decode_multistep_paged``'s stopped-mask: EOS, when present, is
    always the LAST committed token — never inside the accepted prefix —
    so the host can append all ``m`` tokens and finish the request
    without mid-slab divergence. ``m <= limit`` composes the
    ``max_new_tokens``/page-headroom clamp: an accept burst can never
    overshoot the budget or write KV past a frozen row.
    """
    B, K = inp.shape
    m = jnp.zeros((B,), jnp.int32)
    alive = jnp.ones((B,), jnp.bool_)
    for i in range(K):
        can = jnp.logical_and(alive, ract[:, i])
        if i > 0:
            can = jnp.logical_and(can, inp[:, i] == nxt[:, i - 1])
        m = m + can.astype(jnp.int32)
        if eos_id is not None:
            can = jnp.logical_and(can, nxt[:, i] != eos_id)
        alive = can
    return m


def resolve_spec_k(speculate, mesh_shape=(), dtype: str = "float32",
                   bucket: int = 0, default: int = SPEC_K_DEFAULT) -> int:
    """Resolve the draft length K: explicit int → PR 15 registry →
    default — the ``serving_overlap_mb`` resolution ladder (sharded.py)
    applied to the speculation knob. ``"auto"`` consults the default
    tuned-config registry under ``TunedKey("serving_spec_k", mesh_shape,
    dtype, ((bucket,),))`` where ``bucket`` is the workload-
    repetitiveness bucket (``workload.spec_bucket_of``): the best K is a
    property of the traffic (how repetitive) and the mesh (how much a
    wasted verify row costs), not of the model. Mesh-keyed entries enter
    the registry only through the sigcheck gate
    (``aot.registry.GATE_RUNNERS["serving_spec_k"]``) because K scales
    the decode program's EP A2A row count."""
    if isinstance(speculate, bool):
        raise TypeError("speculate must be an int K or 'auto', not bool")
    if isinstance(speculate, int):
        assert speculate >= 1, f"speculate K must be >= 1, got {speculate}"
        return speculate
    assert speculate == "auto", (
        f"speculate must be an int K or 'auto', got {speculate!r}")
    reg = get_default_registry()
    if reg is not None:
        k = reg.get(TunedKey("serving_spec_k",
                             mesh_shape=tuple(int(d) for d in mesh_shape),
                             dtype=str(dtype),
                             shape_bucket=((int(bucket),),)))
        if k is not None:
            return int(k)
    return default


__all__ = ["ngram_draft", "spec_accept", "resolve_spec_k",
           "SPEC_K_DEFAULT"]
