"""Shared tutorial harness: case registry + argparse + mesh bootstrap
(the reference's ``register_test``/``--case`` pattern,
test/nvidia/test_ag_gemm_intra_node.py:44-73, plus ``--list``)."""

from __future__ import annotations

import argparse

_CASES: dict = {}
_SIM_WORLD: list = []   # set by --sim: mesh size (may be < device count)


def register_case(name: str):
    def deco(fn):
        _CASES[name] = fn
        return fn
    return deco


def _force_sim(n: int) -> None:
    """Switch to the CPU simulator. More devices than mesh participants are
    created: the interpreter's device threads can deadlock in its internal
    allocator when every thread simultaneously blocks in a barrier (see
    tests/conftest.py), so the mesh runs over a prefix subset."""
    _SIM_WORLD.append(n)
    from triton_dist_tpu.utils.env import force_virtual_cpu_devices
    force_virtual_cpu_devices(max(8, n + 2), skip_if_satisfied=False)


def tutorial_main(description: str, default_case: str = "correctness"):
    """Parse args, bootstrap the backend, run the selected case, exit 0 on
    success (cases signal failure by raising)."""
    ap = argparse.ArgumentParser(description=description)
    ap.add_argument("--case", default=default_case, choices=sorted(_CASES),
                    help="which case to run")
    ap.add_argument("--sim", type=int, default=None, metavar="N",
                    help="simulate an N-device CPU mesh (interpret mode)")
    ap.add_argument("--list", action="store_true", help="list cases")
    args = ap.parse_args()
    if args.list:
        for name in sorted(_CASES):
            print(name)
        return
    if args.sim:
        _force_sim(args.sim)
    import jax
    print(f"[tutorial] backend={jax.devices()[0].platform} "
          f"devices={len(jax.devices())} case={args.case}")
    _CASES[args.case]()
    print(f"[tutorial] {args.case}: PASS")


def perf_report(name: str, seconds: float, extra: str = "") -> None:
    us = seconds * 1e6
    print(f"[perf] {name}: {us:.1f} us/call {extra}".rstrip())


def time_op(fn, iters: int = 50, warmup: int = 5) -> float:
    """Simple wall-clock per-call timing (block_until_ready); for tunnel-
    accurate numbers use bench.py's differenced chains instead."""
    import time

    import jax
    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def world_size() -> int:
    import jax
    return _SIM_WORLD[0] if _SIM_WORLD else len(jax.devices())


def world_context_2d(axis_names=("node", "x")):
    """Factor the world into a 2-axis (outer, inner) mesh with the outer
    ("node"/slow) axis taking the largest divisor ≤ sqrt(world) — the mesh
    shape the multi-tier tutorials run on. A single chip degenerates to
    (1, 1)."""
    ws = world_size()
    no = 1
    for d in range(int(ws ** 0.5), 0, -1):
        if ws % d == 0:
            no = d
            break
    return world_context(axis_names=axis_names, mesh_shape=(no, ws // no))


def world_context(axis_names=("x",), mesh_shape=None):
    from triton_dist_tpu.shmem.context import initialize_distributed
    if mesh_shape is None:
        if len(axis_names) != 1:
            raise ValueError(
                "world_context needs an explicit mesh_shape for multi-axis "
                f"meshes (axis_names={axis_names}) — the --sim world size "
                "cannot be factorized implicitly")
        mesh_shape = (world_size(),)
    return initialize_distributed(axis_names=axis_names,
                                  mesh_shape=mesh_shape)
