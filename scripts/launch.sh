#!/bin/bash
# Pod/multi-host launch wrapper (analog of reference scripts/launch.sh:1-58,
# which wires torchrun + NVSHMEM env). On TPU the process model is one
# python process per HOST (not per chip) and jax.distributed.initialize()
# picks the cluster up from environment variables, so this script only has
# to pin the env and exec python once per host.
#
# Usage (run the same command on EVERY host of the pod):
#
#   # single host (one chip or one slice):
#   scripts/launch.sh python -m tutorials.t05_ag_gemm --case perf
#
#   # multi-host pod, explicit coordinator (host 0's address):
#   JAX_COORDINATOR_ADDRESS=10.0.0.1:8476 \
#   JAX_NUM_PROCESSES=4 JAX_PROCESS_ID=<this host's index> \
#   scripts/launch.sh python -m tutorials.t05_ag_gemm --case perf
#
#   # GCE/GKE TPU pods: the TPU metadata supplies everything —
#   # jax.distributed.initialize() auto-discovers; just run:
#   scripts/launch.sh python train_script.py
#
# ShmemContext.initialize_distributed() calls jax.distributed.initialize()
# when any of JAX_COORDINATOR_ADDRESS / COORDINATOR_ADDRESS /
# MEGASCALE_COORDINATOR_ADDRESS / TPU_WORKER_ID is set (shmem/context.py),
# so no per-op launcher changes are needed.

set -euo pipefail

SCRIPT_DIR=$(cd -- "$(dirname -- "${BASH_SOURCE[0]}")" &>/dev/null && pwd)
REPO_DIR=$(dirname -- "$SCRIPT_DIR")

# repo importable from anywhere (reference pins PYTHONPATH the same way)
case ":${PYTHONPATH:-}:" in
    *:"${REPO_DIR}":*) ;;
    *) export PYTHONPATH="${REPO_DIR}${PYTHONPATH:+:${PYTHONPATH}}" ;;
esac

# persistent XLA compile cache: first compiles are ~20-40 s on TPU; cached
# afterwards (the analog of the reference's TRITON_CACHE_DIR pinning)
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-"$REPO_DIR/.jax_cache"}
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

# quieter default logs on pods (reference sets NCCL_DEBUG=ERROR)
export TPU_STDERR_LOG_LEVEL=${TPU_STDERR_LOG_LEVEL:-3}
export TF_CPP_MIN_LOG_LEVEL=${TF_CPP_MIN_LOG_LEVEL:-2}

# map generic coordinator env to jax's spelling if only the generic one is
# set (lets one launch line serve ad-hoc clusters)
if [ -n "${COORDINATOR_ADDRESS:-}" ] && [ -z "${JAX_COORDINATOR_ADDRESS:-}" ]; then
  export JAX_COORDINATOR_ADDRESS="$COORDINATOR_ADDRESS"
fi

echo "[launch] repo=$REPO_DIR" \
     "coordinator=${JAX_COORDINATOR_ADDRESS:-<single-host/auto>}" \
     "process=${JAX_PROCESS_ID:-0}/${JAX_NUM_PROCESSES:-1}" >&2

exec "$@"
