"""GEMM-RS overlap op vs golden (parity target: reference
test/nvidia/test_gemm_rs.py — golden = matmul + reduce_scatter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from conftest import TEST_WORLD
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def _golden(ctx, a, b):
    def g(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard, preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(part, "x", scatter_dimension=0, tiled=True)
    sm = ctx.shard_map(g, in_specs=(P(None, "x"), P("x", None)),
                       out_specs=P("x"))
    return jax.jit(sm)(a, b)


@pytest.mark.quick
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_gemm_rs(ctx, dtype):
    n = ctx.num_ranks
    M, K, N = n * 32, n * 64, 128
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32).astype(dtype)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32).astype(dtype)
    a = ctx.shard(a, P(None, "x"))
    b = ctx.shard(b, P("x", None))
    cfg = GemmConfig(block_m=32, block_n=64)
    c = jax.jit(lambda a, b: gemm_rs(ctx, a, b, axis="x", cfg=cfg,
                                     out_dtype=jnp.float32))(a, b)
    golden = _golden(ctx, a, b)
    tol = 5e-2 if dtype == jnp.bfloat16 else 1e-4
    assert_allclose(np.asarray(c), np.asarray(golden), atol=tol, rtol=tol)


def test_gemm_rs_repeated(ctx):
    n = ctx.num_ranks
    M, K, N = n * 32, n * 32, 64
    cfg = GemmConfig(block_m=32, block_n=32)
    f = jax.jit(lambda a, b: gemm_rs(ctx, a, b, axis="x", cfg=cfg))
    for i in range(3):
        a = ctx.shard(jax.random.normal(jax.random.key(i), (M, K)), P(None, "x"))
        b = ctx.shard(jax.random.normal(jax.random.key(50 + i), (K, N)), P("x", None))
        c = f(a, b)
        golden = _golden(ctx, a, b)
        assert_allclose(np.asarray(c), np.asarray(golden), atol=1e-4, rtol=1e-4)
