"""Tutorial 09 — hierarchical (multi-tier) AllGather-GEMM.

Analog of reference tutorials/03 + 07's inter-node tier (ag_gemm_inter_node,
allgather_gemm.py:938-975): the mesh has a slow outer axis ("node" — DCN /
inter-slice) and a fast inner axis (ICI). Each device is the relay for its
own inner index: the local shard rides the outer ring between same-inner-
index peers while being pushed to inner peers, and the GEMM consumes rows
nearest-first — so the slow tier's transfers hide behind compute on rows
already present (see ops.allgather_gemm.ag_overlap_protocol_2d).

Run:  python -m tutorials.t09_ag_gemm_multitier [--sim 6]
      [--case correctness|correctness_persistent|perf]
"""

from tutorials.common import (perf_report, register_case, time_op,
                              tutorial_main, world_context_2d)


def _shapes(ctx, M=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    n = ctx.num_ranks
    axes = ("node", "x")
    M = M or 128 * n
    K, N = 256, 128 * n
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32
                          ).astype(jnp.bfloat16)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32
                          ).astype(jnp.bfloat16)
    return a, b, ctx.shard(a, P(axes)), ctx.shard(b, P(None, axes))


@register_case("correctness")
def correctness():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context_2d()
    a, b, a_s, b_s = _shapes(ctx)
    cfg = GemmConfig(128, 128)
    c = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis=("node", "x"),
                                     cfg=cfg))(a_s, b_s)
    gold = a.astype(jnp.float32) @ b.astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(c, np.float32), gold, rtol=5e-2,
                               atol=5e-1)
    no, ni = ctx.axis_size("node"), ctx.axis_size("x")
    print(f"2-tier AG-GEMM over ({no} nodes x {ni} PEs) == "
          "all_gather+dot golden")


@register_case("correctness_persistent")
def correctness_persistent():
    """Persistent symmetric workspace threaded across repeated calls."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from triton_dist_tpu.ops import ag_gemm_ws, create_ag_gemm_workspace
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context_2d()
    n = ctx.num_ranks
    axes = ("node", "x")
    a, b, a_s, b_s = _shapes(ctx)
    ws = create_ag_gemm_workspace(ctx, a.shape[0] // n, a.shape[1],
                                  jnp.bfloat16, axis=axes)
    f = jax.jit(lambda u, v, w: ag_gemm_ws(ctx, u, v, w, axis=axes,
                                           cfg=GemmConfig(128, 128)))
    gold = a.astype(jnp.float32) @ b.astype(jnp.float32)
    for _ in range(3):
        c, ws = f(a_s, b_s, ws)
        np.testing.assert_allclose(np.asarray(c, np.float32), gold,
                                   rtol=5e-2, atol=5e-1)
    print("persistent-workspace 2-tier AG-GEMM: 3 calls")


@register_case("perf")
def perf():
    import jax

    from triton_dist_tpu.ops import ag_gemm
    from triton_dist_tpu.ops.gemm import GemmConfig
    ctx = world_context_2d()
    n = ctx.num_ranks
    _, _, a_s, b_s = _shapes(ctx, M=256 * n)
    cfg = GemmConfig(128, 128)
    f = jax.jit(lambda u, v: ag_gemm(ctx, u, v, axis=("node", "x"), cfg=cfg))
    s = time_op(lambda: f(a_s, b_s))
    M, K = a_s.shape
    N = b_s.shape[1]
    perf_report("ag_gemm_2d", s,
                f"~{2 * M * N * K / s / max(n, 1) / 1e12:.1f} TFLOP/s/chip "
                "(wall-clock; see bench.py for tunnel-corrected numbers)")


if __name__ == "__main__":
    tutorial_main(__doc__)
