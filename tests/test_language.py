"""dl.* language surface tests (parity: reference test_notify.py,
test_distributed_wait.py — wait/notify/token discipline)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

import triton_dist_tpu.language as dl
from conftest import TEST_WORLD
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose, default_interpret


@pytest.fixture(scope="module")
def ctx():
    return initialize_distributed(axis_names=("x",), mesh_shape=(TEST_WORLD,))


def test_rank_num_ranks(ctx):
    def kernel(out_ref):
        out_ref[0] = dl.rank("x")
        out_ref[1] = dl.num_ranks("x")

    def f():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((2,), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
            interpret=default_interpret(),
        )()

    y = jax.jit(ctx.shard_map(f, in_specs=(), out_specs=P("x")))()
    y = np.asarray(y).reshape(ctx.num_ranks, 2)
    assert list(y[:, 0]) == list(range(ctx.num_ranks))
    assert all(v == ctx.num_ranks for v in y[:, 1])


def test_notify_wait_roundtrip(ctx):
    """Each PE notifies its right neighbor's REGULAR semaphore twice; the
    neighbor waits for exactly 2 arrivals (counted, consumed)."""

    def kernel(in_ref, out_ref, sem, scratch):
        me = dl.rank("x")
        n = dl.num_ranks("x")
        right = dl.symm_at(("x",), "x", jax.lax.rem(me + 1, n))
        dl.notify(sem, right, inc=1)
        dl.notify(sem, right, inc=1)
        token = dl.wait(sem, 2)
        ref = dl.consume_token(in_ref, token)
        pltpu.sync_copy(ref, scratch)
        scratch[...] = scratch[...] + 1.0
        pltpu.sync_copy(scratch, out_ref)

    def f(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR,
                            pltpu.VMEM(x.shape, x.dtype)],
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
            interpret=default_interpret(),
        )(x)

    n = ctx.num_ranks
    x = jnp.ones((n * 8, 128), jnp.float32)
    y = jax.jit(ctx.shard_map(f, in_specs=P("x"), out_specs=P("x")))(x)
    assert_allclose(y, np.asarray(x) + 1.0)
