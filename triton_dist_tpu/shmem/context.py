"""Host-side tpushmem runtime: mesh bootstrap + symmetric buffers.

Role analog of the reference's ``pynvshmem`` host extension + wrapper
(reference shmem/nvshmem_bind/pynvshmem/src/pynvshmem.cc:130-214 and
python/pynvshmem/__init__.py:93-171), re-thought for TPU/JAX:

- *bootstrap*: NVSHMEM's UID handshake over a torch process group
  (pynvshmem/__init__.py:157-171) becomes ``jax.distributed.initialize`` +
  ``jax.sharding.Mesh`` construction — jax is single-controller, so there is
  no per-rank rendezvous to re-implement.
- *symmetric heap*: ``nvshmem_create_tensor(shape)`` (same shape on every PE,
  peer-addressable) becomes a jax Array of shape ``(n_pes, *local_shape)``
  sharded over the mesh axis: inside ``shard_map`` every device sees an
  identically-shaped local ref, and remote refs are addressed *by device id*
  in ``pltpu.make_async_remote_copy`` — symmetric by construction, no
  ``nvshmem_ptr`` pointer translation needed (cf. symm_at,
  dialect DistributedOps.td:135-149).
"""

from __future__ import annotations

import dataclasses
import os
import functools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_DEFAULT_CONTEXT: "ShmemContext | None" = None


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax
    versions that predate the public accessor (e.g. 0.4.37 exposes only
    ``initialize``/``shutdown``): the coordination-service client on the
    private global state is None exactly until ``initialize`` succeeds."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:
        return False


def initialize_distributed(axis_names: Sequence[str] = ("x",),
                           mesh_shape: Sequence[int] | None = None,
                           seed: int = 42) -> "ShmemContext":
    """Bootstrap the distributed runtime and build the default device mesh.

    Analog of the reference's ``initialize_distributed``
    (python/triton_dist/utils.py:91-111): there it creates a NCCL process
    group, seeds, and boots NVSHMEM off a broadcast unique id. Here:
    multi-host jax initializes from cluster env automatically, and the
    "symmetric heap" needs no setup beyond a Mesh.
    """
    global _DEFAULT_CONTEXT
    # Multi-host bootstrap. Must happen BEFORE any backend use (so no
    # jax.process_count()/jax.devices() in this guard). Opt-in via the
    # standard coordinator env vars or TPU-pod env; failures are surfaced,
    # not swallowed, so a pod never silently degrades to single-host.
    multihost_env = any(os.environ.get(k) for k in (
        "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
        "MEGASCALE_COORDINATOR_ADDRESS", "TPU_WORKER_ID",
    ))
    if multihost_env and not _distributed_initialized():
        # jax auto-detects only managed clusters (Slurm/MPI/GKE-TPU);
        # the explicit JAX_NUM_PROCESSES/JAX_PROCESS_ID spelling that
        # scripts/launch.sh documents for ad-hoc pods must be forwarded by
        # hand (coordinator address jax reads itself).
        nproc = os.environ.get("JAX_NUM_PROCESSES")
        pid = os.environ.get("JAX_PROCESS_ID")
        if (nproc is None) != (pid is None):
            missing = "JAX_PROCESS_ID" if pid is None else "JAX_NUM_PROCESSES"
            present = "JAX_NUM_PROCESSES" if pid is None else "JAX_PROCESS_ID"
            raise RuntimeError(
                f"{present} is set but {missing} is not; ad-hoc multi-host "
                "bootstrap needs both (see scripts/launch.sh), or neither "
                "on a managed cluster where jax auto-detects them")
        jax.distributed.initialize(
            num_processes=int(nproc) if nproc else None,
            process_id=int(pid) if pid else None)
    devices = np.array(jax.devices())
    if mesh_shape is None:
        mesh_shape = (devices.size,) + (1,) * (len(axis_names) - 1)
    n_mesh = int(np.prod(mesh_shape))
    if n_mesh > devices.size:
        raise ValueError(f"mesh_shape {mesh_shape} needs {n_mesh} devices, "
                         f"only {devices.size} available")
    if (n_mesh == devices.size and n_mesh > 1
            and devices[0].platform == "cpu"
            and not _distributed_initialized()
            and os.environ.get("TDT_NO_CPU_SPARES") != "1"):
        # (n_mesh > 1: a single-device mesh has no cross-device waits to
        # deadlock — don't churn the backend for it.)
        # (single-process only: in a jax.distributed cluster the local
        # device count is recorded with the coordination service, and
        # re-creating the backend with extra local devices is rejected —
        # "Different local topology for node 0". Multi-process interpret
        # runs keep the spare-device responsibility with the launcher.)
        # Full-participation interpreter deadlock workaround: the Pallas
        # TPU interpreter's per-device kernel threads run on the CPU
        # client's execution pool, which is sized by device count. When
        # EVERY device thread blocks in a semaphore wait simultaneously
        # (any collective with enough in-kernel work), no pool thread is
        # left to drive the cross-device progress machinery and the
        # process hangs (reproduced: ag_gemm [512,512]x[512,1024] at
        # 8-of-8 deadlocks; identical shape at 8-of-12 runs in 4 s).
        # Transparently re-point jax at n + max(4, n) virtual devices
        # (spares = n: thinner ratios still starved occasionally — a
        # 12-of-18 run was observed taking 169 s vs the usual 6 s)
        # and build the mesh over the first n, so a user's all-device
        # CPU mesh just works. Real-chip meshes are untouched.
        # Re-pointing REPLACES the backend: arrays/meshes created before
        # this call die with a deleted-client error — warn so the failure
        # is attributable (create the context first, or opt out).
        import warnings
        warnings.warn(
            f"initialize_distributed: CPU mesh spans all {n_mesh} visible "
            "devices; provisioning spare virtual devices to avoid the "
            "interpreter's full-participation deadlock. This resets the "
            "jax CPU backend — jax arrays created before this call are "
            "invalidated (set TDT_NO_CPU_SPARES=1 to opt out).",
            stacklevel=2)
        from triton_dist_tpu.utils.env import force_virtual_cpu_devices
        force_virtual_cpu_devices(n_mesh + max(4, n_mesh),
                                  skip_if_satisfied=False)
        devices = np.array(jax.devices())
    dev_grid = None
    if n_mesh == devices.size and devices[0].platform == "tpu":
        # Topology-aware device ordering: ring/relay neighbors along the
        # innermost mesh axis should be physically adjacent on the ICI
        # torus. This is the TPU analog of the reference's NVLink/NUMA
        # topology detection feeding its AG method pick
        # (utils.py:504-607, allgather.py:54-69) — here jax's device-coords
        # mesh builder does the detection.
        try:
            from jax.experimental import mesh_utils
            dev_grid = mesh_utils.create_device_mesh(tuple(mesh_shape))
        except Exception:
            dev_grid = None   # odd topologies/subsets: fall back to order
    if dev_grid is None:
        # Prefix subset (e.g. a 4-device test mesh on an 8-device host) or
        # non-TPU backend: plain enumeration order.
        dev_grid = devices[:n_mesh].reshape(tuple(mesh_shape))
    mesh = Mesh(dev_grid, tuple(axis_names))
    ctx = ShmemContext(mesh=mesh)
    _DEFAULT_CONTEXT = ctx
    return ctx


def get_default_context() -> "ShmemContext":
    global _DEFAULT_CONTEXT
    if _DEFAULT_CONTEXT is None:
        _DEFAULT_CONTEXT = initialize_distributed()
    return _DEFAULT_CONTEXT


@functools.lru_cache(maxsize=None)
def _mesh_axis_crosses_slices(mesh: Mesh, axis: str) -> bool:
    """Constant for a given (mesh, axis) — cached so the per-collective
    ``is_dcn_axis`` check costs a dict lookup, not a device scan (only the
    TDT_DCN_AXES env override stays dynamic)."""
    idx = mesh.axis_names.index(axis)
    devs = np.moveaxis(mesh.devices, idx, 0)
    # any column along the axis whose devices span >1 slice_index
    cols = devs.reshape(devs.shape[0], -1)
    for j in range(cols.shape[1]):
        if len({getattr(d, "slice_index", 0) for d in cols[:, j]}) > 1:
            return True
    return False


@dataclasses.dataclass(frozen=True)
class ShmemContext:
    """Mesh + symmetric-buffer factory. Frozen so it can live in closures of
    jitted functions."""

    mesh: Mesh

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def num_ranks(self) -> int:
        return self.mesh.devices.size

    def axis_size(self, axis: str | Sequence[str] | None = None) -> int:
        """Devices along ``axis`` — a name, a tuple of names (product, for
        hierarchical multi-tier PE groups), or None (whole mesh)."""
        if axis is None:
            return self.num_ranks
        if not isinstance(axis, str):
            n = 1
            for a in axis:
                n *= self.mesh.shape[a]
            return n
        return self.mesh.shape[axis]

    def is_dcn_axis(self, axis: str) -> bool:
        """True when neighbouring devices along ``axis`` live on different
        TPU slices — their link is DCN (data-center network), not ICI, and
        ``pltpu.make_async_remote_copy`` cannot cross it. Hierarchical ops
        route such an axis' tier through XLA collectives (host-driven DCN
        transfers) instead of remote DMA; an ICI-only mesh is unchanged.
        This is the TPU analog of the reference's intra/inter-node split
        (its inter-node tier is a different transport — IBRC/IBGDA,
        reference allgather.py:291-375, ep_a2a.py:35-147).

        Detection: ``device.slice_index`` varies along the axis. The
        ``TDT_DCN_AXES`` env var (comma-separated axis names) forces axes
        to DCN for testing/virtual topologies — the AOT topology gate
        compiles the DCN variants this way on hosts with no multi-slice
        hardware."""
        forced = os.environ.get("TDT_DCN_AXES")
        if forced and axis in [a.strip() for a in forced.split(",")]:
            return True
        return _mesh_axis_crosses_slices(self.mesh, axis)

    # -- symmetric heap -----------------------------------------------------

    def create_symm_tensor(self, local_shape: Sequence[int], dtype,
                           axis: str | None = None) -> jax.Array:
        """Symmetric buffer: one ``local_shape`` block per PE along ``axis``
        (default: the whole mesh, flattened). Analog of
        ``pynvshmem.nvshmem_create_tensor`` (pynvshmem/__init__.py:130-136).
        """
        n = self.axis_size(axis)
        spec = P(self.axis_names if axis is None else axis)
        shape = (n, *local_shape)
        sharding = NamedSharding(self.mesh, spec)
        # Allocate each shard in place (no full-array staging on device 0).
        return jnp.zeros(shape, dtype, device=sharding)

    def shard(self, x: jax.Array, spec: P) -> jax.Array:
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    # -- shard_map wrapper --------------------------------------------------

    def shard_map(self, f: Callable[..., Any], in_specs, out_specs,
                  axis_names: Sequence[str] | None = None):
        """SPMD-launch ``f`` over the mesh — the analog of "one process per
        GPU running this kernel" in the reference's torchrun model. Pallas
        kernels with manual DMA/semaphores do not carry varying-manual-axes
        info, hence ``check_vma=False`` (spelled ``check_rep`` on jax
        versions that predate the public ``jax.shard_map``, e.g. 0.4.x —
        same knob, renamed when the API was promoted)."""
        sm = getattr(jax, "shard_map", None)
        if sm is not None:
            return sm(f, mesh=self.mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
        from jax.experimental.shard_map import shard_map as sm_exp
        return sm_exp(f, mesh=self.mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
