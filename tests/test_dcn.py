"""DCN-tier routing (VERDICT r4 Missing #2 / Next #6).

On a real multi-slice mesh, ``pltpu.make_async_remote_copy`` cannot cross
a slice boundary — the outer tier of every hierarchical op must ride XLA
collectives (host-driven DCN) instead. ``ShmemContext.is_dcn_axis``
detects slice crossings from ``device.slice_index``; the ``TDT_DCN_AXES``
env var forces axes to DCN so this virtual topology can be tested (and
AOT-compiled, test_aot_topology.py) without multi-slice hardware. The
reference's analog is its genuinely-different inter-node transport
(IBRC/IBGDA, allgather.py:291-375, ep_a2a.py:35-147).

Every test here asserts the SAME goldens the ICI paths satisfy — the DCN
re-route must be semantics-preserving — plus that an ICI-only mesh never
takes the DCN path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_dist_tpu.ops import all_gather, reduce_scatter
from triton_dist_tpu.ops.all_to_all import (all_to_all_push, combine_2d,
                                            create_all_to_all_context_2d,
                                            dispatch_2d)
from triton_dist_tpu.ops.allgather_gemm import ag_gemm
from triton_dist_tpu.ops.gemm import GemmConfig
from triton_dist_tpu.ops.gemm_reduce_scatter import gemm_rs
from triton_dist_tpu.shmem.context import initialize_distributed
from triton_dist_tpu.utils import assert_allclose


@pytest.fixture(scope="module")
def ctx2d():
    return initialize_distributed(axis_names=("a", "b"), mesh_shape=(2, 3))


@pytest.fixture()
def dcn_major(monkeypatch):
    """Force the major axis onto the DCN tier (2-slice virtual topology)."""
    monkeypatch.setenv("TDT_DCN_AXES", "a")


def test_ici_mesh_unchanged(ctx2d, monkeypatch):
    monkeypatch.delenv("TDT_DCN_AXES", raising=False)
    assert not ctx2d.is_dcn_axis("a")
    assert not ctx2d.is_dcn_axis("b")


def test_forced_detection(ctx2d, dcn_major):
    assert ctx2d.is_dcn_axis("a")
    assert not ctx2d.is_dcn_axis("b")


def test_all_gather_dcn(ctx2d, dcn_major):
    n = 6
    x = jax.random.normal(jax.random.key(0), (n * 8, 128), jnp.float32)
    xs = ctx2d.shard(x, P(("a", "b")))
    y = jax.jit(lambda v: all_gather(ctx2d, v))(xs)
    assert_allclose(np.asarray(y), np.asarray(x))
    # single-axis spelling over the DCN axis
    xs1 = ctx2d.shard(x, P("a"))
    y1 = jax.jit(lambda v: all_gather(ctx2d, v, axis="a"))(xs1)
    assert_allclose(np.asarray(y1), np.asarray(x))


def test_reduce_scatter_dcn(ctx2d, dcn_major):
    n, M = 6, 24
    x = jnp.round(jax.random.normal(jax.random.key(0), (n * M, 128)) * 4)
    xs = ctx2d.shard(x.astype(jnp.float32), P(("a", "b")))
    y = jax.jit(lambda v: reduce_scatter(ctx2d, v))(xs)
    golden = jax.jit(ctx2d.shard_map(
        lambda s: jax.lax.psum_scatter(s, ("a", "b"), scatter_dimension=0,
                                       tiled=True),
        in_specs=P(("a", "b")), out_specs=P(("a", "b"))))(xs)
    assert_allclose(np.asarray(y), np.asarray(golden))


def test_a2a_push_dcn(ctx2d, dcn_major):
    """The wire collective over a DCN axis: slot semantics preserved."""
    na = 2
    payload = jnp.arange(na * na * 8 * 128, dtype=jnp.float32).reshape(
        na * na, 8, 128)
    ps = ctx2d.shard(payload, P("a"))
    (got,) = jax.jit(lambda v: all_to_all_push(ctx2d, v, axis="a"))(ps)
    # golden: slot p of rank r ends up at slot r of rank p
    want = np.asarray(payload).reshape(na, na, 8, 128).swapaxes(0, 1
                                                                ).reshape(
        na * na, 8, 128)
    assert_allclose(np.asarray(got), want)


def test_dispatch_combine_2d_dcn_roundtrip(ctx2d, dcn_major):
    """The full hierarchical EP dispatch/combine with the OUTER tier on
    DCN (XLA all_to_all) and the inner tier on the Pallas kernel — the
    reference's inter-node + intra-node split, semantics unchanged."""
    n, T, H, topk, E = 6, 8, 128, 2, 12
    a2a = create_all_to_all_context_2d(ctx2d, max_tokens=T, hidden=H,
                                       topk=topk, num_experts=E,
                                       dtype=jnp.float32)
    epr = E // n
    tokens = jax.random.normal(jax.random.key(0), (n * T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (n * T, topk), 0, E)
    w = jax.nn.softmax(jax.random.normal(jax.random.key(2), (n * T, topk)),
                       -1)
    scale = np.linspace(0.5, 2.0, E).astype(np.float32)
    scale_j = jnp.asarray(scale)

    def run(t, i, ww):
        recv, recv_ids, layouts = dispatch_2d(a2a, t, i)

        def process(r_shard, id_shard):
            me0 = jax.lax.axis_index("a")
            me1 = jax.lax.axis_index("b")
            rank = me0 * a2a.n_minor + me1
            gid = jnp.where(id_shard >= 0, rank * epr + id_shard, 0)
            s = jnp.take(scale_j, gid)
            s = jnp.where(id_shard >= 0, s, 0.0)
            return r_shard * s[..., None]

        both = P(("a", "b"))
        proc = ctx2d.shard_map(process, in_specs=(both, both),
                               out_specs=both)(recv, recv_ids)
        return combine_2d(a2a, proc, layouts, ww)

    out = jax.jit(run)(ctx2d.shard(tokens, P(("a", "b"))),
                       ctx2d.shard(ids, P(("a", "b"))),
                       ctx2d.shard(w, P(("a", "b"))))
    t = np.asarray(tokens, np.float32)
    idn, wn = np.asarray(ids), np.asarray(w, np.float32)
    golden = np.zeros_like(t)
    for i in range(t.shape[0]):
        for j in range(idn.shape[1]):
            golden[i] += wn[i, j] * (t[i] * scale[idn[i, j]])
    assert_allclose(np.asarray(out, np.float32), golden, rtol=2e-2,
                    atol=2e-2)


def test_ag_gemm_2tier_dcn(ctx2d, dcn_major):
    """2-tier AG-GEMM with the outer tier on DCN: XLA gather outer, Pallas
    overlap inner, rows restored to P((a, b)) order."""
    n = 6
    M, K, N = n * 16, 128, n * 32
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32) * 0.3
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32) * 0.3
    try:
        c = jax.jit(lambda x, y: ag_gemm(ctx2d, x, y, axis=("a", "b")))(
            ctx2d.shard(a, P(("a", "b"))), ctx2d.shard(b, P(None, ("a", "b"))))
    except NotImplementedError as e:   # pragma: no cover
        # this jax version cannot run multi-axis LOGICAL remote DMA (the
        # fast-tier Pallas stage) — same limitation
        # test_gemm_rs_2tier_dcn_outer hits; the DCN routing itself is
        # covered by the single-axis tests above
        pytest.skip(f"multi-axis Pallas DMA unavailable: {e}")
    assert_allclose(np.asarray(c), np.asarray(a) @ np.asarray(b),
                    atol=1e-3, rtol=1e-3)


def test_gemm_rs_dcn(ctx2d, dcn_major):
    """Single-axis GEMM-RS over a DCN axis: routed to XLA dot +
    psum_scatter end to end, same golden as the Pallas ring."""
    na = 2
    M, K, N = na * 16, na * 64, 64
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32) * 0.3
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32) * 0.3
    c = jax.jit(lambda x, y: gemm_rs(ctx2d, x, y, axis="a"))(
        ctx2d.shard(a, P(None, "a")), ctx2d.shard(b, P("a", None)))
    assert_allclose(np.asarray(c), np.asarray(a) @ np.asarray(b),
                    atol=1e-3, rtol=1e-3)


def test_gemm_rs_2tier_dcn_outer(ctx2d, dcn_major):
    """Hierarchical GEMM-RS with the OUTER tier on DCN: the fast-tier
    fused GEMM+RS stays Pallas, the slow ring becomes psum_scatter —
    semantics (and segment order) unchanged."""
    n = 6
    axes = ("a", "b")
    M, K, N = n * 16, n * 32, 64
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32) * 0.3
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32) * 0.3
    cfg = GemmConfig(block_m=16, block_n=32)
    try:
        c = jax.jit(lambda x, y: gemm_rs(ctx2d, x, y, axis=axes, cfg=cfg))(
            ctx2d.shard(a, P(None, axes)), ctx2d.shard(b, P(axes, None)))
    except NotImplementedError as e:   # pragma: no cover
        # this jax version cannot run multi-axis LOGICAL remote DMA (the
        # fast-tier Pallas stage) — same limitation test_ag_gemm_2tier_dcn
        # hits; the routing logic itself is covered by the single-axis and
        # axis-order tests
        pytest.skip(f"multi-axis Pallas DMA unavailable: {e}")
    assert_allclose(np.asarray(c), np.asarray(a) @ np.asarray(b),
                    atol=1e-3, rtol=1e-3)


def test_gemm_rs_dcn_axis_order_enforced(ctx2d, monkeypatch):
    """A DCN axis buried BEHIND an ICI axis must be rejected loudly —
    the fast-tier stage is remote DMA, which cannot cross DCN."""
    monkeypatch.setenv("TDT_DCN_AXES", "b")
    n = 6
    M, K, N = n * 16, n * 32, 64
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    with pytest.raises(ValueError, match="slow tier"):
        gemm_rs(ctx2d, ctx2d.shard(a, P(None, ("a", "b"))),
                ctx2d.shard(b, P(("a", "b"), None)), axis=("a", "b"))


def test_ag_gemm_dcn_axis_order_enforced(ctx2d, monkeypatch):
    """A DCN axis buried BEHIND an ICI axis must be rejected loudly."""
    monkeypatch.setenv("TDT_DCN_AXES", "b")
    n = 6
    M, K, N = n * 16, 128, n * 32
    a = jax.random.normal(jax.random.key(0), (M, K), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (K, N), jnp.float32)
    with pytest.raises(ValueError, match="slow tier"):
        ag_gemm(ctx2d, ctx2d.shard(a, P(("a", "b"))),
                ctx2d.shard(b, P(None, ("a", "b"))), axis=("a", "b"))


def _ag_moe_golden(tokens, ids, weights):
    t, idn, wn = (np.asarray(tokens), np.asarray(ids),
                  np.asarray(weights, np.float32))
    out = np.zeros((t.shape[0], wn.shape[-1]), np.float32)
    for r in range(t.shape[0]):
        if idn[r] >= 0:
            out[r] = t[r] @ wn[idn[r]]
    return out


def _moe_rs_golden(tokens, ids, tw, weights):
    t, idn = np.asarray(tokens), np.asarray(ids)
    wn, twn = np.asarray(weights, np.float32), np.asarray(tw, np.float32)
    T, topk = twn.shape
    N = wn.shape[-1]
    rows = np.zeros((t.shape[0], N), np.float32)
    for r in range(t.shape[0]):
        if idn[r] >= 0:
            rows[r] = t[r] @ wn[idn[r]]
    return (rows.reshape(T, topk, N) * twn[..., None]).sum(axis=1)


def test_ag_moe_dcn(ctx2d, dcn_major):
    """Single-axis AG-MoE over a DCN axis: routed to XLA all_gather +
    masked dense per-expert matmul end to end, same golden as the fused
    Pallas path (invalid -1 ids included)."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    na = 2
    E, H, N, T = 4, 64, na * 64, na * 16
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), -1, E)
    weights = jax.random.normal(jax.random.key(2), (E, H, N),
                                jnp.float32) * 0.1
    out = jax.jit(lambda t, i, w: ag_moe_group_gemm(
        ctx2d, t, i, w, axis="a"))(
        ctx2d.shard(tokens, P("a")), ctx2d.shard(ids, P("a")),
        ctx2d.shard(weights, P(None, None, "a")))
    assert_allclose(np.asarray(out), _ag_moe_golden(tokens, ids, weights),
                    atol=1e-3, rtol=1e-3)


def test_ag_moe_2tier_dcn_prefix(ctx2d, dcn_major):
    """Hierarchical AG-MoE with the outer tier on DCN: the whole gather
    rides XLA collectives (correctness-first fallback — the fused fast
    tier is ICI-only), rows in P((a, b)) order."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    n = 6
    E, H, N, T = 6, 64, n * 64, n * 8
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    weights = jax.random.normal(jax.random.key(2), (E, H, N),
                                jnp.float32) * 0.1
    axes = ("a", "b")
    out = jax.jit(lambda t, i, w: ag_moe_group_gemm(
        ctx2d, t, i, w, axis=axes))(
        ctx2d.shard(tokens, P(axes)), ctx2d.shard(ids, P(axes)),
        ctx2d.shard(weights, P(None, None, axes)))
    assert_allclose(np.asarray(out), _ag_moe_golden(tokens, ids, weights),
                    atol=1e-3, rtol=1e-3)


def test_moe_reduce_rs_dcn(ctx2d, dcn_major):
    """Single-axis GroupGEMM-RS over a DCN axis: routed to masked dense
    per-expert matmul + psum_scatter end to end (the op's golden)."""
    from triton_dist_tpu.ops.moe import moe_reduce_rs
    na = 2
    E, K, N, T, topk = 4, na * 64, 64, na * 8, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    weights = jax.random.normal(jax.random.key(3), (E, K, N),
                                jnp.float32) * 0.1
    out = jax.jit(lambda t, i, w, ww: moe_reduce_rs(
        ctx2d, t, i, ww, w, axis="a"))(
        ctx2d.shard(tokens, P(None, "a")), ids, weights, tw)
    assert_allclose(np.asarray(out), _moe_rs_golden(tokens, ids, tw, weights),
                    atol=1e-3, rtol=1e-3)


def test_moe_reduce_rs_2tier_dcn_outer(ctx2d, dcn_major):
    """Hierarchical GroupGEMM-RS with the OUTER tier on DCN: the fused
    GroupGEMM + fast-tier RS stays Pallas, the slow outer ring becomes an
    XLA psum_scatter — semantics (and segment order) unchanged."""
    from triton_dist_tpu.ops.moe import moe_reduce_rs
    n = 6
    axes = ("a", "b")
    E, K, N, T, topk = 6, n * 32, 64, n * 4, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    weights = jax.random.normal(jax.random.key(3), (E, K, N),
                                jnp.float32) * 0.1
    try:
        out = jax.jit(lambda t, i, w, ww: moe_reduce_rs(
            ctx2d, t, i, ww, w, axis=axes, block_m=16))(
            ctx2d.shard(tokens, P(None, axes)), ids, weights, tw)
    except NotImplementedError as e:   # pragma: no cover
        pytest.skip(f"multi-axis Pallas DMA unavailable: {e}")
    assert_allclose(np.asarray(out), _moe_rs_golden(tokens, ids, tw, weights),
                    atol=1e-3, rtol=1e-3)


def test_ag_moe_dcn_axis_order_enforced(ctx2d, monkeypatch):
    """A DCN axis buried BEHIND an ICI axis must be rejected loudly —
    the fast-tier gather is remote DMA, which cannot cross DCN."""
    from triton_dist_tpu.ops.moe import ag_moe_group_gemm
    monkeypatch.setenv("TDT_DCN_AXES", "b")
    n = 6
    E, H, N, T = 6, 64, n * 64, n * 8
    tokens = jax.random.normal(jax.random.key(0), (T, H), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T,), 0, E)
    weights = jax.random.normal(jax.random.key(2), (E, H, N), jnp.float32)
    with pytest.raises(ValueError, match="slow tier"):
        ag_moe_group_gemm(ctx2d, ctx2d.shard(tokens, P(("a", "b"))),
                          ctx2d.shard(ids, P(("a", "b"))),
                          ctx2d.shard(weights, P(None, None, ("a", "b"))),
                          axis=("a", "b"))


def test_moe_reduce_rs_dcn_axis_order_enforced(ctx2d, monkeypatch):
    """A DCN axis buried BEHIND an ICI axis must be rejected loudly."""
    from triton_dist_tpu.ops.moe import moe_reduce_rs
    monkeypatch.setenv("TDT_DCN_AXES", "b")
    n = 6
    E, K, N, T, topk = 6, n * 32, 64, n * 4, 2
    tokens = jax.random.normal(jax.random.key(0), (T * topk, K), jnp.float32)
    ids = jax.random.randint(jax.random.key(1), (T * topk,), 0, E)
    tw = jax.nn.softmax(jax.random.normal(jax.random.key(2), (T, topk)), -1)
    weights = jax.random.normal(jax.random.key(3), (E, K, N), jnp.float32)
    with pytest.raises(ValueError, match="slow tier"):
        moe_reduce_rs(ctx2d, ctx2d.shard(tokens, P(None, ("a", "b"))),
                      ids, tw, weights, axis=("a", "b"))
