"""Cluster-wide prefix sharing: the KV page-lending tier (ISSUE 17).

ISSUE 13 gave every replica a private ``PrefixCache`` and the router a
``ReplicaPrefixIndex`` hint; a prompt routed AWAY from its prefix's home
replica (spill, dead affinity target, affinity disabled) still paid a
full cold prefill even though the KV existed one replica over. This tier
closes that gap: the cluster index is now authoritative (pruned on kill,
re-registered after restore — cluster.py), and on a borrower-side miss
with a remote hit the owner **lends** the pages.

The lend is a replication, not a handoff (contrast ``migrate_pages``):

- the lender ships only pages ``KVPagePool.check_lendable`` accepts —
  refcount-0 AND index-retained, i.e. pages NOBODY is writing or even
  reading. Sole-ownership/COW rules are untouched: no live sequence on
  either side can observe the copy happening.
- the borrower lands them in freshly allocated pages, indexes them in
  its own ``PrefixCache`` and releases them to its cached LRU — from
  there on they are ordinary cached pages: admission adopts them, decode
  COWs them, eviction reclaims them. A lend therefore turns into a
  regular local prefix hit, which is why the cluster-wide hit rate
  approaches the single-replica hit rate even with router affinity off.
- greedy-decode determinism makes the lent bytes identical to what the
  borrower would have re-prefilled, so every trace stays bit-identical
  to the n=1 golden — the ISSUE 13 adoption argument stretched across
  replicas.

On device meshes the transfer is ``ops.lend_pages`` (per-(layer, page)
``putmem_nbi`` + counted ``signal_op``, sigcheck-registered); the host
engines here exchange the page payload through ``export_prefix`` /
``adopt_prefix`` — the same split as everywhere else in the serving
tier: kernels move bytes, the host ledger mediates who may.

Failure discipline is the PR 7 ladder, host-tier ``FaultPlan`` driven:
each attempt gets a ``Deadline`` rung from a bounded ``Backoff``; a dead
or slow lender burns its rung and re-rolls; an exhausted ladder DEGRADES
to local re-prefill (``lend_degradations``) — a lend failure is never a
request failure and never a stall, the borrower just prefills the prompt
itself like the tier did not exist.

``rewarm`` is the restore-path entry: a restored replica's cache is
empty by contract (re-prefill re-earns KV), but its pre-death prefixes
are known (the kill-time tombstones) and their KV usually survives on
peers — so the cluster re-warms the cache via lends instead of letting
every shared prefix re-prefill cold, and post-restore TTFT for template
traffic lands in the cached band, not the cold one.

``lend_ahead`` (ISSUE 18) is the same machinery inverted for elastic
drains: where ``lend`` pulls on a borrower miss and ``rewarm`` pulls
after a restore, a DRAINING replica **pushes** its hot prefixes to the
rendezvous successors that will inherit their traffic, then retires —
so a graceful scale-down costs the fleet no cold re-prefills at all.
"""

from __future__ import annotations

import time

from triton_dist_tpu.serving.deadline import Backoff, Deadline
from triton_dist_tpu.shmem import faults

__all__ = ["PageLendingTier"]


class PageLendingTier:
    """Host-side lending control plane over one :class:`Cluster`.

    Duck-typed against the engines' lend surface — any engine exposing
    ``prefix_cache`` + ``export_prefix``/``adopt_prefix`` participates
    (SimEngine and ServingEngine both do); engines without it simply
    never lend or borrow.

    ``plan`` pins a :class:`~triton_dist_tpu.shmem.faults.FaultPlan` for
    drills (``None`` consults the ambient ``active_plan()`` like every
    other host-tier consumer); ``deadline_steps`` is the first Backoff
    rung in engine-step space, ``max_retries`` the rung count.
    """

    def __init__(self, cluster, plan: "faults.FaultPlan | None" = None,
                 deadline_steps: int = 4, max_retries: int = 2):
        assert deadline_steps >= 1 and max_retries >= 1
        self.cluster = cluster
        self._plan = plan
        self.deadline_steps = deadline_steps
        self.max_retries = max_retries
        # (lender, borrower, prefix head) of every degraded lend — the
        # typed audit trail drills assert on (mirrors Request.degradations)
        self.degraded: list[tuple[int, int, tuple[int, ...]]] = []

    # -- submit-path lend --------------------------------------------------
    def lend(self, borrower, prompt) -> int:
        """Borrow ``prompt``'s prefix pages for ``borrower`` from the
        index-designated owner, if any. Returns pages adopted (0 = no
        remote owner, borrower already at least as warm, nothing
        lendable, or ladder exhausted → degraded to local prefill)."""
        engine = borrower.engine
        if getattr(engine, "prefix_cache", None) is None \
                or getattr(engine, "adopt_prefix", None) is None:
            return 0
        prompt = tuple(int(t) for t in prompt)
        _, owner = self.cluster.prefix_index.match(prompt)
        if owner is None or owner == borrower.index:
            return 0
        lender = self.cluster.replicas[owner]
        if not lender.alive \
                or getattr(lender.engine, "export_prefix", None) is None:
            return 0    # engines without the lend surface never lend
        return self._transfer(lender, borrower, prompt)

    # -- restore-path re-warm ----------------------------------------------
    def rewarm(self, replica, tombstones) -> int:
        """Re-warm a restored ``replica``'s empty cache from peers: for
        each kill-time tombstoned prefix (deepest-first — one deep lend
        covers every ancestor, whose adopt then early-outs) probe every
        alive peer with a depth-only ``export_prefix(payload=False)``
        (no K/V bytes gathered) and borrow from the deepest exporter
        (ties → lowest index, deterministic); only the chosen lender
        gathers payload, inside ``_transfer``. Returns total pages
        adopted."""
        engine = replica.engine
        if getattr(engine, "prefix_cache", None) is None \
                or getattr(engine, "adopt_prefix", None) is None:
            return 0
        uniq = list(dict.fromkeys(tuple(t) for t in tombstones))
        uniq.sort(key=len, reverse=True)    # stable within a length
        total = 0
        for prefix in uniq:
            best_toks, best_peer = 0, None
            for peer in self.cluster.replicas:
                if (not peer.alive or peer.index == replica.index
                        or getattr(peer.engine, "export_prefix",
                                   None) is None):
                    continue
                toks, _, _ = peer.engine.export_prefix(prefix,
                                                       payload=False)
                if toks > best_toks:
                    best_toks, best_peer = toks, peer
            if best_peer is None:
                continue    # nobody holds it anymore — re-prefills cold
            adopted = self._transfer(best_peer, replica, prefix)
            if adopted > 0:
                total += adopted
                self.cluster.metrics.inc("rewarmed_prefixes")
        return total

    # -- drain-time lend-ahead (ISSUE 18) ----------------------------------
    def lend_ahead(self, draining, prefixes,
                   successor_of) -> dict[tuple, int]:
        """The ROADMAP lend-ahead follow-on, done at drain time: PUSH a
        draining replica's hot prefixes to their rendezvous successors
        before it retires, so the prefix's future traffic radix-hits a
        warm peer instead of re-prefilling cold. ``prefixes`` are the
        drainee's pruned index entries (deepest-first after dedup — one
        deep push covers every ancestor); ``successor_of(prefix)``
        resolves the admitting replica that will win the prefix's
        rendezvous once the drainee is gone. Each push is probed with
        the depth-only ``export_prefix(payload=False)`` (nothing
        lendable → skip, no ladder burned) and shipped through the same
        ``_transfer`` retry/degrade ladder as a pull — a dead or slow
        successor burns Backoff rungs and DEGRADES to cold re-prefill
        on the successor (``lend_degradations``), never blocking the
        retire. Engines without the lend surface (mixed fleets) make
        the whole call a typed no-op, counted as ``lend_ahead_noops``.
        Returns {prefix: successor index} for the pushes that landed —
        the cluster re-points its index at exactly those."""
        m = self.cluster.metrics
        engine = draining.engine
        if engine is None \
                or getattr(engine, "export_prefix", None) is None:
            m.inc("lend_ahead_noops")
            return {}
        uniq = list(dict.fromkeys(tuple(t) for t in prefixes))
        uniq.sort(key=len, reverse=True)    # stable within a length
        placed: dict[tuple, int] = {}
        for prefix in uniq:
            toks, _, _ = engine.export_prefix(prefix, payload=False)
            if toks <= 0:
                continue    # nothing lendable here — successor goes cold
            succ = successor_of(prefix)
            if succ is None or succ.engine is None:
                continue
            if getattr(succ.engine, "adopt_prefix", None) is None:
                m.inc("lend_ahead_noops")
                continue    # mixed fleet: successor can't adopt
            adopted = self._transfer(draining, succ, prefix)
            if adopted > 0:
                placed[prefix] = succ.index
                m.inc("lend_aheads")
                m.inc("lend_ahead_pages", adopted)
        return placed

    # -- the transfer ladder -----------------------------------------------
    def _transfer(self, lender, borrower, prompt) -> int:
        """One lend through the retry/degrade ladder. Each attempt gets a
        Backoff rung as its step-space Deadline; the fault plan decides
        the attempt's fate exactly like a migration chunk send (keyed by
        (lender, borrower) so schedules replay from the seed alone). A
        failed attempt burns its rung — the borrower's clock advances to
        the deadline — and re-rolls; rung exhaustion degrades to local
        re-prefill. Success adopts on the borrower and reports the
        per-page wall latency (the ``lend_us_per_page`` bench row)."""
        m = self.cluster.metrics
        backoff = Backoff(self.deadline_steps,
                          max_retries=self.max_retries)
        now = getattr(borrower.engine, "_steps", 0)
        key = (lender.index, borrower.index)
        t0 = time.perf_counter()
        while True:
            budget = backoff.next_budget()
            if budget is None:
                m.inc("lend_degradations")
                self.degraded.append(key + (tuple(prompt[:8]),))
                return 0
            deadline = Deadline(budget, now)
            attempt = backoff.attempt - 1
            if attempt > 0:
                m.inc("retries")
            plan = self._plan if self._plan is not None \
                else faults.active_plan()
            if plan is not None:
                if plan.peer_dead(now):
                    # dead lender: puts and signals vanish in flight; the
                    # borrower's counted-signal wait burns the whole rung
                    now = deadline.expires_step
                    continue
                action, delay = plan.signal_action(
                    ("lend",) + key, 0, attempt)
                if action == "drop":
                    now = deadline.expires_step
                    continue
                if action == "delay" and delay > deadline.remaining(now):
                    # the landed report arrives after the rung re-armed —
                    # the generation tag marks it stale, attempt re-rolls
                    m.inc("stale_signals")
                    now = deadline.expires_step
                    continue
                # "dup" is an over-signal: the counted wait absorbs it
                # (the tag check is what the sigcheck lint pins)
            tokens, _, payload = lender.engine.export_prefix(prompt)
            if tokens <= 0:
                return 0    # nothing lendable — not a fault, no degrade
            adopted = borrower.engine.adopt_prefix(prompt, tokens,
                                                   payload)
            if adopted <= 0:
                return 0    # borrower already warm / pool too tight
            m.inc("lends")
            m.inc("lent_pages", adopted)
            m.inc("lend_tokens", adopted * borrower.engine.page_size)
            m.observe("lend_us_per_page",
                      (time.perf_counter() - t0) * 1e6 / adopted)
            return adopted
